"""APOC export/import and path-expansion procedures.

Behavioral reference: /root/reference/apoc/export/export.go (Json/Csv/
Cypher/GraphML × All/Data, ToFile/ToString), apoc/import/import.go
(Json/Csv/GraphML round-trips), apoc/path(s)/ (ExpandConfig, SpanningTree,
Elements, Combine, Slice). File writes are gated by
NORNICDB_APOC_EXPORT_ENABLED, file reads by NORNICDB_APOC_IMPORT_ENABLED
(the reference gates file access the same way, apoc/config.go); with a
null/empty file the exporters stream the payload back as a row instead.
"""

from __future__ import annotations

import csv
import io
import json
import os
from typing import Any, Optional
from xml.sax.saxutils import escape as _xml_escape
from xml.sax.saxutils import quoteattr as _xml_attr

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.errors import CypherSyntaxError, CypherTypeError, NornicError
from nornicdb_tpu.storage.types import Edge, Node


def _export_allowed() -> bool:
    return os.environ.get("NORNICDB_APOC_EXPORT_ENABLED", "").lower() in (
        "1", "true", "yes")


def _import_allowed() -> bool:
    return os.environ.get("NORNICDB_APOC_IMPORT_ENABLED", "").lower() in (
        "1", "true", "yes")


def _all_graph(ex: CypherExecutor) -> tuple[list[Node], list[Edge]]:
    return list(ex.storage.all_nodes()), list(ex.storage.all_edges())


def _emit(ex, file: Optional[str], payload: str, fmt: str, n_nodes: int,
          n_rels: int):
    """Write to file (gated) or stream back, with apoc.export.*'s row shape."""
    cols = ["file", "format", "nodes", "relationships", "data"]
    if file:
        if not _export_allowed():
            raise NornicError(
                "file export disabled; set NORNICDB_APOC_EXPORT_ENABLED=1"
            )
        with open(file, "w") as f:
            f.write(payload)
        return cols, [[file, fmt, n_nodes, n_rels, None]]
    return cols, [[None, fmt, n_nodes, n_rels, payload]]


# ---------------------------------------------------------------------------
# exporters (ref: export.go Json/Csv/Cypher/GraphML)
# ---------------------------------------------------------------------------


def _json_payload(nodes: list[Node], rels: list[Edge]) -> str:
    out = io.StringIO()
    for n in nodes:
        rec = {"type": "node", "id": n.id, "labels": list(n.labels),
               "properties": dict(n.properties)}
        out.write(json.dumps(rec, default=str) + "\n")
    for e in rels:
        rec = {"type": "relationship", "id": e.id, "label": e.type,
               "start": {"id": e.start_node}, "end": {"id": e.end_node},
               "properties": dict(e.properties)}
        out.write(json.dumps(rec, default=str) + "\n")
    return out.getvalue()


_CSV_RESERVED = {"_id", "_labels", "_start", "_end", "_type"}


def _csv_col(key: str) -> str:
    """Header column for a property key; reserved names are aliased so a
    user property literally named `_id` can't shadow the structural
    columns."""
    return "_prop" + key if key in _CSV_RESERVED else key


def _csv_payload(nodes: list[Node], rels: list[Edge]) -> str:
    """Union-of-keys header over BOTH node and relationship properties (the
    reference uses first-node keys, which drops columns — deliberately
    diverging to a lossless header). Edge rows carry their id/props too, so
    apoc.import.csv round-trips relationships faithfully."""
    out = io.StringIO()
    w = csv.writer(out)
    prop_keys = sorted({k for n in nodes for k in n.properties}
                       | {k for e in rels for k in e.properties})
    w.writerow(["_id", "_labels"] + [_csv_col(k) for k in prop_keys] +
               ["_start", "_end", "_type"])
    for n in nodes:
        w.writerow([n.id, ";".join(n.labels)] +
                   [_csv_val(n.properties.get(k)) for k in prop_keys] +
                   ["", "", ""])
    for e in rels:
        w.writerow([e.id, ""] +
                   [_csv_val(e.properties.get(k)) for k in prop_keys] +
                   [e.start_node, e.end_node, e.type])
    return out.getvalue()


def _csv_val(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, (list, dict)):
        return json.dumps(v, default=str)
    return str(v)


def _cypher_literal(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, list):
        return "[" + ", ".join(_cypher_literal(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ", ".join(
            f"{_bt(k)}: {_cypher_literal(x)}" for k, x in v.items()) + "}"
    s = str(v).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{s}'"


def _bt(name: str) -> str:
    """Backtick-quoted Cypher identifier; embedded backticks are doubled so
    a hostile label/type/key can't escape the identifier in the replay
    script."""
    return "`" + str(name).replace("`", "``") + "`"


def _cypher_payload(nodes: list[Node], rels: list[Edge]) -> str:
    """CREATE-script export keyed on an `_import_id` property so the
    relationship MATCHes are replayable (the reference emits positional
    n<i> aliases valid only within one statement batch)."""
    out = io.StringIO()
    for n in nodes:
        labels = "".join(f":{_bt(l)}" for l in n.labels)
        props = dict(n.properties)
        props["_import_id"] = n.id
        out.write(f"CREATE ({labels} {_cypher_literal(props)});\n")
    for e in rels:
        out.write(
            "MATCH (a {_import_id: %s}), (b {_import_id: %s}) "
            "CREATE (a)-[:%s %s]->(b);\n"
            % (_cypher_literal(e.start_node), _cypher_literal(e.end_node),
               _bt(e.type), _cypher_literal(dict(e.properties)))
        )
    return out.getvalue()


def _graphml_payload(nodes: list[Node], rels: list[Edge]) -> str:
    # attribute positions use quoteattr (escape() leaves '"' alone, which
    # would break label="..." on values containing quotes)
    out = io.StringIO()
    out.write('<?xml version="1.0" encoding="UTF-8"?>\n')
    out.write('<graphml xmlns="http://graphml.graphdrawing.org/xmlns">\n')
    keys = sorted({k for n in nodes for k in n.properties}
                  | {k for e in rels for k in e.properties} | {"labels"})
    for k in keys:
        out.write(f"  <key id={_xml_attr(k)} for=\"all\" "
                  f"attr.name={_xml_attr(k)} attr.type=\"string\"/>\n")
    out.write('  <graph id="G" edgedefault="directed">\n')
    for n in nodes:
        out.write(f"    <node id={_xml_attr(n.id)}>\n")
        out.write(f'      <data key="labels">{_xml_escape(";".join(n.labels))}'
                  "</data>\n")
        for k, v in n.properties.items():
            out.write(f"      <data key={_xml_attr(k)}>"
                      f"{_xml_escape(_csv_val(v))}</data>\n")
        out.write("    </node>\n")
    for e in rels:
        out.write(f"    <edge id={_xml_attr(e.id)} "
                  f"source={_xml_attr(e.start_node)} "
                  f"target={_xml_attr(e.end_node)} "
                  f"label={_xml_attr(e.type)}>\n")
        for k, v in e.properties.items():
            out.write(f"      <data key={_xml_attr(k)}>"
                      f"{_xml_escape(_csv_val(v))}</data>\n")
        out.write("    </edge>\n")
    out.write("  </graph>\n</graphml>\n")
    return out.getvalue()


def _split_data_args(args) -> tuple[list, list, Optional[str]]:
    nodes = list(args[0] or []) if args else []
    rels = list(args[1] or []) if len(args) > 1 else []
    file = args[2] if len(args) > 2 and args[2] else None
    return nodes, rels, file


@procedure("apoc.export.json.all")
def export_json_all(ex: CypherExecutor, args, row):
    file = args[0] if args and args[0] else None
    nodes, rels = _all_graph(ex)
    return _emit(ex, file, _json_payload(nodes, rels), "json",
                 len(nodes), len(rels))


@procedure("apoc.export.json.data")
def export_json_data(ex: CypherExecutor, args, row):
    nodes, rels, file = _split_data_args(args)
    return _emit(ex, file, _json_payload(nodes, rels), "json",
                 len(nodes), len(rels))


@procedure("apoc.export.csv.all")
def export_csv_all(ex: CypherExecutor, args, row):
    file = args[0] if args and args[0] else None
    nodes, rels = _all_graph(ex)
    return _emit(ex, file, _csv_payload(nodes, rels), "csv",
                 len(nodes), len(rels))


@procedure("apoc.export.csv.data")
def export_csv_data(ex: CypherExecutor, args, row):
    nodes, rels, file = _split_data_args(args)
    return _emit(ex, file, _csv_payload(nodes, rels), "csv",
                 len(nodes), len(rels))


@procedure("apoc.export.cypher.all")
def export_cypher_all(ex: CypherExecutor, args, row):
    file = args[0] if args and args[0] else None
    nodes, rels = _all_graph(ex)
    return _emit(ex, file, _cypher_payload(nodes, rels), "cypher",
                 len(nodes), len(rels))


@procedure("apoc.export.cypher.data")
def export_cypher_data(ex: CypherExecutor, args, row):
    nodes, rels, file = _split_data_args(args)
    return _emit(ex, file, _cypher_payload(nodes, rels), "cypher",
                 len(nodes), len(rels))


@procedure("apoc.export.graphml.all")
def export_graphml_all(ex: CypherExecutor, args, row):
    file = args[0] if args and args[0] else None
    nodes, rels = _all_graph(ex)
    return _emit(ex, file, _graphml_payload(nodes, rels), "graphml",
                 len(nodes), len(rels))


@procedure("apoc.export.graphml.data")
def export_graphml_data(ex: CypherExecutor, args, row):
    nodes, rels, file = _split_data_args(args)
    return _emit(ex, file, _graphml_payload(nodes, rels), "graphml",
                 len(nodes), len(rels))


# ---------------------------------------------------------------------------
# importers (ref: import.go — mirror of the exporters above)
# ---------------------------------------------------------------------------


def _require_import(file: str) -> str:
    if not _import_allowed():
        raise NornicError(
            "file import disabled; set NORNICDB_APOC_IMPORT_ENABLED=1"
        )
    with open(file) as f:
        return f.read()


@procedure("apoc.import.json")
def import_json(ex: CypherExecutor, args, row):
    """Reads the jsonl produced by apoc.export.json.* — ids are preserved."""
    if not args:
        raise CypherSyntaxError("apoc.import.json(file)")
    text = _require_import(str(args[0]))
    n_nodes = n_rels = 0
    deferred: list[dict] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        if rec.get("type") == "node":
            ex.storage.create_node(Node(
                id=rec["id"], labels=list(rec.get("labels") or []),
                properties=dict(rec.get("properties") or {})))
            n_nodes += 1
        else:
            deferred.append(rec)
    for rec in deferred:
        ex.storage.create_edge(Edge(
            id=rec["id"], type=rec.get("label", "RELATED_TO"),
            start_node=rec["start"]["id"], end_node=rec["end"]["id"],
            properties=dict(rec.get("properties") or {})))
        n_rels += 1
    return ["nodes", "relationships"], [[n_nodes, n_rels]]


@procedure("apoc.import.csv")
def import_csv(ex: CypherExecutor, args, row):
    """Reads the union-header CSV produced by apoc.export.csv.*."""
    if not args:
        raise CypherSyntaxError("apoc.import.csv(file)")
    text = _require_import(str(args[0]))
    rows = list(csv.reader(io.StringIO(text)))
    if not rows:
        return ["nodes", "relationships"], [[0, 0]]
    header = rows[0]
    # property columns: everything except the structural ones; `_prop<name>`
    # aliases map back to their reserved-looking original keys
    prop_cols = [(h, h[5:] if h.startswith("_prop") else h)
                 for h in header if h not in _CSV_RESERVED]
    idx = {h: i for i, h in enumerate(header)}
    n_nodes = n_rels = 0
    for r in rows[1:]:
        if not r:
            continue
        props = {k: r[idx[h]] for h, k in prop_cols if r[idx[h]] != ""}
        if r[idx["_start"]]:  # edge rows are the ones with endpoints
            kwargs = {"id": r[idx["_id"]]} if r[idx["_id"]] else {}
            ex.storage.create_edge(Edge(
                start_node=r[idx["_start"]], end_node=r[idx["_end"]],
                type=r[idx["_type"]] or "RELATED_TO", properties=props,
                **kwargs))
            n_rels += 1
        elif r[idx["_id"]]:
            ex.storage.create_node(Node(
                id=r[idx["_id"]],
                labels=[l for l in r[idx["_labels"]].split(";") if l],
                properties=props))
            n_nodes += 1
    return ["nodes", "relationships"], [[n_nodes, n_rels]]


@procedure("apoc.import.graphml")
def import_graphml(ex: CypherExecutor, args, row):
    if not args:
        raise CypherSyntaxError("apoc.import.graphml(file)")
    import xml.etree.ElementTree as ET

    text = _require_import(str(args[0]))
    ns = {"g": "http://graphml.graphdrawing.org/xmlns"}
    root = ET.fromstring(text)
    n_nodes = n_rels = 0
    graph = root.find("g:graph", ns)
    if graph is None:
        raise NornicError("graphml: no <graph> element")
    for el in graph.findall("g:node", ns):
        props = {}
        labels: list[str] = []
        for d in el.findall("g:data", ns):
            if d.get("key") == "labels":
                labels = [l for l in (d.text or "").split(";") if l]
            else:
                # ElementTree yields text=None for <data></data>; that was
                # an empty string at export time, not a null
                props[d.get("key")] = d.text or ""
        ex.storage.create_node(Node(id=el.get("id"), labels=labels,
                                    properties=props))
        n_nodes += 1
    for el in graph.findall("g:edge", ns):
        props = {d.get("key"): d.text or "" for d in el.findall("g:data", ns)}
        kwargs = {}
        if el.get("id"):
            kwargs["id"] = el.get("id")
        ex.storage.create_edge(Edge(
            start_node=el.get("source"), end_node=el.get("target"),
            type=el.get("label") or "RELATED_TO", properties=props, **kwargs))
        n_rels += 1
    return ["nodes", "relationships"], [[n_nodes, n_rels]]


# ---------------------------------------------------------------------------
# apoc.path.* (ref: apoc/path/path.go ExpandConfig/SpanningTree,
# apoc/paths/paths.go Elements/Combine/Slice)
# ---------------------------------------------------------------------------


def _path_obj(nodes: list[Node], rels: list[Edge]) -> dict:
    return {"__path__": True, "nodes": nodes, "relationships": rels}


def _parse_rel_filter(spec: Optional[str]) -> tuple[set[str], set[str]]:
    """"KNOWS>|<WORKS_WITH|BOTH" -> (outgoing types, incoming types);
    empty spec allows everything both ways."""
    out_t: set[str] = set()
    in_t: set[str] = set()
    if not spec:
        return out_t, in_t
    for part in str(spec).split("|"):
        part = part.strip()
        if not part:
            continue
        if part.endswith(">"):
            out_t.add(part.rstrip(">"))
        elif part.startswith("<"):
            in_t.add(part.lstrip("<"))
        else:
            out_t.add(part)
            in_t.add(part)
    return out_t, in_t


def _parse_label_filter(spec: Optional[str]) -> tuple[set[str], set[str]]:
    """"+Person|-Banned" -> (whitelist, blacklist)."""
    white: set[str] = set()
    black: set[str] = set()
    for part in str(spec or "").split("|"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("-"):
            black.add(part[1:])
        else:
            white.add(part.lstrip("+"))
    return white, black


def _resolve_start(ex, start) -> Node:
    """The start argument accepts a Node, an id string, or a map carrying
    an `id` key (ref: apoc.path.* taking {id: ...} in the reference
    tests)."""
    if isinstance(start, Node):
        return start
    if isinstance(start, dict):
        start = start.get("id")
    node = ex.get_node_or_none(str(start)) if start is not None else None
    if node is None:
        raise CypherTypeError(f"start node not found: {start!r}")
    return node


def _expand(ex, start, rel_spec, label_spec, min_level: int,
            max_level: int, uniqueness: str = "RELATIONSHIP_PATH",
            limit: Optional[int] = None, bfs: bool = False) -> list[dict]:
    start = _resolve_start(ex, start)
    out_t, in_t = _parse_rel_filter(rel_spec)
    no_filter = not rel_spec
    white, black = _parse_label_filter(label_spec)
    results: list[dict] = []

    def node_ok(n: Node) -> bool:
        if black and any(l in black for l in n.labels):
            return False
        if white and not any(l in white for l in n.labels):
            return False
        return True

    # iterative walk (deep graphs with large maxLevel must not hit the
    # interpreter recursion limit); RELATIONSHIP_PATH uniqueness derives
    # the per-path seen-sets from the path itself, NODE_GLOBAL keeps one
    # shared visited set (first path to a node claims it). NODE_GLOBAL
    # callers (spanningTree) need BFS order so the claiming path is a
    # shortest one — DFS would claim nodes via long detours and then
    # truncate their subtrees at maxLevel.
    from collections import deque

    global_seen = {start.id}
    stack: deque[tuple[Node, list[Node], list[Edge]]] = deque(
        [(start, [start], [])])
    while stack:
        node, nodes, rels = stack.popleft() if bfs else stack.pop()
        if limit is not None and len(results) >= limit:
            break
        depth = len(rels)
        if depth >= min_level:
            results.append(_path_obj(list(nodes), list(rels)))
        if depth >= max_level:
            continue
        path_rel_ids = {e.id for e in rels}
        steps: list[tuple[Edge, str]] = []
        for e in ex.storage.get_outgoing_edges(node.id):
            if no_filter or e.type in out_t:
                steps.append((e, e.end_node))
        for e in ex.storage.get_incoming_edges(node.id):
            if no_filter or e.type in in_t:
                steps.append((e, e.start_node))
        for e, nxt_id in reversed(steps):  # preserve first-edge-first order
            if e.id in path_rel_ids:
                continue
            if uniqueness == "NODE_GLOBAL" and nxt_id in global_seen:
                continue
            nxt = ex.get_node_or_none(nxt_id)
            if nxt is None or not node_ok(nxt):
                continue
            if uniqueness == "NODE_GLOBAL":
                global_seen.add(nxt_id)
            stack.append((nxt, nodes + [nxt], rels + [e]))
    return results


@procedure("apoc.path.expand")
def apoc_path_expand(ex: CypherExecutor, args, row):
    """apoc.path.expand(start, relFilter, labelFilter, minLevel, maxLevel)"""
    if not args:
        raise CypherSyntaxError(
            "apoc.path.expand(start, relFilter, labelFilter, min, max)")
    start = args[0]
    rel_spec = args[1] if len(args) > 1 else None
    label_spec = args[2] if len(args) > 2 else None
    min_level = int(args[3]) if len(args) > 3 else 0
    max_level = int(args[4]) if len(args) > 4 else 3
    # minLevel 0 includes the zero-length start-only path, same as
    # expandConfig (APOC semantics)
    paths = _expand(ex, start, rel_spec, label_spec, min_level, max_level)
    return ["path"], [[p] for p in paths]


@procedure("apoc.path.expandconfig")
def apoc_path_expand_config(ex: CypherExecutor, args, row):
    """apoc.path.expandConfig(start, {relationshipFilter, labelFilter,
    minLevel, maxLevel, uniqueness, limit})"""
    if not args:
        raise CypherSyntaxError("apoc.path.expandConfig(start, config)")
    start = args[0]
    cfg = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    paths = _expand(
        ex, start,
        cfg.get("relationshipFilter"), cfg.get("labelFilter"),
        max(int(cfg.get("minLevel", 1)), 0),
        int(cfg.get("maxLevel", 3)),
        uniqueness=str(cfg.get("uniqueness", "RELATIONSHIP_PATH")),
        limit=int(cfg["limit"]) if cfg.get("limit") is not None else None,
    )
    return ["path"], [[p] for p in paths]


@procedure("apoc.path.spanningtree")
def apoc_path_spanning_tree(ex: CypherExecutor, args, row):
    """BFS spanning tree: one path per reachable node (NODE_GLOBAL)."""
    if not args:
        raise CypherSyntaxError("apoc.path.spanningTree(start, config)")
    start = args[0]
    cfg = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    paths = _expand(
        ex, start,
        cfg.get("relationshipFilter"), cfg.get("labelFilter"),
        1, int(cfg.get("maxLevel", 3)), uniqueness="NODE_GLOBAL", bfs=True,
    )
    return ["path"], [[p] for p in paths]


@procedure("apoc.path.elements")
def apoc_path_elements(ex: CypherExecutor, args, row):
    """Interleaved [n0, r0, n1, r1, ...] (ref paths.go Elements)."""
    p = args[0] if args else None
    if not (isinstance(p, dict) and p.get("__path__")):
        raise CypherSyntaxError("apoc.path.elements(path)")
    out: list[Any] = []
    nodes, rels = p["nodes"], p["relationships"]
    for i, n in enumerate(nodes):
        out.append(n)
        if i < len(rels):
            out.append(rels[i])
    return ["value"], [[out]]


@procedure("apoc.path.combine")
def apoc_path_combine(ex: CypherExecutor, args, row):
    """Join two paths sharing an endpoint node (ref paths.go Combine)."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.path.combine(first, second)")
    a, b = args[0], args[1]
    for p in (a, b):
        if not (isinstance(p, dict) and p.get("__path__")):
            raise CypherSyntaxError("apoc.path.combine expects two paths")
    if not a["nodes"] or not b["nodes"] or \
            a["nodes"][-1].id != b["nodes"][0].id:
        raise CypherSyntaxError(
            "apoc.path.combine: first path must end where second begins")
    return ["path"], [[_path_obj(a["nodes"] + b["nodes"][1:],
                                 a["relationships"] + b["relationships"])]]


@procedure("apoc.path.slice")
def apoc_path_slice(ex: CypherExecutor, args, row):
    """Sub-path [offset, offset+length] in relationship units."""
    p = args[0] if args else None
    if not (isinstance(p, dict) and p.get("__path__")):
        raise CypherSyntaxError("apoc.path.slice(path, offset, length)")
    offset = int(args[1]) if len(args) > 1 else 0
    length = int(args[2]) if len(args) > 2 else len(p["relationships"])
    rels = p["relationships"][offset : offset + length]
    nodes = p["nodes"][offset : offset + length + 1]
    return ["path"], [[_path_obj(nodes, rels)]]
