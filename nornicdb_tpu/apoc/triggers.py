"""apoc.trigger — Cypher statements fired by storage events.

Behavioral reference: /root/reference/apoc/trigger — triggers registered as
(name, cypher, selector); on write events the statement runs with the
affected entities bound ($createdNodes, $deletedNodes,
$createdRelationships, $deletedRelationships, $assignedNodeProperties).
Triggers are paused/resumed/removed by name; nested trigger cascades are
suppressed (the reference fires triggers post-transaction, not
recursively).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.storage.types import Edge, Engine, Node

_EVENT_PARAM = {
    "node_created": "createdNodes",
    "node_deleted": "deletedNodes",
    "node_updated": "assignedNodeProperties",
    "edge_created": "createdRelationships",
    "edge_deleted": "deletedRelationships",
}


@dataclass
class Trigger:
    name: str
    statement: str
    selector: dict[str, Any] = field(default_factory=dict)
    paused: bool = False
    fired: int = 0
    errors: int = 0


class TriggerManager:
    """Holds the trigger registry for one executor + storage pair."""

    def __init__(self, executor):
        self.executor = executor
        self._lock = threading.RLock()
        self._triggers: dict[str, Trigger] = {}
        self._firing = threading.local()
        executor.storage.on_event(self._on_event)

    # -- registry -----------------------------------------------------------
    def add(self, name: str, statement: str,
            selector: Optional[dict] = None) -> Trigger:
        with self._lock:
            t = Trigger(name, statement, selector or {})
            self._triggers[name] = t
            return t

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._triggers.pop(name, None) is not None

    def remove_all(self) -> int:
        with self._lock:
            n = len(self._triggers)
            self._triggers.clear()
            return n

    def pause(self, name: str, paused: bool = True) -> Optional[Trigger]:
        with self._lock:
            t = self._triggers.get(name)
            if t is not None:
                t.paused = paused
            return t

    def list(self) -> list[Trigger]:
        with self._lock:
            return list(self._triggers.values())

    # -- firing --------------------------------------------------------------
    def _on_event(self, kind: str, entity: Any) -> None:
        param = _EVENT_PARAM.get(kind)
        if param is None:
            return
        if getattr(self._firing, "active", False):
            return  # no recursive cascades (ref: post-tx firing)
        with self._lock:
            triggers = [t for t in self._triggers.values() if not t.paused]
        if not triggers:
            return
        params: dict[str, Any] = {p: [] for p in _EVENT_PARAM.values()}
        params[param] = [entity]
        self._firing.active = True
        try:
            for t in triggers:
                phase = t.selector.get("phase")
                if phase and phase not in ("after", "afterAsync"):
                    continue
                try:
                    self.executor.execute(t.statement, params)
                    t.fired += 1
                except Exception:
                    t.errors += 1  # a broken trigger must not break writes
        finally:
            self._firing.active = False
