"""apoc.trigger — Cypher statements fired by storage events.

Behavioral reference: /root/reference/apoc/trigger (trigger.go) — triggers
registered as (name, cypher, selector {label, event, phase}); statements run
with the affected entities bound ($createdNodes, $deletedNodes,
$createdRelationships, $deletedRelationships, $assignedNodeProperties,
$assignedRelationshipProperties). The registry is database-global (one per
storage engine), shared across sessions.

Known deviations, documented rather than faked:
  - Firing is synchronous per storage event (the reference batches
    post-transaction). Phases "before"/"after"/"afterAsync" all fire at
    the same point; selecting them filters nothing out.
  - $assignedNodeProperties / $assignedRelationshipProperties carry
    {key: [{node|relationship, key, new}]} without `old` values — the
    event stream has no pre-images yet.
Recursive cascades are suppressed (a trigger's own writes don't re-fire
triggers), matching the reference's post-tx semantics in effect.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.storage.types import Edge, Engine, Node
from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)

_EVENT_PARAM = {
    "node_created": "createdNodes",
    "node_deleted": "deletedNodes",
    "node_updated": "assignedNodeProperties",
    "edge_created": "createdRelationships",
    "edge_deleted": "deletedRelationships",
    "edge_updated": "assignedRelationshipProperties",
}

# selector {"event": ...} values accepted per event kind (ref: trigger.go)
_EVENT_NAME = {
    "node_created": "create",
    "node_deleted": "delete",
    "node_updated": "update",
    "edge_created": "create",
    "edge_deleted": "delete",
    "edge_updated": "update",
}

_PHASES = ("before", "after", "afterAsync")


@dataclass
class Trigger:
    name: str
    statement: str
    selector: dict[str, Any] = field(default_factory=dict)
    paused: bool = False
    fired: int = 0
    errors: int = 0


def manager_for(executor) -> "TriggerManager":
    """Database-global registry: ONE manager per storage engine, shared by
    every session executor (ref: APOC's per-database trigger store)."""
    storage = executor.storage
    mgr = getattr(storage, "_apoc_trigger_manager", None)
    if mgr is None:
        mgr = TriggerManager(executor)
        storage._apoc_trigger_manager = mgr
    return mgr


class TriggerManager:
    def __init__(self, executor):
        # dedicated executor so trigger statements never share a session's
        # explicit-transaction state
        from nornicdb_tpu.cypher.executor import CypherExecutor

        self.executor = CypherExecutor(
            executor.storage, schema=executor.schema, db=executor.db
        )
        self._lock = threading.RLock()
        self._triggers: dict[str, Trigger] = {}
        self._firing = threading.local()
        executor.storage.on_event(self._on_event)

    # -- registry -----------------------------------------------------------
    def add(self, name: str, statement: str,
            selector: Optional[dict] = None) -> Trigger:
        with self._lock:
            t = Trigger(name, statement, selector or {})
            self._triggers[name] = t
            return t

    def get(self, name: str) -> Optional[Trigger]:
        with self._lock:
            return self._triggers.get(name)

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._triggers.pop(name, None) is not None

    def remove_all(self) -> int:
        with self._lock:
            n = len(self._triggers)
            self._triggers.clear()
            return n

    def pause(self, name: str, paused: bool = True) -> Optional[Trigger]:
        with self._lock:
            t = self._triggers.get(name)
            if t is not None:
                t.paused = paused
            return t

    def list(self) -> list[Trigger]:
        with self._lock:
            return list(self._triggers.values())

    # -- firing --------------------------------------------------------------
    @staticmethod
    def _matches_selector(t: Trigger, kind: str, entity: Any) -> bool:
        sel = t.selector or {}
        want_event = sel.get("event")
        if want_event and want_event != _EVENT_NAME.get(kind):
            return False
        want_label = sel.get("label")
        if want_label:
            if isinstance(entity, Node):
                if want_label not in entity.labels:
                    return False
            elif isinstance(entity, Edge):
                if want_label != entity.type:
                    return False
        phase = sel.get("phase")
        if phase and phase not in _PHASES:
            return False  # unknown phase: never fire (registration-time typo)
        return True

    @staticmethod
    def _params_for(kind: str, entity: Any) -> dict[str, Any]:
        params: dict[str, Any] = {
            "createdNodes": [], "deletedNodes": [],
            "createdRelationships": [], "deletedRelationships": [],
            "assignedNodeProperties": {}, "assignedRelationshipProperties": {},
        }
        param = _EVENT_PARAM[kind]
        if kind == "node_updated":
            params[param] = {
                k: [{"node": entity, "key": k, "new": v}]
                for k, v in entity.properties.items()
            }
        elif kind == "edge_updated":
            params[param] = {
                k: [{"relationship": entity, "key": k, "new": v}]
                for k, v in entity.properties.items()
            }
        else:
            params[param] = [entity]
        return params

    def _on_event(self, kind: str, entity: Any) -> None:
        if kind not in _EVENT_PARAM:
            return
        if getattr(self._firing, "active", False):
            return  # no recursive cascades
        with self._lock:
            triggers = [
                t for t in self._triggers.values()
                if not t.paused and self._matches_selector(t, kind, entity)
            ]
        if not triggers:
            return
        params = self._params_for(kind, entity)
        self._firing.active = True
        try:
            for t in triggers:
                try:
                    self.executor.execute(t.statement, params)
                    t.fired += 1
                except Exception:
                    # a broken trigger must not break writes — but its
                    # failures must be visible, not just a silent counter
                    log.warning("trigger %s failed", t.name, exc_info=True)
                    count_error("apoc.trigger")
                    t.errors += 1
        finally:
            self._firing.active = False
