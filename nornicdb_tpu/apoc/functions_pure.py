"""APOC pure-function gap fill: math / number / util / stats / scoring /
json / hashing / convert / date / agg / bitwise / diff / coll / temporal /
xml / spatial / text categories.

Behavioral reference: /root/reference/apoc/apoc.go registerAllFunctions —
names, arities and result conventions follow the example strings registered
there (e.g. `apoc.math.ceil(3.14) => 4.0` returns a float where the Java
original returns long). Implementations are original; non-obvious algorithms
(xxHash, CityHash, Double Metaphone) are clean-room from their public specs.
"""

from __future__ import annotations

import base64
import json as _json
import math
import random
import re
import time
import uuid as _uuid
import zlib

from nornicdb_tpu.apoc.functions_ext import (
    _nums,
    hashing_fnv1a64,
    hashing_murmur3,
    stats_percentile,
)
from nornicdb_tpu.apoc.registry import register

_U32 = 0xFFFFFFFF
_U64 = 0xFFFFFFFFFFFFFFFF


# ============================================================== apoc.math
# (ref: apoc/math/math.go — float-returning wrappers over the stdlib)
def _math(name, fn, arity=1):
    @register(f"apoc.math.{name}")
    def f(*args):
        if any(a is None for a in args[:arity]):
            return None
        return fn(*[float(a) for a in args])

    f.__name__ = f"math_{name}"
    return f


_math("abs", abs)
_math("ceil", lambda x: float(math.ceil(x)))
_math("floor", lambda x: float(math.floor(x)))
_math("sqrt", math.sqrt)
_math("log", math.log)
_math("log10", math.log10)
_math("exp", math.exp)
_math("sin", math.sin)
_math("cos", math.cos)
_math("tan", math.tan)
_math("asin", math.asin)
_math("acos", math.acos)
_math("atan", math.atan)
_math("atan2", math.atan2, arity=2)
_math("pow", lambda a, b: float(a ** b), arity=2)


@register("apoc.math.maxDouble")
def math_max_double(*args):
    vals = _nums(args[0]) if len(args) == 1 and isinstance(args[0], list) \
        else _nums(list(args))
    return max(vals) if vals else None


@register("apoc.math.minDouble")
def math_min_double(*args):
    vals = _nums(args[0]) if len(args) == 1 and isinstance(args[0], list) \
        else _nums(list(args))
    return min(vals) if vals else None


@register("apoc.math.normalize")
def math_normalize(value, lo, hi):
    if value is None or lo is None or hi is None or hi == lo:
        return None
    return (float(value) - float(lo)) / (float(hi) - float(lo))


@register("apoc.math.random")
def math_random():
    return random.random()


@register("apoc.math.randomInt")
def math_random_int(lo, hi):
    return random.randint(int(lo), int(hi))


@register("apoc.math.percentile")
def math_percentile(xs, p):
    return stats_percentile(xs, p)


@register("apoc.math.median")
def math_median(xs):
    return stats_percentile(xs, 0.5)


@register("apoc.math.mean")
def math_mean(xs):
    v = _nums(xs)
    return sum(v) / len(v) if v else None


@register("apoc.math.stdev")
def math_stdev(xs, population=False):
    v = _nums(xs)
    if len(v) < 2:
        return 0.0
    m = sum(v) / len(v)
    var = sum((x - m) ** 2 for x in v) / (len(v) if population else len(v) - 1)
    return math.sqrt(var)


@register("apoc.math.variance")
def math_variance(xs, population=True):
    v = _nums(xs)
    if not v:
        return None
    m = sum(v) / len(v)
    n = len(v) if population or len(v) < 2 else len(v) - 1
    return sum((x - m) ** 2 for x in v) / n


@register("apoc.math.mode")
def math_mode(xs):
    if not xs:
        return None
    counts: dict = {}
    for x in xs:
        counts[x] = counts.get(x, 0) + 1
    return max(counts, key=lambda k: (counts[k],))


@register("apoc.math.range")
def math_range(lo, hi, step=1):
    step = int(step) or 1
    return list(range(int(lo), int(hi) + (1 if step > 0 else -1), step))


@register("apoc.math.sum")
def math_sum(xs):
    return sum(_nums(xs))


@register("apoc.math.product")
def math_product(xs):
    out = 1.0
    for x in _nums(xs):
        out *= x
    return out


# ============================================================ apoc.number
# (ref: apoc/number/number.go — int-preserving where the example shows ints)
@register("apoc.number.abs")
def number_abs(x):
    return None if x is None else abs(x)


@register("apoc.number.ceil")
def number_ceil(x):
    return None if x is None else math.ceil(float(x))


@register("apoc.number.floor")
def number_floor(x):
    return None if x is None else math.floor(float(x))


@register("apoc.number.round")
def number_round(x, digits=0):
    """Half-up rounding (the reference rounds 0.5 away from the floor, not
    banker's)."""
    if x is None:
        return None
    q = 10 ** int(digits)
    out = math.floor(float(x) * q + 0.5) / q
    return int(out) if not digits else out


@register("apoc.number.sign")
def number_sign(x):
    if x is None:
        return None
    return 0 if x == 0 else (1 if x > 0 else -1)


@register("apoc.number.exact")
def number_exact(x, digits=2):
    if x is None:
        return None
    q = 10 ** int(digits)
    return math.floor(float(x) * q + 0.5) / q


@register("apoc.number.parse")
def number_parse(text, pattern=None):
    """Parse '12,345.67'-style grouped decimals (ref number.go Parse)."""
    if text is None:
        return None
    s = str(text).replace(",", "").strip()
    v = float(s)
    return int(v) if v.is_integer() and "." not in s else v


@register("apoc.number.isEven")
def number_is_even(x):
    return None if x is None else int(x) % 2 == 0


@register("apoc.number.isOdd")
def number_is_odd(x):
    return None if x is None else int(x) % 2 == 1


@register("apoc.number.isPrime")
def number_is_prime(x):
    if x is None:
        return None
    n = int(x)
    if n < 2:
        return False
    if n < 4:
        return True
    if n % 2 == 0:
        return False
    i = 3
    while i * i <= n:
        if n % i == 0:
            return False
        i += 2
    return True


@register("apoc.number.gcd")
def number_gcd(a, b):
    return math.gcd(int(a), int(b))


@register("apoc.number.lcm")
def number_lcm(a, b):
    a, b = int(a), int(b)
    return abs(a * b) // math.gcd(a, b) if a and b else 0


@register("apoc.number.factorial")
def number_factorial(n):
    n = int(n)
    if n < 0:
        raise ValueError("factorial of negative number")
    return math.factorial(n)


@register("apoc.number.fibonacci")
def number_fibonacci(n):
    n = int(n)
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


@register("apoc.number.power")
def number_power(a, b):
    out = float(a) ** float(b)
    return int(out) if out.is_integer() else out


@register("apoc.number.sqrt")
def number_sqrt(x):
    out = math.sqrt(float(x))
    return int(out) if out.is_integer() else out


@register("apoc.number.log")
def number_log(x):
    return math.log(float(x))


@register("apoc.number.log10")
def number_log10(x):
    return math.log10(float(x))


@register("apoc.number.exp")
def number_exp(x):
    return math.exp(float(x))


@register("apoc.number.clamp")
def number_clamp(x, lo, hi):
    return max(lo, min(hi, x))


@register("apoc.number.lerp")
def number_lerp(a, b, t):
    out = float(a) + (float(b) - float(a)) * float(t)
    return int(out) if out.is_integer() else out


@register("apoc.number.normalize")
def number_normalize(x, lo, hi):
    return math_normalize(x, lo, hi)


@register("apoc.number.map")
def number_map(x, in_lo, in_hi, out_lo, out_hi):
    """Map x from [in_lo, in_hi] to [out_lo, out_hi] (ref number.go Map)."""
    if in_hi == in_lo:
        return None
    t = (float(x) - float(in_lo)) / (float(in_hi) - float(in_lo))
    out = float(out_lo) + t * (float(out_hi) - float(out_lo))
    return int(out) if out.is_integer() else out


@register("apoc.number.random")
def number_random():
    return random.random()


@register("apoc.number.randomInt")
def number_random_int(lo, hi):
    return random.randint(int(lo), int(hi))


# ============================================================== apoc.util
@register("apoc.util.uuid")
@register("apoc.util.randomUUID")
def util_uuid():
    return str(_uuid.uuid4())


@register("apoc.util.coalesce")
def util_coalesce(*args):
    for a in args:
        if a is not None:
            return a
    return None


@register("apoc.util.case")
def util_case(pairs, default=None):
    """[[cond, value], ...] -> first value whose cond is truthy."""
    for pair in pairs or []:
        if isinstance(pair, list) and len(pair) == 2 and pair[0]:
            return pair[1]
    return default


@register("apoc.util.when")
def util_when(cond, then_val, else_val=None):
    return then_val if cond else else_val


@register("apoc.util.typeOf")
def util_type_of(v):
    from nornicdb_tpu.storage.types import Edge, Node

    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "LIST"
    if isinstance(v, Node):
        return "NODE"
    if isinstance(v, Edge):
        return "RELATIONSHIP"
    if isinstance(v, dict):
        return "MAP"
    return type(v).__name__.upper()


@register("apoc.util.merge")
def util_merge(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        return {**a, **b}
    if isinstance(a, list) and isinstance(b, list):
        return a + b
    return b if b is not None else a


def _digest(algo, s):
    import hashlib

    h = hashlib.new(algo)
    h.update(str(s).encode("utf-8"))
    return h


@register("apoc.util.sha256")
@register("apoc.util.sha256Hex")
def util_sha256(s):
    return _digest("sha256", s).hexdigest()


@register("apoc.util.sha1Hex")
def util_sha1_hex(s):
    return _digest("sha1", s).hexdigest()


@register("apoc.util.md5Hex")
def util_md5_hex(s):
    return _digest("md5", s).hexdigest()


@register("apoc.util.sha256Base64")
def util_sha256_b64(s):
    return base64.b64encode(_digest("sha256", s).digest()).decode()


@register("apoc.util.sha1Base64")
def util_sha1_b64(s):
    return base64.b64encode(_digest("sha1", s).digest()).decode()


@register("apoc.util.md5Base64")
def util_md5_b64(s):
    return base64.b64encode(_digest("md5", s).digest()).decode()


@register("apoc.util.validatePattern")
def util_validate_pattern(value, pattern):
    if value is None or pattern is None:
        return None
    # bounded engine: user-supplied patterns must not wedge the query
    # thread via catastrophic backtracking (same guarantee as Cypher =~)
    from nornicdb_tpu.cypher.expr import regex_fullmatch

    return regex_fullmatch(str(pattern), str(value))


@register("apoc.util.repeat")
def util_repeat(value, times):
    times = int(times)
    if isinstance(value, str):
        return value * times
    return [value] * times


@register("apoc.util.range")
def util_range(lo, hi, step=1):
    return math_range(lo, hi, step)


@register("apoc.util.partition")
def util_partition(xs, size):
    size = int(size)
    if size <= 0:
        return []
    return [xs[i:i + size] for i in range(0, len(xs or []), size)]


@register("apoc.util.compressWithAlgorithm")
def util_compress_algo(data, algo="gzip"):
    """Returns base64 of the compressed bytes (transport-safe value form)."""
    raw = str(data).encode("utf-8")
    algo = str(algo).lower()
    if algo == "gzip":
        import gzip

        out = gzip.compress(raw)
    elif algo in ("deflate", "zlib"):
        out = zlib.compress(raw)
    else:
        raise ValueError(f"unsupported compression algorithm {algo!r}")
    return base64.b64encode(out).decode()


@register("apoc.util.decompressWithAlgorithm")
def util_decompress_algo(data, algo="gzip"):
    raw = base64.b64decode(str(data))
    algo = str(algo).lower()
    if algo == "gzip":
        import gzip

        return gzip.decompress(raw).decode("utf-8")
    if algo in ("deflate", "zlib"):
        return zlib.decompress(raw).decode("utf-8")
    raise ValueError(f"unsupported compression algorithm {algo!r}")


@register("apoc.util.now")
@register("apoc.util.timestamp")
def util_now():
    return int(time.time() * 1000)


@register("apoc.util.nowInSeconds")
def util_now_seconds():
    return int(time.time())


@register("apoc.util.parseTimestamp")
def util_parse_timestamp(s, fmt=None):
    """ISO-8601 (or epoch-millis string) -> epoch millis."""
    if s is None:
        return None
    s = str(s)
    if s.isdigit():
        return int(s)
    from datetime import datetime, timezone

    dt = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * 1000)


@register("apoc.util.formatTimestamp")
def util_format_timestamp(ts, fmt="iso"):
    if ts is None:
        return None
    from datetime import datetime, timezone

    dt = datetime.fromtimestamp(int(ts) / 1000.0, tz=timezone.utc)
    if fmt in ("iso", None):
        return dt.isoformat().replace("+00:00", "Z")
    # java-style subset: yyyy MM dd HH mm ss
    out = (str(fmt).replace("yyyy", "%Y").replace("MM", "%m")
           .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
           .replace("ss", "%S"))
    return dt.strftime(out)


# ============================================================= apoc.stats
@register("apoc.stats.min")
def stats_min(xs):
    v = _nums(xs)
    return min(v) if v else None


@register("apoc.stats.max")
def stats_max(xs):
    v = _nums(xs)
    return max(v) if v else None


@register("apoc.stats.sum")
def stats_sum(xs):
    return sum(_nums(xs))


@register("apoc.stats.count")
def stats_count(xs):
    return len(xs or [])


@register("apoc.stats.range")
def stats_range(xs):
    v = _nums(xs)
    return (max(v) - min(v)) if v else None


@register("apoc.stats.stdDev")
def stats_stddev(xs, population=False):
    return math_stdev(xs, population)


@register("apoc.stats.degrees")
def stats_degrees(degree_list):
    """Summary stats over a degree list: {min,max,mean,total} (ref
    stats.go Degrees shape)."""
    v = _nums(degree_list)
    if not v:
        return {"min": 0, "max": 0, "mean": 0.0, "total": 0}
    return {"min": min(v), "max": max(v), "mean": sum(v) / len(v),
            "total": sum(v)}


# =========================================================== apoc.scoring
@register("apoc.scoring.tf")
def scoring_tf(term, doc):
    """Term frequency: occurrences / doc length (whitespace tokens)."""
    words = str(doc).lower().split()
    if not words:
        return 0.0
    return words.count(str(term).lower()) / len(words)


@register("apoc.scoring.idf")
def scoring_idf(term, docs):
    t = str(term).lower()
    n = len(docs or [])
    if not n:
        return 0.0
    df = sum(1 for d in docs if t in str(d).lower().split())
    return math.log((n + 1) / (df + 1)) + 1.0


@register("apoc.scoring.bm25")
def scoring_bm25(term, doc, docs, k1=1.2, b=0.75):
    tf_count = str(doc).lower().split().count(str(term).lower())
    dl = len(str(doc).split())
    avgdl = (sum(len(str(d).split()) for d in docs) / len(docs)) if docs else 1
    idf = scoring_idf(term, docs)
    denom = tf_count + k1 * (1 - b + b * dl / max(avgdl, 1e-9))
    return idf * (tf_count * (k1 + 1)) / max(denom, 1e-9)


@register("apoc.scoring.normalize")
def scoring_normalize(scores):
    v = _nums(scores)
    if not v:
        return []
    lo, hi = min(v), max(v)
    if hi == lo:
        return [0.0 for _ in v]
    return [(x - lo) / (hi - lo) for x in v]


@register("apoc.scoring.percentile")
def scoring_percentile(scores, p):
    return stats_percentile(scores, p)


@register("apoc.scoring.zScore")
def scoring_zscore(value, values):
    v = _nums(values)
    if len(v) < 2:
        return 0.0
    m = sum(v) / len(v)
    sd = math.sqrt(sum((x - m) ** 2 for x in v) / (len(v) - 1))
    return (float(value) - m) / sd if sd else 0.0


@register("apoc.scoring.pageRank")
def scoring_pagerank(node_ids, edges, damping=0.85, iters=20):
    """PageRank over explicit (src, dst) pairs (value-level twin of the
    gds.pageRank procedure; ref scoring.go PageRank)."""
    ids = list(node_ids or [])
    if not ids:
        return {}
    out_deg: dict = {i: 0 for i in ids}
    incoming: dict = {i: [] for i in ids}
    for e in edges or []:
        s, d = (e[0], e[1]) if isinstance(e, list) else (e["start"], e["end"])
        if s in out_deg and d in incoming:
            out_deg[s] += 1
            incoming[d].append(s)
    n = len(ids)
    rank = {i: 1.0 / n for i in ids}
    for _ in range(int(iters)):
        new = {}
        sink = sum(rank[i] for i in ids if out_deg[i] == 0)
        for i in ids:
            s = sum(rank[j] / out_deg[j] for j in incoming[i])
            new[i] = (1 - damping) / n + damping * (s + sink / n)
        rank = new
    return rank


# ============================================================== apoc.json
@register("apoc.json.values")
def json_values(j):
    obj = _json.loads(j) if isinstance(j, str) else j
    if isinstance(obj, dict):
        return list(obj.values())
    if isinstance(obj, list):
        return obj
    return [obj]


@register("apoc.json.type")
def json_type(v):
    if isinstance(v, str):
        try:
            v = _json.loads(v)
        except (ValueError, TypeError):
            return "string"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    return "object"


@register("apoc.json.unflatten")
def json_unflatten(flat):
    """{'a.b': 1} -> {'a': {'b': 1}} (inverse of apoc.json.flatten)."""
    out: dict = {}
    for k, v in (flat or {}).items():
        parts = str(k).split(".")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out


@register("apoc.json.filter")
def json_filter(j, keys):
    """Keep only the listed top-level keys."""
    obj = _json.loads(j) if isinstance(j, str) else j
    keep = set(keys or [])
    if isinstance(obj, dict):
        return {k: v for k, v in obj.items() if k in keep}
    return obj


@register("apoc.json.map")
def json_map(j, mapping):
    """Rename top-level keys via {'old': 'new'}."""
    obj = _json.loads(j) if isinstance(j, str) else j
    if not isinstance(obj, dict):
        return obj
    m = mapping or {}
    return {m.get(k, k): v for k, v in obj.items()}


@register("apoc.json.reduce")
def json_reduce(j, op="sum", init=0):
    """Reduce numeric leaf values: sum/min/max/count."""
    obj = _json.loads(j) if isinstance(j, str) else j

    def leaves(o):
        if isinstance(o, dict):
            for v in o.values():
                yield from leaves(v)
        elif isinstance(o, list):
            for v in o:
                yield from leaves(v)
        elif isinstance(o, (int, float)) and not isinstance(o, bool):
            yield o

    vals = list(leaves(obj))
    if op == "count":
        return len(vals)
    if op == "min":
        return min(vals) if vals else init
    if op == "max":
        return max(vals) if vals else init
    return sum(vals, init if isinstance(init, (int, float)) else 0)


# =========================================================== apoc.hashing
@register("apoc.hashing.sha384")
def hashing_sha384(s):
    return _digest("sha384", s).hexdigest()


@register("apoc.hashing.fnv1")
def hashing_fnv1(s):
    """FNV-1 (multiply-then-xor) 32-bit."""
    h = 0x811C9DC5
    for b in str(s).encode("utf-8"):
        h = (h * 0x01000193) & _U32
        h ^= b
    return h


@register("apoc.hashing.fnv164")
def hashing_fnv164(s):
    h = 0xCBF29CE484222325
    for b in str(s).encode("utf-8"):
        h = (h * 0x100000001B3) & _U64
        h ^= b
    return h


@register("apoc.hashing.murmurHash3")
def hashing_murmurhash3(s, seed=0):
    return hashing_murmur3(s, seed)


def _xx_rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & _U32


@register("apoc.hashing.xxHash32")
def hashing_xxhash32(s, seed=0):
    """xxHash32, clean-room from the public spec."""
    data = str(s).encode("utf-8")
    seed = int(seed) & _U32
    p1, p2, p3, p4, p5 = (2654435761, 2246822519, 3266489917,
                          668265263, 374761393)
    n = len(data)
    i = 0
    if n >= 16:
        acc = [(seed + p1 + p2) & _U32, (seed + p2) & _U32, seed,
               (seed - p1) & _U32]
        while i <= n - 16:
            for vi in range(4):
                lane = int.from_bytes(data[i:i + 4], "little")
                acc[vi] = (
                    _xx_rotl32((acc[vi] + lane * p2) & _U32, 13) * p1
                ) & _U32
                i += 4
        h = (_xx_rotl32(acc[0], 1) + _xx_rotl32(acc[1], 7)
             + _xx_rotl32(acc[2], 12) + _xx_rotl32(acc[3], 18)) & _U32
    else:
        h = (seed + p5) & _U32
    h = (h + n) & _U32
    while i <= n - 4:
        lane = int.from_bytes(data[i:i + 4], "little")
        h = (_xx_rotl32((h + lane * p3) & _U32, 17) * p4) & _U32
        i += 4
    while i < n:
        h = (_xx_rotl32((h + data[i] * p5) & _U32, 11) * p1) & _U32
        i += 1
    h ^= h >> 15
    h = (h * p2) & _U32
    h ^= h >> 13
    h = (h * p3) & _U32
    h ^= h >> 16
    return h


def _xx_rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & _U64


@register("apoc.hashing.xxHash64")
def hashing_xxhash64(s, seed=0):
    """xxHash64, clean-room from the public spec."""
    data = str(s).encode("utf-8")
    seed = int(seed) & _U64
    p1, p2, p3, p4, p5 = (11400714785074694791, 14029467366897019727,
                          1609587929392839161, 9650029242287828579,
                          2870177450012600261)

    def rnd(acc, lane):
        return (_xx_rotl64((acc + lane * p2) & _U64, 31) * p1) & _U64

    def merge(acc, v):
        return ((acc ^ rnd(0, v)) * p1 + p4) & _U64

    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + p1 + p2) & _U64
        v2 = (seed + p2) & _U64
        v3 = seed
        v4 = (seed - p1) & _U64
        while i <= n - 32:
            v1 = rnd(v1, int.from_bytes(data[i:i + 8], "little"))
            v2 = rnd(v2, int.from_bytes(data[i + 8:i + 16], "little"))
            v3 = rnd(v3, int.from_bytes(data[i + 16:i + 24], "little"))
            v4 = rnd(v4, int.from_bytes(data[i + 24:i + 32], "little"))
            i += 32
        h = (_xx_rotl64(v1, 1) + _xx_rotl64(v2, 7)
             + _xx_rotl64(v3, 12) + _xx_rotl64(v4, 18)) & _U64
        h = merge(h, v1)
        h = merge(h, v2)
        h = merge(h, v3)
        h = merge(h, v4)
    else:
        h = (seed + p5) & _U64
    h = (h + n) & _U64
    while i <= n - 8:
        h = ((_xx_rotl64(h ^ rnd(0, int.from_bytes(data[i:i + 8], "little")),
                         27) * p1) + p4) & _U64
        i += 8
    if i <= n - 4:
        lane = int.from_bytes(data[i:i + 4], "little")
        h = ((_xx_rotl64(h ^ ((lane * p1) & _U64), 23) * p2) + p3) & _U64
        i += 4
    while i < n:
        h = (_xx_rotl64(h ^ ((data[i] * p5) & _U64), 11) * p1) & _U64
        i += 1
    h ^= h >> 33
    h = (h * p2) & _U64
    h ^= h >> 29
    h = (h * p3) & _U64
    h ^= h >> 32
    return h


@register("apoc.hashing.cityHash64")
def hashing_cityhash64(s):
    """64-bit string hash in the CityHash role. The reference's internal
    cityHash64 is likewise a reduced variant (hashing.go:145); this uses the
    xxHash64 core with a distinct seed, documented as not bit-identical to
    Google CityHash."""
    return hashing_xxhash64(s, seed=0x9AE16A3B2F90404F)


@register("apoc.hashing.rendezvousHash")
def hashing_rendezvous(key, nodes):
    """Highest-random-weight node pick (ref hashing.go:205)."""
    if not nodes:
        return ""
    best, best_h = nodes[0], -1
    for node in nodes:
        h = hashing_fnv1a64(f"{key}{node}")
        if h > best_h:
            best, best_h = node, h
    return best


@register("apoc.hashing.fingerprintGraph")
def hashing_fingerprint_graph(nodes, rels):
    """SHA256 over the canonical repr of nodes+rels (ref hashing.go:185)."""
    from nornicdb_tpu.apoc.functions_ext import _props_of

    def canon(x):
        try:
            return _json.dumps(_props_of(x), sort_keys=True, default=str)
        except (TypeError, ValueError):
            return repr(x)

    payload = ("|".join(sorted(canon(n) for n in (nodes or [])))
               + "||" + "|".join(sorted(canon(r) for r in (rels or []))))
    return _digest("sha256", payload).hexdigest()


# ============================================================== apoc.coll
@register("apoc.coll.containsDuplicates")
def coll_contains_duplicates(xs):
    seen = []
    for x in xs or []:
        if x in seen:
            return True
        seen.append(x)
    return False


@register("apoc.coll.randomItem")
def coll_random_item(xs):
    return random.choice(xs) if xs else None


@register("apoc.coll.randomItems")
def coll_random_items(xs, n, allow_repeats=False):
    if not xs:
        return []
    n = int(n)
    if allow_repeats:
        return [random.choice(xs) for _ in range(n)]
    return random.sample(list(xs), min(n, len(xs)))


# =========================================================== apoc.bitwise
@register("apoc.bitwise.reverseBits")
def bitwise_reverse_bits(value, width=64):
    v = int(value) & ((1 << int(width)) - 1)
    out = 0
    for _ in range(int(width)):
        out = (out << 1) | (v & 1)
        v >>= 1
    return out


@register("apoc.bitwise.rotateLeft")
def bitwise_rotate_left(value, shift, width=64):
    width = int(width)
    mask = (1 << width) - 1
    v = int(value) & mask
    s = int(shift) % width
    return ((v << s) | (v >> (width - s))) & mask


@register("apoc.bitwise.rotateRight")
def bitwise_rotate_right(value, shift, width=64):
    width = int(width)
    s = int(shift) % width
    return bitwise_rotate_left(value, width - s, width)


# ============================================================== apoc.diff
@register("apoc.diff.deep")
def diff_deep(a, b):
    """Recursive diff of nested maps: {added, removed, changed} with dotted
    paths."""
    out = {"added": {}, "removed": {}, "changed": {}}

    def walk(x, y, prefix):
        xk = set(x.keys()) if isinstance(x, dict) else set()
        yk = set(y.keys()) if isinstance(y, dict) else set()
        for k in yk - xk:
            out["added"][f"{prefix}{k}"] = y[k]
        for k in xk - yk:
            out["removed"][f"{prefix}{k}"] = x[k]
        for k in xk & yk:
            if isinstance(x[k], dict) and isinstance(y[k], dict):
                walk(x[k], y[k], f"{prefix}{k}.")
            elif x[k] != y[k]:
                out["changed"][f"{prefix}{k}"] = {"left": x[k], "right": y[k]}

    walk(a or {}, b or {}, "")
    return out


@register("apoc.diff.patch")
def diff_patch(obj, diff):
    """Apply a diff.deep result: right-side wins."""
    out = _json.loads(_json.dumps(obj or {}))  # deep copy of plain data

    def set_path(d, path, value):
        parts = path.split(".")
        cur = d
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = value

    def del_path(d, path):
        parts = path.split(".")
        cur = d
        for p in parts[:-1]:
            if not isinstance(cur, dict) or p not in cur:
                return
            cur = cur[p]
        if isinstance(cur, dict):
            cur.pop(parts[-1], None)

    for path, v in (diff or {}).get("added", {}).items():
        set_path(out, path, v)
    for path in (diff or {}).get("removed", {}):
        del_path(out, path)
    for path, ch in (diff or {}).get("changed", {}).items():
        set_path(out, path, ch.get("right") if isinstance(ch, dict) else ch)
    return out


@register("apoc.diff.merge")
def diff_merge(d1, d2):
    """Combine two diffs; the second wins on conflicts."""
    out = {"added": {}, "removed": {}, "changed": {}}
    for d in (d1 or {}), (d2 or {}):
        for k in out:
            out[k].update(d.get(k, {}))
    return out


@register("apoc.diff.summary")
def diff_summary(diff):
    d = diff or {}
    return {
        "added": len(d.get("added", {})),
        "removed": len(d.get("removed", {})),
        "changed": len(d.get("changed", {})),
    }


# =============================================================== apoc.agg
@register("apoc.agg.percentile")
def agg_percentile(xs, p=0.5):
    return stats_percentile(xs, p)


@register("apoc.agg.stdev")
def agg_stdev(xs):
    return math_stdev(xs)


@register("apoc.agg.histogram")
def agg_histogram(xs):
    out: dict = {}
    for x in xs or []:
        k = str(x)
        out[k] = out.get(k, 0) + 1
    return out


@register("apoc.agg.graph")
def agg_graph(nodes, rels):
    return {"nodes": list(nodes or []), "relationships": list(rels or [])}
