"""APOC graph procedures (storage-touching).

Behavioral reference: /root/reference/apoc/create, merge, refactor, path(s),
periodic, neighbors categories; wired through the Cypher procedure registry
the way the reference routes CALL apoc.* via its registry
(pkg/cypher/call.go, apoc/apoc.go:121).
"""

from __future__ import annotations

from typing import Any

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.errors import CypherSyntaxError
from nornicdb_tpu.storage.types import Edge, Node, new_id


@procedure("apoc.create.node")
def apoc_create_node(ex: CypherExecutor, args, row):
    labels = args[0] if args else []
    props = args[1] if len(args) > 1 else {}
    node = Node(labels=list(labels or []), properties=dict(props or {}))
    created = ex.storage.create_node(node)
    return ["node"], [[created]]


@procedure("apoc.create.nodes")
def apoc_create_nodes(ex: CypherExecutor, args, row):
    labels = args[0] if args else []
    props_list = args[1] if len(args) > 1 else []
    out = []
    for props in props_list or []:
        node = Node(labels=list(labels or []), properties=dict(props or {}))
        out.append([ex.storage.create_node(node)])
    return ["node"], out


@procedure("apoc.create.relationship")
def apoc_create_rel(ex: CypherExecutor, args, row):
    if len(args) < 4:
        raise CypherSyntaxError("apoc.create.relationship(from, type, props, to)")
    from_n, rel_type, props, to_n = args[0], args[1], args[2], args[3]
    edge = Edge(
        start_node=from_n.id if isinstance(from_n, Node) else str(from_n),
        end_node=to_n.id if isinstance(to_n, Node) else str(to_n),
        type=str(rel_type),
        properties=dict(props or {}),
    )
    created = ex.storage.create_edge(edge)
    return ["rel"], [[created]]


@procedure("apoc.create.uuid")
def apoc_uuid(ex: CypherExecutor, args, row):
    return ["uuid"], [[new_id()]]


@procedure("apoc.merge.node")
def apoc_merge_node(ex: CypherExecutor, args, row):
    """(ref: apoc/merge) — match on identProps, set onCreateProps when new."""
    labels = args[0] if args else []
    ident = args[1] if len(args) > 1 else {}
    on_create = args[2] if len(args) > 2 else {}
    if not ident:
        raise CypherSyntaxError(
            "apoc.merge.node: you need to supply at least one identifying property"
        )
    for n in ex.storage.get_nodes_by_label(labels[0]) if labels else ex.storage.all_nodes():
        if all(n.properties.get(k) == v for k, v in (ident or {}).items()):
            if all(l in n.labels for l in labels or []):
                return ["node"], [[n]]
    node = Node(labels=list(labels or []),
                properties={**(ident or {}), **(on_create or {})})
    return ["node"], [[ex.storage.create_node(node)]]


@procedure("apoc.merge.relationship")
def apoc_merge_rel(ex: CypherExecutor, args, row):
    from_n, rel_type = args[0], str(args[1])
    ident = args[2] if len(args) > 2 else {}
    on_create = args[3] if len(args) > 3 else {}
    to_n = args[4] if len(args) > 4 else None
    for e in ex.storage.get_outgoing_edges(from_n.id):
        if e.type == rel_type and e.end_node == to_n.id and all(
            e.properties.get(k) == v for k, v in (ident or {}).items()
        ):
            return ["rel"], [[e]]
    edge = Edge(
        start_node=from_n.id, end_node=to_n.id, type=rel_type,
        properties={**(ident or {}), **(on_create or {})},
    )
    return ["rel"], [[ex.storage.create_edge(edge)]]


@procedure("apoc.refactor.rename.label")
def apoc_rename_label(ex: CypherExecutor, args, row):
    old, new = str(args[0]), str(args[1])
    count = 0
    for n in ex.storage.get_nodes_by_label(old):
        n.labels = [new if l == old else l for l in n.labels]
        ex.storage.update_node(n)
        count += 1
    return ["total"], [[count]]


@procedure("apoc.refactor.rename.type")
def apoc_rename_type(ex: CypherExecutor, args, row):
    old, new = str(args[0]), str(args[1])
    count = 0
    for e in ex.storage.get_edges_by_type(old):
        e.type = new
        ex.storage.update_edge(e)
        count += 1
    return ["total"], [[count]]


@procedure("apoc.node.degree")
def apoc_node_degree(ex: CypherExecutor, args, row):
    node = args[0]
    direction = str(args[1]) if len(args) > 1 else "both"
    d = ex.storage.degree(node.id, direction.lower().strip("<>") or "both")
    return ["value"], [[d]]


@procedure("apoc.neighbors.tohop")
def apoc_neighbors(ex: CypherExecutor, args, row):
    node = args[0]
    rel_types: set[str] = set()
    if len(args) > 1 and isinstance(args[1], str):
        # "KNOWS|WORKS_WITH>" style spec; direction arrows are stripped
        rel_types = {t.strip("<>") for t in args[1].split("|") if t.strip("<>")}
    hops = int(args[2]) if len(args) > 2 else int(args[1]) if len(args) > 1 and not isinstance(args[1], str) else 1
    seen = {node.id}
    frontier = [node.id]
    out = []
    for _ in range(hops):
        nxt = []
        for nid in frontier:
            for e in ex.storage.get_outgoing_edges(nid):
                if rel_types and e.type not in rel_types:
                    continue
                if e.end_node not in seen:
                    seen.add(e.end_node)
                    nxt.append(e.end_node)
            for e in ex.storage.get_incoming_edges(nid):
                if rel_types and e.type not in rel_types:
                    continue
                if e.start_node not in seen:
                    seen.add(e.start_node)
                    nxt.append(e.start_node)
        for nid in nxt:
            n = ex.get_node_or_none(nid)
            if n is not None:
                out.append([n])
        frontier = nxt
    return ["node"], out


@procedure("apoc.path.subgraphnodes")
def apoc_subgraph_nodes(ex: CypherExecutor, args, row):
    node = args[0]
    cfg = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    max_level = int(cfg.get("maxLevel", 3))
    seen = {node.id}
    frontier = [node.id]
    out = [[node]]
    for _ in range(max_level):
        nxt = []
        for nid in frontier:
            for e in ex.storage.get_outgoing_edges(nid):
                if e.end_node not in seen:
                    seen.add(e.end_node)
                    nxt.append(e.end_node)
            for e in ex.storage.get_incoming_edges(nid):
                if e.start_node not in seen:
                    seen.add(e.start_node)
                    nxt.append(e.start_node)
        for nid in nxt:
            n = ex.get_node_or_none(nid)
            if n is not None:
                out.append([n])
        frontier = nxt
    return ["node"], out


@procedure("apoc.periodic.iterate")
def apoc_periodic_iterate(ex: CypherExecutor, args, row):
    """(ref: apoc/periodic, pkg/cypher/call_apoc_periodic.go) — run the outer
    query, then the inner update in batches binding each outer row."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "apoc.periodic.iterate(outerQuery, innerQuery, config)"
        )
    outer_q, inner_q = str(args[0]), str(args[1])
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    batch_size = int(cfg.get("batchSize", 1000))
    outer = ex.execute(outer_q)
    total = len(outer.rows)
    batches = 0
    failed = 0
    from nornicdb_tpu.cypher.parser import parse as _parse
    from nornicdb_tpu.cypher import ast as _ast

    inner_stmt = _parse(inner_q)
    if not isinstance(inner_stmt, _ast.Query):
        raise CypherSyntaxError("inner query must be a Cypher query")
    for start in range(0, total, batch_size):
        batch_rows = [
            dict(zip(outer.columns, r)) for r in outer.rows[start : start + batch_size]
        ]
        batches += 1
        try:
            ex._run_query(inner_stmt, {}, start_rows=batch_rows)
        except Exception:
            failed += 1
    return (
        ["batches", "total", "errorMessages", "failedBatches"],
        [[batches, total, {}, failed]],
    )


@procedure("apoc.help")
def apoc_help(ex: CypherExecutor, args, row):
    from nornicdb_tpu.apoc.registry import all_functions

    prefix = str(args[0]).lower() if args else ""
    return ["name"], [[f] for f in all_functions() if prefix in f]


def _trigger_manager(ex: CypherExecutor):
    from nornicdb_tpu.apoc.triggers import manager_for

    return manager_for(ex)  # database-global registry, shared by sessions


@procedure("apoc.trigger.add")
def apoc_trigger_add(ex: CypherExecutor, args, row):
    """(ref: apoc/trigger) apoc.trigger.add(name, statement, selector)"""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.trigger.add(name, statement, selector)")
    selector = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    t = _trigger_manager(ex).add(str(args[0]), str(args[1]), selector)
    return ["name", "query", "paused"], [[t.name, t.statement, t.paused]]


@procedure("apoc.trigger.remove")
def apoc_trigger_remove(ex: CypherExecutor, args, row):
    if not _trigger_manager(ex).remove(str(args[0])):
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "removed"], [[str(args[0]), True]]


@procedure("apoc.trigger.removeall")
def apoc_trigger_remove_all(ex: CypherExecutor, args, row):
    return ["removed"], [[_trigger_manager(ex).remove_all()]]


@procedure("apoc.trigger.pause")
def apoc_trigger_pause(ex: CypherExecutor, args, row):
    t = _trigger_manager(ex).pause(str(args[0]), True)
    if t is None:
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "paused"], [[t.name, t.paused]]


@procedure("apoc.trigger.resume")
def apoc_trigger_resume(ex: CypherExecutor, args, row):
    t = _trigger_manager(ex).pause(str(args[0]), False)
    if t is None:
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "paused"], [[t.name, t.paused]]


@procedure("apoc.trigger.list")
def apoc_trigger_list(ex: CypherExecutor, args, row):
    return (
        ["name", "query", "paused", "fired", "errors"],
        [[t.name, t.statement, t.paused, t.fired, t.errors]
         for t in _trigger_manager(ex).list()],
    )
