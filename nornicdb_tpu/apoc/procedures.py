"""APOC graph procedures (storage-touching).

Behavioral reference: /root/reference/apoc/create, merge, refactor, path(s),
periodic, neighbors categories; wired through the Cypher procedure registry
the way the reference routes CALL apoc.* via its registry
(pkg/cypher/call.go, apoc/apoc.go:121).
"""

from __future__ import annotations

import logging
from typing import Any

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.errors import CypherSyntaxError
from nornicdb_tpu.storage.types import Edge, Node, new_id
from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)


@procedure("apoc.create.node")
def apoc_create_node(ex: CypherExecutor, args, row):
    labels = args[0] if args else []
    props = args[1] if len(args) > 1 else {}
    node = Node(labels=list(labels or []), properties=dict(props or {}))
    created = ex.storage.create_node(node)
    return ["node"], [[created]]


@procedure("apoc.create.nodes")
def apoc_create_nodes(ex: CypherExecutor, args, row):
    labels = args[0] if args else []
    props_list = args[1] if len(args) > 1 else []
    out = []
    for props in props_list or []:
        node = Node(labels=list(labels or []), properties=dict(props or {}))
        out.append([ex.storage.create_node(node)])
    return ["node"], out


@procedure("apoc.create.relationship")
def apoc_create_rel(ex: CypherExecutor, args, row):
    if len(args) < 4:
        raise CypherSyntaxError("apoc.create.relationship(from, type, props, to)")
    from_n, rel_type, props, to_n = args[0], args[1], args[2], args[3]
    edge = Edge(
        start_node=from_n.id if isinstance(from_n, Node) else str(from_n),
        end_node=to_n.id if isinstance(to_n, Node) else str(to_n),
        type=str(rel_type),
        properties=dict(props or {}),
    )
    created = ex.storage.create_edge(edge)
    return ["rel"], [[created]]


@procedure("apoc.create.uuid")
def apoc_uuid(ex: CypherExecutor, args, row):
    return ["uuid"], [[new_id()]]


@procedure("apoc.merge.node")
def apoc_merge_node(ex: CypherExecutor, args, row):
    """(ref: apoc/merge) — match on identProps, set onCreateProps when new."""
    labels = args[0] if args else []
    ident = args[1] if len(args) > 1 else {}
    on_create = args[2] if len(args) > 2 else {}
    if not ident:
        raise CypherSyntaxError(
            "apoc.merge.node: you need to supply at least one identifying property"
        )
    for n in ex.storage.get_nodes_by_label(labels[0]) if labels else ex.storage.all_nodes():
        if all(n.properties.get(k) == v for k, v in (ident or {}).items()):
            if all(l in n.labels for l in labels or []):
                return ["node"], [[n]]
    node = Node(labels=list(labels or []),
                properties={**(ident or {}), **(on_create or {})})
    return ["node"], [[ex.storage.create_node(node)]]


@procedure("apoc.merge.relationship")
def apoc_merge_rel(ex: CypherExecutor, args, row):
    from_n, rel_type = args[0], str(args[1])
    ident = args[2] if len(args) > 2 else {}
    on_create = args[3] if len(args) > 3 else {}
    to_n = args[4] if len(args) > 4 else None
    for e in ex.storage.get_outgoing_edges(from_n.id):
        if e.type == rel_type and e.end_node == to_n.id and all(
            e.properties.get(k) == v for k, v in (ident or {}).items()
        ):
            return ["rel"], [[e]]
    edge = Edge(
        start_node=from_n.id, end_node=to_n.id, type=rel_type,
        properties={**(ident or {}), **(on_create or {})},
    )
    return ["rel"], [[ex.storage.create_edge(edge)]]


@procedure("apoc.refactor.rename.label")
def apoc_rename_label(ex: CypherExecutor, args, row):
    old, new = str(args[0]), str(args[1])
    count = 0
    for n in ex.storage.get_nodes_by_label(old):
        n.labels = [new if l == old else l for l in n.labels]
        ex.storage.update_node(n)
        count += 1
    return ["total"], [[count]]


@procedure("apoc.refactor.rename.type")
def apoc_rename_type(ex: CypherExecutor, args, row):
    old, new = str(args[0]), str(args[1])
    count = 0
    for e in ex.storage.get_edges_by_type(old):
        e.type = new
        ex.storage.update_edge(e)
        count += 1
    return ["total"], [[count]]


@procedure("apoc.node.degree")
def apoc_node_degree(ex: CypherExecutor, args, row):
    node = args[0]
    direction = str(args[1]) if len(args) > 1 else "both"
    d = ex.storage.degree(node.id, direction.lower().strip("<>") or "both")
    return ["value"], [[d]]


@procedure("apoc.neighbors.tohop")
def apoc_neighbors(ex: CypherExecutor, args, row):
    from nornicdb_tpu.cypher.gds_procedures import _resolve_node

    node = _resolve_node(ex, args[0])
    rel_types: set[str] = set()
    if len(args) > 1 and isinstance(args[1], str):
        # "KNOWS|WORKS_WITH>" style spec; direction arrows are stripped
        rel_types = {t.strip("<>") for t in args[1].split("|") if t.strip("<>")}
    hops = int(args[2]) if len(args) > 2 else int(args[1]) if len(args) > 1 and not isinstance(args[1], str) else 1
    seen = {node.id}
    frontier = [node.id]
    out = []
    for _ in range(hops):
        nxt = []
        for nid in frontier:
            for e in ex.storage.get_outgoing_edges(nid):
                if rel_types and e.type not in rel_types:
                    continue
                if e.end_node not in seen:
                    seen.add(e.end_node)
                    nxt.append(e.end_node)
            for e in ex.storage.get_incoming_edges(nid):
                if rel_types and e.type not in rel_types:
                    continue
                if e.start_node not in seen:
                    seen.add(e.start_node)
                    nxt.append(e.start_node)
        for nid in nxt:
            n = ex.get_node_or_none(nid)
            if n is not None:
                out.append([n])
        frontier = nxt
    return ["node"], out


@procedure("apoc.path.subgraphnodes")
def apoc_subgraph_nodes(ex: CypherExecutor, args, row):
    node = args[0]
    cfg = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    max_level = int(cfg.get("maxLevel", 3))
    seen = {node.id}
    frontier = [node.id]
    out = [[node]]
    for _ in range(max_level):
        nxt = []
        for nid in frontier:
            for e in ex.storage.get_outgoing_edges(nid):
                if e.end_node not in seen:
                    seen.add(e.end_node)
                    nxt.append(e.end_node)
            for e in ex.storage.get_incoming_edges(nid):
                if e.start_node not in seen:
                    seen.add(e.start_node)
                    nxt.append(e.start_node)
        for nid in nxt:
            n = ex.get_node_or_none(nid)
            if n is not None:
                out.append([n])
        frontier = nxt
    return ["node"], out


@procedure("apoc.periodic.iterate")
def apoc_periodic_iterate(ex: CypherExecutor, args, row):
    """(ref: apoc/periodic, pkg/cypher/call_apoc_periodic.go) — run the outer
    query, then the inner update in batches binding each outer row."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "apoc.periodic.iterate(outerQuery, innerQuery, config)"
        )
    outer_q, inner_q = str(args[0]), str(args[1])
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    batch_size = int(cfg.get("batchSize", 1000))
    outer = ex.execute(outer_q)
    total = len(outer.rows)
    batches = 0
    failed = 0
    from nornicdb_tpu.cypher.parser import parse as _parse
    from nornicdb_tpu.cypher import ast as _ast

    inner_stmt = _parse(inner_q)
    if not isinstance(inner_stmt, _ast.Query):
        raise CypherSyntaxError("inner query must be a Cypher query")
    for start in range(0, total, batch_size):
        batch_rows = [
            dict(zip(outer.columns, r)) for r in outer.rows[start : start + batch_size]
        ]
        batches += 1
        try:
            ex._run_query(inner_stmt, {}, start_rows=batch_rows)
        except Exception:
            # contract: iterate continues past failed batches, but operators
            # need to see WHY batches failed, not just the count
            log.warning("apoc.periodic.iterate batch %d failed", batches,
                        exc_info=True)
            count_error("apoc.periodic_iterate")
            failed += 1
    return (
        ["batches", "total", "errorMessages", "failedBatches"],
        [[batches, total, {}, failed]],
    )


@procedure("apoc.help")
def apoc_help(ex: CypherExecutor, args, row):
    from nornicdb_tpu.apoc.registry import all_functions

    prefix = str(args[0]).lower() if args else ""
    return ["name"], [[f] for f in all_functions() if prefix in f]


def _trigger_manager(ex: CypherExecutor):
    from nornicdb_tpu.apoc.triggers import manager_for

    return manager_for(ex)  # database-global registry, shared by sessions


@procedure("apoc.trigger.add")
def apoc_trigger_add(ex: CypherExecutor, args, row):
    """(ref: apoc/trigger) apoc.trigger.add(name, statement, selector)"""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.trigger.add(name, statement, selector)")
    selector = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    t = _trigger_manager(ex).add(str(args[0]), str(args[1]), selector)
    return ["name", "query", "paused"], [[t.name, t.statement, t.paused]]


@procedure("apoc.trigger.remove")
def apoc_trigger_remove(ex: CypherExecutor, args, row):
    if not _trigger_manager(ex).remove(str(args[0])):
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "removed"], [[str(args[0]), True]]


@procedure("apoc.trigger.removeall")
def apoc_trigger_remove_all(ex: CypherExecutor, args, row):
    return ["removed"], [[_trigger_manager(ex).remove_all()]]


@procedure("apoc.trigger.pause")
def apoc_trigger_pause(ex: CypherExecutor, args, row):
    t = _trigger_manager(ex).pause(str(args[0]), True)
    if t is None:
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "paused"], [[t.name, t.paused]]


@procedure("apoc.trigger.resume")
def apoc_trigger_resume(ex: CypherExecutor, args, row):
    t = _trigger_manager(ex).pause(str(args[0]), False)
    if t is None:
        raise CypherSyntaxError(f"trigger {args[0]!r} not found")
    return ["name", "paused"], [[t.name, t.paused]]


@procedure("apoc.trigger.list")
def apoc_trigger_list(ex: CypherExecutor, args, row):
    return (
        ["name", "query", "paused", "fired", "errors"],
        [[t.name, t.statement, t.paused, t.fired, t.errors]
         for t in _trigger_manager(ex).list()],
    )


# ---------------------------------------------------------------------------
# apoc.cypher.* (ref: apoc/cypher/cypher.go — Run/RunMany/DoIt/RunFirstColumn)
# ---------------------------------------------------------------------------


@procedure("apoc.cypher.run")
def apoc_cypher_run(ex: CypherExecutor, args, row):
    """apoc.cypher.run(statement, params) -> value rows as maps."""
    if not args:
        raise CypherSyntaxError("apoc.cypher.run(statement, params)")
    params = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    res = ex.execute(str(args[0]), params)
    return ["value"], [[dict(zip(res.columns, r))] for r in res.rows]


@procedure("apoc.cypher.doit")
def apoc_cypher_doit(ex: CypherExecutor, args, row):
    """Like apoc.cypher.run but explicitly allowed to write (same here:
    the inner executor enforces RBAC at the session layer, not here)."""
    return apoc_cypher_run(ex, args, row)


@procedure("apoc.cypher.runmany")
def apoc_cypher_run_many(ex: CypherExecutor, args, row):
    """Semicolon-separated statements, each run in order; returns per-
    statement row counts (ref cypher.go RunMany)."""
    if not args:
        raise CypherSyntaxError("apoc.cypher.runMany(statements, params)")
    params = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    out = []
    for i, stmt in enumerate(s.strip() for s in _split_statements(str(args[0]))):
        if not stmt:
            continue
        res = ex.execute(stmt, params)
        out.append([i, len(res.rows)])
    return ["statement", "rowCount"], out


def _split_statements(text: str) -> list[str]:
    """Split on ';' outside of Cypher string literals / backtick names."""
    parts, buf = [], []
    quote = None
    i = 0
    while i < len(text):
        c = text[i]
        if quote:
            buf.append(c)
            if c == "\\" and quote in "'\"" and i + 1 < len(text):
                buf.append(text[i + 1])
                i += 1
            elif c == quote:
                quote = None
        elif c in ("'", '"', "`"):
            quote = c
            buf.append(c)
        elif c == ";":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(c)
        i += 1
    parts.append("".join(buf))
    return parts


@procedure("apoc.cypher.runfirstcolumnsingle")
def apoc_cypher_first_single(ex: CypherExecutor, args, row):
    if not args:
        raise CypherSyntaxError("apoc.cypher.runFirstColumnSingle(statement, params)")
    params = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    res = ex.execute(str(args[0]), params)
    val = res.rows[0][0] if res.rows and res.rows[0] else None
    return ["value"], [[val]]


@procedure("apoc.cypher.runfirstcolumnmany")
def apoc_cypher_first_many(ex: CypherExecutor, args, row):
    if not args:
        raise CypherSyntaxError("apoc.cypher.runFirstColumnMany(statement, params)")
    params = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    res = ex.execute(str(args[0]), params)
    return ["value"], [[r[0]] for r in res.rows if r]


# ---------------------------------------------------------------------------
# apoc.schema.* (ref: apoc/schema/schema.go — Nodes/Assert/index+constraint
# introspection against the SchemaManager)
# ---------------------------------------------------------------------------


@procedure("apoc.schema.nodes")
def apoc_schema_nodes(ex: CypherExecutor, args, row):
    """Rows follow apoc's contract: status is the online state, type is the
    index/constraint kind (e.g. RANGE, UNIQUENESS)."""
    out = []
    for idx in ex.schema.list_indexes():
        out.append([f":{idx.label}({','.join(idx.properties)})",
                    idx.label, idx.properties, "ONLINE",
                    str(idx.kind).upper()])
    for c in ex.schema.list_constraints():
        kind = "UNIQUENESS" if c.kind == "unique" else str(c.kind).upper()
        out.append([f":{c.label}({','.join(c.properties)})",
                    c.label, c.properties, "ONLINE", kind])
    return ["name", "label", "properties", "status", "type"], out


@procedure("apoc.schema.relationships")
def apoc_schema_rels(ex: CypherExecutor, args, row):
    return ["name", "type", "properties", "status"], []


@procedure("apoc.schema.assert")
def apoc_schema_assert(ex: CypherExecutor, args, row):
    """apoc.schema.assert(indexMap, constraintMap[, dropExisting]) —
    declaratively converge schema: create what's listed, drop the rest
    when dropExisting (default true), matching apoc's contract."""
    want_idx = args[0] if args and isinstance(args[0], dict) else {}
    want_con = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    drop_existing = bool(args[2]) if len(args) > 2 else True
    out = []
    existing_con = {
        (c.label, tuple(c.properties)): c for c in ex.schema.list_constraints()
    }
    wanted_idx_keys = set()
    for label, prop_lists in (want_idx or {}).items():
        for props in prop_lists or []:
            props = props if isinstance(props, list) else [props]
            wanted_idx_keys.add((label, tuple(props)))
            if ex.schema.find_index(label, props) is not None:
                out.append([label, props, "KEPT", "INDEX"])
                continue
            name = f"apoc_idx_{label}_{'_'.join(props)}"
            ex.schema.create_index(name, "property", label, props,
                                   if_not_exists=True)
            out.append([label, props, "CREATED", "INDEX"])
    wanted_con_keys = set()
    for label, prop_lists in (want_con or {}).items():
        for props in prop_lists or []:
            props = props if isinstance(props, list) else [props]
            wanted_con_keys.add((label, tuple(props)))
            if (label, tuple(props)) in existing_con:
                out.append([label, props, "KEPT", "CONSTRAINT"])
                continue
            name = f"apoc_con_{label}_{'_'.join(props)}"
            ex.schema.create_constraint(name, label, props,
                                        if_not_exists=True)
            out.append([label, props, "CREATED", "CONSTRAINT"])
    if drop_existing:
        for idx in list(ex.schema.list_indexes()):
            if idx.kind == "vector":
                continue  # vector indexes back live search; never implicit-drop
            if (idx.label, tuple(idx.properties)) not in wanted_idx_keys:
                ex.schema.drop_index(idx.name, if_exists=True)
                out.append([idx.label, idx.properties, "DROPPED", "INDEX"])
        for c in list(ex.schema.list_constraints()):
            if (c.label, tuple(c.properties)) not in wanted_con_keys:
                ex.schema.drop_constraint(c.name, if_exists=True)
                out.append([c.label, c.properties, "DROPPED", "CONSTRAINT"])
    return ["label", "key", "action", "type"], out


# ---------------------------------------------------------------------------
# apoc.nodes.* (ref: apoc/nodes/nodes.go — Link/Delete/Connected/Collapse)
# ---------------------------------------------------------------------------


@procedure("apoc.nodes.link")
def apoc_nodes_link(ex: CypherExecutor, args, row):
    """Chain a list of nodes with rels of the given type (ref nodes.go Link)."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.nodes.link(nodes, relType)")
    nodes, rel_type = args[0] or [], str(args[1])
    created = 0
    for a, b in zip(nodes, nodes[1:]):
        ex.storage.create_edge(Edge(start_node=a.id, end_node=b.id,
                                    type=rel_type))
        created += 1
    return ["created"], [[created]]


@procedure("apoc.nodes.delete")
def apoc_nodes_delete(ex: CypherExecutor, args, row):
    """Detach-delete the given nodes (ref nodes.go Delete)."""
    nodes = args[0] or []
    if isinstance(nodes, Node):
        nodes = [nodes]
    count = 0
    for n in nodes:
        for e in list(ex.storage.get_outgoing_edges(n.id)) + list(
            ex.storage.get_incoming_edges(n.id)
        ):
            ex.storage.delete_edge(e.id)
        ex.storage.delete_node(n.id)
        count += 1
    return ["value"], [[count]]


@procedure("apoc.nodes.connected")
def apoc_nodes_connected(ex: CypherExecutor, args, row):
    if len(args) < 2:
        raise CypherSyntaxError("apoc.nodes.connected(a, b[, types])")
    a, b = args[0], args[1]
    want = set()
    if len(args) > 2 and args[2]:
        want = {t.strip("<>") for t in str(args[2]).split("|")}
    for e in ex.storage.get_outgoing_edges(a.id):
        if e.end_node == b.id and (not want or e.type in want):
            return ["value"], [[True]]
    for e in ex.storage.get_incoming_edges(a.id):
        if e.start_node == b.id and (not want or e.type in want):
            return ["value"], [[True]]
    return ["value"], [[False]]


@procedure("apoc.nodes.collapse")
def apoc_nodes_collapse(ex: CypherExecutor, args, row):
    """Merge a list of nodes into the first: union labels/props, rewire
    edges, delete the rest (ref nodes.go Collapse)."""
    nodes = args[0] or []
    # dedup by id: collect() without DISTINCT can repeat the target, and
    # treating a duplicate as an "other" would delete the merged node
    seen_ids: set[str] = set()
    nodes = [n for n in nodes if n.id not in seen_ids and not seen_ids.add(n.id)]
    if len(nodes) < 2:
        return ["node"], [[nodes[0]]] if nodes else []
    target = nodes[0]
    for other in nodes[1:]:
        for l in other.labels:
            if l not in target.labels:
                target.labels.append(l)
        for k, v in other.properties.items():
            target.properties.setdefault(k, v)
        for e in list(ex.storage.get_outgoing_edges(other.id)):
            ex.storage.delete_edge(e.id)
            if e.end_node != target.id:
                ex.storage.create_edge(Edge(start_node=target.id,
                                            end_node=e.end_node, type=e.type,
                                            properties=e.properties))
        for e in list(ex.storage.get_incoming_edges(other.id)):
            ex.storage.delete_edge(e.id)
            if e.start_node != target.id:
                ex.storage.create_edge(Edge(start_node=e.start_node,
                                            end_node=target.id, type=e.type,
                                            properties=e.properties))
        ex.storage.delete_node(other.id)
    ex.storage.update_node(target)
    return ["node"], [[target]]


# ---------------------------------------------------------------------------
# apoc.log.* (ref: apoc/log/log.go — levelled logging through the server's
# logger rather than a side-channel)
# ---------------------------------------------------------------------------


def _apoc_log(level: str, args):
    import logging

    msg = str(args[0]) if args else ""
    params = args[1:] if len(args) > 1 else ()
    try:
        msg = msg % tuple(params) if params else msg
    except (TypeError, ValueError):
        msg = " ".join([msg] + [str(p) for p in params])
    logging.getLogger("nornicdb.apoc").log(
        getattr(logging, level.upper(), logging.INFO), "%s", msg
    )
    return ["value"], [[msg]]


@procedure("apoc.log.info")
def apoc_log_info(ex, args, row):
    return _apoc_log("info", args)


@procedure("apoc.log.debug")
def apoc_log_debug(ex, args, row):
    return _apoc_log("debug", args)


@procedure("apoc.log.warn")
def apoc_log_warn(ex, args, row):
    return _apoc_log("warning", args)


@procedure("apoc.log.error")
def apoc_log_error(ex, args, row):
    return _apoc_log("error", args)


# ---------------------------------------------------------------------------
# apoc.graph.fromData (ref: apoc/graph/graph.go — virtual graph handles)
# ---------------------------------------------------------------------------


@procedure("apoc.graph.fromdata")
def apoc_graph_from_data(ex: CypherExecutor, args, row):
    """Bundle nodes+rels into a named virtual graph map (not persisted)."""
    nodes = args[0] if args else []
    rels = args[1] if len(args) > 1 else []
    name = str(args[2]) if len(args) > 2 else "graph"
    props = args[3] if len(args) > 3 and isinstance(args[3], dict) else {}
    return ["graph"], [[{
        "name": name, "nodes": list(nodes or []),
        "relationships": list(rels or []), "properties": props,
    }]]


@procedure("apoc.meta.stats")
def apoc_meta_stats(ex: CypherExecutor, args, row):
    """(ref: apoc/meta — label/type counts for the whole database)."""
    labels: dict[str, int] = {}
    n_nodes = 0
    for n in ex.storage.all_nodes():
        n_nodes += 1
        for l in n.labels:
            labels[l] = labels.get(l, 0) + 1
    types: dict[str, int] = {}
    n_edges = 0
    for e in ex.storage.all_edges():
        n_edges += 1
        types[e.type] = types.get(e.type, 0) + 1
    return (
        ["nodeCount", "relCount", "labels", "relTypes"],
        [[n_nodes, n_edges, labels, types]],
    )


# ---------------------------------------------------------------------------
# apoc.lock.* (ref: apoc/lock/lock.go — advisory per-entity locks in a
# database-global registry). Deviations, documented:
#   - the reference releases at transaction end; here release is explicit
#     (unlockNodes/unlockAll) because executor transactions are per-session;
#   - blocking acquires are BOUNDED (default 30s) and raise on timeout —
#     an unbounded in-query block is a DoS lever, same rationale as the
#     apoc.util.sleep cap;
#   - unlockNodes/unlockAll release only locks held by THIS session;
#     apoc.lock.clear (admin escape hatch, ref lock.go Clear) force-releases
#     everything, e.g. after a crashed session leaked its locks.
# ---------------------------------------------------------------------------

_LOCK_WAIT_DEFAULT_MS = 30_000.0


def _lock_registry(ex: CypherExecutor):
    storage = ex.storage
    reg = getattr(storage, "_apoc_lock_registry", None)
    if reg is None:
        import threading

        # locks: eid -> Lock; owners: eid -> (owner_key, count)
        reg = {"mu": threading.Lock(), "locks": {}, "owners": {}}
        storage._apoc_lock_registry = reg
    return reg


def _entity_ids(args) -> list[str]:
    items = args[0] if args else []
    if not isinstance(items, list):
        items = [items]
    # sorted for a stable order, deduped so one call never self-deadlocks
    return sorted({x.id if hasattr(x, "id") else str(x) for x in items})


def _acquire(reg, eid: str, owner, timeout_s: float) -> bool:
    """Owner-aware acquire: reentrant for the same session (count bump),
    bounded wait otherwise."""
    import threading

    with reg["mu"]:
        lk = reg["locks"].setdefault(eid, threading.Lock())
        holder = reg["owners"].get(eid)
        if holder is not None and holder[0] == owner:
            reg["owners"][eid] = (owner, holder[1] + 1)
            return True
    # session-scoped ownership: the lock is held across procedure calls and
    # released by the paired apoc.lock.release procedure, not try/finally
    got = lk.acquire(timeout=timeout_s) if timeout_s > 0 else lk.acquire(  # nornlint: disable=NL-CC01
        blocking=False)
    if got:
        with reg["mu"]:
            reg["owners"][eid] = (owner, 1)
    return got


def _release(reg, eid: str, owner) -> bool:
    with reg["mu"]:
        holder = reg["owners"].get(eid)
        if holder is None or holder[0] != owner:
            return False  # not ours: never release another session's lock
        if holder[1] > 1:
            reg["owners"][eid] = (owner, holder[1] - 1)
            return True
        del reg["owners"][eid]
        reg["locks"][eid].release()
        return True


@procedure("apoc.lock.nodes")
def apoc_lock_nodes(ex: CypherExecutor, args, row):
    """Acquire advisory locks (sorted order; bounded wait, raises on
    timeout rather than hanging the session)."""
    reg = _lock_registry(ex)
    ids = _entity_ids(args)
    timeout_s = (float(args[1]) if len(args) > 1 and args[1] is not None
                 else _LOCK_WAIT_DEFAULT_MS) / 1000.0
    acquired: list[str] = []
    for eid in ids:
        if not _acquire(reg, eid, ex, timeout_s):
            for got in acquired:  # all-or-nothing
                _release(reg, got, ex)
            raise CypherSyntaxError(
                f"apoc.lock.nodes: timed out waiting for lock on {eid!r}")
        acquired.append(eid)
    return ["locked"], [[len(ids)]]


@procedure("apoc.lock.trylock")
def apoc_lock_try(ex: CypherExecutor, args, row):
    """apoc.lock.tryLock(nodeOrList, timeoutMs) -> acquired (all-or-nothing
    when given a list)."""
    if not args:
        raise CypherSyntaxError("apoc.lock.tryLock(node, timeoutMs)")
    reg = _lock_registry(ex)
    ids = _entity_ids(args)  # handles both a single node and a list
    timeout_s = float(args[1]) / 1000.0 if len(args) > 1 else 0.0
    acquired: list[str] = []
    ok = True
    for eid in ids:
        if _acquire(reg, eid, ex, timeout_s):
            acquired.append(eid)
        else:
            ok = False
            break
    if not ok:
        for eid in acquired:
            _release(reg, eid, ex)
    return ["acquired"], [[ok]]


@procedure("apoc.lock.islocked")
def apoc_lock_islocked(ex: CypherExecutor, args, row):
    reg = _lock_registry(ex)
    eid = _entity_ids(args)[0] if args else ""
    with reg["mu"]:
        return ["locked"], [[eid in reg["owners"]]]


@procedure("apoc.lock.unlocknodes")
def apoc_lock_unlock(ex: CypherExecutor, args, row):
    reg = _lock_registry(ex)
    released = sum(1 for eid in _entity_ids(args) if _release(reg, eid, ex))
    return ["released"], [[released]]


@procedure("apoc.lock.unlockall")
def apoc_lock_unlock_all(ex: CypherExecutor, args, row):
    """Release every lock THIS session holds."""
    reg = _lock_registry(ex)
    with reg["mu"]:
        mine = {eid: count for eid, (owner, count) in reg["owners"].items()
                if owner is ex}
    for eid, count in mine.items():
        for _ in range(count):  # fully unwind reentrant holds
            _release(reg, eid, ex)
    return ["released"], [[len(mine)]]


@procedure("apoc.lock.clear")
def apoc_lock_clear(ex: CypherExecutor, args, row):
    """Force-release ALL locks regardless of owner (ref: lock.go Clear) —
    the admin escape hatch for locks leaked by a dead session."""
    reg = _lock_registry(ex)
    with reg["mu"]:
        n = len(reg["owners"])
        for eid in list(reg["owners"]):
            del reg["owners"][eid]
            reg["locks"][eid].release()
    return ["cleared"], [[n]]


procedure("apoc.lock.relationships")(apoc_lock_nodes)  # same registry
procedure("apoc.lock.unlockrelationships")(apoc_lock_unlock)


# ---------------------------------------------------------------------------
# apoc.search.* (ref: apoc/search/search.go — label+property scans with
# operator support; here they use the label index instead of full scans)
# ---------------------------------------------------------------------------


def _search_op(val, op: str, want) -> bool:
    """Delegates to the Cypher expression helpers so CALL apoc.search.*
    results always agree with the equivalent WHERE filter (same null,
    bool-vs-int, and string-coercion semantics)."""
    from nornicdb_tpu.cypher.expr import _compare, _eq

    if op in ("=", "==", "exact"):
        return _eq(val, want) is True
    if op in ("!=", "<>"):
        return _eq(val, want) is False
    if op == "contains":
        return (isinstance(val, str) and isinstance(want, str)
                and want in val)
    if op in ("starts with", "startswith", "prefix"):
        return (isinstance(val, str) and isinstance(want, str)
                and val.startswith(want))
    if op in ("ends with", "endswith", "suffix"):
        return (isinstance(val, str) and isinstance(want, str)
                and val.endswith(want))
    if op in (">", ">=", "<", "<="):
        return _compare(op, val, want) is True
    return False


def _criteria_match(props: dict, criteria: dict, mode: str) -> bool:
    """all/any criteria with Cypher equality: a missing key or a null
    criterion never matches (three-valued logic, matching WHERE)."""
    from nornicdb_tpu.cypher.expr import _eq

    checks = (k in props and _eq(props[k], v) is True
              for k, v in criteria.items())
    return all(checks) if mode == "all" else any(checks)


@procedure("apoc.search.node")
def apoc_search_node(ex: CypherExecutor, args, row):
    """apoc.search.node(label, property, value[, operator='='])"""
    if len(args) < 3:
        raise CypherSyntaxError("apoc.search.node(label, property, value)")
    label, prop, value = str(args[0]), str(args[1]), args[2]
    op = str(args[3]).lower() if len(args) > 3 else "="
    out = []
    for n in ex.storage.get_nodes_by_label(label):
        if prop in n.properties and _search_op(n.properties[prop], op, value):
            out.append([n])
    return ["node"], out


@procedure("apoc.search.nodeall")
def apoc_search_node_all(ex: CypherExecutor, args, row):
    """apoc.search.nodeAll(label, criteriaMap) — every criterion must hold."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.search.nodeAll(label, criteria)")
    label = str(args[0])
    criteria = args[1] if isinstance(args[1], dict) else {}
    out = []
    for n in ex.storage.get_nodes_by_label(label):
        if _criteria_match(n.properties, criteria, "all"):
            out.append([n])
    return ["node"], out


@procedure("apoc.search.nodeany")
def apoc_search_node_any(ex: CypherExecutor, args, row):
    if len(args) < 2:
        raise CypherSyntaxError("apoc.search.nodeAny(label, criteria)")
    label = str(args[0])
    criteria = args[1] if isinstance(args[1], dict) else {}
    out = []
    for n in ex.storage.get_nodes_by_label(label):
        if _criteria_match(n.properties, criteria, "any"):
            out.append([n])
    return ["node"], out


@procedure("apoc.search.multisearchall")
def apoc_search_multi_all(ex: CypherExecutor, args, row):
    """apoc.search.multiSearchAll(labels, criteria) — union over labels,
    all-criteria match, deduped by node id."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.search.multiSearchAll(labels, criteria)")
    labels = args[0] if isinstance(args[0], list) else [args[0]]
    criteria = args[1] if isinstance(args[1], dict) else {}
    seen: set[str] = set()
    out = []
    for label in labels:
        for n in ex.storage.get_nodes_by_label(str(label)):
            if n.id in seen:
                continue
            if _criteria_match(n.properties, criteria, "all"):
                seen.add(n.id)
                out.append([n])
    return ["node"], out


@procedure("apoc.search.multisearchany")
def apoc_search_multi_any(ex: CypherExecutor, args, row):
    if len(args) < 2:
        raise CypherSyntaxError("apoc.search.multiSearchAny(labels, criteria)")
    labels = args[0] if isinstance(args[0], list) else [args[0]]
    criteria = args[1] if isinstance(args[1], dict) else {}
    seen: set[str] = set()
    out = []
    for label in labels:
        for n in ex.storage.get_nodes_by_label(str(label)):
            if n.id in seen:
                continue
            if _criteria_match(n.properties, criteria, "any"):
                seen.add(n.id)
                out.append([n])
    return ["node"], out


# ---------------------------------------------------------------------------
# apoc.refactor.* gaps (ref: apoc/refactor/refactor.go — CloneNodes,
# SetType, InvertRelationship, RedirectRelationship, RenameProperty,
# ExtractNode, NormalizeAsBoolean; rename.label/type live above)
# ---------------------------------------------------------------------------


@procedure("apoc.refactor.clonenodes")
def apoc_clone_nodes(ex: CypherExecutor, args, row):
    """apoc.refactor.cloneNodes(nodes[, withRelationships=false])"""
    nodes = (args[0] or []) if args else []
    if isinstance(nodes, Node):
        nodes = [nodes]
    with_rels = bool(args[1]) if len(args) > 1 else False
    out = []
    for n in nodes:
        # snapshot BOTH edge lists before any insert, or the incoming scan
        # picks up the clone edges we just created; self-loops appear in
        # both lists, so dedup by id and remap both endpoints to the clone
        outgoing = list(ex.storage.get_outgoing_edges(n.id)) if with_rels else []
        incoming = [e for e in ex.storage.get_incoming_edges(n.id)
                    if e.start_node != n.id] if with_rels else []
        clone = ex.storage.create_node(
            Node(labels=list(n.labels), properties=dict(n.properties)))
        for e in outgoing:
            end = clone.id if e.end_node == n.id else e.end_node
            ex.storage.create_edge(Edge(
                start_node=clone.id, end_node=end, type=e.type,
                properties=dict(e.properties)))
        for e in incoming:
            ex.storage.create_edge(Edge(
                start_node=e.start_node, end_node=clone.id, type=e.type,
                properties=dict(e.properties)))
        out.append([n, clone])
    return ["input", "output"], out


@procedure("apoc.refactor.settype")
def apoc_set_type(ex: CypherExecutor, args, row):
    """apoc.refactor.setType(rel, newType) — in-place mutation; update_edge
    re-indexes the type map, so the edge keeps its id and created_at."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.refactor.setType(rel, newType)")
    e, new_type = args[0], str(args[1])
    updated = e.copy()
    updated.type = new_type
    updated = ex.storage.update_edge(updated)
    return ["input", "output"], [[e, updated]]


@procedure("apoc.refactor.invert")
def apoc_invert_rel(ex: CypherExecutor, args, row):
    """Flip a relationship's direction."""
    if not args:
        raise CypherSyntaxError("apoc.refactor.invert(rel)")
    e = args[0]
    # endpoint changes need delete+recreate (adjacency maps key on the
    # endpoints); create FIRST so a failure never destroys the original
    created = ex.storage.create_edge(Edge(
        start_node=e.end_node, end_node=e.start_node, type=e.type,
        properties=dict(e.properties)))
    ex.storage.delete_edge(e.id)
    return ["input", "output"], [[e, created]]


@procedure("apoc.refactor.to")
def apoc_redirect_to(ex: CypherExecutor, args, row):
    """apoc.refactor.to(rel, newEndNode) — redirect the end node."""
    if len(args) < 2:
        raise CypherSyntaxError("apoc.refactor.to(rel, endNode)")
    e, target = args[0], args[1]
    created = ex.storage.create_edge(Edge(  # create-then-delete: a missing
        start_node=e.start_node, end_node=target.id, type=e.type,  # target
        properties=dict(e.properties)))  # must not destroy the original
    ex.storage.delete_edge(e.id)
    return ["input", "output"], [[e, created]]


@procedure("apoc.refactor.from")
def apoc_redirect_from(ex: CypherExecutor, args, row):
    if len(args) < 2:
        raise CypherSyntaxError("apoc.refactor.from(rel, startNode)")
    e, source = args[0], args[1]
    created = ex.storage.create_edge(Edge(
        start_node=source.id, end_node=e.end_node, type=e.type,
        properties=dict(e.properties)))
    ex.storage.delete_edge(e.id)
    return ["input", "output"], [[e, created]]


@procedure("apoc.refactor.rename.nodeproperty")
def apoc_rename_node_prop(ex: CypherExecutor, args, row):
    if len(args) < 2:
        raise CypherSyntaxError(
            "apoc.refactor.rename.nodeProperty(old, new[, nodes])")
    old_name, new_name = str(args[0]), str(args[1])
    scope = args[2] if len(args) > 2 and args[2] else None
    targets = scope if scope is not None else list(ex.storage.all_nodes())
    count = 0
    for n in targets:
        if old_name in n.properties:
            n.properties[new_name] = n.properties.pop(old_name)
            ex.storage.update_node(n)
            count += 1
    return ["total"], [[count]]


@procedure("apoc.refactor.extractnode")
def apoc_extract_node(ex: CypherExecutor, args, row):
    """Turn a relationship into a node with connecting edges
    (rel A-[R]->B  becomes  A-[OUT]->(:R)-[IN]->B)."""
    if not args:
        raise CypherSyntaxError(
            "apoc.refactor.extractNode(rel[, labels, outType, inType])")
    e = args[0]
    labels = args[1] if len(args) > 1 and args[1] else [e.type]
    out_type = str(args[2]) if len(args) > 2 else "OUT"
    in_type = str(args[3]) if len(args) > 3 else "IN"
    mid = ex.storage.create_node(Node(labels=list(labels),
                                      properties=dict(e.properties)))
    ex.storage.delete_edge(e.id)
    ex.storage.create_edge(Edge(start_node=e.start_node, end_node=mid.id,
                                type=out_type))
    ex.storage.create_edge(Edge(start_node=mid.id, end_node=e.end_node,
                                type=in_type))
    return ["input", "output"], [[e, mid]]


@procedure("apoc.refactor.normalizeasboolean")
def apoc_normalize_bool(ex: CypherExecutor, args, row):
    """apoc.refactor.normalizeAsBoolean(entity, prop, trueValues, falseValues)"""
    if len(args) < 4:
        raise CypherSyntaxError(
            "apoc.refactor.normalizeAsBoolean(entity, prop, trues, falses)")
    entity, prop = args[0], str(args[1])
    trues = args[2] or []
    falses = args[3] or []
    val = entity.properties.get(prop)
    if val in trues:
        entity.properties[prop] = True
    elif val in falses:
        entity.properties[prop] = False
    else:
        entity.properties.pop(prop, None)  # unmappable: drop, per apoc
    if isinstance(entity, Node):
        ex.storage.update_node(entity)
    else:
        ex.storage.update_edge(entity)
    return ["entity"], [[entity]]


# ---------------------------------------------------------------------------
# apoc.meta.* introspection (ref: apoc/meta/meta.go — Schema/Data/
# NodeTypeProperties/RelTypeProperties)
# ---------------------------------------------------------------------------


def _cypher_type_of(v) -> str:
    """Delegates to apoc.meta.type so schema introspection and the
    meta.type function can never disagree on a value's type name."""
    from nornicdb_tpu.apoc.functions import meta_type

    return str(meta_type(v)).upper()


@procedure("apoc.meta.schema")
def apoc_meta_schema(ex: CypherExecutor, args, row):
    """One map describing every label: property names -> {type, count} and
    outgoing relationship types (ref meta.go Schema)."""
    schema: dict[str, Any] = {}
    nodes_by_id: dict[str, Any] = {}
    for n in ex.storage.all_nodes():
        nodes_by_id[n.id] = n
        for label in n.labels:
            entry = schema.setdefault(
                label, {"type": "node", "count": 0, "properties": {},
                        "relationships": {}})
            entry["count"] += 1
            for k, v in n.properties.items():
                p = entry["properties"].setdefault(
                    k, {"type": _cypher_type_of(v), "count": 0})
                p["count"] += 1
                if p["type"] != _cypher_type_of(v):
                    p["type"] = "ANY"  # mixed types across nodes
    for e in ex.storage.all_edges():
        src = nodes_by_id.get(e.start_node)
        if src is None:
            continue
        for label in src.labels:
            entry = schema.get(label)
            if entry is not None:
                rel = entry["relationships"].setdefault(
                    e.type, {"direction": "out", "count": 0})
                rel["count"] += 1
    return ["value"], [[schema]]


@procedure("apoc.meta.nodetypeproperties")
def apoc_meta_node_type_props(ex: CypherExecutor, args, row):
    """Row per (label, property): observed types + counts (ref meta.go
    NodeTypeProperties / db.schema.nodeTypeProperties shape)."""
    seen: dict[tuple, dict] = {}
    totals: dict[str, int] = {}
    for n in ex.storage.all_nodes():
        for label in n.labels:
            totals[label] = totals.get(label, 0) + 1
            for k, v in n.properties.items():
                rec = seen.setdefault((label, k), {"types": set(), "count": 0})
                rec["types"].add(_cypher_type_of(v))
                rec["count"] += 1
    rows = []
    for (label, prop), rec in sorted(seen.items()):
        rows.append([f":`{label}`", [label], prop,
                     sorted(rec["types"]), rec["count"] == totals[label]])
    return (["nodeType", "nodeLabels", "propertyName", "propertyTypes",
             "mandatory"], rows)


@procedure("apoc.meta.reltypeproperties")
def apoc_meta_rel_type_props(ex: CypherExecutor, args, row):
    seen: dict[tuple, dict] = {}
    totals: dict[str, int] = {}
    for e in ex.storage.all_edges():
        totals[e.type] = totals.get(e.type, 0) + 1
        for k, v in e.properties.items():
            rec = seen.setdefault((e.type, k), {"types": set(), "count": 0})
            rec["types"].add(_cypher_type_of(v))
            rec["count"] += 1
    rows = []
    for (rtype, prop), rec in sorted(seen.items()):
        rows.append([f":`{rtype}`", prop, sorted(rec["types"]),
                     rec["count"] == totals[rtype]])
    return ["relType", "propertyName", "propertyTypes", "mandatory"], rows


@procedure("apoc.meta.data")
def apoc_meta_data(ex: CypherExecutor, args, row):
    """Row per (label, property/relationship) — the tabular twin of
    apoc.meta.schema (ref meta.go Data)."""
    _, rows_ = apoc_meta_schema(ex, args, row)
    schema = rows_[0][0]
    out = []
    for label, entry in sorted(schema.items()):
        for prop, info in sorted(entry["properties"].items()):
            out.append([label, prop, info["type"], False, info["count"]])
        for rtype, info in sorted(entry["relationships"].items()):
            out.append([label, rtype, "RELATIONSHIP", True, info["count"]])
    return ["label", "property", "type", "isRelationship", "count"], out
