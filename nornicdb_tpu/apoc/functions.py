"""APOC core function library.

Behavioral reference: /root/reference/apoc/ — the ~45 category subdirs
(SURVEY.md §2.1 APOC row). This module implements the high-traffic core:
coll, text, map, math, number, convert, date/temporal, hashing, json, meta,
agg, label, node, util. Graph-touching procedures (create/merge/refactor/
path/periodic) live in procedures.py.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import json as _json
import math as _math
import random
import re
import statistics
import time
import urllib.parse
import zlib
from typing import Any, Optional

from nornicdb_tpu.apoc.registry import register
from nornicdb_tpu.storage.types import Edge, Node


# ================================================================= coll
@register("apoc.coll.sum")
def coll_sum(xs):
    return sum(xs or [])


@register("apoc.coll.avg")
def coll_avg(xs):
    return sum(xs) / len(xs) if xs else None


@register("apoc.coll.min")
def coll_min(xs):
    return min(xs) if xs else None


@register("apoc.coll.max")
def coll_max(xs):
    return max(xs) if xs else None


@register("apoc.coll.sort")
def coll_sort(xs):
    return sorted(xs or [])


@register("apoc.coll.sortNodes")
def coll_sort_nodes(nodes, prop):
    return sorted(nodes or [], key=lambda n: (n.properties.get(prop) is None,
                                              n.properties.get(prop)))


@register("apoc.coll.reverse")
def coll_reverse(xs):
    return list(reversed(xs or []))


@register("apoc.coll.contains")
def coll_contains(xs, v):
    return v in (xs or [])


@register("apoc.coll.indexOf")
def coll_index_of(xs, v):
    try:
        return (xs or []).index(v)
    except ValueError:
        return -1


@register("apoc.coll.distinct")
@register("apoc.coll.toSet")
def coll_to_set(xs):
    out = []
    seen = set()
    for x in xs or []:
        k = _json.dumps(x, sort_keys=True, default=str)
        if k not in seen:
            seen.add(k)
            out.append(x)
    return out


@register("apoc.coll.flatten")
def coll_flatten(xs):
    out = []
    for x in xs or []:
        if isinstance(x, list):
            out.extend(x)
        else:
            out.append(x)
    return out


@register("apoc.coll.pairs")
def coll_pairs(xs):
    xs = xs or []
    if not xs:
        return []
    # APOC includes the trailing [last, null] pair
    return [[xs[i], xs[i + 1] if i + 1 < len(xs) else None] for i in range(len(xs))]


@register("apoc.coll.zip")
def coll_zip(a, b):
    return [[x, y] for x, y in zip(a or [], b or [])]


@register("apoc.coll.union")
def coll_union(a, b):
    return coll_to_set((a or []) + (b or []))


@register("apoc.coll.intersection")
def coll_intersection(a, b):
    bset = {_json.dumps(x, sort_keys=True, default=str) for x in (b or [])}
    return [x for x in coll_to_set(a or [])
            if _json.dumps(x, sort_keys=True, default=str) in bset]


@register("apoc.coll.subtract")
def coll_subtract(a, b):
    bset = {_json.dumps(x, sort_keys=True, default=str) for x in (b or [])}
    return [x for x in coll_to_set(a or [])
            if _json.dumps(x, sort_keys=True, default=str) not in bset]


@register("apoc.coll.split")
def coll_split(xs, v):
    out, cur = [], []
    for x in xs or []:
        if x == v:
            out.append(cur)
            cur = []
        else:
            cur.append(x)
    out.append(cur)
    return out


@register("apoc.coll.partition")
def coll_partition(xs, size):
    xs = xs or []
    size = int(size)
    return [xs[i : i + size] for i in range(0, len(xs), size)]


@register("apoc.coll.shuffle")
def coll_shuffle(xs):
    out = list(xs or [])
    random.shuffle(out)
    return out


@register("apoc.coll.randomItem")
def coll_random_item(xs):
    return random.choice(xs) if xs else None


@register("apoc.coll.frequencies")
def coll_frequencies(xs):
    counts: dict[str, dict] = {}
    for x in xs or []:
        k = _json.dumps(x, sort_keys=True, default=str)
        if k not in counts:
            counts[k] = {"item": x, "count": 0}
        counts[k]["count"] += 1
    return list(counts.values())


@register("apoc.coll.occurrences")
def coll_occurrences(xs, v):
    return sum(1 for x in xs or [] if x == v)


@register("apoc.coll.insert")
def coll_insert(xs, idx, v):
    out = list(xs or [])
    out.insert(int(idx), v)
    return out


@register("apoc.coll.remove")
def coll_remove(xs, idx, length=1):
    out = list(xs or [])
    i = int(idx)
    del out[i : i + int(length)]
    return out


@register("apoc.coll.stdev")
def coll_stdev(xs, biased=False):
    if not xs or len(xs) < 2:
        return 0.0
    return statistics.pstdev(xs) if biased else statistics.stdev(xs)


# ================================================================= text
@register("apoc.text.join")
def text_join(xs, sep):
    return (sep or "").join(str(x) for x in (xs or []) if x is not None)


@register("apoc.text.split")
def text_split(s, regex):
    if s is None:
        return None
    return re.split(regex, s)


@register("apoc.text.replace")
def text_replace(s, regex, repl):
    if s is None:
        return None
    return re.sub(regex, repl, s)


@register("apoc.text.regexGroups")
def text_regex_groups(s, regex):
    if s is None:
        return []
    return [[m.group(0), *m.groups()] for m in re.finditer(regex, s)]


@register("apoc.text.capitalize")
def text_capitalize(s):
    return None if s is None else (s[:1].upper() + s[1:])


@register("apoc.text.decapitalize")
def text_decapitalize(s):
    return None if s is None else (s[:1].lower() + s[1:])


@register("apoc.text.upperCamelCase")
def text_upper_camel(s):
    if s is None:
        return None
    return "".join(w.capitalize() for w in re.split(r"[\s_\-]+", s))


@register("apoc.text.camelCase")
def text_camel(s):
    v = text_upper_camel(s)
    return None if v is None else (v[:1].lower() + v[1:])


@register("apoc.text.snakeCase")
def text_snake(s):
    if s is None:
        return None
    s = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s)
    return re.sub(r"[\s_\-]+", "_", s).lower()


@register("apoc.text.random")
def text_random(length, valid="A-Za-z0-9"):
    import string

    chars = ""
    for rng in re.findall(r"(\w-\w|\w)", valid):
        if "-" in rng and len(rng) == 3:
            chars += "".join(chr(c) for c in range(ord(rng[0]), ord(rng[2]) + 1))
        else:
            chars += rng
    chars = chars or string.ascii_letters
    return "".join(random.choice(chars) for _ in range(int(length)))


@register("apoc.text.lpad")
def text_lpad(s, count, delim=" "):
    s = "" if s is None else str(s)
    return s.rjust(int(count), delim or " ")


@register("apoc.text.rpad")
def text_rpad(s, count, delim=" "):
    s = "" if s is None else str(s)
    return s.ljust(int(count), delim or " ")


@register("apoc.text.format")
def text_format(fmt, params):
    return fmt % tuple(params or [])


@register("apoc.text.slug")
def text_slug(s, delim="-"):
    if s is None:
        return None
    return re.sub(r"[^\w]+", delim, s.strip()).strip(delim).lower()


@register("apoc.text.distance")
@register("apoc.text.levenshteinDistance")
def text_levenshtein(a, b):
    if a is None or b is None:
        return None
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        curr = [i]
        for j, cb in enumerate(b, 1):
            curr.append(min(prev[j] + 1, curr[j - 1] + 1, prev[j - 1] + (ca != cb)))
        prev = curr
    return prev[-1]


@register("apoc.text.levenshteinSimilarity")
def text_levenshtein_sim(a, b):
    if a is None or b is None:
        return None
    if not a and not b:
        return 1.0
    return 1.0 - text_levenshtein(a, b) / max(len(a), len(b))


@register("apoc.text.indexOf")
def text_index_of(s, lookup, from_=0):
    if s is None:
        return None
    return s.find(lookup, int(from_))


@register("apoc.text.clean")
def text_clean(s):
    if s is None:
        return None
    return re.sub(r"[^a-z0-9]", "", s.lower())


@register("apoc.text.compareCleaned")
def text_compare_cleaned(a, b):
    return text_clean(a) == text_clean(b)


@register("apoc.text.urlencode")
def text_urlencode(s):
    return None if s is None else urllib.parse.quote(s, safe="")


@register("apoc.text.urldecode")
def text_urldecode(s):
    return None if s is None else urllib.parse.unquote(s)


@register("apoc.text.base64Encode")
def text_b64(s):
    import base64

    return None if s is None else base64.b64encode(s.encode()).decode()


@register("apoc.text.base64Decode")
def text_unb64(s):
    import base64

    return None if s is None else base64.b64decode(s).decode()


@register("apoc.text.charAt")
def text_char_at(s, i):
    if s is None or int(i) >= len(s):
        return None
    return ord(s[int(i)])


@register("apoc.text.code")
def text_code(i):
    return chr(int(i))


@register("apoc.text.hexValue")
def text_hex(v):
    return f"{int(v):X}"


# ================================================================= map
@register("apoc.map.fromPairs")
def map_from_pairs(pairs):
    return {str(k): v for k, v in (pairs or [])}


@register("apoc.map.fromLists")
def map_from_lists(keys, values):
    return {str(k): v for k, v in zip(keys or [], values or [])}


@register("apoc.map.merge")
def map_merge(a, b):
    out = dict(a or {})
    out.update(b or {})
    return out


@register("apoc.map.mergeList")
def map_merge_list(maps):
    out: dict = {}
    for m in maps or []:
        out.update(m or {})
    return out


@register("apoc.map.setKey")
def map_set_key(m, key, value):
    out = dict(m or {})
    out[str(key)] = value
    return out


@register("apoc.map.removeKey")
def map_remove_key(m, key):
    out = dict(m or {})
    out.pop(key, None)
    return out


@register("apoc.map.removeKeys")
def map_remove_keys(m, keys):
    out = dict(m or {})
    for k in keys or []:
        out.pop(k, None)
    return out


@register("apoc.map.clean")
def map_clean(m, keys, values):
    keys = set(keys or [])
    values = values or []
    return {
        k: v
        for k, v in (m or {}).items()
        if k not in keys and v not in values and v is not None
    }


@register("apoc.map.get")
def map_get(m, key, default=None):
    return (m or {}).get(key, default)


@register("apoc.map.submap")
def map_submap(m, keys):
    return {k: (m or {}).get(k) for k in keys or []}


@register("apoc.map.sortedProperties")
def map_sorted_props(m):
    return [[k, (m or {})[k]] for k in sorted(m or {})]


@register("apoc.map.flatten")
def map_flatten(m, delimiter="."):
    out = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{delimiter}{k}" if prefix else str(k), v)
        else:
            out[prefix] = node

    walk("", m or {})
    return out


@register("apoc.map.groupBy")
def map_group_by(items, key):
    out = {}
    for item in items or []:
        k = item.get(key) if isinstance(item, dict) else None
        if k is not None:
            out[str(k)] = item
    return out


@register("apoc.map.groupByMulti")
def map_group_by_multi(items, key):
    out: dict = {}
    for item in items or []:
        k = item.get(key) if isinstance(item, dict) else None
        if k is not None:
            out.setdefault(str(k), []).append(item)
    return out


@register("apoc.map.values")
def map_values(m, keys=None, add_null=False):
    if keys is None:
        return list((m or {}).values())
    out = []
    for k in keys:
        v = (m or {}).get(k)
        if v is not None or add_null:
            out.append(v)
    return out


# ================================================================= math/number
@register("apoc.math.round")
def math_round(v, precision=0):
    return round(float(v), int(precision))


@register("apoc.math.maxLong")
def math_max_long():
    return 2**63 - 1


@register("apoc.math.minLong")
def math_min_long():
    return -(2**63)


@register("apoc.math.sigmoid")
def math_sigmoid(v):
    return 1.0 / (1.0 + _math.exp(-float(v)))


@register("apoc.math.tanh")
def math_tanh(v):
    return _math.tanh(float(v))


@register("apoc.math.cosh")
def math_cosh(v):
    return _math.cosh(float(v))


@register("apoc.math.sinh")
def math_sinh(v):
    return _math.sinh(float(v))


@register("apoc.number.format")
def number_format(v, pattern=None):
    if isinstance(v, float):
        return f"{v:,.2f}" if pattern is None else f"{v:,}"
    return f"{int(v):,}"


@register("apoc.number.parseInt")
def number_parse_int(s, radix=10):
    try:
        return int(str(s), int(radix))
    except (ValueError, TypeError):
        return None


@register("apoc.number.parseFloat")
def number_parse_float(s):
    try:
        return float(s)
    except (ValueError, TypeError):
        return None


# ================================================================= convert
@register("apoc.convert.toList")
def convert_to_list(v):
    if v is None:
        return []
    if isinstance(v, list):
        return v
    return [v]


@register("apoc.convert.toMap")
def convert_to_map(v):
    if isinstance(v, (Node, Edge)):
        return dict(v.properties)
    if isinstance(v, dict):
        return dict(v)
    return None


@register("apoc.convert.toString")
def convert_to_string(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


@register("apoc.convert.toInteger")
def convert_to_integer(v):
    try:
        return int(float(v)) if isinstance(v, str) else int(v)
    except (ValueError, TypeError):
        return None


@register("apoc.convert.toFloat")
def convert_to_float(v):
    try:
        return float(v)
    except (ValueError, TypeError):
        return None


@register("apoc.convert.toBoolean")
def convert_to_boolean(v):
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.lower() in ("true", "1", "yes")
    if isinstance(v, (int, float)):
        return v != 0
    return False


@register("apoc.convert.toJson")
def convert_to_json(v):
    def default(o):
        if isinstance(o, (Node, Edge)):
            return o.to_dict()
        return str(o)

    return _json.dumps(v, default=default)


@register("apoc.convert.fromJsonMap")
def convert_from_json_map(s):
    v = _json.loads(s)
    return v if isinstance(v, dict) else None


@register("apoc.convert.fromJsonList")
def convert_from_json_list(s):
    v = _json.loads(s)
    return v if isinstance(v, list) else None


# ================================================================= date
@register("apoc.date.format")
def date_format(epoch, unit="ms", fmt="yyyy-MM-dd HH:mm:ss"):
    seconds = float(epoch) / (1000.0 if unit == "ms" else 1.0)
    py_fmt = (
        fmt.replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
        .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S")
    )
    return _dt.datetime.fromtimestamp(seconds, _dt.timezone.utc).strftime(py_fmt)


@register("apoc.date.parse")
def date_parse(s, unit="ms", fmt="yyyy-MM-dd HH:mm:ss"):
    py_fmt = (
        fmt.replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
        .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S")
    )
    dt = _dt.datetime.strptime(s, py_fmt).replace(tzinfo=_dt.timezone.utc)
    seconds = dt.timestamp()
    return int(seconds * 1000) if unit == "ms" else int(seconds)


@register("apoc.date.currentTimestamp")
def date_now():
    return int(time.time() * 1000)


@register("apoc.date.add")
def date_add(epoch, unit, value, value_unit):
    ms = {"ms": 1, "s": 1000, "m": 60000, "h": 3600000, "d": 86400000}
    delta_ms = int(value) * ms.get(value_unit, 1)
    # the addend converts into the epoch's own unit
    return int(epoch) + delta_ms // ms.get(unit, 1)


@register("apoc.date.convert")
def date_convert(v, from_unit, to_unit):
    ms = {"ms": 1, "s": 1000, "m": 60000, "h": 3600000, "d": 86400000}
    return int(int(v) * ms.get(from_unit, 1) / ms.get(to_unit, 1))


@register("apoc.temporal.format", category="temporal")
def temporal_format(epoch_ms, fmt="yyyy-MM-dd"):
    return date_format(epoch_ms, "ms", fmt)


# ================================================================= hashing
@register("apoc.hashing.md5", category="hashing")
def hash_md5(v):
    return hashlib.md5(str(v).encode()).hexdigest()


@register("apoc.hashing.sha1", category="hashing")
def hash_sha1(v):
    return hashlib.sha1(str(v).encode()).hexdigest()


@register("apoc.hashing.sha256", category="hashing")
def hash_sha256(v):
    return hashlib.sha256(str(v).encode()).hexdigest()


@register("apoc.hashing.sha512", category="hashing")
def hash_sha512(v):
    return hashlib.sha512(str(v).encode()).hexdigest()


@register("apoc.hashing.crc32", category="hashing")
def hash_crc32(v):
    return zlib.crc32(str(v).encode()) & 0xFFFFFFFF


@register("apoc.util.md5")
def util_md5(values):
    return hashlib.md5("".join(str(v) for v in values).encode()).hexdigest()


@register("apoc.util.sha1")
def util_sha1(values):
    return hashlib.sha1("".join(str(v) for v in values).encode()).hexdigest()


@register("apoc.util.validatePredicate")
def util_validate(predicate, message, params=None):
    if predicate:
        raise ValueError(message % tuple(params or []) if params else message)
    return True


# ================================================================= label/meta
@register("apoc.label.exists")
def label_exists(node, label):
    return isinstance(node, Node) and label in node.labels


@register("apoc.meta.type")
def meta_type(v):
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "LIST"
    if isinstance(v, Node):
        return "NODE"
    if isinstance(v, Edge):
        return "RELATIONSHIP"
    if isinstance(v, dict):
        return "PATH" if v.get("__path__") else "MAP"
    return type(v).__name__.upper()


@register("apoc.meta.isType")
def meta_is_type(v, t):
    return meta_type(v) == t


# ================================================================= node/rel
@register("apoc.node.degree")
def node_degree_fn(node):
    # resolved via executor-bound variant in procedures.py when storage needed
    raise ValueError("apoc.node.degree requires executor context")


@register("apoc.rel.type")
def rel_type(rel):
    return rel.type if isinstance(rel, Edge) else None


@register("apoc.any.properties")
def any_properties(v):
    if isinstance(v, (Node, Edge)):
        return dict(v.properties)
    return v if isinstance(v, dict) else None


@register("apoc.any.property")
def any_property(v, key):
    props = any_properties(v)
    return None if props is None else props.get(key)


# ================================================================= agg
@register("apoc.agg.first", category="agg")
def agg_first(xs):
    return xs[0] if xs else None


@register("apoc.agg.last", category="agg")
def agg_last(xs):
    return xs[-1] if xs else None


@register("apoc.agg.median", category="agg")
def agg_median(xs):
    return statistics.median(xs) if xs else None


@register("apoc.agg.percentiles", category="agg")
def agg_percentiles(xs, ps=None):
    if not xs:
        return {}
    ps = ps or [0.5, 0.75, 0.9, 0.95, 0.99]
    ordered = sorted(xs)
    out = {}
    for p in ps:
        idx = max(min(int(round(p * (len(ordered) - 1))), len(ordered) - 1), 0)
        out[str(p)] = ordered[idx]
    return out


@register("apoc.agg.product", category="agg")
def agg_product(xs):
    out = 1
    for x in xs or []:
        out *= x
    return out


@register("apoc.agg.statistics", category="agg")
def agg_statistics(xs):
    if not xs:
        return {"count": 0}
    return {
        "count": len(xs),
        "sum": sum(xs),
        "min": min(xs),
        "max": max(xs),
        "mean": sum(xs) / len(xs),
        "stdev": statistics.pstdev(xs) if len(xs) > 1 else 0.0,
    }


# ================================================================= atomic
# (ref: apoc/atomic — numeric read-modify-write on properties; the executor
# passes entities by value so these operate on maps/lists functionally)
@register("apoc.atomic.add", category="atomic")
def atomic_add(m, key, value):
    out = dict(m or {})
    out[key] = (out.get(key) or 0) + value
    return out


@register("apoc.atomic.subtract", category="atomic")
def atomic_subtract(m, key, value):
    return atomic_add(m, key, -value)


@register("apoc.atomic.concat", category="atomic")
def atomic_concat(m, key, value):
    out = dict(m or {})
    out[key] = str(out.get(key) or "") + str(value)
    return out


@register("apoc.atomic.insert", category="atomic")
def atomic_insert(m, key, value):
    out = dict(m or {})
    lst = list(out.get(key) or [])
    lst.append(value)
    out[key] = lst
    return out


# ================================================================= load
@register("apoc.load.json", category="load")
def load_json(url):
    """file:// JSON loader, gated like the reference's import setting
    (requires NORNICDB_APOC_IMPORT_ENABLED=true — arbitrary local file reads
    must be an explicit operator decision, not a default; NORNICDB_IMPORT_DIR
    confines paths when set)."""
    from nornicdb_tpu.config import resolve_import_url

    try:
        path = resolve_import_url(str(url))
    except PermissionError as e:
        raise ValueError(str(e)) from None
    with open(path) as f:
        return _json.load(f)


@register("apoc.load.jsonArray", category="load")
def load_json_array(url):
    v = load_json(url)
    return v if isinstance(v, list) else [v]


# ================================================================= more coll
@register("apoc.coll.duplicates")
def coll_duplicates(xs):
    seen, dups, out = set(), set(), []
    for x in xs or []:
        k = _json.dumps(x, sort_keys=True, default=str)
        if k in seen and k not in dups:
            dups.add(k)
            out.append(x)
        seen.add(k)
    return out


@register("apoc.coll.dropDuplicateNeighbors")
def coll_drop_dup_neighbors(xs):
    out = []
    for x in xs or []:
        if not out or out[-1] != x:
            out.append(x)
    return out


@register("apoc.coll.fill")
def coll_fill(item, count):
    return [item] * int(count)


@register("apoc.coll.sumLongs")
def coll_sum_longs(xs):
    return int(sum(int(x) for x in xs or []))


@register("apoc.coll.containsAll")
def coll_contains_all(xs, values):
    pool = {_json.dumps(x, sort_keys=True, default=str) for x in xs or []}
    return all(
        _json.dumps(v, sort_keys=True, default=str) in pool for v in values or []
    )


@register("apoc.coll.runningTotal")
def coll_running_total(xs):
    out, acc = [], 0
    for x in xs or []:
        acc += x
        out.append(acc)
    return out


# ================================================================= more text
@register("apoc.text.fuzzyMatch")
def text_fuzzy_match(a, b):
    if a is None or b is None:
        return None
    return text_levenshtein_sim(a.lower(), b.lower()) > 0.7


@register("apoc.text.sorensenDiceSimilarity")
def text_dice(a, b):
    if a is None or b is None:
        return None
    def bigrams(s):
        s = s.lower()
        return {s[i : i + 2] for i in range(len(s) - 1)}
    ba, bb = bigrams(a), bigrams(b)
    if not ba and not bb:
        return 1.0
    return 2 * len(ba & bb) / (len(ba) + len(bb))


@register("apoc.text.repeat")
def text_repeat(s, count):
    return None if s is None else s * int(count)


@register("apoc.text.byteCount")
def text_byte_count(s, charset="UTF-8"):
    return None if s is None else len(s.encode(charset))


@register("apoc.text.swapCase")
def text_swap_case(s):
    return None if s is None else s.swapcase()
