"""Three-tier exponential memory decay.

Behavioral reference: /root/reference/pkg/decay/decay.go —
half-lives EPISODIC 7d / SEMANTIC 69d / PROCEDURAL 693d (:80-125),
score = 0.4*recency + 0.3*frequency + 0.3*importance
(pkg/nornicdb/db.go:951-959), reinforcement on access (:582),
archive below threshold (default 0.05), periodic recalculation (:643),
Kalman-smoothed variant (kalman_adapter.go).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from nornicdb_tpu.filter.kalman import DECAY_PREDICTION, Kalman
from nornicdb_tpu.storage.types import EPISODIC, PROCEDURAL, SEMANTIC, Engine, Node

logger = logging.getLogger(__name__)

DAY = 86400.0

# (ref: decay.go:80-125)
HALF_LIVES = {
    EPISODIC: 7 * DAY,
    SEMANTIC: 69 * DAY,
    PROCEDURAL: 693 * DAY,
}

ARCHIVED_LABEL = "Archived"


def half_life(memory_type: str) -> float:
    """(ref: HalfLife decay.go:810)"""
    return HALF_LIVES.get(memory_type, HALF_LIVES[SEMANTIC])


@dataclass
class DecayConfig:
    recency_weight: float = 0.4
    frequency_weight: float = 0.3
    importance_weight: float = 0.3
    archive_threshold: float = 0.05
    reinforce_boost: float = 0.1
    interval: float = 3600.0
    kalman_smoothing: bool = False


@dataclass
class DecayStats:
    recalculations: int = 0
    nodes_scored: int = 0
    archived: int = 0
    reinforced: int = 0


class DecayManager:
    """(ref: decay.Manager decay.go:275)"""

    def __init__(
        self,
        storage: Engine,
        config: Optional[DecayConfig] = None,
        archive_threshold: Optional[float] = None,
        now_fn: Callable[[], float] = time.time,
    ):
        self.storage = storage
        self.config = config or DecayConfig()
        if archive_threshold is not None:
            self.config.archive_threshold = archive_threshold
        self.now = now_fn
        self.stats = DecayStats()
        self._timer: Optional[threading.Timer] = None
        self._stopped = False
        self._kalman: dict[str, Kalman] = {}
        # optional temporal modulation hook: node_id -> multiplier where
        # 0.5 halves the decay speed and 2.0 doubles it (ref: pkg/temporal
        # decay_integration.go; wire temporal.DecayIntegration
        # .get_decay_modifier(...).multiplier here)
        self.rate_modifier: Optional[Callable[[str], float]] = None
        self._modifier_errors = 0
        self._modifier_error_logged_at = float("-inf")

    # -- scoring -------------------------------------------------------------
    def calculate_score(self, node: Node, now: Optional[float] = None) -> float:
        """(ref: CalculateScore decay.go:503; weights db.go:951-959)"""
        now = self.now() if now is None else now
        hl = half_life(node.memory_type)
        if self.rate_modifier is not None:
            # multiplier scales decay SPEED, so it divides the half-life
            # (x0.5 = memories live twice as long)
            try:
                mult = float(self.rate_modifier(node.id))
            except Exception:
                # per-node call site inside recalculate_all: a persistent
                # modifier failure (storage down) would otherwise emit one
                # traceback PER NODE per pass — rate-limit to one per 60s
                # with a suppressed-failure count
                self._modifier_errors += 1
                mono = time.monotonic()
                if mono - self._modifier_error_logged_at >= 60.0:
                    self._modifier_error_logged_at = mono
                    logger.exception(
                        "decay rate modifier failed for %s; using 1.0 "
                        "(%d failure(s) since last report)",
                        node.id, self._modifier_errors,
                    )
                    self._modifier_errors = 0
                mult = 1.0
            if mult > 0 and math.isfinite(mult):
                hl = hl / mult
        age = max(now - node.last_accessed, 0.0)
        recency = math.exp(-math.log(2.0) * age / hl)
        # frequency: saturating log scale (10+ accesses ~ 1.0)
        frequency = min(math.log1p(node.access_count) / math.log(11.0), 1.0)
        importance = float(node.properties.get("importance", 0.5))
        importance = min(max(importance, 0.0), 1.0)
        score = (
            self.config.recency_weight * recency
            + self.config.frequency_weight * frequency
            + self.config.importance_weight * importance
        )
        if self.config.kalman_smoothing:
            filt = self._kalman.setdefault(node.id, Kalman(DECAY_PREDICTION))
            score = filt.process(score)
        return min(max(score, 0.0), 1.0)

    def reinforce(self, node_id: str) -> float:
        """Boost on access (ref: Reinforce decay.go:582)."""
        node = self.storage.get_node(node_id)
        node.access_count += 1
        node.last_accessed = self.now()
        node.decay_score = min(node.decay_score + self.config.reinforce_boost, 1.0)
        if ARCHIVED_LABEL in node.labels:
            node.labels.remove(ARCHIVED_LABEL)  # resurrection on access
        self.storage.update_node(node)
        self.stats.reinforced += 1
        return node.decay_score

    # -- recalculation -----------------------------------------------------------
    def recalculate_all(self) -> tuple[int, int]:
        """Rescore every node; archive those below threshold
        (ref: periodic loop decay.go:643). Returns (scored, archived)."""
        scored = archived = 0
        now = self.now()
        for node in self.storage.all_nodes():
            score = self.calculate_score(node, now)
            changed = abs(score - node.decay_score) > 1e-9
            node.decay_score = score
            if score < self.config.archive_threshold and ARCHIVED_LABEL not in node.labels:
                node.labels.append(ARCHIVED_LABEL)
                archived += 1
                changed = True
            if changed:
                self.storage.update_node(node)
            scored += 1
        self.stats.recalculations += 1
        self.stats.nodes_scored += scored
        self.stats.archived += archived
        return scored, archived

    def archived_nodes(self) -> list[Node]:
        return self.storage.get_nodes_by_label(ARCHIVED_LABEL)

    # -- lifecycle -----------------------------------------------------------------
    def start(self) -> None:
        """(ref: Start decay.go:643 — ticker loop)"""
        self._stopped = False
        self._schedule()

    def _schedule(self) -> None:
        if self._stopped:
            return
        self._timer = threading.Timer(self.config.interval, self._tick)
        self._timer.daemon = True
        self._timer.start()

    def _tick(self) -> None:
        try:
            self.recalculate_all()
        except Exception:
            # the periodic timer must survive a bad pass, but silently
            # eating it hid real storage failures from operators
            logger.exception("periodic decay recalculation failed")
        self._schedule()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
