"""Memory decay (ref: /root/reference/pkg/decay/)."""

from nornicdb_tpu.decay.decay import (
    ARCHIVED_LABEL,
    HALF_LIVES,
    DecayConfig,
    DecayManager,
    DecayStats,
    half_life,
)

__all__ = [
    "ARCHIVED_LABEL", "HALF_LIVES", "DecayConfig", "DecayManager",
    "DecayStats", "half_life",
]
