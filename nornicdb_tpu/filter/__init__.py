"""Kalman filter library (ref: /root/reference/pkg/filter/)."""

from nornicdb_tpu.filter.kalman import (
    CO_ACCESS,
    DECAY_PREDICTION,
    LATENCY,
    AdaptiveKalman,
    Kalman,
    KalmanConfig,
    VelocityKalman,
    process_if_enabled,
)

__all__ = [
    "CO_ACCESS", "DECAY_PREDICTION", "LATENCY", "AdaptiveKalman",
    "Kalman", "KalmanConfig", "VelocityKalman", "process_if_enabled",
]
