"""1-D Kalman filter library: basic, velocity-state and adaptive-R variants.

Behavioral reference: /root/reference/pkg/filter/kalman.go:122 (Kalman),
preset configs :56-107, Process/Predict/PredictWithUncertainty :366-435,
kalman_velocity.go, kalman_adaptive.go. Feature-flag gating mirrors
ProcessIfEnabled.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class KalmanConfig:
    """(ref: configs kalman.go:56-107)"""

    process_noise: float = 1e-3  # Q
    measurement_noise: float = 1e-1  # R
    initial_estimate: float = 0.0
    initial_uncertainty: float = 1.0


# Presets (ref: kalman.go preset constructors)
DECAY_PREDICTION = KalmanConfig(process_noise=1e-4, measurement_noise=5e-2)
CO_ACCESS = KalmanConfig(process_noise=1e-3, measurement_noise=1e-1)
LATENCY = KalmanConfig(process_noise=1e-2, measurement_noise=2e-1)


class Kalman:
    """Scalar Kalman filter (ref: filter.Kalman kalman.go:122)."""

    def __init__(self, config: Optional[KalmanConfig] = None):
        self.config = config or KalmanConfig()
        self.estimate = self.config.initial_estimate
        self.uncertainty = self.config.initial_uncertainty
        self.initialized = False
        self.updates = 0

    def process(self, measurement: float) -> float:
        """Predict + update with one measurement (ref: Process :366)."""
        if not self.initialized:
            self.estimate = measurement
            self.uncertainty = self.config.measurement_noise
            self.initialized = True
            self.updates = 1
            return self.estimate
        # predict
        self.uncertainty += self.config.process_noise
        # update
        gain = self.uncertainty / (self.uncertainty + self.config.measurement_noise)
        self.estimate += gain * (measurement - self.estimate)
        self.uncertainty *= 1.0 - gain
        self.updates += 1
        return self.estimate

    def predict(self) -> float:
        return self.estimate

    def predict_with_uncertainty(self) -> tuple[float, float]:
        """(ref: PredictWithUncertainty :435)"""
        return self.estimate, math.sqrt(
            max(self.uncertainty + self.config.process_noise, 0.0)
        )

    def reset(self) -> None:
        self.estimate = self.config.initial_estimate
        self.uncertainty = self.config.initial_uncertainty
        self.initialized = False
        self.updates = 0


class VelocityKalman:
    """Position+velocity state filter for trend tracking
    (ref: kalman_velocity.go)."""

    def __init__(self, config: Optional[KalmanConfig] = None):
        self.config = config or KalmanConfig()
        self.position = 0.0
        self.velocity = 0.0
        # covariance matrix [p00 p01; p10 p11]
        u = self.config.initial_uncertainty
        self.p = [[u, 0.0], [0.0, u]]
        self.initialized = False
        self._last_t: Optional[float] = None

    def process(self, measurement: float, t: float) -> float:
        if not self.initialized:
            self.position = measurement
            self.initialized = True
            self._last_t = t
            return self.position
        dt = max(t - (self._last_t or t), 1e-9)
        self._last_t = t
        q, r = self.config.process_noise, self.config.measurement_noise
        # predict
        self.position += self.velocity * dt
        p = self.p
        p00 = p[0][0] + dt * (p[1][0] + p[0][1]) + dt * dt * p[1][1] + q
        p01 = p[0][1] + dt * p[1][1]
        p10 = p[1][0] + dt * p[1][1]
        p11 = p[1][1] + q
        # update
        s = p00 + r
        k0 = p00 / s
        k1 = p10 / s
        resid = measurement - self.position
        self.position += k0 * resid
        self.velocity += k1 * resid
        self.p = [
            [(1 - k0) * p00, (1 - k0) * p01],
            [p10 - k1 * p00, p11 - k1 * p01],
        ]
        return self.position

    def predict_at(self, t: float) -> float:
        if self._last_t is None:
            return self.position
        return self.position + self.velocity * (t - self._last_t)


class AdaptiveKalman(Kalman):
    """Adaptive measurement-noise variant: R tracks the innovation variance
    (ref: kalman_adaptive.go)."""

    def __init__(self, config: Optional[KalmanConfig] = None, alpha: float = 0.3):
        import dataclasses

        # private copy: this filter mutates measurement_noise, and shared
        # preset configs (DECAY_PREDICTION etc.) must not drift
        super().__init__(dataclasses.replace(config) if config else None)
        self.alpha = alpha

    def process(self, measurement: float) -> float:
        if self.initialized:
            innovation = measurement - self.estimate
            est_r = innovation * innovation - self.uncertainty
            if est_r > 0:
                self.config.measurement_noise = (
                    (1 - self.alpha) * self.config.measurement_noise
                    + self.alpha * est_r
                )
        return super().process(measurement)


def process_if_enabled(
    filt: Kalman, measurement: float, enabled: bool = True
) -> float:
    """(ref: ProcessIfEnabled — feature-flag-gated path)"""
    if not enabled:
        return measurement
    return filt.process(measurement)
