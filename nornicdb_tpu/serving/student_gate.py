"""Eval gate for the distilled production embedder.

The round-5 quality tables showed the distilled students
(models/bge_m3.BGE_DISTILL_*) recover most of the teacher's retrieval
quality at 4-8x less compute — but "most" is a measurement, not a
promise, per checkpoint.  This gate makes the speed/quality trade an
operator knob with a hard floor: a student is only admitted as the
production embedder when its retrieval MRR (eval.py harness) over an
eval suite meets ``ServingConfig.student_min_mrr``.  Below the floor the
config is REJECTED at startup (:class:`StudentGateError`) — the server
refuses to come up quietly degraded.

Suites are JSON ``{"docs": {id: text}, "cases": [{"query", "relevant"}]}``
(``student_eval_suite``); without one, a deterministic builtin suite of
topical documents exercises basic retrieval structure (any semantically
coherent embedder scores ~1.0; a random or collapsed one scores ~1/n).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from nornicdb_tpu.errors import StudentGateError
from nornicdb_tpu.eval import EvalCase, EvalReport, Harness

logger = logging.getLogger(__name__)

# deterministic topical vocabulary: each topic's docs share a core word
# set, queries re-use a disjoint slice of it, and topics don't overlap —
# retrieval structure, not memorized strings
_TOPICS = {
    "graph": ("graph database node edge traversal cypher index "
              "adjacency shortest path query engine"),
    "vector": ("vector embedding similarity cosine search corpus "
               "nearest neighbor ann recall dense retrieval"),
    "storage": ("storage engine wal append fsync snapshot segment "
                "compaction durability crash recovery log"),
    "replication": ("replication raft leader follower election quorum "
                    "append entries commit heartbeat term"),
    "serving": ("serving batch queue latency throughput admission "
                "deadline shed backpressure scheduler packed"),
    "device": ("device accelerator tpu backend probe degrade recover "
               "fallback hbm transfer upload lifecycle"),
    "auth": ("auth token jwt password login role permission session "
             "credential lockout security"),
    "telemetry": ("telemetry metrics histogram counter gauge trace span "
                  "prometheus exposition slow query capture"),
}


def builtin_eval_suite() -> tuple[dict[str, str], list[EvalCase]]:
    """(docs, cases): 3 docs per topic, one query per topic+doc pairing."""
    docs: dict[str, str] = {}
    cases: list[EvalCase] = []
    for topic, words in _TOPICS.items():
        w = words.split()
        ids = []
        for j in range(3):
            did = f"{topic}-{j}"
            # overlapping word windows keep intra-topic docs mutually
            # closer than any cross-topic pair
            docs[did] = " ".join(w[j : j + 8])
            ids.append(did)
        cases.append(EvalCase(query=" ".join(w[2:7]), relevant=ids))
        cases.append(EvalCase(query=" ".join(w[4:9]), relevant=ids))
    return docs, cases


def load_eval_suite(path: str) -> tuple[dict[str, str], list[EvalCase]]:
    """JSON suite with its own doc corpus (the eval.py harness format
    plus a ``docs`` map, since the gate indexes from scratch)."""
    with open(path) as f:
        data = json.load(f)
    docs = {str(k): str(v) for k, v in data["docs"].items()}
    cases = [
        EvalCase(c["query"], [str(r) for r in c["relevant"]])
        for c in data["cases"]
    ]
    return docs, cases


def evaluate_embedder(
    embedder, docs: dict[str, str], cases: list[EvalCase], k: int = 10
) -> EvalReport:
    """Embed the suite's docs with ``embedder``, brute-force cosine
    retrieval, and score with the eval.py harness."""
    ids = list(docs.keys())
    mat = np.stack(
        [np.asarray(v, np.float32) for v in embedder.embed_batch(
            [docs[i] for i in ids]
        )]
    )
    norms = np.linalg.norm(mat, axis=1, keepdims=True)
    mat = mat / np.maximum(norms, 1e-12)

    def search_fn(query: str, n: int) -> list[str]:
        q = np.asarray(embedder.embed(query), np.float32)
        qn = np.linalg.norm(q)
        q = q / max(qn, 1e-12)
        scores = mat @ q
        top = np.argsort(-scores)[:n]
        return [ids[i] for i in top]

    return Harness(search_fn, k=min(k, len(ids))).run(cases)


def gate_student(
    embedder, min_mrr: float, suite_path: str = ""
) -> EvalReport:
    """Admit ``embedder`` as the production embedder only if its eval MRR
    clears ``min_mrr``; raise :class:`StudentGateError` otherwise."""
    docs, cases = (
        load_eval_suite(suite_path) if suite_path else builtin_eval_suite()
    )
    report = evaluate_embedder(embedder, docs, cases)
    mrr = report.metrics.mrr
    if mrr < min_mrr:
        raise StudentGateError(
            f"distilled student {embedder.model()!r} rejected: eval MRR "
            f"{mrr:.4f} < required {min_mrr:.4f} "
            f"({len(docs)} docs, {len(cases)} queries"
            f"{', suite ' + suite_path if suite_path else ', builtin suite'}"
            "). Fix: retrain/re-distill the student, lower "
            "serving.student_min_mrr, or set serving.embedder=full."
        )
    logger.info(
        "student embedder %s admitted: eval MRR %.4f >= %.4f",
        embedder.model(), mrr, min_mrr,
    )
    return report
