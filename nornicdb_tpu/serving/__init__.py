"""nornicdb_tpu.serving — continuous ragged batching for embed + search.

The subsystem that owns the production request path (ROADMAP item 3):

* :class:`ServingEngine` — continuous batching engine; an
  :class:`~nornicdb_tpu.embed.base.Embedder` wrapper with admission
  control, deadline shedding, ragged token packing, and double-buffered
  host staging (serving/engine.py).
* :class:`RaggedPacker` / :class:`PackedBatch` — token-concatenated
  variable-length packing over static shape classes (serving/ragged.py).
* :func:`gate_student` — the eval-gated distilled-embedder admission
  check (serving/student_gate.py).
* :mod:`~nornicdb_tpu.serving.stats` — the metric families in the tested
  docs/observability.md catalog.

See docs/operations.md "Embed serving tuning" for the knobs
(``ServingConfig`` / ``NORNICDB_SERVING_*``).
"""

from nornicdb_tpu.serving.engine import EngineStats, ServingEngine
from nornicdb_tpu.serving.ragged import (
    CAPACITY_CLASSES,
    PackedBatch,
    RaggedPacker,
    unpack_results,
)
from nornicdb_tpu.serving.student_gate import (
    builtin_eval_suite,
    evaluate_embedder,
    gate_student,
    load_eval_suite,
)

__all__ = [
    "CAPACITY_CLASSES",
    "EngineStats",
    "PackedBatch",
    "RaggedPacker",
    "ServingEngine",
    "builtin_eval_suite",
    "evaluate_embedder",
    "gate_student",
    "load_eval_suite",
    "unpack_results",
]
