"""Serving-engine metric families.

Registered at import time (idempotent by-name resolution, same pattern as
search/service.py) so the docs/observability.md catalog — a tested
contract — renders these families in every process that serves traffic,
whether or not a ServingEngine was ever constructed.  server/http.py
imports this module for exactly that reason.
"""

from __future__ import annotations

from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

# real (non-padding) tokens per packed device batch: the throughput axis
# the ragged scheduler optimizes — compare against PACK_EFFICIENCY to see
# whether small batches come from low load or from a tight token budget
PACKED_TOKENS_HIST = _REGISTRY.histogram(
    "nornicdb_serving_packed_tokens",
    "Real (non-padding) tokens per ragged-packed embed batch",
    buckets=(64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384),
)
# real tokens / (rows * capacity) per pack: 1.0 = zero padding. The
# padded-bucket path this engine replaces sits at ~0.2-0.5 on mixed text.
PACK_EFFICIENCY_HIST = _REGISTRY.histogram(
    "nornicdb_serving_pack_efficiency",
    "Real-token fraction of each packed batch's (rows x capacity) grid",
    buckets=(0.25, 0.5, 0.625, 0.75, 0.875, 0.9375, 1.0),
)
# admission-control sheds by path (embed engine vs search batcher) and
# reason (queue_full at submit, deadline at/after dispatch,
# predicted_deadline = the cost model shed it at submit)
SHEDS = _REGISTRY.counter(
    "nornicdb_serving_sheds_total",
    "Requests shed by serving admission control",
    labels=("path", "reason"),
)
for _path in ("embed", "search"):
    for _reason in ("queue_full", "deadline", "predicted_deadline"):
        SHEDS.labels(_path, _reason)  # eager cells: render at 0
# host-staging overlap: fraction of tokenize+pack wall time that ran
# while the device was busy with the previous batch (WindVE-style
# double buffering; ~0 means staging serializes with compute)
STAGING_OVERLAP = _REGISTRY.gauge(
    "nornicdb_serving_staging_overlap_ratio",
    "Fraction of host staging time overlapped with device compute",
)
# which production embedder is serving (one-hot; set by cli serve after
# the student passes its eval gate, or by ServingEngine construction)
EMBEDDER_GAUGE = _REGISTRY.gauge(
    "nornicdb_serving_embedder",
    "Selected production embedder (one-hot by model)",
    labels=("model",),
)
_EMBEDDER_CELLS = {m: EMBEDDER_GAUGE.labels(m) for m in ("full", "student")}
QUEUE_DEPTH = _REGISTRY.gauge(
    "nornicdb_serving_queue_depth",
    "Embed texts currently queued in the continuous batching engine",
)
QUEUE_TOKENS = _REGISTRY.gauge(
    "nornicdb_serving_queue_tokens",
    "Tokens currently queued in the continuous batching engine",
)
BATCHES = _REGISTRY.counter(
    "nornicdb_serving_batches_total",
    "Packed device batches dispatched by the serving engine",
)
# embed-queue retry visibility (satellite: retries/fallbacks previously
# vanished into logs) — resolved by embed/queue.py at use sites too
EMBED_RETRIES = _REGISTRY.counter(
    "nornicdb_embed_retries_total",
    "EmbedWorker embed_batch attempts that failed and were retried",
)


def set_embedder_selection(model: str) -> None:
    """One-hot the production-embedder gauge (``full`` or ``student``)."""
    for name, cell in _EMBEDDER_CELLS.items():
        cell.set(1.0 if name == model else 0.0)
