"""Continuous ragged batching engine for the embed request path.

The serving path this replaces ran the full model synchronously per
request batch, padded to fixed-shape buckets — the reason the embed north
star (BASELINE.json, >=10k emb/s/chip) was missed ~11x.  This engine owns
the path end to end:

* **Continuous batching.**  Callers (HTTP /nornicdb/embed, the search
  service's query embed, EmbedWorker's background drains) enqueue texts;
  a scheduler packs whatever is queued — across requests — into ragged
  token-packed grids (serving/ragged.py) and dispatches ONE segment-masked
  forward per pack (models/bge_m3.forward_packed).  Compute scales with
  real tokens, not bucket padding.
* **Admission control.**  Bounded queue (texts + tokens); a full queue
  sheds at submit with :class:`ResourceExhausted`, surfaced as HTTP 429 /
  gRPC RESOURCE_EXHAUSTED / Bolt transient failure at the edges.  Batch
  sizing is queue-depth-aware: a deep queue dispatches full token budgets
  immediately, a shallow one waits ``batch_wait_ms`` for companions.
* **Deadline shedding.**  Requests carry a deadline; expired work is shed
  at dispatch time and waiting callers give up at the deadline — under a
  hung accelerator the backend manager (PR 6) bounds the device path and
  the deadline bounds everything else, so no request blocks indefinitely.
* **Double-buffered host staging** (WindVE's CPU<->accelerator queue
  decoupling, PAPERS.md): a staging thread tokenizes + packs batch N+1
  while the compute thread runs batch N — XLA execution releases the GIL,
  so host staging genuinely overlaps device compute.  The overlap ratio
  is exported as a gauge.

The engine IS an :class:`~nornicdb_tpu.embed.base.Embedder`: drop it
around any inner embedder (``CachedEmbedder(ServingEngine(TPUEmbedder()))``)
and every existing consumer batches continuously.  Inner embedders
without a packed path (HashEmbedder, HTTP embedders) still get the queue,
admission control, and cross-request batching via one ``embed_batch``
call per drained batch.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from nornicdb_tpu.embed.base import Embedder
from nornicdb_tpu.errors import ClosedError, ResourceExhausted
from nornicdb_tpu.serving import stats as _stats
from nornicdb_tpu.serving.ragged import RaggedPacker, unpack_results
from nornicdb_tpu.telemetry import budget as _budget
from nornicdb_tpu.telemetry import costmodel as _costmodel
from nornicdb_tpu.telemetry import deviceprof as _deviceprof
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

logger = logging.getLogger(__name__)


@dataclass
class _Request:
    """One embed_batch call in flight: completes when every text lands."""

    results: list
    remaining: int
    event: threading.Event = field(default_factory=threading.Event)
    error: Optional[Exception] = None
    deadline: float = 0.0  # monotonic; 0 = none
    shed: bool = False     # terminally shed (dispatcher must skip)
    ctx: object = None     # caller's trace span (cross-thread hand-off)
    enqueued: float = 0.0  # perf_counter at submit (queue-wait span)
    queue_wait_recorded: bool = False  # once per request, not per batch


@dataclass
class _Item:
    """One text of a request, the packing granularity."""

    text: str
    req: _Request
    idx: int            # position in the request's results
    est_tokens: int     # admission accounting (cheap, pre-tokenize)
    seq: Optional[list[int]] = None  # real tokens, staged lazily


@dataclass
class EngineStats:
    batches: int = 0
    packed_batches: int = 0
    texts: int = 0
    tokens: int = 0
    padded_tokens: int = 0
    sheds_queue_full: int = 0
    sheds_deadline: int = 0
    sheds_predicted: int = 0
    staging_seconds: float = 0.0
    overlap_seconds: float = 0.0
    device_seconds: float = 0.0

    def as_dict(self) -> dict:
        eff = (
            self.tokens / self.padded_tokens if self.padded_tokens else 0.0
        )
        overlap = (
            self.overlap_seconds / self.staging_seconds
            if self.staging_seconds else 0.0
        )
        return {
            "batches": self.batches,
            "packed_batches": self.packed_batches,
            "texts": self.texts,
            "tokens": self.tokens,
            "pack_efficiency": round(eff, 4),
            "sheds_queue_full": self.sheds_queue_full,
            "sheds_deadline": self.sheds_deadline,
            "sheds_predicted": self.sheds_predicted,
            "staging_overlap_ratio": round(overlap, 4),
            "device_seconds": round(self.device_seconds, 4),
        }


class ServingEngine(Embedder):
    """Continuous batching front for an inner embedder.

    Thread model: caller threads do admission + a cheap length estimate
    and block on their request event; the staging thread tokenizes and
    packs; the compute thread dispatches packs.  No engine lock is ever
    held across tokenization or a device op (NL-DEV01 — the inner
    embedder gates the device through the backend manager itself).
    """

    def __init__(self, inner: Embedder, config=None):
        if config is None:
            from nornicdb_tpu.config import AppConfig, load_from_env

            config = load_from_env(AppConfig()).serving
        self.inner = inner
        self.config = config
        self.stats = EngineStats()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque[_Item] = deque()
        self._queued_texts = 0
        self._queued_tokens = 0
        self._staged: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(config.staging_depth))
        )
        self._stop = threading.Event()
        self._started = False
        self._device_busy = False
        self._threads: list[threading.Thread] = []
        # ragged path needs a packed forward + a tokenizer on the inner
        # embedder; anything else still gets continuous batching through
        # plain embed_batch calls
        tok = getattr(inner, "tokenizer", None)
        self._tokenizer = tok if hasattr(tok, "encode") else None
        self._packer: Optional[RaggedPacker] = None
        if self._tokenizer is not None and hasattr(inner, "embed_packed"):
            cfg = getattr(inner, "cfg", None)
            self._packer = RaggedPacker(
                pad_id=self._tokenizer.pad_id,
                pad_token_id=getattr(cfg, "pad_token_id", 1),
                max_len=getattr(inner, "max_len", 512),
                max_rows=max(1, int(config.max_rows)),
                max_cells=max(64, int(config.max_batch_tokens) // 2),
            )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        for name, fn in (
            ("nornicdb-serving-stage", self._staging_loop),
            ("nornicdb-serving-compute", self._compute_loop),
        ):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        """Stop the pipeline; queued and staged requests fail fast with
        ClosedError rather than stranding their callers."""
        self._stop.set()
        with self._cond:
            items = list(self._queue)
            self._queue.clear()
            self._queued_texts = 0
            self._queued_tokens = 0
            self._cond.notify_all()
        for item in items:
            self._fail(item.req, ClosedError("serving engine stopped"))
        while True:
            try:
                _, items = self._staged.get_nowait()
            except queue_mod.Empty:
                break
            for item in items:
                self._fail(item.req, ClosedError("serving engine stopped"))
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    @property
    def running(self) -> bool:
        return any(t.is_alive() for t in self._threads)

    # -- Embedder surface --------------------------------------------------
    def dimensions(self) -> int:
        return self.inner.dimensions()

    def model(self) -> str:
        return self.inner.model()

    def embed_batch(self, texts: Sequence[str]) -> list[np.ndarray]:
        if not texts:
            return []
        if self._stop.is_set():
            raise ClosedError("serving engine stopped")
        self.start()
        cfg = self.config
        est = [len(t.split()) + 2 for t in texts]
        req = _Request(results=[None] * len(texts), remaining=len(texts))
        # worker-hop trace propagation (the QueryBatcher pattern): the
        # compute thread attaches this to record serving.batch and the
        # retroactive queue-wait span in the CALLER's trace
        req.ctx = _tracer.capture()
        req.enqueued = time.perf_counter()
        if cfg.deadline_ms > 0:
            req.deadline = time.monotonic() + cfg.deadline_ms / 1000.0
        with self._cond:
            # an empty queue always admits (a single oversized request
            # must serve, just in several packs); a non-empty one sheds
            # anything that would push the bounds past their limits
            if self._queued_texts > 0 and (
                self._queued_texts + len(texts) > cfg.max_queue
                or self._queued_tokens + sum(est) > cfg.max_queue_tokens
            ):
                self.stats.sheds_queue_full += 1
                _stats.SHEDS.labels("embed", "queue_full").inc()
                raise ResourceExhausted(
                    f"embed queue full ({self._queued_texts} texts / "
                    f"{self._queued_tokens} tokens queued); retry with "
                    "backoff", reason="queue_full",
                )
            if req.deadline:
                # predictive admission: the learned per-token cost of
                # the queued backlog plus this request, conservatively
                # scaled, must fit the deadline — shed at SUBMIT instead
                # of after the queue burns device time (fails open while
                # the cost model is cold)
                decision = _costmodel.COST_MODEL.decide(
                    "embed", "serving", "embed", units=sum(est),
                    slack_s=cfg.deadline_ms / 1000.0,
                    units_ahead=self._queued_tokens,
                )
                if not decision.admit:
                    self.stats.sheds_predicted += 1
                    _stats.SHEDS.labels("embed", "predicted_deadline").inc()
                    raise ResourceExhausted(
                        f"predicted completion "
                        f"{decision.predicted_s * 1e3:.0f}ms exceeds the "
                        f"{cfg.deadline_ms:.0f}ms deadline budget; retry "
                        "with backoff", reason="predicted_deadline",
                    )
                _budget.open_budget(
                    _tracer.current_trace_id(), "embed",
                    cfg.deadline_ms / 1000.0,
                    {"device_sync": decision.predicted_s},
                )
            for i, t in enumerate(texts):
                self._queue.append(_Item(t, req, i, est[i]))
            self._queued_texts += len(texts)
            self._queued_tokens += sum(est)
            _stats.QUEUE_DEPTH.set(self._queued_texts)
            _stats.QUEUE_TOKENS.set(self._queued_tokens)
            self._cond.notify_all()
        self._await(req)
        _costmodel.record_latency(
            "embed", time.perf_counter() - req.enqueued)
        if req.error is not None:
            raise req.error
        return list(req.results)

    def _await(self, req: _Request) -> None:
        """Bounded wait: give up at the request deadline (plus a grace for
        an in-flight dispatch — the device path itself is bounded by the
        backend manager's acquire timeout), never block indefinitely."""
        grace = 1.0
        while True:
            timeout = 1.0
            if req.deadline:
                timeout = min(
                    1.0, max(0.01, req.deadline + grace - time.monotonic())
                )
            if self._stop.is_set():
                timeout = min(timeout, 0.05)
            if req.event.wait(timeout=timeout):
                return
            if self._stop.is_set() and not self.running:
                req.error = ClosedError("serving engine stopped")
                return
            if req.deadline and time.monotonic() > req.deadline + grace:
                # dispatcher may still be running this batch; mark the
                # request shed so a late result is discarded quietly
                req.shed = True
                req.error = ResourceExhausted(
                    "embed deadline exceeded", reason="deadline"
                )
                self.stats.sheds_deadline += 1
                _stats.SHEDS.labels("embed", "deadline").inc()
                return

    # -- pipeline ----------------------------------------------------------
    def _fail(self, req: _Request, err: Exception) -> None:
        req.error = err
        req.event.set()

    def _shed_expired(self, now: float) -> None:
        """Drop queued items whose request deadline already passed (called
        under the lock)."""
        if not self._queue:
            return
        keep: deque[_Item] = deque()
        for item in self._queue:
            if item.req.deadline and now > item.req.deadline:
                if not item.req.shed:
                    item.req.shed = True
                    self.stats.sheds_deadline += 1
                    _stats.SHEDS.labels("embed", "deadline").inc()
                    self._fail(item.req, ResourceExhausted(
                        "embed deadline exceeded before dispatch",
                        reason="deadline",
                    ))
                self._queued_texts -= 1
                self._queued_tokens -= item.est_tokens
            else:
                keep.append(item)
        self._queue = keep
        # keep the depth gauges live even when shedding empties the
        # queue (no _take_batch follows to refresh them)
        _stats.QUEUE_DEPTH.set(self._queued_texts)
        _stats.QUEUE_TOKENS.set(self._queued_tokens)

    def _staging_loop(self) -> None:
        cfg = self.config
        window = max(0.0, cfg.batch_wait_ms / 1000.0)
        while not self._stop.is_set():
            with self._cond:
                while not self._queue and not self._stop.is_set():
                    self._cond.wait(0.5)
                if self._stop.is_set():
                    return
                self._shed_expired(time.monotonic())
                if not self._queue:
                    continue
                # queue-depth-aware sizing: dispatch now when a full token
                # budget is queued, else linger up to the batch window so
                # low-traffic requests pick up companions
                if self._queued_tokens < cfg.max_batch_tokens and window:
                    self._cond.wait(window)
                    self._shed_expired(time.monotonic())
                    if not self._queue:
                        continue
                # bounded snapshot of the FIFO head for tokenization
                # OUTSIDE the lock (the staging thread is the only
                # writer of item.seq; shed items are simply wasted work)
                scan = []
                for item in self._queue:
                    scan.append(item)
                    if len(scan) >= 4096:
                        break
            t0 = time.perf_counter()
            busy0 = self._device_busy
            scanned = 0
            scan_budget = max(64, int(cfg.max_batch_tokens)) * 2
            for item in scan:
                if item.seq is None and self._packer is not None:
                    item.seq = (
                        self._tokenizer.encode(
                            item.text, max_len=self._packer.max_len
                        )
                        or [self._tokenizer.pad_id]
                    )
                scanned += len(item.seq) if item.seq is not None else 1
                if scanned >= scan_budget:
                    break
            with self._cond:
                items, cap = self._take_batch()
                _stats.QUEUE_DEPTH.set(self._queued_texts)
                _stats.QUEUE_TOKENS.set(self._queued_tokens)
            if not items:
                continue
            try:
                pack = self._build_pack(items, cap)
            except Exception as e:
                logger.exception("serving pack build failed")
                for item in items:
                    self._fail(item.req, e)
                continue
            t1 = time.perf_counter()
            busy1 = self._device_busy
            # staging time covers tokenize + plan + pack — the full host
            # cost the overlap gauge claims to measure
            self.stats.staging_seconds += t1 - t0
            self.stats.overlap_seconds += (t1 - t0) * (busy0 + busy1) / 2.0
            if self.stats.staging_seconds > 0:
                _stats.STAGING_OVERLAP.set(
                    self.stats.overlap_seconds / self.stats.staging_seconds
                )
            while not self._stop.is_set():
                try:
                    # bounded put: the staging queue depth IS the double
                    # buffer — staging blocks here (not on the device)
                    # when compute falls behind
                    self._staged.put((pack, items), timeout=0.5)
                    break
                except queue_mod.Full:
                    continue
            else:
                for item in items:
                    self._fail(item.req, ClosedError("serving engine stopped"))

    def _take_batch(self) -> tuple[list[_Item], int]:
        """Pop the next pack's worth of items (called under the lock).
        Returns (items, planned_capacity); capacity 0 = unpacked path."""
        cfg = self.config
        cap = 0
        if self._packer is None:
            take = min(len(self._queue), 1024)
            items = [self._queue.popleft() for _ in range(take)]
        else:
            # class-segregated packing: the head-of-line item's capacity
            # class defines this pack's attention width, and only texts
            # that fit it ride along — short texts never pay a long
            # text's C^2 attention (longer texts head their own later
            # pack; deadline shedding bounds any wait). Tokenization
            # happened OUTSIDE the lock in the staging loop; the first
            # untokenized item marks the scan boundary.
            budget = max(64, int(cfg.max_batch_tokens))
            scan_budget = budget * 2
            eligible: list[_Item] = []
            total = scanned = 0
            for item in self._queue:
                if item.seq is None:
                    break  # beyond the pre-tokenized window
                n = len(item.seq)
                if cap == 0:
                    # short heads (<=32 tok) target ~2x their length so
                    # rows tile 2+ texts; longer heads take their own
                    # class — doubling C for them buys little fill but
                    # pays C^2 attention (a 50-token text 1-per-64-row
                    # beats 2-per-128-row on measured cells/s)
                    cap = self._packer.capacity_for(
                        min(2 * n, self._packer.max_len) if n <= 32 else n
                    )
                scanned += n
                # class band: texts shorter than cap/8 wait for a
                # narrower pack instead of paying this pack's C^2
                # attention (the head itself is always admitted, so
                # every text is eligible for the pack it heads)
                if cap // 8 <= n <= cap or not eligible:
                    eligible.append(item)
                    total += n
                    if total >= budget:
                        break
                if scanned >= scan_budget:
                    break
            take, _, _ = self._packer.plan(
                [len(i.seq) for i in eligible],
                budget_tokens=budget,
                capacity=cap,
            )
            chosen = set(id(i) for i in eligible[:take])
            items = [i for i in self._queue if id(i) in chosen]
            self._queue = deque(
                i for i in self._queue if id(i) not in chosen
            )
        for item in items:
            self._queued_texts -= 1
            self._queued_tokens -= item.est_tokens
        return items, cap

    def _build_pack(self, items: list[_Item], capacity: int = 0):
        if self._packer is None:
            return None
        return self._packer.pack(
            [i.seq for i in items], capacity=capacity
        )

    def _compute_loop(self) -> None:
        while not self._stop.is_set():
            try:
                pack, items = self._staged.get(timeout=0.5)
            except queue_mod.Empty:
                continue
            self._device_busy = True
            t0 = time.perf_counter()
            # per-caller queue wait recorded retroactively into EACH
            # batched request's trace; the device span attaches to the
            # batch leader's (the QueryBatcher convention)
            reqs = []
            seen_req_ids = set()
            for item in items:
                if id(item.req) not in seen_req_ids:
                    seen_req_ids.add(id(item.req))
                    reqs.append(item.req)
            for req in reqs:
                # once per REQUEST: a request split across several fused
                # batches must not re-record queue wait spanning earlier
                # batches' device compute
                if req.ctx is not None and not req.queue_wait_recorded:
                    req.queue_wait_recorded = True
                    _tracer.add_span("serving.queue_wait", req.enqueued,
                                     t0, parent=req.ctx)
            leader_ctx = next(
                (r.ctx for r in reqs if r.ctx is not None), None)
            try:
                with _tracer.attach(leader_ctx), _tracer.span(
                    "serving.batch", {"texts": len(items)}
                ):
                    if pack is not None:
                        emb = self.inner.embed_packed(pack)
                        vecs = unpack_results(pack, emb)
                    else:
                        vecs = self.inner.embed_batch(
                            [i.text for i in items]
                        )
            except Exception as e:
                self._device_busy = False
                for item in items:
                    self._fail(item.req, e)
                continue
            self._device_busy = False
            dt = time.perf_counter() - t0
            # the embed path joins the deviceprof ledger (and with it
            # the cost model) keyed by packed-token pow2 class
            tokens = (pack.tokens if pack is not None
                      else sum(i.est_tokens for i in items))
            _deviceprof.record_execute(
                "serving", "embed",
                _deviceprof.pow2_class(max(tokens, 1), "t"), dt)
            self.stats.device_seconds += dt
            self.stats.batches += 1
            self.stats.texts += len(items)
            _stats.BATCHES.inc()
            if pack is not None:
                self.stats.packed_batches += 1
                self.stats.tokens += pack.tokens
                r, c = pack.ids.shape
                self.stats.padded_tokens += r * c
                _stats.PACKED_TOKENS_HIST.observe(pack.tokens)
                _stats.PACK_EFFICIENCY_HIST.observe(pack.efficiency)
            for item, vec in zip(items, vecs):
                req = item.req
                req.results[item.idx] = vec
                req.remaining -= 1
                if req.remaining <= 0 and not req.shed:
                    req.event.set()

    # -- observability -----------------------------------------------------
    def stats_snapshot(self) -> dict:
        out = self.stats.as_dict()
        with self._lock:
            out["queue_texts"] = self._queued_texts
            out["queue_tokens"] = self._queued_tokens
        out["ragged"] = self._packer is not None
        out["model"] = self.inner.model()
        if self._packer is not None:
            out["capacity_classes"] = list(self._packer.capacities)
        shapes = getattr(self.inner, "packed_shapes", None)
        if shapes:
            out["packed_programs"] = sorted(shapes)
        return out
