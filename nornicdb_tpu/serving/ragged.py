"""Ragged token packing for the continuous batching engine.

Replaces pad-to-bucket embedding batches (TPUEmbedder.embed_batch: every
text padded to a power-of-two length bucket, batches padded to batch
classes) with token-concatenated packed grids: variable-length token
sequences share rows of an (R, C) buffer, delimited by segment ids, and
one segment-masked forward (models/bge_m3.forward_packed) embeds them all
— compute scales with real tokens, not padded shapes (Ragged Paged
Attention, PAPERS.md, is the TPU kernel shape this feeds).

Recompile discipline (NL-JAX03): packs are quantized to a small static
shape-class grid — capacity C from CAPACITY_CLASSES, row count R a power
of two chosen from the queued work (packing fills rows, so R padding
never ships empty rows), CLS-gather width a power of two.  The jit cache
is bounded by |R classes| x |C classes| x |S classes| and in steady state
a workload touches a handful of entries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

# row capacities (token columns). The smallest class keeps attention
# width — the packed path's only FLOP overhead vs per-request — tight for
# short-text traffic; the largest is clamped to the embedder's max_len.
CAPACITY_CLASSES = (32, 64, 128, 256, 512)
# packed rows per dispatch: quantized to ROW_CLASSES up to this
# (engine-configurable)
MAX_ROWS = 16
# row-count classes: powers of two plus 1.5x intermediates — remainders
# after a big pack land in a near-fitting class instead of cascading
# through tiny power-of-two tails (compile count stays bounded)
ROW_CLASSES = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)


def _rows_at_most(n: int) -> int:
    best = 1
    for r in ROW_CLASSES:
        if r <= n:
            best = r
    return best


def _pow2_at_most(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _rows_at_least(n: int) -> int:
    for r in ROW_CLASSES:
        if r >= n:
            return r
    return ROW_CLASSES[-1]


@dataclass
class PackedBatch:
    """One device dispatch worth of token-packed texts.

    Arrays are the forward_packed operands; ``order`` maps segment slot s
    (0-based, segment id s+1) back to the caller's sequence index.
    """

    ids: np.ndarray        # (R, C) int32, pad_id-filled
    seg: np.ndarray        # (R, C) int32, 0 = padding, 1..S = segments
    positions: np.ndarray  # (R, C) int32, XLM-R per-segment positions
    cls_rows: np.ndarray   # (S_cap,) int32 — segment-start rows
    cls_cols: np.ndarray   # (S_cap,) int32 — segment-start cols
    order: list[int] = field(default_factory=list)  # segment -> input index
    tokens: int = 0        # real tokens packed

    @property
    def n_segments(self) -> int:
        return len(self.order)

    @property
    def shape_class(self) -> tuple[int, int, int]:
        return (*self.ids.shape, len(self.cls_rows))

    @property
    def efficiency(self) -> float:
        r, c = self.ids.shape
        return self.tokens / float(r * c) if r * c else 0.0


class RaggedPacker:
    """Greedy first-fit-decreasing packer over static shape classes."""

    def __init__(
        self,
        pad_id: int,
        pad_token_id: int,
        max_len: int = 512,
        max_rows: int = MAX_ROWS,
        max_cells: int = 4096,
    ):
        self.pad_id = pad_id
        # position offset (XLM-R: positions start at pad_token_id + 1)
        self.pad_token_id = pad_token_id
        self.max_len = max_len
        self.max_rows = max(1, _rows_at_most(max_rows))
        # grid-area bound: attention memory/time scales R*C^2, so wide
        # capacities get proportionally fewer rows (a (64,128) grid runs
        # ~2x slower per cell than (32,128) on CPU XLA)
        self.max_cells = max(CAPACITY_CLASSES[0], max_cells)
        # classes <= max_len, PLUS max_len itself when the grid doesn't
        # reach it (trained/student checkpoints use max_len values like
        # max_positions - 8): without the final class, capacity_for()
        # would silently truncate 257..max_len-token texts that the
        # per-request path embeds in full — breaking equivalence
        caps = [c for c in CAPACITY_CLASSES if c <= max_len]
        if not caps or caps[-1] < max_len:
            caps.append(max_len)
        self.capacities = tuple(caps)

    def capacity_for(self, longest: int) -> int:
        for c in self.capacities:
            if longest <= c:
                return c
        return self.capacities[-1]

    def plan(
        self,
        lengths: Sequence[int],
        budget_tokens: int = 0,
        capacity: int = 0,
    ) -> tuple[int, int, int]:
        """(n_seqs_to_take, R, C) for the next pack over a FIFO prefix.

        Capacity defaults to the smallest class covering the prefix's
        longest sequence (callers may pin a wider one so rows tile
        several texts); rows quantize DOWN to a row class so packing
        fills them (leftover sequences wait for the next pack — two
        tight dispatches beat one half-empty grid)."""
        if not lengths:
            return 0, 1, capacity or self.capacities[0]
        c = capacity or self.capacity_for(max(lengths))
        # one-pass FIFO first-fit with a hard row cap: O(n * rows), no
        # re-simulation (an earlier trim-loop variant re-ran first-fit
        # per dropped item and dominated the schedule at depth)
        row_cap = min(self.max_rows, max(1, self.max_cells // c))
        free: list[int] = []
        take = 0
        total = 0
        for n in lengths:
            n = min(n, c)
            for i, f in enumerate(free):
                if f >= n:
                    free[i] -= n
                    break
            else:
                if len(free) >= row_cap:
                    break  # grid full: the rest is the next pack's work
                free.append(c - n)
            take += 1
            total += n
            if budget_tokens > 0 and total >= budget_tokens:
                break
        r = _rows_at_least(len(free))
        return take, r, c

    @staticmethod
    def _rows_needed(lengths: Sequence[int], capacity: int) -> int:
        """First-fit-decreasing row count for the given capacity."""
        free: list[int] = []
        for n in sorted(lengths, reverse=True):
            n = min(n, capacity)
            for i, f in enumerate(free):
                if f >= n:
                    free[i] -= n
                    break
            else:
                free.append(capacity - n)
        return len(free)

    def pack(
        self,
        seqs: Sequence[Sequence[int]],
        rows: int = 0,
        capacity: int = 0,
    ) -> PackedBatch:
        """Pack token sequences into one (R, C) grid.

        Sequences longer than the capacity class are truncated to it
        (callers tokenize with max_len <= the largest class, so this only
        guards foreign input).  Raises ValueError if the planned grid
        can't hold every sequence — plan() prevents that for its own
        prefixes."""
        if not seqs:
            raise ValueError("pack() needs at least one sequence")
        lengths = [len(s) for s in seqs]
        if not capacity:
            # smallest class covering the longest sequence; escalate when
            # the row cap binds (direct callers may pack more than one
            # planned prefix — the engine's plan() never hits this)
            capacity = self.capacity_for(max(lengths))
            while (
                self._rows_needed(lengths, capacity) > self.max_rows
                and capacity < self.capacities[-1]
            ):
                capacity = self.capacities[
                    self.capacities.index(capacity) + 1
                ]
        order = sorted(
            range(len(seqs)), key=lambda i: len(seqs[i]), reverse=True
        )
        r = rows or _rows_at_least(self._rows_needed(lengths, capacity))
        ids = np.full((r, capacity), self.pad_id, np.int32)
        seg = np.zeros((r, capacity), np.int32)
        positions = np.full((r, capacity), self.pad_token_id, np.int32)
        fill = [0] * r  # next free column per row
        cls_rows: list[int] = [0] * len(seqs)
        cls_cols: list[int] = [0] * len(seqs)
        seg_order: list[int] = []
        tokens = 0
        for seg_slot, idx in enumerate(order):
            s = list(seqs[idx])[:capacity]
            n = len(s)
            for row in range(r):
                if capacity - fill[row] >= n:
                    col = fill[row]
                    ids[row, col : col + n] = s
                    seg[row, col : col + n] = seg_slot + 1
                    positions[row, col : col + n] = (
                        np.arange(1, n + 1, dtype=np.int32)
                        + self.pad_token_id
                    )
                    cls_rows[seg_slot] = row
                    cls_cols[seg_slot] = col
                    fill[row] = col + n
                    tokens += n
                    break
            else:
                raise ValueError(
                    f"pack overflow: seq of {n} tokens does not fit "
                    f"{r}x{capacity} grid"
                )
            seg_order.append(idx)
        # CLS-gather width: power of two with a floor of 8 — merging the
        # tiny classes (1/2/4 segments) into one keeps the jit program
        # count down at a gather cost of a few unused rows (NL-JAX03)
        s_cap = max(8, _pow2_at_least(len(seqs)))
        pad = s_cap - len(seqs)
        return PackedBatch(
            ids=ids,
            seg=seg,
            positions=positions,
            cls_rows=np.asarray(cls_rows + [0] * pad, np.int32),
            cls_cols=np.asarray(cls_cols + [0] * pad, np.int32),
            order=seg_order,
            tokens=tokens,
        )


def unpack_results(
    packed: PackedBatch, embeddings: np.ndarray, n_inputs: Optional[int] = None
) -> list[np.ndarray]:
    """Scatter (S_cap, D) forward_packed output back to input order."""
    out: list[Optional[np.ndarray]] = [None] * (
        n_inputs if n_inputs is not None else len(packed.order)
    )
    for seg_slot, idx in enumerate(packed.order):
        out[idx] = np.asarray(embeddings[seg_slot], np.float32)
    return out  # type: ignore[return-value]
