"""Device mesh helpers.

The reference's distribution layer is a planned-only sharded vector index
(docs/architecture/clustering-roadmap.md, "Sharded ... Planned") plus a
host-side TCP transport (pkg/replication/transport.go). The TPU-native design
promotes the data plane to first-class XLA collectives over ICI: pick a Mesh,
annotate shardings, let XLA insert the collectives (scaling-book recipe).

Axis conventions used across the framework:
  "data"  — shards the corpus / batch dimension (vector search, DP training)
  "model" — shards model weights (TP)
  "seq"   — shards the sequence dimension (ring attention / context parallel)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """shard_map with replication checking off, across the jax API rename
    (check_rep in jax<0.7, check_vma after)."""
    try:
        from jax import shard_map as _sm
    except ImportError:  # jax < 0.5 exports it under experimental
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except TypeError:
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def make_mesh(
    axis_shapes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    backend=None,
) -> Mesh:
    """Build a Mesh. Default: all devices on one "data" axis.

    make_mesh({"data": 4, "model": 2}) lays an 8-device mesh as 4x2.

    Enumerating devices is a COLD backend acquisition (PJRT init on a
    fresh process), so the default goes through the backend lifecycle
    manager: bounded wait on its worker thread, DeviceUnavailable when
    the backend is degraded — never an unbounded hang on the caller.
    ``backend`` injects a specific BackendManager (tests).
    """
    if devices is not None:
        devs = list(devices)
    elif backend is not None:
        if not backend.await_ready():
            from nornicdb_tpu.errors import DeviceUnavailable

            raise DeviceUnavailable(
                f"backend {backend.state}: cannot enumerate mesh devices"
            )
        devs = list(jax.devices())
    else:
        from nornicdb_tpu import backend as _backend

        devs = list(_backend.devices())
    if not axis_shapes:
        axis_shapes = {"data": len(devs)}
    names = tuple(axis_shapes)
    shape = tuple(axis_shapes[n] for n in names)
    total = int(np.prod(shape))
    if total != len(devs):
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {len(devs)}")
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, names)


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Rows sharded across `axis`, features replicated."""
    return NamedSharding(mesh, P(axis, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_device_count() -> int:
    # gated: device enumeration is a cold backend acquisition
    from nornicdb_tpu import backend as _backend

    return len(_backend.devices())


def can_shard() -> bool:
    """True when a mesh data plane is worth building: more than one
    device is reachable through the backend manager.  Raises
    DeviceUnavailable (from the gated enumeration) while degraded — the
    caller decides whether to retry or pin single-device serving."""
    return local_device_count() > 1
