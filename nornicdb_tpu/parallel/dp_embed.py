"""Data-parallel embedding serving: shard the batch over a device mesh.

The embeddings north star (BASELINE.json >=10k emb/s/chip) is a per-chip
number; fleet throughput comes from DP over ICI. This wraps the bge-m3
forward in one jit'd program whose batch dim is sharded across the mesh's
`data` axis — XLA splits the batch per chip and all-gathers the (B, dims)
output, so serving scales linearly with chips without touching the model
code (scaling-book recipe: annotate shardings, let XLA place collectives).

Validated on the virtual CPU mesh by tests + __graft_entry__.dryrun
(multi-chip hardware is not available in this rig)."""

from __future__ import annotations

import jax
import numpy as np

from nornicdb_tpu.parallel.mesh import make_mesh


class DataParallelEmbedder:
    """Wrap a TPUEmbedder-compatible encoder for mesh-wide batches."""

    def __init__(self, embedder, n_devices: int = 0, devices=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.inner = embedder
        devs = list(devices) if devices is not None else jax.devices()
        if n_devices:
            devs = devs[:n_devices]
        self.mesh = make_mesh({"data": len(devs)}, devices=devs)
        self._data_sharding = NamedSharding(self.mesh, P("data"))
        self._replicated = NamedSharding(self.mesh, P())

        cfg = embedder.cfg

        def fwd(params, ids, mask):
            from nornicdb_tpu.models import bge_m3

            return bge_m3.forward(params, cfg, ids, mask)

        self._fwd = jax.jit(
            fwd,
            in_shardings=(self._replicated, self._data_sharding,
                          self._data_sharding),
            out_shardings=self._data_sharding,
        )

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def embed_batch(self, texts) -> list[np.ndarray]:
        """Tokenize with the inner embedder's bucketing policy, but run the
        forward sharded: batch pads to a multiple of the mesh size."""
        import jax.numpy as jnp

        if not texts:
            return []
        tok = self.inner.tokenizer
        seqs = [tok.encode(t, max_len=self.inner.max_len) or [tok.pad_id]
                for t in texts]
        blen = self.inner._bucket_len(max(len(s) for s in seqs))
        n = len(seqs)
        d = self.n_devices
        rows = ((n + d - 1) // d) * d
        ids = np.full((rows, blen), tok.pad_id, np.int32)
        mask = np.zeros((rows, blen), np.int32)
        for i, s in enumerate(seqs):
            ids[i, : len(s)] = s
            mask[i, : len(s)] = 1
        emb = self._fwd(self.inner.params, jnp.asarray(ids), jnp.asarray(mask))
        emb = np.asarray(emb, np.float32)
        return [emb[i] for i in range(n)]
