"""Distribution layer: mesh, sharded vector index, ring attention.

Two communication planes (SURVEY.md §5 "Distributed communication backend"):
  - device plane: XLA collectives over ICI, expressed inside jit'd programs
    (this package) — replaces the reference's *planned* shard layer;
  - host plane: WAL shipping / Raft / snapshots over DCN
    (nornicdb_tpu.replication) — mirrors pkg/replication/transport.go.
"""

from nornicdb_tpu.parallel.dp_embed import DataParallelEmbedder
from nornicdb_tpu.parallel.mesh import (
    can_shard,
    data_sharding,
    local_device_count,
    make_mesh,
    replicated,
)
from nornicdb_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
)
from nornicdb_tpu.parallel.sharded_index import ShardedCorpus, ShardStats

__all__ = [
    "DataParallelEmbedder",
    "can_shard",
    "data_sharding",
    "local_device_count",
    "make_mesh",
    "replicated",
    "make_ring_attention",
    "reference_attention",
    "ShardedCorpus",
    "ShardStats",
]
