"""Ring attention: exact attention over sequences sharded across chips.

The reference has no attention in its serving path (SURVEY.md §5
"long-context"), but this framework runs its embedding/assistant models on
TPU, and long-context is first-class: sequences shard over a "seq" mesh axis;
K/V blocks rotate around the ring via ppermute while each chip accumulates
flash-attention-style online softmax for its local Q block. Communication
overlaps with compute and total memory per chip is O(T/S).

Causal masking uses global position offsets so the sharded result matches
single-device attention exactly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nornicdb_tpu.parallel.mesh import shard_map_compat

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One (Tq x Tk) attention block with stable online-softmax stats.

    q: (B, Tq, H, Dh); k/v: (B, Tk, H, Dh); mask: (Tq, Tk) additive.
    Returns (numerator (B, Tq, H, Dh), row_max (B, H, Tq), row_sum (B, H, Tq)).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale + mask[None, None, :, :]
    m = jnp.max(s, axis=-1)  # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # noqa: E741
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def _ring_body(axis_name: str, n_blocks: int, causal: bool):
    def body(carry, step):
        k, v, o_acc, m_acc, l_acc, q, my_idx = carry
        # which shard's K/V block do we currently hold?
        src = (my_idx - step) % n_blocks
        tq = q.shape[1]
        tk = k.shape[1]
        if causal:
            q_pos = my_idx * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
            k_pos = src * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
            mask = jnp.where(k_pos <= q_pos, 0.0, NEG_INF).astype(jnp.float32)
        else:
            mask = jnp.zeros((tq, tk), jnp.float32)
        o, m, l = _block_attn(q, k, v, mask)  # noqa: E741
        # online-softmax merge of the new block into the accumulator
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)  # rescale old
        beta = jnp.exp(m - m_new)  # rescale new
        l_new = l_acc * alpha + l * beta
        o_new = (
            o_acc * jnp.moveaxis(alpha, 1, -1)[..., None]
            + o * jnp.moveaxis(beta, 1, -1)[..., None]
        )
        # rotate K/V to the next chip on the ICI ring
        perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return (k, v, o_new, m_new, l_new, q, my_idx), None

    return body


def make_ring_attention(
    mesh: Mesh, axis_name: str = "seq", causal: bool = True
):
    """Build a jit'd ring-attention callable for (B, T, H, Dh) inputs with T
    sharded over `axis_name`."""
    n_blocks = mesh.shape[axis_name]

    def local_fn(q, k, v):
        my_idx = jax.lax.axis_index(axis_name)
        b, tq, h, dh = q.shape
        o0 = jnp.zeros((b, tq, h, dh), jnp.float32)
        m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, tq), jnp.float32)
        carry, _ = jax.lax.scan(
            _ring_body(axis_name, n_blocks, causal),
            (k, v, o0, m0, l0, q, my_idx),
            jnp.arange(n_blocks),
        )
        _, _, o_acc, m_acc, l_acc, _, _ = carry
        denom = jnp.moveaxis(l_acc, 1, -1)[..., None]
        return (o_acc / jnp.maximum(denom, 1e-30)).astype(q.dtype)

    spec = P(None, axis_name, None, None)
    sharded = shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return jax.jit(sharded)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Single-device exact attention, for parity tests."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
