"""Sharded vector index: the corpus rows shard across a TPU mesh; each chip
computes a local top-k; partial results merge over ICI all-gather.

This realises the reference's *planned* sharded vector index
(/root/reference/docs/architecture/clustering-roadmap.md "Sharded ...
Planned") as the framework's primary ANN path — at TPU-pod scale, sharded
brute-force scoring beats HNSW for corpora ≤ tens of millions (SURVEY.md §7
step 4). Scores are always exact; candidate membership defaults to
approx_max_k / the streaming Pallas bin-reduce kernel (recall_target 0.95
per shard, the TPU-native top-k) with an exact=True full-sort opt-in for
recall 1.0.

Data plane: XLA collectives over ICI inside one jit'd program (shard_map).
No host-side shard coordinator exists — the "merge" is an all_gather + top_k
epilogue compiled into the same program as the scoring GEMM, so one search
(of any batch size) is ONE device dispatch.

local_k sizing contract
-----------------------
Each shard contributes ``local_k = clamp(max(k, requested_local_k),
1, local_n)`` candidates to the merge.  In exact mode this is provably
lossless for any live-row distribution: a shard can contribute at most k
rows to the global top-k, and a shard with fewer than local_k live rows
returns ALL of them (the remainder are -inf sentinels whose indices are
masked to -1 before the merge, so padding can never surface as a
candidate — see ops.similarity.merge_topk).  In approx mode local_k is a
recall knob: per-shard bin-reduce membership is ~0.95 at local_k == k, and
oversampling (SearchConfig.local_k > k) buys recall back at the cost of a
wider all-gather.  The shard_local_k_overflows metric counts merges where
one shard's list saturated — the signal to raise it.

IVF under sharding: centroids are replicated (every shard probes the same
n_probe clusters in-program), inverted lists are per-shard
(ops.ivf.build_sharded_ivf_layout), and the layout serves only while its
build-time epoch matches the corpus layout epoch (PR 2's invalidation
contract — covered-row overwrites and slot remaps kill it, plain
adds/removes don't).

int8 compressed residency (``quantized=True``): device HBM holds int8
codes + per-row scales instead of f32 rows (≈4x the rows per HBM byte;
the IVF block array quantizes too), candidate selection oversamples
``rescore_factor × k`` per query, and the merged candidate set is
exact-rescored in f32 from the host mirror — served (id, score) pairs
bit-match the deterministic f32 rescore (ops.host_search.rescore_rows).
The f32 truth never leaves the host; WindVE's CPU↔accelerator split as a
storage policy (PAPERS.md). docs/operations.md "Recall tuning" has the
memory math.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nornicdb_tpu.errors import DeviceUnavailable
from nornicdb_tpu.ops.host_search import quantize_rows_np, rescore_rows
from nornicdb_tpu.telemetry import deviceprof as _deviceprof
from nornicdb_tpu.ops.ivf import _next_pow2
from nornicdb_tpu.ops.similarity import (
    _SHARD_LOCALK_OVERFLOWS,
    _SHARD_REBALANCES,
    _SHARD_ROWS_GAUGE,
    _SHARDED_MERGE_HIST,
    _SHARDED_SEARCH_HIST,
    HostCorpus,
    _patch_rows,
    _patch_rows_donated,
    _patch_valid,
    _patch_valid_donated,
    cosine_topk,
    dot_scores,
    l2_normalize,
    merge_topk,
    topk_backend,
    topk_backend_int8,
)
from nornicdb_tpu.parallel.mesh import make_mesh, shard_map_compat

logger = logging.getLogger(__name__)

# Collective programs launched from two host threads can interleave their
# per-device enqueue order and deadlock at the all_gather rendezvous
# (reproduced live on the 8-device CPU mesh: a recall() on the main thread
# racing the embed worker's dispatch left every device waiting for a
# participant enqueued behind the OTHER program). The same out-of-order
# enqueue hazard exists on a real mesh, so every sharded serving dispatch
# in the process serializes through this leaf lock. It guards only WARM,
# already-gated dispatches (never backend acquisition — NL-DEV01-safe) and
# nothing else is ever acquired while holding it; result materialization
# happens inside so the program has fully retired before the next launch.
_COLLECTIVE_DISPATCH_LOCK = threading.Lock()


@functools.partial(
    jax.jit,
    static_argnames=("k", "local_k", "axis", "mesh_static", "use_bf16",
                     "exact", "streaming"),
)
def _sharded_search(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    local_k: int,
    axis: str,
    mesh_static: Mesh,
    use_bf16: bool = True,
    exact: bool = False,
    streaming: Optional[bool] = None,
):
    """One XLA program: per-shard GEMM + top-local_k, ICI all-gather of
    (vals, global_idx) only, global merge.  Per-shard scoring dispatches
    through topk_backend, so on TPU at scale each chip runs the streaming
    Pallas bin-reduce kernel over its corpus shard (TPU-KNN shape); the
    exact=True fallback full-sorts per shard instead."""

    def shard_fn(q, c, v):
        local_n = c.shape[0]
        n_shards = mesh_static.shape[axis]
        lk = max(1, min(local_k, local_n))
        vals, idx = topk_backend(
            q, c, v, lk, exact=exact, use_bf16=use_bf16,
            streaming=streaming,
        )
        shard = jax.lax.axis_index(axis)
        gidx = idx + shard * local_n
        # sentinel at the source: a near-empty shard pads its list with
        # -inf entries whose per-shard indices are arbitrary — mask them
        # to -1 BEFORE they cross the interconnect, so no consumer can
        # resolve a padding slot into an id
        gidx = jnp.where(jnp.isfinite(vals), gidx, -1)
        # (S, Q, local_k) partials on every chip, then merged identically
        vals_all = jax.lax.all_gather(vals, axis)
        idx_all = jax.lax.all_gather(gidx, axis)
        return merge_topk(vals_all, idx_all, min(k, lk * n_shards))

    return shard_map_compat(
        shard_fn,
        mesh=mesh_static,
        in_specs=(P(), P(axis, None), P(axis)),
        out_specs=(P(), P()),
    )(queries, corpus, valid)


@functools.partial(
    jax.jit,
    static_argnames=("k", "local_k", "axis", "mesh_static", "streaming"),
)
def _sharded_search_int8(
    queries: jax.Array,   # (B, D) f32 L2-normalized, replicated
    codes: jax.Array,     # (N, D) int8 corpus codes, sharded on N
    scales: jax.Array,    # (N,) f32 quantize_rows scales, sharded
    valid: jax.Array,     # (N,) bool, sharded
    k: int,
    local_k: int,
    axis: str,
    mesh_static: Mesh,
    streaming: Optional[bool] = None,
):
    """Compressed-residency sharded search: each shard scores its int8
    code slice (streaming int8 Pallas kernel on TPU, dequant-GEMM XLA
    fallback elsewhere) — no f32/bf16 corpus copy exists on device. Same
    all-gather merge and (vals, global_idx) wire format as the dense
    program; candidate scores carry int8 noise and the caller rescores
    the merged set exactly from the host f32 mirror."""

    def shard_fn(q, c8, sc, v):
        local_n = c8.shape[0]
        n_shards = mesh_static.shape[axis]
        lk = max(1, min(local_k, local_n))
        vals, idx = topk_backend_int8(q, c8, sc, v, lk, streaming=streaming)
        shard = jax.lax.axis_index(axis)
        gidx = idx + shard * local_n
        gidx = jnp.where(jnp.isfinite(vals), gidx, -1)
        vals_all = jax.lax.all_gather(vals, axis)
        idx_all = jax.lax.all_gather(gidx, axis)
        return merge_topk(vals_all, idx_all, min(k, lk * n_shards))

    return shard_map_compat(
        shard_fn,
        mesh=mesh_static,
        in_specs=(P(), P(axis, None), P(axis), P(axis)),
        out_specs=(P(), P()),
    )(queries, codes, scales, valid)


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probe", "axis", "mesh_static", "has_residual",
                     "quantized"),
)
def _sharded_ivf_topk(
    queries: jax.Array,        # (B, D) L2-normalized, replicated
    centroids: jax.Array,      # (K, D) replicated
    blocks: jax.Array,         # (S, K, Cmax, D) sharded on S (int8 when
                               # quantized)
    counts: jax.Array,         # (S, K) sharded
    slotmap: jax.Array,        # (S, K, Cmax) GLOBAL slots, sharded
    residual: jax.Array,       # (S, Rmax, D) sharded (dummy when absent)
    residual_slots: jax.Array,  # (S, Rmax) sharded (dummy when absent)
    block_scales: jax.Array,   # (S, K, Cmax) f32 dequant multipliers
                               # (dummy unless quantized)
    residual_scales: jax.Array,  # (S, Rmax) f32 (dummy unless quantized)
    k: int,
    n_probe: int,
    axis: str,
    mesh_static: Mesh,
    has_residual: bool,
    quantized: bool,
):
    """Fused sharded IVF: replicated centroid probe → per-shard block
    gather + bf16 scoring → per-shard residual scan → local top-k over
    GLOBAL slots → all_gather merge.  One device dispatch per batch, same
    wire format ((vals, global_slot) pairs) as the dense sharded path.

    ``quantized=True``: the blocks hold int8 codes (exactly representable
    in bf16, so the same einsum runs) and the per-row dequant multiplier
    rides the f32 epilogue — dead/pad rows carry multiplier 0 and are
    masked by the live-count test anyway."""

    def shard_fn(q, cent, blk, cnt, smap, res, rslots, bsc, rsc):
        blk, cnt, smap = blk[0], cnt[0], smap[0]
        cmax = blk.shape[1]
        cscores = dot_scores(q, cent)                 # (B, K), replicated
        _, probes = jax.lax.top_k(cscores, n_probe)    # (B, P) same on all
        gathered = blk[probes]                         # (B, P, Cmax, D)
        scores = jnp.einsum(
            "bd,bpcd->bpc",
            q.astype(jnp.bfloat16),
            gathered.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        if quantized:
            scores = scores * bsc[0][probes]           # (B, P, Cmax)
        live = jnp.arange(cmax)[None, None, :] < cnt[probes][:, :, None]
        scores = jnp.where(live, scores, -jnp.inf)
        cand = smap[probes]                            # (B, P, Cmax)
        b = scores.shape[0]
        flat_v = scores.reshape(b, -1)
        flat_s = cand.reshape(b, -1)
        if has_residual:
            r, rs = res[0], rslots[0]
            rscores = dot_scores(q, r)
            if quantized:
                rscores = rscores * rsc[0][None, :]
            rscores = jnp.where((rs >= 0)[None, :], rscores, -jnp.inf)
            flat_v = jnp.concatenate([flat_v, rscores], axis=1)
            flat_s = jnp.concatenate(
                [flat_s, jnp.broadcast_to(rs[None, :], rscores.shape)],
                axis=1,
            )
        kk = min(k, flat_v.shape[1])
        vals, pos = jax.lax.top_k(flat_v, kk)
        slots_top = jnp.take_along_axis(flat_s, pos, axis=1)
        vals_all = jax.lax.all_gather(vals, axis)
        slots_all = jax.lax.all_gather(slots_top, axis)
        n_shards = mesh_static.shape[axis]
        return merge_topk(vals_all, slots_all, min(k, kk * n_shards))

    rspec = P(axis) if has_residual else P()
    bspec = P(axis) if quantized else P()
    rsspec = P(axis) if (quantized and has_residual) else P()
    return shard_map_compat(
        shard_fn,
        mesh=mesh_static,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), rspec, rspec,
                  bspec, rsspec),
        out_specs=(P(), P()),
    )(queries, centroids, blocks, counts, slotmap, residual, residual_slots,
      block_scales, residual_scales)


@dataclass
class ShardStats:
    """Mesh-serving accounting for one ShardedCorpus (stats()["shard"],
    /admin/stats, and the nornicdb_shard_* metric families)."""

    dispatches: int = 0          # fused dense dispatches (1 per batch)
    ivf_dispatches: int = 0      # fused IVF dispatches (1 per batch)
    rebalances: int = 0          # grow/compact/recovery full re-shards
    local_k_overflows: int = 0   # approx merges saturated by one shard
    promotions: int = 0          # auto single-device -> sharded swaps
    rescored_queries: int = 0    # int8-residency queries exact-rescored
    last_dispatch_s: float = 0.0
    last_merge_s: float = 0.0
    last_rescore_s: float = 0.0
    rows_per_shard: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return asdict(self)


class ShardedCorpus(HostCorpus):
    """Mesh-sharded, device-resident embedding corpus.

    Host keeps the (ids, vectors) truth (HostCorpus); the device copy is a
    padded (Np, D) matrix laid out P("data", None) across the mesh, with
    rows aligned to 128 * n_shards so every shard stays lane-aligned.

    Mirrors gpu.EmbeddingIndex semantics (Add/Remove/Search, dirty-tracking
    resync — /root/reference/pkg/gpu/gpu.go:1224-1619) but the buffer spans
    every chip on the mesh instead of one GPU.  Grow/compact remap the
    shard boundaries (every shard's slice changes), which the sync driver
    serves as one full re-shard upload — counted as a rebalance; steady-
    state writes keep PR 2's incremental per-shard patching.
    """

    def __init__(
        self,
        dims: int,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        dtype=jnp.bfloat16,
        compact_ratio: float = 0.3,
        backend=None,
        quantized: bool = False,
        rescore_factor: int = 4,
    ):
        # int8 compressed residency (WindVE's CPU↔accelerator split as a
        # storage policy): with quantized=True only int8 codes + per-row
        # scales live on device (≈4x the rows per HBM byte; the f32 truth
        # stays in the host mirror), candidate selection oversamples
        # rescore_factor × k on device, and the merged candidate set is
        # re-scored exactly in f32 from the host mirror — served scores
        # bit-match the f32 exact path for the same ids.
        self.quantized = bool(quantized)
        self.rescore_factor = max(1, int(rescore_factor))
        # building a mesh enumerates devices — a COLD backend acquisition.
        # make_mesh gates through the BackendManager (bounded wait on its
        # worker thread) and raises DeviceUnavailable when degraded; the
        # search service catches that and falls back to a single-device
        # corpus, which itself serves from host arrays until recovery.
        self.mesh = mesh if mesh is not None else make_mesh(backend=backend)
        self.axis = axis
        self.dtype = dtype
        self.n_shards = self.mesh.shape[axis]
        super().__init__(
            dims,
            # 128 * n_shards (not lcm): every PER-SHARD slice must itself be
            # a lane multiple, or the per-shard streaming kernel's tile
            # cannot divide the local row count
            align=128 * self.n_shards,
            compact_ratio=compact_ratio,
            backend=backend,
        )
        self._dev = None
        self._dev_valid = None
        self._dev_i8: Optional[tuple[jax.Array, jax.Array]] = None
        self._sharding = NamedSharding(self.mesh, P(self.axis, None))
        self._vsharding = NamedSharding(self.mesh, P(self.axis))
        self._repsharding = NamedSharding(self.mesh, P())
        self.shard_stats = ShardStats()
        # sharded IVF layout (ops.ivf.ShardedIVFLayout) + the recovery
        # contract fields HostCorpus._on_backend_recovered drives
        self._sivf = None
        self._pending_clusters: Optional[tuple] = None
        self._last_fit_host: Optional[tuple] = None
        # fleet telemetry: mesh-resident byte accounting per component
        # (summed with any other live corpora at /metrics render)
        _deviceprof.register_hbm(self, ShardedCorpus._hbm_bytes)

    @staticmethod
    def _hbm_bytes(self) -> dict:
        """Lock-free HBM accounting (scrape thread): f32 buffers, int8
        codes+scales, and the sharded IVF layout's device arrays."""
        out = {"corpus_f32": 0, "corpus_int8": 0, "ivf": 0}
        dev, valid, i8, sivf = (self._dev, self._dev_valid, self._dev_i8,
                                self._sivf)
        for arr in (dev, valid):
            if arr is not None:
                out["corpus_f32"] += int(arr.size) * arr.dtype.itemsize
        if i8 is not None:
            for arr in i8:
                out["corpus_int8"] += int(arr.size) * arr.dtype.itemsize
        if sivf is not None:
            for name in ("blocks", "counts", "slotmap", "centroids",
                         "residual", "residual_slots", "block_scales",
                         "residual_scales"):
                arr = getattr(sivf, name, None)
                if arr is not None and not isinstance(arr, np.ndarray):
                    out["ivf"] += int(arr.size) * arr.dtype.itemsize
        return out

    @property
    def local_n(self) -> int:
        """Rows resident per shard (capacity / n_shards; lane-aligned)."""
        return self.capacity // self.n_shards

    # -- device sync -------------------------------------------------------
    # The generic HostCorpus._sync driver (dirty-block coalescing, deferred
    # compaction, patch-vs-full policy, stats) drives these two hooks.
    def _device_ready(self) -> bool:
        if self.quantized:
            i8 = self._dev_i8
            return i8 is not None and int(i8[0].shape[0]) == self.capacity
        return super()._device_ready()

    def _upload_full(self) -> None:
        # NL-DEV01 suppressions: warm transfers under _sync_lock by design
        # (gated upstream by _sync's _device_ok_nowait; the mesh was
        # enumerated through the manager at construction) — same rationale
        # as DeviceCorpus._upload_full
        if self.quantized:
            # compressed residency: quantize on the HOST so the f32 corpus
            # never materializes in device memory — the transfer and the
            # resident footprint are both N*D bytes + 4N scales, 4x less
            # than the f32 layout this mode exists to avoid
            codes, scales = quantize_rows_np(self._host)
            self._dev_i8 = (
                jax.device_put(  # nornlint: disable=NL-DEV01
                    jnp.asarray(codes),  # nornlint: disable=NL-DEV01
                    self._sharding,
                ),
                jax.device_put(  # nornlint: disable=NL-DEV01
                    jnp.asarray(scales),  # nornlint: disable=NL-DEV01
                    self._vsharding,
                ),
            )
            self._dev = None
            self._dev_valid = jax.device_put(  # nornlint: disable=NL-DEV01
                jnp.asarray(self._valid),  # nornlint: disable=NL-DEV01
                self._vsharding,
            )
            self._update_shard_rows()
            return
        self._dev = jax.device_put(  # nornlint: disable=NL-DEV01
            jnp.asarray(self._host, dtype=self.dtype),  # nornlint: disable=NL-DEV01
            self._sharding,
        )
        self._dev_valid = jax.device_put(  # nornlint: disable=NL-DEV01
            jnp.asarray(self._valid),  # nornlint: disable=NL-DEV01
            self._vsharding,
        )
        self._update_shard_rows()

    def _apply_patch(
        self, start_row: int, rows: np.ndarray, valid_rows: np.ndarray,
        donate: bool,
    ) -> None:
        """Patch one dirty run into the mesh-sharded buffer. XLA partitions
        the dynamic_update_slice, so a run touches only the shards it
        overlaps; device_put re-pins the P(axis, None) layout (a no-op when
        GSPMD already kept it, which it does for update-slice)."""
        # NL-DEV01 suppressions: warm patches under _sync_lock by design
        # (same rationale as _upload_full above).
        # Dispatch lock: GSPMD lowers a dynamic_update_slice whose start
        # falls on the PARTITIONED dim to an all_gather + update + reslice,
        # so the patch is itself a collective program — it must not race a
        # search dispatch (observed pool-starvation deadlock on the CPU
        # mesh: the patch's rendezvous and a search's rendezvous each held
        # half the device threads). Order is always _sync_lock -> dispatch
        # lock; the dispatch lock is a leaf.
        start = np.int32(start_row)
        with _COLLECTIVE_DISPATCH_LOCK:
            try:
                patch = _patch_rows_donated if donate else _patch_rows
                vpatch = _patch_valid_donated if donate else _patch_valid
                if self.quantized:
                    # requantize ONLY the patched rows on the host
                    # (per-row symmetric quantization is block-local by
                    # construction — the _requantize_rows contract of the
                    # single-device int8 mirror) and patch codes + scales
                    # in place
                    codes, scales = quantize_rows_np(rows)
                    self._dev_i8 = (
                        jax.device_put(  # nornlint: disable=NL-DEV01
                            patch(self._dev_i8[0],
                                  jnp.asarray(codes),  # nornlint: disable=NL-DEV01
                                  start),
                            self._sharding,
                        ),
                        jax.device_put(  # nornlint: disable=NL-DEV01
                            vpatch(self._dev_i8[1],
                                   jnp.asarray(scales),  # nornlint: disable=NL-DEV01
                                   start),
                            self._vsharding,
                        ),
                    )
                else:
                    self._dev = jax.device_put(  # nornlint: disable=NL-DEV01
                        patch(self._dev,
                              jnp.asarray(rows, dtype=self.dtype),  # nornlint: disable=NL-DEV01
                              start),
                        self._sharding,
                    )
                self._dev_valid = jax.device_put(  # nornlint: disable=NL-DEV01
                    vpatch(self._dev_valid,
                           jnp.asarray(valid_rows),  # nornlint: disable=NL-DEV01
                           start),
                    self._vsharding,
                )
            except Exception:
                # a failing donated patch has CONSUMED an unknown subset
                # of the sharded buffers — drop them all so
                # _device_ready() reports false and the next _sync
                # rebuilds via _upload_full (NL-JAX04)
                self._dev = None
                self._dev_valid = None
                self._dev_i8 = None
                raise
            # retire EVERY patch before releasing: the valid-mask patch is
            # its own collective program enqueued after the row patch — an
            # async collective still enqueueing while a search launches
            # reintroduces the race
            if self.quantized:
                self._dev_i8[0].block_until_ready()  # nornlint: disable=NL-LK02
                self._dev_i8[1].block_until_ready()  # nornlint: disable=NL-LK02
            else:
                self._dev.block_until_ready()  # nornlint: disable=NL-LK02
            self._dev_valid.block_until_ready()  # nornlint: disable=NL-LK02

    # -- shard lifecycle ---------------------------------------------------
    def _note_rebalance(self, reason: str) -> None:
        self.shard_stats.rebalances += 1
        _SHARD_REBALANCES.inc()
        logger.info("sharded corpus rebalance (%s): capacity=%d shards=%d",
                    reason, self.capacity, self.n_shards)

    def _grow(self, min_capacity: int = 0) -> None:
        # capacity change moves every shard boundary: the next sync is a
        # full re-shard upload (re-pinned NamedSharding), and any fitted
        # per-shard inverted lists describe the old boundaries
        super()._grow(min_capacity)
        self.clear_clusters()
        self._note_rebalance("grow")

    def _compact(self) -> None:
        # compaction remaps slots across shard boundaries (live rows pack
        # to the front): full re-shard, stale layouts dropped
        super()._compact()
        self.clear_clusters()
        self._note_rebalance("compact")

    def _on_backend_recovered(self, mode: str) -> None:
        """Recovery re-upload goes through the same per-shard path: "full"
        drops the mesh-resident buffers and the next sync re-shards the
        whole corpus (counted as a rebalance); "dirty" trusts surviving
        shard buffers and patches only degraded-era blocks."""
        had_dev = self._dev is not None
        super()._on_backend_recovered(mode)
        if mode != "dirty" and had_dev:
            self._note_rebalance("recovery")

    def _on_backend_ready(self) -> None:
        """Post-recovery: wake the uploader (base) and re-install any
        cluster fit stashed while degraded — on a throwaway thread, never
        the manager's probe thread (same rationale as DeviceCorpus)."""
        super()._on_backend_ready()
        with self._sync_lock:
            pending, self._pending_clusters = self._pending_clusters, None
            if pending is None and self._sivf is None:
                # a degraded-era rebalance (grow/compact) ran
                # clear_clusters(), dropping the stash with the layout;
                # the id-based host copy survives slot remaps — reinstall
                # it rather than serving full sharded scans until the next
                # periodic recluster (the set_clusters stash contract)
                pending = self._last_fit_host
        if pending is None:
            return

        def _install() -> None:
            try:
                self.set_clusters(pending[0], pending[1])
            except Exception:
                logger.exception(
                    "post-recovery sharded cluster install failed"
                )

        threading.Thread(
            target=_install, name="nornicdb-shard-cluster-reinstall",
            daemon=True,
        ).start()

    def _update_shard_rows(self) -> list[int]:
        """Per-shard live-row counts -> stats + the shard gauge. Called
        under _sync_lock (full upload) and lock-free from stats(): the
        mask scan is O(capacity), and a /metrics scrape must not stall
        searches/writes queued on _sync_lock for it. The single ref read
        is atomic and in-place bit flips only skew counts by in-flight
        writes — stats-grade accuracy."""
        valid = self._valid
        per = valid.reshape(self.n_shards, -1).sum(axis=1)
        rows = [int(x) for x in per]
        self.shard_stats.rows_per_shard = rows
        for s, n in enumerate(rows):
            _SHARD_ROWS_GAUGE.labels(str(s)).set(float(n))
        return rows

    def _device_bytes(self) -> int:
        """Resident device bytes across the mesh (corpus + IVF layout):
        the number the int8 residency math in docs/operations.md is
        checked against."""
        n = 0
        for arr in (self._dev, self._dev_valid):
            if arr is not None:
                n += int(arr.size) * arr.dtype.itemsize
        if self._dev_i8 is not None:
            for arr in self._dev_i8:
                n += int(arr.size) * arr.dtype.itemsize
        sivf = self._sivf
        if sivf is not None:
            for arr in (sivf.blocks, sivf.counts, sivf.slotmap,
                        sivf.centroids, sivf.residual, sivf.residual_slots,
                        sivf.block_scales, sivf.residual_scales):
                if arr is not None:
                    n += int(arr.size) * arr.dtype.itemsize
        return n

    def stats(self) -> dict:
        out = super().stats()
        rows = self._update_shard_rows()
        shard = self.shard_stats.as_dict()
        shard.update(
            n_shards=self.n_shards,
            local_n=self.local_n,
            rows_per_shard=rows,
            ivf_fitted=self._sivf is not None,
            quantized=self.quantized,
            rescore_factor=self.rescore_factor,
            device_bytes=self._device_bytes(),
        )
        out["shard"] = shard
        return out

    # -- IVF under sharding ------------------------------------------------
    def clear_clusters(self) -> None:
        self._sivf = None
        self._layout_slots = None
        self._pending_clusters = None

    def cluster(self, k: int = 0, iters: int = 10, seed: int = 0,
                sample: int = 0) -> int:
        """Fit k-means over live rows and install the per-shard inverted
        lists.  Same optimistic-install dance as DeviceCorpus.cluster: the
        fit and the layout build (device transfers included) run OUTSIDE
        _sync_lock; a layout-epoch change during either voids the
        install.  ``sample`` caps the Lloyd fit (ops.kmeans.kmeans_fit)
        for 10M-row-class corpora."""
        from nornicdb_tpu.ops.kmeans import kmeans_fit

        if not self._device_gate():
            return 0  # degraded: pruning is a device-path optimization
        with self._sync_lock:
            live = [i for i, id_ in enumerate(self._ids) if id_ is not None]
            if len(live) < 2:
                return 0
            data = self._host[live]  # fancy indexing copies: snapshot
            epoch_at_read = self._layout_epoch
            mask = np.zeros(self.capacity, bool)
            mask[live] = True
            if (
                self._layout_slots is not None
                and self._layout_slots.size == self.capacity
            ):
                mask |= self._layout_slots
            self._layout_slots = mask
        res = kmeans_fit(data, k=k, iters=iters, seed=seed, sample=sample)
        with self._sync_lock:
            if self._layout_epoch != epoch_at_read:
                return 0  # slot space moved mid-fit: caller may recluster
            # id-based host copy: full-mode recovery re-installs from this
            self._last_fit_host = (
                np.asarray(res.centroids, np.float32),
                {
                    self._ids[slot]: int(res.assignments[row])
                    for row, slot in enumerate(live)
                    if slot < len(self._ids) and self._ids[slot] is not None
                },
            )
        self._install_sharded_layout(
            np.asarray(live), res.assignments,
            np.asarray(res.centroids, np.float32),
            expect_epoch=epoch_at_read,
        )
        return res.k

    def set_clusters(
        self, centroids: np.ndarray, assignments_by_id: dict[str, int]
    ) -> None:
        """Install externally computed clusters (the search service's fit)
        as per-shard inverted lists.  Degraded backends stash the fit and
        install it on recovery (_on_backend_ready) — full scan keeps
        serving meanwhile."""
        if not self._device_ok_nowait():
            with self._sync_lock:
                self._pending_clusters = (
                    np.asarray(centroids, np.float32),
                    dict(assignments_by_id),
                )
                self._last_fit_host = self._pending_clusters
            return
        fit_host = (np.asarray(centroids, np.float32),
                    dict(assignments_by_id))
        with self._sync_lock:
            self._last_fit_host = fit_host
            slot_assignments = np.full(self.capacity, -1, np.int32)
            for id_, c in assignments_by_id.items():
                slot = self._slot_of.get(id_)
                if slot is not None:
                    slot_assignments[slot] = c
            # the old layout describes the replaced clustering — drop it
            # even when no live rows match; a stashed degraded-era fit is
            # superseded too
            self._sivf = None
            self._layout_slots = None
            self._pending_clusters = None
            live = np.nonzero((slot_assignments >= 0) & self._valid)[0]
            epoch_at_read = self._layout_epoch
        if live.size:
            self._install_sharded_layout(
                live, slot_assignments[live],
                np.asarray(centroids, np.float32),
                expect_epoch=epoch_at_read,
            )

    def _install_sharded_layout(
        self,
        live_slots: np.ndarray,
        live_assignments: np.ndarray,
        centroids: np.ndarray,
        expect_epoch: Optional[int] = None,
    ) -> None:
        """Build + optimistically install the per-shard IVF layout.  The
        build (H2D transfers included) runs OUTSIDE the lock (NL-DEV01);
        the snapshot pins the layout epoch and the install is skipped if
        the epoch moved (the widened _layout_slots mask makes covered-row
        overwrites bump it, same contract as DeviceCorpus)."""
        from nornicdb_tpu.ops.ivf import build_sharded_ivf_layout

        with self._sync_lock:
            if expect_epoch is not None and self._layout_epoch != expect_epoch:
                return
            epoch_at_read = self._layout_epoch
            rows = self._host[live_slots]  # fancy indexing copies: snapshot
            mask = np.zeros(self.capacity, bool)
            mask[live_slots] = True
            self._layout_slots = mask
        layout = build_sharded_ivf_layout(
            rows, live_slots.astype(np.int32),
            np.asarray(live_assignments, np.int32), centroids,
            n_shards=self.n_shards, local_n=self.local_n,
            shard_sharding=self._vsharding,
            replicated_sharding=self._repsharding,
            dtype=self.dtype, epoch=epoch_at_read,
            quantize=self.quantized,
        )
        with self._sync_lock:
            if self._layout_epoch != epoch_at_read:
                return  # mutated mid-build: discard the stale layout
            self._sivf = layout

    def _rescore_host(
        self, q: np.ndarray, slots: np.ndarray, host: np.ndarray, k: int,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Exact f32 re-score of device-selected candidates from the host
        mirror: the epilogue that makes int8 residency serve EXACT scores.
        ``host`` must be the array captured with the buffers the slots came
        from (a racing compaction REBINDS self._host; the captured array
        keeps the slot space the device scored). The gather runs under
        _sync_lock because in-place overwrites mutate rows without
        rebinding — same torn-read rule as _search_host.

        Returns (vals (B, k), slots (B, k)) with -inf/-1 padding; ties
        break by ascending slot, the host_topk/lax.top_k rule. Scores come
        from ops.host_search.rescore_rows — the deterministic f32 kernel
        score_subset's host twin uses — so the same (id, query) pair
        rescored anywhere yields the same bits."""
        norms = np.linalg.norm(q, axis=1, keepdims=True)
        qn = (q / np.maximum(norms, 1e-12)).astype(np.float32)
        b = q.shape[0]
        out_v = np.full((b, k), -np.inf, np.float32)
        out_s = np.full((b, k), -1, np.int64)
        t0 = time.perf_counter()
        # only the GATHER needs the lock (fancy indexing copies, so the
        # torn-read hazard is the in-place overwrite during the copy);
        # scoring + sorting run on the copies with no lock, so a batch's
        # rescore epilogue never serializes writers or other searches
        with self._sync_lock:
            gathered = []
            for qi in range(b):
                sel = slots[qi][slots[qi] >= 0]
                gathered.append((sel, host[sel] if sel.size else None))
        for qi, (sel, rows_sel) in enumerate(gathered):
            if rows_sel is None:
                continue
            scores = rescore_rows(rows_sel, qn[qi])
            order = np.lexsort((sel, -scores))[:k]
            out_v[qi, :order.size] = scores[order]
            out_s[qi, :order.size] = sel[order]
        self.shard_stats.rescored_queries += b
        self.shard_stats.last_rescore_s = time.perf_counter() - t0
        return out_v, out_s

    def _pruned_search(
        self, q: np.ndarray, k: int, min_similarity: float, n_probe: int,
        local_k: int = 0,
    ) -> Optional[list[list[tuple[str, float]]]]:
        """Fused sharded IVF path; None when no valid layout is installed
        (caller falls back to the full sharded scan — recall unaffected).
        ``local_k`` oversamples each shard's pre-merge contribution (the
        per-shard top-k over its probed blocks + residual) past k — the
        same recall knob it is on the dense path, here recovering true
        neighbors a shard-local truncation at k would cut. With a
        quantized layout the device program additionally oversamples
        rescore_factor × k and the merged set is exact-rescored from the
        host mirror before formatting."""
        with self._sync_lock:
            # a pending compaction would remap slots out from under the
            # layout's epoch check — run the sync first, like the dense path
            self._sync()
            ids = self._ids
            host = self._host
            layout = self._sivf
            layout_ok = (
                layout is not None and layout.epoch == self._layout_epoch
            )
        if not layout_ok:
            return None
        b = q.shape[0]
        b_pad = _next_pow2(b)
        q2 = q
        if b_pad != b:
            q2 = np.concatenate(
                [q, np.zeros((b_pad - b, q.shape[1]), np.float32)]
            )
        quantized = layout.quantized
        k_dev = k * self.rescore_factor if quantized else k
        k_prog = _next_pow2(max(k_dev, local_k, 8))
        qdtype = jnp.float32 if quantized else self.dtype
        qn = l2_normalize(jnp.asarray(q2, dtype=qdtype))
        n_probe = max(1, min(n_probe, layout.k))
        has_res = layout.residual is not None
        dummy = jnp.zeros((1, 1), self.dtype)
        dummy_i = jnp.zeros((1, 1), jnp.int32)
        dummy_f = jnp.zeros((1, 1), jnp.float32)
        t0 = time.perf_counter()
        with _COLLECTIVE_DISPATCH_LOCK:
            vals, slots = _sharded_ivf_topk(
                qn, layout.centroids, layout.blocks, layout.counts,
                layout.slotmap,
                layout.residual if has_res else dummy,
                layout.residual_slots if has_res else dummy_i,
                layout.block_scales if quantized else dummy_f,
                (layout.residual_scales if (quantized and has_res)
                 else dummy_f),
                k=k_prog, n_probe=n_probe, axis=self.axis,
                mesh_static=self.mesh, has_residual=has_res,
                quantized=quantized,
            )
            keep = max(k_dev, local_k)
            vals_np = np.asarray(vals, np.float32)[:b, :keep]
            slots_np = np.asarray(slots)[:b, :keep]
        t1 = time.perf_counter()
        self.shard_stats.ivf_dispatches += 1
        self.shard_stats.last_dispatch_s = t1 - t0
        _SHARDED_SEARCH_HIST.observe(t1 - t0)
        _deviceprof.record_execute(
            "search", "sharded_ivf", _deviceprof.pow2_class(b, "b"),
            t1 - t0)
        if quantized:
            vals_np, slots_np = self._rescore_host(q, slots_np, host, k)
        out = self._format_results(
            vals_np[:, :k], slots_np[:, :k], b, k, min_similarity, ids=ids,
        )
        merge_s = time.perf_counter() - t1
        self.shard_stats.last_merge_s = merge_s
        _SHARDED_MERGE_HIST.observe(merge_s)
        return out

    def _quantized_search(
        self, q: np.ndarray, k: int, min_similarity: float,
        local_k: int, streaming: Optional[bool],
    ) -> list[list[tuple[str, float]]]:
        """Compressed-residency full scan: the int8 sharded program
        selects rescore_factor × k candidates per query (one fused device
        dispatch), then the merged set is exact-rescored from the host f32
        mirror. Served (id, score) pairs bit-match the f32 exact path for
        every returned id; only candidate MEMBERSHIP carries int8 noise,
        which the oversample is sized to absorb."""
        b = q.shape[0]
        b_pad = _next_pow2(b)
        q2 = q
        if b_pad != b:
            q2 = np.concatenate(
                [q, np.zeros((b_pad - b, q.shape[1]), np.float32)]
            )
        # inline borrow (the _pruned_search idiom): the host mirror must be
        # captured ATOMICALLY with the int8 buffers — a background
        # compaction rebinds self._host, and slots of the old buffer
        # resolved through the new array would read other rows' vectors
        with self._sync_lock:
            self._sync()
            self._readers += 1
            i8 = self._dev_i8
            dev_valid = self._dev_valid
            ids = self._ids
            host = self._host
        try:
            if i8 is None or dev_valid is None:
                raise DeviceUnavailable(
                    "no resident int8 buffer (degraded)"
                )
            cap = int(i8[0].shape[0])
            local_n = cap // self.n_shards
            k_dev = min(_next_pow2(max(k * self.rescore_factor, 8)), cap)
            lk = max(1, min(_next_pow2(max(k_dev, local_k, 8)), local_n))
            qd = l2_normalize(jnp.asarray(q2, dtype=jnp.float32))
            t0 = time.perf_counter()
            with _COLLECTIVE_DISPATCH_LOCK:
                _vals, idx = _sharded_search_int8(
                    qd, i8[0], i8[1], dev_valid, k_dev, lk,
                    self.axis, self.mesh, streaming=streaming,
                )
                # materialize inside the borrow + dispatch lock, same
                # rationale as the dense path
                idx_np = np.asarray(idx)[:b]
            t1 = time.perf_counter()
            self.shard_stats.dispatches += 1
            self.shard_stats.last_dispatch_s = t1 - t0
            _SHARDED_SEARCH_HIST.observe(t1 - t0)
            _deviceprof.record_execute(
                "search", "sharded_int8", _deviceprof.pow2_class(b, "b"),
                t1 - t0)
            if lk < local_n:
                self._note_local_k_overflows(idx_np, lk, local_n)
            vals_np, slots_np = self._rescore_host(q, idx_np, host, k)
            out = self._format_results(
                vals_np, slots_np, b, k, min_similarity, ids=ids,
            )
            merge_s = time.perf_counter() - t1
            self.shard_stats.last_merge_s = merge_s
            _SHARDED_MERGE_HIST.observe(merge_s)
            return out
        finally:
            with self._sync_lock:
                self._readers -= 1

    # -- search ------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        k: int,
        min_similarity: float = -1.0,
        exact: bool = False,
        n_probe: int = 0,
        streaming: Optional[bool] = None,
        local_k: int = 0,
    ) -> list[list[tuple[str, float]]]:
        """Sharded cosine top-k: per-shard GEMM + top-local_k, ICI
        all-gather merge — one device dispatch for the whole (possibly
        batched) query block.  Scores are exact; with the default
        exact=False per-shard candidate membership uses approx_max_k or
        the streaming Pallas kernel (recall ~0.95+, tunable via local_k
        oversampling); exact=True gives recall 1.0 with tie-breaking
        identical to the single-device full scan.  n_probe > 0 with a
        fitted cluster index routes through the fused sharded IVF
        program instead.  quantized=True corpora select candidates from
        the int8 codes and exact-rescore the merged set from the host
        f32 mirror (exact=True serves the host mirror directly)."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if len(self._slot_of) == 0:
            return [[] for _ in range(q.shape[0])]
        # same lifecycle gate as DeviceCorpus.search: cold acquisition on
        # the manager's worker thread, degraded -> exact host fallback
        if not self._device_gate():
            return self._search_host(q, k, min_similarity)
        try:
            if n_probe > 0:
                pruned = self._pruned_search(
                    q, k, min_similarity, n_probe, local_k=local_k
                )
                if pruned is not None:
                    return pruned
            if self.quantized:
                if exact:
                    # quantized device membership cannot honor the
                    # recall-1.0 contract; the host f32 mirror can —
                    # identical ids/scores/tie order to a DeviceCorpus
                    # full sort, by construction
                    return self._host_exact_topk(q, k, min_similarity)
                return self._quantized_search(
                    q, k, min_similarity, local_k, streaming
                )
            b = q.shape[0]
            # power-of-two shape classes for batch, k, and local_k: the
            # program is shape-keyed jit over a collective, and the
            # QueryBatcher hands us every coalesced batch size from
            # 1..batch_max — without padding each one compiles a fresh
            # XLA program on the serving hot path (same rationale and
            # scheme as _pruned_search).  Padding lk upward only widens
            # each shard's contribution, so exact mode stays lossless
            # (lk >= min(k, local_n) still holds) and approx recall can
            # only improve; padded query rows are zeros, sliced off the
            # result before formatting.
            b_pad = _next_pow2(b)
            q2 = q
            if b_pad != b:
                q2 = np.concatenate(
                    [q, np.zeros((b_pad - b, q.shape[1]), np.float32)]
                )
            with self._borrow_device() as (dev, dev_valid, _i8, ids, _):
                # shard geometry comes from the BORROWED buffer, not self:
                # _borrow_device's sync may have just grown/re-sharded the
                # corpus (and a concurrent grow can rebind self._dev
                # again mid-search) — lk sized off a stale local_n would
                # silently cut exact-mode candidates on the new shards,
                # and overflow attribution would divide by the wrong width
                cap = int(dev.shape[0])
                local_n = cap // self.n_shards
                k_prog = min(_next_pow2(max(k, 8)), cap)
                lk = max(1, min(_next_pow2(max(k, local_k, 8)), local_n))
                qd = l2_normalize(jnp.asarray(q2, dtype=self.dtype))
                t0 = time.perf_counter()
                with _COLLECTIVE_DISPATCH_LOCK:
                    vals, idx = _sharded_search(
                        qd, dev, dev_valid, k_prog, lk,
                        self.axis, self.mesh, exact=exact,
                        streaming=streaming,
                    )
                    # materialize inside the borrow so the patcher can't
                    # donate the buffers this program is still reading (and
                    # inside the dispatch lock so the collective retires
                    # before another program may enqueue)
                    vals_np = np.asarray(vals, np.float32)[:b]
                    idx_np = np.asarray(idx)[:b]
                t1 = time.perf_counter()
        except DeviceUnavailable:
            return self._search_host(q, k, min_similarity)
        self.shard_stats.dispatches += 1
        self.shard_stats.last_dispatch_s = t1 - t0
        _SHARDED_SEARCH_HIST.observe(t1 - t0)
        _deviceprof.record_execute(
            "search", "sharded", _deviceprof.pow2_class(b, "b"), t1 - t0)
        if not exact and lk < local_n:
            # detect saturation on the UNSLICED merged width: a shard
            # contributing all lk of its oversampled candidates is the
            # truncation signal, regardless of the caller's k
            self._note_local_k_overflows(idx_np, lk, local_n)
        out = self._format_results(
            vals_np[:, :k], idx_np[:, :k], q.shape[0], k, min_similarity,
            ids=ids,
        )
        merge_s = time.perf_counter() - t1
        self.shard_stats.last_merge_s = merge_s
        _SHARDED_MERGE_HIST.observe(merge_s)
        return out

    def _note_local_k_overflows(
        self, idx: np.ndarray, lk: int, local_n: int
    ) -> None:
        """Count merged results where a single shard saturated its
        local_k contribution: in approx mode that shard's bin-reduce list
        was truncated exactly where real candidates may have been cut, so
        the operator signal is "raise SearchConfig.local_k"."""
        # a shard can contribute at most the merged width idx.shape[1]
        # (k_prog) entries — with local_k oversampled past that, `>= lk`
        # would be unreachable and the counter would read 0 forever,
        # silencing the exact signal the knob is tuned by. Saturating the
        # whole merged output is the strongest observable truncation sign.
        sat = min(lk, idx.shape[1])
        hits = 0
        for qi in range(idx.shape[0]):
            live = idx[qi][idx[qi] >= 0]
            if live.size == 0:
                continue
            per_shard = np.bincount(live // local_n, minlength=self.n_shards)
            if int(per_shard.max()) >= sat:
                hits += 1
        if hits:
            self.shard_stats.local_k_overflows += hits
            _SHARD_LOCALK_OVERFLOWS.inc(hits)
