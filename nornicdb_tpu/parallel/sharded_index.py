"""Sharded vector index: the corpus rows shard across a TPU mesh; each chip
computes a local top-k; partial results merge over ICI all-gather.

This realises the reference's *planned* sharded vector index
(/root/reference/docs/architecture/clustering-roadmap.md "Sharded ...
Planned") as the framework's primary ANN path — at TPU-pod scale, sharded
brute-force scoring beats HNSW for corpora ≤ tens of millions (SURVEY.md §7
step 4). Scores are always exact; candidate membership defaults to
approx_max_k (recall_target 0.95 per shard, the TPU-native top-k) with an
exact=True full-sort opt-in for recall 1.0.

Data plane: XLA collectives over ICI inside one jit'd program (shard_map).
No host-side shard coordinator exists — the "merge" is an all_gather + top_k
epilogue compiled into the same program as the scoring GEMM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nornicdb_tpu.errors import DeviceUnavailable
from nornicdb_tpu.ops.similarity import (
    HostCorpus,
    _patch_rows,
    _patch_rows_donated,
    _patch_valid,
    _patch_valid_donated,
    cosine_topk,
    l2_normalize,
    merge_topk,
    topk_backend,
)
from nornicdb_tpu.parallel.mesh import make_mesh, shard_map_compat


@functools.partial(
    jax.jit,
    static_argnames=("k", "axis", "mesh_static", "use_bf16", "exact",
                     "streaming"),
)
def _sharded_search(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    axis: str,
    mesh_static: Mesh,
    use_bf16: bool = True,
    exact: bool = False,
    streaming: Optional[bool] = None,
):
    """One XLA program: per-shard GEMM + top-k, ICI all-gather, global merge.
    Per-shard scoring dispatches through topk_backend, so on TPU at scale
    each chip runs the streaming Pallas kernel over its corpus shard."""

    def shard_fn(q, c, v):
        local_n = c.shape[0]
        n_shards = mesh_static.shape[axis]
        local_k = min(k, local_n)  # a shard holds at most local_n candidates
        vals, idx = topk_backend(
            q, c, v, local_k, exact=exact, use_bf16=use_bf16,
            streaming=streaming,
        )
        shard = jax.lax.axis_index(axis)
        gidx = idx + shard * local_n
        # (S, Q, local_k) partials on every chip, then merged identically
        vals_all = jax.lax.all_gather(vals, axis)
        idx_all = jax.lax.all_gather(gidx, axis)
        return merge_topk(vals_all, idx_all, min(k, local_k * n_shards))

    return shard_map_compat(
        shard_fn,
        mesh=mesh_static,
        in_specs=(P(), P(axis, None), P(axis)),
        out_specs=(P(), P()),
    )(queries, corpus, valid)


class ShardedCorpus(HostCorpus):
    """Mesh-sharded, device-resident embedding corpus.

    Host keeps the (ids, vectors) truth (HostCorpus); the device copy is a
    padded (Np, D) matrix laid out P("data", None) across the mesh, with rows
    aligned to lcm(128, n_shards) so every shard stays lane-aligned.

    Mirrors gpu.EmbeddingIndex semantics (Add/Remove/Search, dirty-tracking
    resync — /root/reference/pkg/gpu/gpu.go:1224-1619) but the buffer spans
    every chip on the mesh instead of one GPU.
    """

    def __init__(
        self,
        dims: int,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        dtype=jnp.bfloat16,
        compact_ratio: float = 0.3,
        backend=None,
    ):
        # building a mesh enumerates devices — a COLD backend acquisition.
        # make_mesh gates through the BackendManager (bounded wait on its
        # worker thread) and raises DeviceUnavailable when degraded; the
        # search service catches that and falls back to a single-device
        # corpus, which itself serves from host arrays until recovery.
        self.mesh = mesh if mesh is not None else make_mesh(backend=backend)
        self.axis = axis
        self.dtype = dtype
        self.n_shards = self.mesh.shape[axis]
        super().__init__(
            dims,
            # 128 * n_shards (not lcm): every PER-SHARD slice must itself be
            # a lane multiple, or the per-shard streaming kernel's tile
            # cannot divide the local row count
            align=128 * self.n_shards,
            compact_ratio=compact_ratio,
            backend=backend,
        )
        self._dev = None
        self._dev_valid = None
        self._sharding = NamedSharding(self.mesh, P(self.axis, None))
        self._vsharding = NamedSharding(self.mesh, P(self.axis))

    # -- device sync -------------------------------------------------------
    # The generic HostCorpus._sync driver (dirty-block coalescing, deferred
    # compaction, patch-vs-full policy, stats) drives these two hooks.
    def _upload_full(self) -> None:
        # NL-DEV01 suppressions: warm transfers under _sync_lock by design
        # (gated upstream by _sync's _device_ok_nowait; the mesh was
        # enumerated through the manager at construction) — same rationale
        # as DeviceCorpus._upload_full
        self._dev = jax.device_put(  # nornlint: disable=NL-DEV01
            jnp.asarray(self._host, dtype=self.dtype),  # nornlint: disable=NL-DEV01
            self._sharding,
        )
        self._dev_valid = jax.device_put(  # nornlint: disable=NL-DEV01
            jnp.asarray(self._valid),  # nornlint: disable=NL-DEV01
            self._vsharding,
        )

    def _apply_patch(
        self, start_row: int, rows: np.ndarray, valid_rows: np.ndarray,
        donate: bool,
    ) -> None:
        """Patch one dirty run into the mesh-sharded buffer. XLA partitions
        the dynamic_update_slice, so a run touches only the shards it
        overlaps; device_put re-pins the P(axis, None) layout (a no-op when
        GSPMD already kept it, which it does for update-slice)."""
        # NL-DEV01 suppressions: warm patches under _sync_lock by design
        # (same rationale as _upload_full above)
        start = np.int32(start_row)
        patch = _patch_rows_donated if donate else _patch_rows
        self._dev = jax.device_put(  # nornlint: disable=NL-DEV01
            patch(self._dev,
                  jnp.asarray(rows, dtype=self.dtype),  # nornlint: disable=NL-DEV01
                  start),
            self._sharding,
        )
        vpatch = _patch_valid_donated if donate else _patch_valid
        self._dev_valid = jax.device_put(  # nornlint: disable=NL-DEV01
            vpatch(self._dev_valid,
                   jnp.asarray(valid_rows),  # nornlint: disable=NL-DEV01
                   start),
            self._vsharding,
        )

    # -- search ------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        k: int,
        min_similarity: float = -1.0,
        exact: bool = False,
        streaming: Optional[bool] = None,
    ) -> list[list[tuple[str, float]]]:
        """Sharded cosine top-k: per-shard GEMM + top-k, ICI all-gather merge.
        Scores are exact; with the default exact=False per-shard candidate
        membership uses approx_max_k or the streaming Pallas kernel
        (recall ~0.95+); exact=True gives recall 1.0."""
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if len(self._slot_of) == 0:
            return [[] for _ in range(q.shape[0])]
        # same lifecycle gate as DeviceCorpus.search: cold acquisition on
        # the manager's worker thread, degraded -> exact host fallback
        if not self._device_gate():
            return self._search_host(q, k, min_similarity)
        try:
            with self._borrow_device() as (dev, dev_valid, _i8, ids, _):
                qd = l2_normalize(jnp.asarray(q, dtype=self.dtype))
                vals, idx = _sharded_search(
                    qd, dev, dev_valid, min(k, self.capacity),
                    self.axis, self.mesh, exact=exact, streaming=streaming,
                )
                # materialize inside the borrow so the patcher can't donate
                # the buffers this program is still reading
                vals_np = np.asarray(vals, np.float32)
                idx_np = np.asarray(idx)
        except DeviceUnavailable:
            return self._search_host(q, k, min_similarity)
        return self._format_results(
            vals_np, idx_np, q.shape[0], k, min_similarity, ids=ids,
        )
