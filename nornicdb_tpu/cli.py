"""Command-line interface.

Behavioral reference: /root/reference/cmd/nornicdb/main.go:71-208 — cobra
commands serve / init / import / shell / decay {recalculate,archive,stats};
runServe wiring (:210-649): config -> DB -> embedder -> auth -> HTTP + Bolt
servers -> signal handling.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _open_db(args):
    import nornicdb_tpu
    from nornicdb_tpu.db import Config

    cfg = Config(log_queries=bool(getattr(args, "log_queries", False)))
    if cfg.log_queries:
        import logging

        logging.basicConfig(level=logging.INFO)
    return nornicdb_tpu.open_db(args.data_dir, cfg)


def cmd_serve(args) -> int:
    """(ref: runServe main.go:210)"""
    import nornicdb_tpu.telemetry as telemetry
    from nornicdb_tpu.auth import Authenticator, ROLE_ADMIN
    from nornicdb_tpu.config import load as load_app_config
    from nornicdb_tpu.embed import CachedEmbedder, HashEmbedder, TPUEmbedder
    from nornicdb_tpu.multidb import SYSTEM_DB
    from nornicdb_tpu.server import BoltServer, HttpServer

    # apply nornicdb.yaml/env telemetry + backend-lifecycle knobs to the
    # process-global tracer / slow-query log / device manager before any
    # server starts taking traffic
    app_cfg = load_app_config()
    telemetry.configure(**vars(app_cfg.telemetry))
    from nornicdb_tpu import backend as backend_mod

    backend_mod.configure(app_cfg.backend)
    # vector-serving knobs (backend selection, sharded promotion, recall
    # tuning) become the defaults for every SearchService this process
    # builds — docs/operations.md "Sharded serving tuning"
    from nornicdb_tpu.search import service as search_service

    search_service.configure_defaults(**vars(app_cfg.search))
    # generation-serving knobs (paged-KV geometry, concurrency, deadline,
    # degraded-backend policy) become the defaults for the genserve engine
    # this process builds behind Heimdall/GraphRAG — docs/generation.md
    from nornicdb_tpu import genserve as genserve_mod

    genserve_mod.configure(app_cfg.genserve)
    # kick off PJRT init + first-touch on the manager's worker thread NOW,
    # so the first search/embed finds a READY (or already-degraded) backend
    # instead of paying the acquire timeout inline
    backend_mod.manager().ensure_started()

    db = _open_db(args)
    # embedder: trained checkpoint > TPU bge-m3 preset > hash fallback
    if args.embedder == "trained" or (
        args.embedder == "tpu" and os.environ.get("NORNICDB_EMBEDDER_MODEL")
    ):
        from nornicdb_tpu.models.pretrain import load_embedder

        model_dir = os.environ.get("NORNICDB_EMBEDDER_MODEL", "")
        if not model_dir:
            raise SystemExit(
                "--embedder trained requires NORNICDB_EMBEDDER_MODEL=<dir>"
            )
        embedder = load_embedder(model_dir)
    elif args.embedder == "tpu":
        from nornicdb_tpu.models import bge_m3

        cfg_name = getattr(bge_m3, args.model_preset.upper().replace("-", "_"))
        embedder = TPUEmbedder(cfg=cfg_name)
    else:
        embedder = HashEmbedder(args.embed_dims)
    # distilled production embedder, behind the eval gate: the student
    # checkpoint only replaces the full encoder when its retrieval MRR
    # clears serving.student_min_mrr — otherwise the config is REJECTED
    # at startup with the measured number (docs/operations.md "Embed
    # serving tuning"; serving/student_gate.py)
    from nornicdb_tpu.errors import StudentGateError
    from nornicdb_tpu.serving import ServingEngine, gate_student
    from nornicdb_tpu.serving.stats import set_embedder_selection

    serving_cfg = app_cfg.serving
    if args.embedder == "student":
        # CLI shorthand for serving.embedder=student (config/env also work)
        serving_cfg.embedder = "student"
        if not serving_cfg.student_model_dir:
            serving_cfg.student_model_dir = os.environ.get(
                "NORNICDB_EMBEDDER_MODEL", ""
            )
    if serving_cfg.embedder == "student":
        from nornicdb_tpu.models.pretrain import load_embedder

        student_dir = serving_cfg.student_model_dir
        if not student_dir:
            raise SystemExit(
                "serving.embedder=student requires "
                "serving.student_model_dir (NORNICDB_STUDENT_MODEL)"
            )
        student = load_embedder(student_dir)
        try:
            report = gate_student(
                student,
                serving_cfg.student_min_mrr,
                serving_cfg.student_eval_suite,
            )
        except StudentGateError as e:
            raise SystemExit(f"serving config rejected: {e}")
        print(
            f"student embedder admitted: eval MRR "
            f"{report.metrics.mrr:.4f} >= {serving_cfg.student_min_mrr}"
        )
        embedder = student
        set_embedder_selection("student")
    else:
        set_embedder_selection("full")
    if serving_cfg.enabled:
        # continuous ragged batching engine fronts every embed path
        # (HTTP /nornicdb/embed, query embedding, EmbedWorker drains);
        # the cache sits outside so hits skip the queue entirely
        embedder = ServingEngine(embedder, serving_cfg)
    db.set_embedder(CachedEmbedder(embedder))
    # with an assistant checkpoint mounted, build + warm the generation
    # engine now: the paged prefill/decode programs compile before traffic
    # instead of inside the first request's deadline
    if os.environ.get("NORNICDB_ASSISTANT_MODEL") and \
            app_cfg.genserve.enabled:
        _ = db.heimdall
        gen_engine = db.genserve_engine()
        if gen_engine is not None:
            gen_engine.warmup()

    authenticator = None
    if args.auth:
        from nornicdb_tpu.errors import AlreadyExistsError

        system = db.database_manager.get_storage(SYSTEM_DB)
        authenticator = Authenticator(system)
        try:
            authenticator.create_user(
                "admin", os.environ.get("NORNICDB_ADMIN_PASSWORD", "admin"),
                ROLE_ADMIN,
            )
        except AlreadyExistsError:
            pass  # exists from a previous run

    http_server = HttpServer(
        db, host=args.host, port=args.http_port,
        authenticator=authenticator, auth_required=args.auth,
        serve_ui=not args.headless,
    )
    http_server.start()
    bolt_server = BoltServer(
        lambda q, p, d: (db.executor_for(d) if d else db.executor).execute(q, p),
        host=args.host, port=args.bolt_port,
        authenticator=authenticator, auth_required=args.auth,
        session_executor_factory=db.session_executor,
    )
    bolt_server.start()
    # Qdrant gRPC on :6334, feature-flagged like the reference
    # (NORNICDB_QDRANT_GRPC_ENABLED, ref: server.go feature flag)
    qdrant_server = None
    if os.environ.get("NORNICDB_QDRANT_GRPC_ENABLED", "").lower() in (
        "1", "true", "yes",
    ):
        from nornicdb_tpu.server.qdrant_grpc import QdrantGrpcServer

        qdrant_server = QdrantGrpcServer(
            http_server.qdrant,  # shared registry: REST + gRPC, one index
            host=args.host,
            port=int(os.environ.get("NORNICDB_QDRANT_GRPC_PORT", "6334")),
            authenticator=authenticator,
            snapshot_dir=os.path.join(args.data_dir, "qdrant-snapshots")
            if args.data_dir else None,
        )
        qdrant_server.start()
    # native gRPC search on :50051, feature-flagged like the reference's
    # nornicgrpc service (ref: search_service.go)
    grpc_server = None
    if os.environ.get("NORNICDB_GRPC_ENABLED", "").lower() in (
        "1", "true", "yes",
    ):
        try:
            from nornicdb_tpu.server.grpc_search import GrpcSearchServer

            grpc_server = GrpcSearchServer(
                db, host=args.host,
                port=int(os.environ.get("NORNICDB_GRPC_PORT", "50051")),
            )
            grpc_server.start()
        except ImportError:
            print("NORNICDB_GRPC_ENABLED set but grpcio is not installed; "
                  "native gRPC disabled", file=sys.stderr)
    # prefork protocol workers: N subprocesses on a shared SO_REUSEPORT
    # public port, serving vector search through the device broker with a
    # shared-memory fallback (docs/operations.md "Multi-process serving")
    workers_cfg = app_cfg.workers
    n_http_workers = (args.workers if args.workers is not None
                      else workers_cfg.http)
    http_pool = grpc_pool = None
    rate = ((workers_cfg.rate_limit, workers_cfg.rate_burst)
            if workers_cfg.rate_limit > 0 else None)
    if n_http_workers > 0:
        from nornicdb_tpu.server.workers import WorkerPool

        http_pool = WorkerPool(
            db, http_server.port, n_workers=n_http_workers,
            host="127.0.0.1" if args.host == "0.0.0.0" else args.host,
            kind="http", public_port=workers_cfg.port,
            rate_limit=rate, broker=workers_cfg.broker,
            read_plane=workers_cfg.read_plane,
            respawn=workers_cfg.respawn,
            publish_interval=workers_cfg.publish_interval,
            auth_required=args.auth,
            metrics=workers_cfg.metrics,
            metrics_interval=workers_cfg.metrics_interval,
        ).start()
    if workers_cfg.grpc > 0 and grpc_server is not None:
        from nornicdb_tpu.server.workers import WorkerPool

        grpc_pool = WorkerPool(
            db, grpc_server.port, n_workers=workers_cfg.grpc,
            host="127.0.0.1" if args.host == "0.0.0.0" else args.host,
            kind="grpc", public_port=workers_cfg.grpc_port,
            rate_limit=rate,
            # share the HTTP pool's broker: one device owner per host
            broker=(http_pool.broker if http_pool is not None
                    and http_pool.broker is not None
                    else workers_cfg.broker),
            read_plane=workers_cfg.read_plane,
            respawn=workers_cfg.respawn,
            publish_interval=workers_cfg.publish_interval,
            auth_required=args.auth,
            metrics=workers_cfg.metrics,
            metrics_interval=workers_cfg.metrics_interval,
        ).start()
    print(f"NornicDB-TPU serving: bolt://{args.host}:{bolt_server.port} "
          f"http://{args.host}:{http_server.port}"
          + (f" qdrant-grpc://{args.host}:{qdrant_server.port}"
             if qdrant_server else "")
          + (f" grpc://{args.host}:{grpc_server.port}"
             if grpc_server else "")
          + (f" http-workers://{http_pool.host}:{http_pool.port}"
             f" x{http_pool.n_workers}" if http_pool else "")
          + (f" grpc-workers://{grpc_pool.host}:{grpc_pool.port}"
             f" x{grpc_pool.n_workers}" if grpc_pool else "")
          + f" (data: {args.data_dir or 'memory'})")

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        print("shutting down...")
        if grpc_pool is not None:
            grpc_pool.stop()
        if http_pool is not None:
            http_pool.stop()
        if grpc_server is not None:
            grpc_server.stop()
        if qdrant_server is not None:
            qdrant_server.stop()
        bolt_server.stop()
        http_server.stop()
        db.close()
    return 0


def cmd_init(args) -> int:
    db = _open_db(args)
    db.close()
    print(f"initialized data directory {args.data_dir}")
    return 0


def cmd_shell(args) -> int:
    """(ref: nornicdb shell)"""
    db = _open_db(args)
    print("NornicDB-TPU shell. Cypher queries, or :quit")
    try:
        while True:
            try:
                line = input("cypher> ").strip()
            except EOFError:
                break
            if not line:
                continue
            if line in (":quit", ":exit", "quit", "exit"):
                break
            try:
                result = db.cypher(line)
                if result.columns:
                    print("\t".join(result.columns))
                    for row in result.rows:
                        print("\t".join(str(v) for v in row))
                stats = result.stats.as_dict()
                if stats:
                    print(f"-- {stats}")
            except Exception as e:
                print(f"error: {e}")
    finally:
        db.close()
    return 0


def cmd_import(args) -> int:
    """Neo4j-style JSON / Mimir JSONL import (ref: nornicdb import,
    storage loader.go + mimir_loader.go)."""
    from nornicdb_tpu.storage.io import import_json, load_mimir

    db = _open_db(args)
    try:
        if args.format == "mimir":
            n_nodes, n_edges = load_mimir(db.storage, args.file)
        else:
            with open(args.file) as f:
                data = json.load(f)
            n_nodes, n_edges = import_json(db.storage, data)
    finally:
        db.close()
    print(f"imported {n_nodes} nodes, {n_edges} relationships")
    return 0


def cmd_export(args) -> int:
    """Neo4j-style JSON export (ref: types.go:475-707)."""
    from nornicdb_tpu.storage.io import export_json

    db = _open_db(args)
    try:
        data = export_json(db.storage)
    finally:
        db.close()
    out = json.dumps(data, indent=2, default=str)
    if args.file == "-":
        print(out)
    else:
        with open(args.file, "w") as f:
            f.write(out)
        print(f"exported {len(data['nodes'])} nodes, "
              f"{len(data['relationships'])} relationships to {args.file}")
    return 0


def _require_data_dir(args) -> bool:
    """backup/restore against an empty data dir would silently operate on an
    ephemeral in-memory engine — success messages with nothing persisted."""
    if not args.data_dir:
        print("error: --data-dir (or NORNICDB_DATA_DIR) is required for "
              "this command", file=sys.stderr)
        return False
    return True


def cmd_backup(args) -> int:
    """Full-fidelity backup archive (ref: badger_backup.go role)."""
    if not _require_data_dir(args):
        return 2
    db = _open_db(args)
    try:
        path = db.backup(args.file if args.file != "-" else None)
    finally:
        db.close()
    print(f"backup written to {path}")
    return 0


def cmd_restore(args) -> int:
    if not _require_data_dir(args):
        return 2
    db = _open_db(args)
    try:
        counts = db.restore(args.file)
    finally:
        db.close()
    print(f"restored {counts['nodes']} nodes, {counts['edges']} edges")
    return 0


def cmd_eval(args) -> int:
    """Search-quality evaluation (ref: cmd/eval, pkg/eval harness)."""
    from nornicdb_tpu.embed import HashEmbedder
    from nornicdb_tpu.eval import Harness

    db = _open_db(args)
    try:
        if db.embedder is None:
            db.set_embedder(HashEmbedder(args.embed_dims))
            db.process_pending_embeddings()
        cases = Harness.load_suite(args.suite)
        thresholds = json.loads(args.thresholds) if args.thresholds else {}
        harness = Harness(
            lambda q, k: [r["id"] for r in db.search.search(q, limit=k)],
            k=args.k, thresholds=thresholds,
        )
        report = harness.run(cases)
        print(json.dumps({"metrics": report.metrics.as_dict(),
                          "passed": report.passed}, indent=2))
        return 0 if report.passed else 1
    finally:
        db.close()


def cmd_decay(args) -> int:
    """(ref: nornicdb decay {recalculate,archive,stats})"""
    db = _open_db(args)
    try:
        if args.action == "recalculate":
            scored, archived = db.decay.recalculate_all()
            print(f"scored {scored} nodes, archived {archived}")
        elif args.action == "stats":
            print(json.dumps(vars(db.decay.stats)))
        elif args.action == "archive":
            nodes = db.decay.archived_nodes()
            print(f"{len(nodes)} archived nodes")
    finally:
        db.close()
    return 0


def cmd_dataset(args) -> int:
    """(ref: neural/scripts dataset tooling)"""
    from itertools import chain

    from nornicdb_tpu.models import dataset

    if args.action == "validate":
        report = dataset.validate_jsonl(args.file)
        print(json.dumps(report, indent=2))
        return 0 if report["invalid"] == 0 else 1
    gens = []
    if args.kind in ("cypher", "all"):
        gens.append(dataset.generate_cypher_examples(
            args.count if args.kind == "cypher"
            else args.count - args.count // 2,  # odd counts stay exact
            seed=args.seed))
    if args.kind in ("heimdall", "all"):
        gens.append(dataset.generate_heimdall_examples(
            args.count if args.kind == "heimdall" else args.count // 2,
            seed=args.seed))
    n = dataset.write_jsonl(args.file, chain(*gens))
    print(f"wrote {n} examples to {args.file}")
    return 0


def cmd_train(args) -> int:
    """(replaces the reference's offline neural/train.py pipeline with
    first-class in-image training; see models/pretrain.py)"""
    from nornicdb_tpu.models import pretrain

    if args.model == "assistant":
        # facts + ACTION-MODE corpus: the served assistant must emit
        # machine-parseable query/status actions (measured held-out rates
        # in tests/test_heimdall_actions.py)
        corpus = (pretrain.synth_corpus(0, repeats=6)
                  + pretrain.synth_action_corpus(0, repeats=6))
        stats = pretrain.train_assistant(
            args.out, steps=args.steps or 1400, batch=24, seq_len=64,
            hidden=128, lr=2e-3, corpus=corpus,
        )
    else:
        stats = pretrain.train_encoder(args.out, steps=args.steps or 250)
    print(json.dumps({"model": args.model, "out": args.out, **stats}))
    return 0


def cmd_kmeans_test_data(args) -> int:
    """K-means test-data generator (ref: cmd/kmeans-test-data, 884 LoC —
    synthetic/clustered embedding corpora for clustering benchmarks; the
    download/movies modes need egress, so this build ships the two
    deterministic generators plus optional direct DB import)."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    if args.mode == "clusters":
        centers = rng.normal(0, 1.0, (args.clusters, args.dims))
        assign = rng.integers(0, args.clusters, args.count)
        emb = centers[assign] + rng.normal(0, 0.15, (args.count, args.dims))
    else:  # synthetic: isotropic Gaussian -> uniform directions on the sphere
        assign = None
        emb = rng.normal(0, 1.0, (args.count, args.dims))
    emb = emb / np.maximum(
        np.linalg.norm(emb, axis=1, keepdims=True), 1e-12)

    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "embeddings.npz")
    if assign is not None:
        np.savez_compressed(path, embeddings=emb.astype(np.float32),
                            cluster=assign.astype(np.int32))
    else:
        np.savez_compressed(path, embeddings=emb.astype(np.float32))
    print(json.dumps({"mode": args.mode, "count": args.count,
                      "dims": args.dims, "out": path}))

    # --db overrides, else the global --data-dir (the flag pattern every
    # other subcommand uses); neither set = generate files only
    target = args.db or args.data_dir
    if target:
        args = argparse.Namespace(**{**vars(args), "data_dir": target})
        db = _open_db(args)
        try:
            from nornicdb_tpu.storage import Node

            from nornicdb_tpu.errors import AlreadyExistsError

            imported = skipped = 0
            for i in range(args.count):
                props = {"kind": "kmeans-test"}
                if assign is not None:
                    props["cluster"] = int(assign[i])
                try:
                    db.storage.create_node(Node(
                        id=f"kmtest-{args.seed}-{i}",
                        labels=["KMeansTest"],
                        properties=props,
                        embedding=emb[i].astype(np.float32),
                    ))
                    imported += 1
                except AlreadyExistsError:
                    skipped += 1  # re-run with the same seed: idempotent
            db.flush()
            print(json.dumps({"imported": imported, "skipped": skipped,
                              "db": target}))
        finally:
            db.close()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nornicdb", description="NornicDB-TPU")
    p.add_argument("--data-dir", default=os.environ.get("NORNICDB_DATA_DIR", ""),
                   help="data directory (empty = in-memory)")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("serve", help="run the database server")
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--bolt-port", type=int, default=7687)
    s.add_argument("--http-port", type=int, default=7474)
    s.add_argument("--auth", action="store_true", help="require authentication")
    s.add_argument("--headless", action="store_true",
                   help="no browser UI (ref: -tags noui builds)")
    s.add_argument("--embedder", choices=["hash", "tpu", "trained", "student"],
                   default="tpu")
    s.add_argument("--embed-dims", type=int, default=1024)
    s.add_argument("--model-preset", default="bge_small")
    s.add_argument("--log-queries", action="store_true",
                   help="log every Cypher statement with wall time")
    s.add_argument("--workers", type=int, default=None,
                   help="prefork HTTP protocol workers (overrides the "
                        "workers.http config; 0 disables)")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("init", help="initialize a data directory")
    s.set_defaults(fn=cmd_init)

    s = sub.add_parser("shell", help="interactive Cypher shell")
    s.set_defaults(fn=cmd_shell)

    s = sub.add_parser("import", help="import Neo4j-style JSON or Mimir JSONL")
    s.add_argument("file")
    s.add_argument("--format", choices=["json", "mimir"], default="json")
    s.set_defaults(fn=cmd_import)

    s = sub.add_parser("export", help="export the graph as Neo4j-style JSON")
    s.add_argument("file", help="output path, or - for stdout")
    s.set_defaults(fn=cmd_export)

    s = sub.add_parser("backup", help="write a full-fidelity backup archive")
    s.add_argument("file", nargs="?", default="-",
                   help="output .json.gz path (default: <data-dir>/backups/)")
    s.set_defaults(fn=cmd_backup)

    s = sub.add_parser("restore", help="restore a backup archive")
    s.add_argument("file", help="backup .json.gz path")
    s.set_defaults(fn=cmd_restore)

    s = sub.add_parser("eval", help="run a search-quality evaluation suite")
    s.add_argument("suite", help="JSON suite: [{query, relevant: [ids]}]")
    s.add_argument("--k", type=int, default=10)
    s.add_argument("--embed-dims", type=int, default=256)
    s.add_argument("--thresholds", default="", help='JSON e.g. {"mrr": 0.8}')
    s.set_defaults(fn=cmd_eval)

    s = sub.add_parser("decay", help="memory decay operations")
    s.add_argument("action", choices=["recalculate", "archive", "stats"])
    s.set_defaults(fn=cmd_decay)

    s = sub.add_parser(
        "train",
        help="train in-image model checkpoints (assistant decoder via LM "
             "loss, embedding encoder via InfoNCE) on the synthetic domain "
             "corpus — the zero-egress replacement for mounting GGUF weights",
    )
    s.add_argument("model", choices=["assistant", "encoder"])
    s.add_argument("--out", required=True, help="checkpoint output directory")
    s.add_argument("--steps", type=int, default=0,
                   help="train steps (default: per-model preset)")
    s.set_defaults(fn=cmd_train)

    s = sub.add_parser(
        "kmeans-test-data",
        help="generate synthetic/clustered embedding corpora for k-means "
             "benchmarks (ref: cmd/kmeans-test-data)",
    )
    s.add_argument("--mode", choices=["synthetic", "clusters"],
                   default="clusters")
    s.add_argument("--count", type=int, default=5000)
    s.add_argument("--dims", type=int, default=1024)
    s.add_argument("--clusters", type=int, default=20)
    s.add_argument("--out", default="./data/kmeans-test")
    s.add_argument("--db", default="",
                   help="NornicDB data directory (if set, imports directly)")
    s.add_argument("--seed", type=int, default=42)
    s.set_defaults(fn=cmd_kmeans_test_data)

    s = sub.add_parser(
        "dataset",
        help="generate / validate instruction-tuning datasets "
             "(ref: neural/scripts/generate_*_dataset.py, "
             "validate_dataset.py)",
    )
    s.add_argument("action", choices=["generate", "validate"])
    s.add_argument("file", help="JSONL path")
    s.add_argument("--kind", choices=["cypher", "heimdall", "all"],
                   default="all")
    s.add_argument("--count", type=int, default=1000)
    s.add_argument("--seed", type=int, default=42)
    s.set_defaults(fn=cmd_dataset)

    s = sub.add_parser(
        "oauth-provider",
        help="run the standalone OAuth 2.0 test provider "
             "(ref: cmd/oauth-provider — local OAuth integration testing)",
    )
    s.add_argument("--port", type=int, default=8888)
    s.add_argument("--client-id", default="nornicdb-local-test")
    s.add_argument("--client-secret", default="local-test-secret-123")
    s.set_defaults(fn=lambda a: __import__(
        "nornicdb_tpu.server.oauth_provider", fromlist=["main"]
    ).main(a.port, a.client_id, a.client_secret))

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
