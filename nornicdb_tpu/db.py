"""Core DB facade (ref: /root/reference/pkg/nornicdb/db.go).

`open()` assembles the storage chain, schema manager, search service, embed
queue, decay manager and inference engine, and exposes the memory-centric API:
Store / Recall / Remember / Link / Neighbors / Forget / Cypher
(ref: db.go:1365-1776).

Subsystems are attached progressively; the facade stays importable with only
the storage layer present.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage import (
    Edge,
    Engine,
    Node,
    SchemaManager,
    new_id,
    open_storage,
)
from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)


@dataclass
class Config:
    """DB configuration (ref: pkg/config/config.go:82-420, subset)."""

    async_writes: bool = True
    flush_interval: float = 0.05
    wal_sync: bool = False
    # at-rest encryption (ref: db.go:781-809 — PBKDF2-derived key)
    encryption_passphrase: str = ""
    # durable engine: wal (memory + WAL replay) | segment (native C++ KV)
    storage_engine: str = "wal"
    auto_compact: bool = False
    auto_compact_interval: float = 300.0
    # embedding
    embed_enabled: bool = True
    embed_dimensions: int = 1024
    embed_chunk_tokens: int = 512
    embed_chunk_overlap: int = 50
    embed_workers: int = 1
    # decay
    decay_enabled: bool = False
    decay_interval: float = 3600.0
    archive_threshold: float = 0.05
    # inference (auto-TLP)
    inference_enabled: bool = True
    similarity_threshold: float = 0.85
    # integration adapters (ref: topology_integration.go, cluster_integration.go)
    topology_integration: bool = False
    cluster_integration: bool = False
    # search
    search_brute_force_max: int = 5000
    # query cache (ref: pkg/cache, ConfigureGlobalCache main.go:320)
    query_cache_enabled: bool = True
    query_cache_size: int = 1000
    query_cache_ttl: float = 60.0
    log_queries: bool = False  # (ref: --log-queries cmd/nornicdb/main.go:137)
    feature_flags: dict[str, bool] = field(default_factory=dict)


class DB:
    """The core database handle (ref: nornicdb.DB db.go:434)."""

    def __init__(self, data_dir: str = "", config: Optional[Config] = None):
        self.config = config or Config()
        self.data_dir = data_dir
        self._base_storage: Engine = open_storage(
            data_dir,
            async_writes=self.config.async_writes,
            flush_interval=self.config.flush_interval,
            wal_sync=self.config.wal_sync,
            auto_compact=self.config.auto_compact,
            auto_compact_interval=self.config.auto_compact_interval,
            encryption_passphrase=self.config.encryption_passphrase,
            engine=self.config.storage_engine,
        )
        # The default database is itself a namespace on the shared base
        # engine, exactly like the reference's "nornic" namespace
        # (ref: NamespacedEngine wrap, db.go:896) — so multi-database views
        # never leak into default-DB scans.
        from nornicdb_tpu.multidb import DEFAULT_DB
        from nornicdb_tpu.storage import NamespacedEngine

        self.default_database = DEFAULT_DB
        self._migrate_unprefixed(self._base_storage, DEFAULT_DB)
        self.storage: Engine = NamespacedEngine(self._base_storage, DEFAULT_DB)
        self.schema = SchemaManager()
        self.schema.attach(self.storage)
        self._lock = threading.RLock()
        self._closed = False
        self._decay_started = False
        # attached lazily by subsystem setters
        self._embedder = None
        self._embed_worker = None
        self._search = None
        self._decay = None
        self._inference = None
        self._temporal = None
        self._executor = None
        self._dbmanager = None
        self._db_executors: dict[str, Any] = {}
        self._query_cache = None
        self._heimdall = None
        self._genserve = None
        self._graphrag = None
        self._vectorspaces = None
        self._qdrant = None
        if self.config.decay_enabled:
            _ = self.decay  # starts the periodic recalculation ticker

    @staticmethod
    def _migrate_unprefixed(base: Engine, namespace: str) -> None:
        """Re-key data persisted before namespacing (bare uuid ids) into the
        default namespace so old data dirs keep working."""
        stale_nodes = [n for n in base.all_nodes() if ":" not in n.id]
        if not stale_nodes:
            return
        stale_edges = [e for e in base.all_edges() if ":" not in e.id]
        pending = set(base.pending_embed_ids())
        for e in stale_edges:
            base.delete_edge(e.id)
        for n in stale_nodes:
            base.delete_node(n.id)
        for n in stale_nodes:
            migrated = n.copy()
            migrated.id = f"{namespace}:{n.id}"
            base.create_node(migrated)
            if n.id in pending:
                base.mark_pending_embed(migrated.id)
        for e in stale_edges:
            migrated = e.copy()
            migrated.id = f"{namespace}:{e.id}"
            if ":" not in migrated.start_node:
                migrated.start_node = f"{namespace}:{migrated.start_node}"
            if ":" not in migrated.end_node:
                migrated.end_node = f"{namespace}:{migrated.end_node}"
            base.create_edge(migrated)

    def invalidate_database_cache(self, name: str) -> None:
        """Drop the cached per-DB executor after DROP DATABASE / limit changes."""
        with self._lock:
            self._db_executors.pop(name, None)

    # -- subsystem wiring --------------------------------------------------
    def set_embedder(self, embedder) -> None:
        """(ref: DB.SetEmbedder db.go:1074) — also starts the embed worker."""
        old_engine = self.serving_engine()
        self._embedder = embedder
        if old_engine is not None and old_engine is not self.serving_engine():
            # the replaced chain carried a continuous batching engine:
            # stop its pipeline threads instead of leaking them
            old_engine.stop()
        if self._search is not None:
            self._search.embedder = embedder
        if self._embed_worker is not None:
            self._embed_worker.stop()
            self._embed_worker = None
        if self.config.embed_enabled and embedder is not None:
            from nornicdb_tpu.embed.queue import EmbedWorker, EmbedWorkerConfig

            self._embed_worker = EmbedWorker(
                # the worker drains the BASE engine so pending nodes from
                # every database namespace get embedded, not just the default
                self._base_storage,
                embedder,
                EmbedWorkerConfig(
                    chunk_tokens=self.config.embed_chunk_tokens,
                    chunk_overlap=self.config.embed_chunk_overlap,
                    workers=self.config.embed_workers,
                ),
                # debounced k-means refit after bulk embedding
                # (ref: scheduleClusteringDebounced embed_queue.go:257)
                on_cluster_trigger=lambda: self.search.recluster(),
                # the learning loop: freshly-embedded nodes feed auto-TLP
                # (ref: SURVEY.md §3.3 embed -> inference.OnStore)
                on_embedded=self._on_embedded,
            )
            self._embed_worker.start()

    def _on_embedded(self, node) -> None:
        # node comes from the base engine with a namespaced id; auto-TLP
        # currently runs over the default database only
        prefix = f"{self.default_database}:"
        if self.config.inference_enabled and node.id.startswith(prefix):
            bare = node.copy()
            bare.id = node.id[len(prefix):]
            self.inference.on_store(bare)

    @property
    def embedder(self):
        return self._embedder

    def serving_engine(self):
        """The continuous batching ServingEngine in the embedder chain
        (CachedEmbedder(ServingEngine(inner)) is the `cli serve` stack),
        or None when serving isn't engine-fronted."""
        from nornicdb_tpu.serving import ServingEngine

        e = self._embedder
        seen = 0
        while e is not None and seen < 8:
            if isinstance(e, ServingEngine):
                return e
            e = getattr(e, "inner", None)
            seen += 1
        return None

    @property
    def search(self):
        with self._lock:
            svc = self._search
        if svc is not None:
            return svc
        from nornicdb_tpu.search.service import SearchService

        # construct + backfill OUTSIDE the db lock: the index build may
        # cold-acquire the device backend (bounded by the lifecycle
        # manager, but still seconds — NL-DEV01 bans it under any lock)
        # and can itself take seconds on a large corpus. Losers of the
        # creation race detach their event subscription and discard.
        svc = SearchService(
            self.storage,
            embedder=self._embedder,
            brute_force_max=self.config.search_brute_force_max,
            vectorspaces=self.vectorspaces,
        )
        # wire storage events + backfill existing nodes
        # (ref: db.go:1020-1033, EnsureSearchIndexesBuilt db.go:1044)
        svc.attach(self.storage)
        svc.build_indexes()
        with self._lock:
            if self._search is None:
                self._search = svc
                return svc
            winner = self._search
        svc.detach(self.storage)
        svc.shutdown()  # stop the loser's uploader thread; let it GC
        return winner

    @property
    def vectorspaces(self):
        """Canonical named vector spaces (ref: pkg/vectorspace registry)."""
        with self._lock:
            if self._vectorspaces is None:
                from nornicdb_tpu.vectorspace import VectorSpaceRegistry

                self._vectorspaces = VectorSpaceRegistry()
            return self._vectorspaces

    def qdrant_registry(self):
        """The ONE QdrantCollections registry for this db: the HTTP
        /collections/* surface, the Qdrant gRPC services, and the device
        broker's worker-side search path must share it — per-transport
        registries would each build their own per-collection device
        corpora (double residency) and drift on upserts (ref: the
        reference's "single unified vector index", pkg/qdrantgrpc
        server.go).

        Constructed OUTSIDE the db lock (the `search` property's
        pattern): the registry rebuild scans every persisted point and
        builds per-collection device corpora — seconds on a large point
        set, and every db-lock user would stall behind it. Losers of the
        creation race discard their registry before it serves anything."""
        with self._lock:
            if self._qdrant is not None:
                return self._qdrant
        from nornicdb_tpu.server.qdrant import QdrantCollections

        registry = QdrantCollections(
            self.storage, vectorspaces=self.vectorspaces
        )
        with self._lock:
            if self._qdrant is None:
                self._qdrant = registry
            return self._qdrant

    @property
    def query_cache(self):
        if self._query_cache is None:
            from nornicdb_tpu.cache import QueryCache
            from nornicdb_tpu.storage import Edge as _Edge, Node as _Node

            cache = QueryCache(
                capacity=self.config.query_cache_size,
                ttl=self.config.query_cache_ttl,
            )

            # Direct storage mutations (store/forget, decay, retention,
            # Qdrant upserts) must invalidate too — not just Cypher writes.
            def _on_event(kind: str, entity) -> None:
                if isinstance(entity, _Node):
                    if entity.labels:
                        cache.invalidate_labels(set(entity.labels))
                    else:
                        cache.clear()
                elif isinstance(entity, _Edge):
                    labels: set = set()
                    for nid in (entity.start_node, entity.end_node):
                        try:
                            labels.update(self.storage.get_node(nid).labels)
                        except Exception:
                            # endpoint vanished mid-event: we can't scope the
                            # invalidation, so drop everything (sound) — but
                            # leave a trace + counter so a hot loop of these
                            # (cache thrash) is visible to operators
                            log.debug("query-cache label scope lookup failed "
                                      "for %s; clearing cache", nid,
                                      exc_info=True)
                            count_error("db.query_cache_invalidate")
                            cache.clear()
                            return
                    if labels:
                        cache.invalidate_labels(labels)
                    else:
                        cache.clear()

            self.storage.on_event(_on_event)
            self._query_cache = cache
        return self._query_cache

    @property
    def executor(self):
        if self._executor is None:
            from nornicdb_tpu.cypher.executor import CypherExecutor

            cache = self.query_cache if self.config.query_cache_enabled else None
            self._executor = CypherExecutor(
                self.storage, schema=self.schema, db=self, cache=cache,
                log_queries=self.config.log_queries,
            )
        return self._executor

    @property
    def heimdall(self):
        """(ref: pkg/heimdall manager wiring). With a trained checkpoint
        mounted (NORNICDB_ASSISTANT_MODEL=<dir>, produced by
        `nornicdb train` / models.pretrain.train_assistant) the assistant
        runs the real prefill+KV-cache decode path; otherwise the
        deterministic template fallback (ref: llama_stub.go builds)."""
        if self._heimdall is None:
            from nornicdb_tpu.heimdall import HeimdallManager, TemplateGenerator

            generator = None
            model_dir = os.environ.get("NORNICDB_ASSISTANT_MODEL", "")
            if model_dir:
                try:
                    from nornicdb_tpu.models.pretrain import load_generator

                    generator = load_generator(model_dir)
                except Exception:  # bad checkpoint: fall back, loudly
                    log.warning(
                        "assistant checkpoint %r failed to load; using "
                        "template generator", model_dir, exc_info=True,
                    )
                    count_error("heimdall.checkpoint_load")
            if generator is None:
                generator = TemplateGenerator(self)
            self._heimdall = HeimdallManager(
                self._wire_genserve(generator), db=self)
        return self._heimdall

    def set_heimdall_generator(self, generator) -> None:
        from nornicdb_tpu.heimdall import HeimdallManager

        self._heimdall = HeimdallManager(
            self._wire_genserve(generator), db=self)

    def _wire_genserve(self, generator):
        """Front a weights-backed generator with the genserve
        continuous-batching engine (paged-KV decode, admission control,
        deadline shedding — docs/generation.md).  Template/stub
        generators pass through unchanged; so does genserve.enabled=False
        (the synchronous per-request path stays the escape hatch)."""
        if self._genserve is not None:
            self._genserve.stop()
            self._genserve = None
        self._graphrag = None  # rebuilt against the new engine on demand
        if not all(hasattr(generator, a)
                   for a in ("params", "cfg", "tokenizer")):
            return generator
        from nornicdb_tpu import genserve

        gcfg = genserve.current_config()
        if not getattr(gcfg, "enabled", True):
            return generator
        from nornicdb_tpu.heimdall import EngineGenerator

        self._genserve = genserve.GenerationEngine(
            generator.params, generator.cfg,
            tokenizer=generator.tokenizer, config=gcfg)
        return EngineGenerator(
            self._genserve,
            max_context=getattr(generator, "max_context", 256))

    def genserve_engine(self):
        """The generation engine behind Heimdall, or None when generation
        is template-backed / disabled (observability surfaces must not
        force the assistant to build)."""
        return self._genserve

    def graphrag(self):
        """GraphRAG answer service over this DB's search + adjacency +
        generation engine (``POST /nornicdb/rag/answer``).  Cached: the
        service resolves its config once, not per request."""
        if self._graphrag is None:
            from nornicdb_tpu.genserve import GraphRAGService

            _ = self.heimdall  # builds the engine when weights exist
            self._graphrag = GraphRAGService(self, engine=self._genserve)
        return self._graphrag

    @property
    def decay(self):
        if self._decay is None:
            from nornicdb_tpu.decay.decay import DecayConfig, DecayManager

            self._decay = DecayManager(
                self.storage,
                config=DecayConfig(
                    archive_threshold=self.config.archive_threshold,
                    interval=self.config.decay_interval,
                ),
            )
            if self.config.decay_enabled and not self._decay_started:
                # periodic recalculation ticker (ref: decay.Start decay.go:643)
                self._decay.start()
                self._decay_started = True
        return self._decay

    @property
    def inference(self):
        if self._inference is None:
            from nornicdb_tpu.inference.engine import InferenceEngine

            engine = InferenceEngine(
                self.storage,
                similarity_fn=self._similarity_candidates,
                similarity_threshold=self.config.similarity_threshold,
            )
            if self.config.topology_integration:
                from nornicdb_tpu.inference.integrations import TopologyIntegration

                TopologyIntegration(self.storage).attach(engine)
            if self.config.cluster_integration:
                from nornicdb_tpu.inference.integrations import ClusterIntegration

                ClusterIntegration(
                    lambda: self.search.cluster_assignments
                ).attach(engine)
            self._inference = engine
        return self._inference

    @property
    def database_manager(self):
        """(ref: multidb.NewDatabaseManager cmd/nornicdb/main.go:501)"""
        with self._lock:
            if self._dbmanager is None:
                from nornicdb_tpu.multidb import DatabaseManager

                self._dbmanager = DatabaseManager(
                    self._base_storage,
                    on_invalidate=self.invalidate_database_cache,
                )
            return self._dbmanager

    def session_executor(self, database: Optional[str] = None):
        """A FRESH executor with its own explicit-transaction scope, for
        per-connection sessions (Bolt BEGIN/COMMIT isolation). Shares
        storage, schema, facade hooks and the query cache."""
        from nornicdb_tpu.cypher.executor import CypherExecutor

        if database and self.database_manager.resolve(database) != self.default_database:
            # share the database's CACHED schema (executor_for builds and
            # attaches it once): a fresh SchemaManager per session would
            # forget indexes/constraints created by earlier requests and
            # leak a permanent on_event subscription + full-store scan
            # per session
            base = self.executor_for(database)
            return CypherExecutor(base.storage, schema=base.schema, db=self,
                                  log_queries=self.config.log_queries)
        cache = self.query_cache if self.config.query_cache_enabled else None
        return CypherExecutor(self.storage, schema=self.schema, db=self,
                              cache=cache,
                              log_queries=self.config.log_queries)

    def executor_for(self, database: str):
        """Per-database Cypher executor over the namespaced engine
        (ref: :USE handling executor.go:500-541). Cached under the RESOLVED
        name so alias-routed executors die with their target database."""
        database = self.database_manager.resolve(database)
        if database == self.default_database:
            return self.executor
        with self._lock:
            ex = self._db_executors.get(database)
            if ex is None:
                from nornicdb_tpu.cypher.executor import CypherExecutor
                from nornicdb_tpu.storage import SchemaManager

                storage = self.database_manager.get_storage(database)
                schema = SchemaManager()
                schema.attach(storage)
                ex = CypherExecutor(storage, schema=schema, db=self,
                                    log_queries=self.config.log_queries)
                self._db_executors[database] = ex
            return ex

    @property
    def temporal(self):
        if self._temporal is None:
            from nornicdb_tpu.temporal.tracker import TemporalTracker

            self._temporal = TemporalTracker()
        return self._temporal

    def _similarity_candidates(self, embedding, k: int = 10):
        return self.search.vector_candidates(embedding, k=k)

    # -- memory-centric API (ref: db.go:1365-1776) --------------------------
    def store(
        self,
        content: str,
        *,
        labels: Optional[list[str]] = None,
        properties: Optional[dict[str, Any]] = None,
        memory_type: str = "semantic",
        node_id: Optional[str] = None,
    ) -> Node:
        """Store a memory node; queues it for auto-embedding (ref: Store db.go:1365)."""
        props = dict(properties or {})
        props.setdefault("content", content)
        node = Node(
            id=node_id or new_id(),
            labels=list(labels or ["Memory"]),
            properties=props,
            memory_type=memory_type,
        )
        created = self.storage.create_node(node)
        if self.config.embed_enabled:
            self.storage.mark_pending_embed(created.id)
        if self.config.inference_enabled and self._inference is not None:
            self._inference.on_store(created)
        return created

    def recall(self, query: str, limit: int = 10) -> list[dict[str, Any]]:
        """Hybrid search over stored memories (ref: Recall db.go)."""
        results = self.search.search(query, limit=limit)
        for r in results:
            self.touch(r["id"])
        return results

    def remember(self, node_id: str) -> Node:
        """Fetch + reinforce a memory (ref: Remember db.go)."""
        node = self.touch(node_id)
        if self.config.inference_enabled:
            self.inference.on_access(node_id)
        return node

    def touch(self, node_id: str) -> Node:
        """Record an access: bump access_count + last_accessed."""
        node = self.storage.get_node(node_id)
        node.access_count += 1
        node.last_accessed = time.time()
        if self._temporal is not None:
            self._temporal.record_access(node_id)
        return self.storage.update_node(node)

    def link(
        self,
        from_id: str,
        to_id: str,
        rel_type: str = "RELATED_TO",
        *,
        properties: Optional[dict[str, Any]] = None,
        confidence: float = 1.0,
        auto_generated: bool = False,
    ) -> Edge:
        """(ref: Link db.go)"""
        edge = Edge(
            start_node=from_id,
            end_node=to_id,
            type=rel_type,
            properties=dict(properties or {}),
            confidence=confidence,
            auto_generated=auto_generated,
        )
        return self.storage.create_edge(edge)

    def neighbors(self, node_id: str, depth: int = 1) -> list[Node]:
        """BFS neighborhood (ref: Neighbors db.go)."""
        seen = {node_id}
        frontier = [node_id]
        out: list[Node] = []
        for _ in range(depth):
            nxt: list[str] = []
            for nid in frontier:
                for e in self.storage.get_outgoing_edges(nid):
                    if e.end_node not in seen:
                        seen.add(e.end_node)
                        nxt.append(e.end_node)
                for e in self.storage.get_incoming_edges(nid):
                    if e.start_node not in seen:
                        seen.add(e.start_node)
                        nxt.append(e.start_node)
            out.extend(self.storage.batch_get_nodes(nxt))
            frontier = nxt
        return out

    def forget(self, node_id: str) -> None:
        """(ref: Forget db.go) — index removal rides the node_deleted event."""
        self.storage.delete_node(node_id)

    # -- Cypher ------------------------------------------------------------
    def cypher(self, query: str, params: Optional[dict[str, Any]] = None):
        """Execute a Cypher query (ref: ExecuteCypher db.go)."""
        return self.executor.execute(query, params or {})

    execute_cypher = cypher

    # -- maintenance -------------------------------------------------------
    def process_pending_embeddings(self, batch: int = 0) -> int:
        """Synchronously drain the pending-embed queue (test/CLI hook)."""
        if self._embed_worker is None:
            return 0
        return self._embed_worker.drain(batch)

    def flush(self) -> None:
        self.storage.flush()

    def wal_stats(self) -> Optional[dict[str, Any]]:
        """WAL health incl. degraded-mode flag (ref: wal_degraded.go), or
        None when the store has no WAL (in-memory / segment engine)."""
        eng = self._base_storage
        while eng is not None:
            wal = getattr(eng, "wal", None)
            if wal is not None:
                return dict(vars(wal.stats))
            eng = getattr(eng, "base", None)
        return None

    def adjacency_stats(self) -> Optional[dict[str, Any]]:
        """CSR adjacency snapshot counters (storage/adjacency.py), or None
        before the first traversal/GDS query attaches one."""
        snap = getattr(self.storage, "_adjacency_snapshot", None)
        return snap.stats_snapshot() if snap is not None else None

    def cypher_stats(self) -> Optional[dict[str, Any]]:
        """Columnar Cypher engine counters (plan-cache hit/miss/
        invalidations + per-outcome query counts), or None before the
        executor exists — stats must never force its lazy construction."""
        col = getattr(self._executor, "columnar", None)
        return col.stats_snapshot() if col is not None else None

    # -- backup / restore (ref: badger_backup.go + /admin/backup,
    # db_admin.go admin ops) -----------------------------------------------
    def backup(self, dest_path: Optional[str] = None) -> str:
        """Full-fidelity gzip backup of the BASE engine — every database
        namespace, with embeddings/decay/access state intact (export_json
        deliberately drops those; backup must not) — plus the default-db
        schema. Returns the archive path."""
        import gzip
        import json as _json
        import time as _time

        self.flush()
        if dest_path is None:
            bdir = os.path.join(self.data_dir or ".", "backups")
            os.makedirs(bdir, exist_ok=True)
            stamp = _time.strftime("%Y%m%d-%H%M%S")
            dest_path = os.path.join(bdir, f"backup-{stamp}.json.gz")
            seq = 1
            while os.path.exists(dest_path):  # two backups in one second
                dest_path = os.path.join(
                    bdir, f"backup-{stamp}-{seq}.json.gz")
                seq += 1
        nodes = [n.to_dict() for n in self._base_storage.all_nodes()]
        node_ids = {n["id"] for n in nodes}
        # the two passes are not one atomic snapshot: a concurrent writer
        # can add a node+edge between them. Keep the archive a consistent
        # prefix by dropping edges whose endpoints missed the node pass.
        edges = [
            e.to_dict() for e in self._base_storage.all_edges()
            if e.start_node in node_ids and e.end_node in node_ids
        ]
        payload = {
            "version": 1,
            "nodes": nodes,
            "edges": edges,
            "pending_embed": list(self._base_storage.pending_embed_ids()),
            "schema": {
                "indexes": [
                    {"name": i.name, "kind": i.kind, "label": i.label,
                     "properties": list(i.properties),
                     "options": dict(i.options)}
                    for i in self.schema.list_indexes()
                ],
                "constraints": [
                    {"name": c.name, "label": c.label,
                     "properties": list(c.properties), "kind": c.kind}
                    for c in self.schema.list_constraints()
                ],
            },
        }
        tmp = dest_path + ".tmp"
        with gzip.open(tmp, "wt") as f:
            _json.dump(payload, f)
        os.replace(tmp, dest_path)  # a torn backup must never look complete
        return dest_path

    def restore(self, src_path: str, skip_existing: bool = True) -> dict:
        """Load a backup archive into the base engine. Existing records are
        kept (skip_existing) or cause an error; returns counts."""
        import gzip
        import json as _json

        from nornicdb_tpu.errors import AlreadyExistsError
        from nornicdb_tpu.storage.types import Edge, Node

        with gzip.open(src_path, "rt") as f:
            payload = _json.load(f)
        # DDL first so the index value-maps exist while data loads
        sch = payload.get("schema", {})
        for i in sch.get("indexes", []):
            self.schema.create_index(i["name"], i["kind"], i["label"],
                                     i["properties"], i.get("options"),
                                     if_not_exists=True)
        for c in sch.get("constraints", []):
            self.schema.create_constraint(c["name"], c["label"],
                                          c["properties"], c.get("kind", "unique"),
                                          if_not_exists=True)
        n_nodes = n_edges = skipped_edges = 0
        for nd in payload.get("nodes", []):
            try:
                self._base_storage.create_node(Node.from_dict(nd))
                n_nodes += 1
            except AlreadyExistsError:
                if not skip_existing:
                    raise
        for ed in payload.get("edges", []):
            try:
                self._base_storage.create_edge(Edge.from_dict(ed))
                n_edges += 1
            except AlreadyExistsError:
                if not skip_existing:
                    raise
            except NotFoundError:
                skipped_edges += 1  # dangling edge in a foreign archive
        for nid in payload.get("pending_embed", []):
            self._base_storage.mark_pending_embed(nid)
        # schema value-maps only fill from storage events on the default-DB
        # view; restored records arrive via the base engine, so backfill the
        # index/constraint maps explicitly (idempotent)
        for n in self.storage.all_nodes():
            self.schema.index_node(n)
        # a live DatabaseManager caches the database list in memory; an
        # archive can introduce new databases (system-DB metadata nodes)
        if self._dbmanager is not None:
            self._dbmanager._load_metadata()
        out = {"nodes": n_nodes, "edges": n_edges}
        if skipped_edges:
            out["skipped_edges"] = skipped_edges
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._embed_worker is not None:
            self._embed_worker.stop()
        engine = self.serving_engine()
        if engine is not None:
            # stop the continuous batching pipeline; queued requests fail
            # fast with ClosedError instead of stranding callers
            engine.stop()
        if self._decay is not None:
            self._decay.stop()
        if self._genserve is not None:
            # generation engine: queued/running requests fail fast with
            # ClosedError instead of stranding callers
            self._genserve.stop()
        self._base_storage.close()

    def __enter__(self) -> "DB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open(data_dir: str = "", config: Optional[Config] = None) -> DB:  # noqa: A001
    """Open a database (ref: nornicdb.Open db.go:750)."""
    return DB(data_dir, config)
