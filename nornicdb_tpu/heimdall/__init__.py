"""Heimdall AI assistant (ref: /root/reference/pkg/heimdall/)."""

from nornicdb_tpu.heimdall.context import (
    CYPHER_PRIMER,
    GenerateParams,
    PromptContext,
    PromptExample,
    TokenBudget,
    estimate_tokens,
)
from nornicdb_tpu.heimdall.manager import (
    Bifrost,
    EngineGenerator,
    Generator,
    HeimdallManager,
    HeimdallMetrics,
    QwenGenerator,
    TemplateGenerator,
)
from nornicdb_tpu.heimdall.registry import (
    MODEL_CLASSIFICATION,
    MODEL_EMBEDDING,
    MODEL_REASONING,
    DatabaseEvent,
    EventDispatcher,
    MetricsRegistry,
    ModelInfo,
    ModelRegistry,
)

__all__ = [
    "Bifrost", "EngineGenerator", "Generator", "HeimdallManager",
    "HeimdallMetrics", "QwenGenerator", "TemplateGenerator",
    "PromptContext", "PromptExample", "TokenBudget", "GenerateParams",
    "CYPHER_PRIMER", "estimate_tokens",
    "ModelInfo", "ModelRegistry", "MetricsRegistry",
    "DatabaseEvent", "EventDispatcher",
    "MODEL_EMBEDDING", "MODEL_REASONING", "MODEL_CLASSIFICATION",
]
