"""Heimdall AI assistant (ref: /root/reference/pkg/heimdall/)."""

from nornicdb_tpu.heimdall.manager import (
    Bifrost,
    Generator,
    HeimdallManager,
    HeimdallMetrics,
    QwenGenerator,
    TemplateGenerator,
)

__all__ = [
    "Bifrost", "Generator", "HeimdallManager", "HeimdallMetrics",
    "QwenGenerator", "TemplateGenerator",
]
