"""Heimdall: the in-process AI assistant (TPU SLM).

Behavioral reference: /root/reference/pkg/heimdall/ —
Manager.Generate (scheduler.go:178), Handler.handleChatCompletions
(handler.go:207), action parsing from model output (tryParseAction :516),
streaming (:561), Bifrost SSE notification bus (bifrost.go:15), model
registry (types.go:20-37), plugin actions (plugin.go), metrics
(metrics.go).

The generation backend is the Qwen2 decoder on TPU
(nornicdb_tpu.models.qwen2 — replaces pkg/localllm llama.cpp), with a
deterministic template fallback when no weights are mounted.
"""

from __future__ import annotations

import json
import logging
import queue
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

from nornicdb_tpu.telemetry.metrics import count_error as _count_error

log = logging.getLogger(__name__)

from nornicdb_tpu.heimdall.context import (
    GenerateParams,
    PromptContext,
    PromptExample,
    estimate_tokens,
)
from nornicdb_tpu.heimdall.registry import (
    MODEL_CLASSIFICATION,
    MODEL_EMBEDDING,
    MODEL_REASONING,
    EventDispatcher,
    MetricsRegistry,
    ModelInfo,
    ModelRegistry,
)


@dataclass
class HeimdallMetrics:
    generations: int = 0
    tokens_generated: int = 0
    actions_executed: int = 0
    errors: int = 0
    total_latency: float = 0.0


class Bifrost:
    """Notification bus to UI subscribers (ref: bifrost.go:15 — SSE bus)."""

    def __init__(self) -> None:
        self._subs: list[queue.Queue] = []
        self._lock = threading.Lock()

    def subscribe(self) -> queue.Queue:
        # bounded: a stalled SSE client must not grow memory without
        # limit — broadcast's drop-on-full branch handles overflow
        q: queue.Queue = queue.Queue(maxsize=1000)
        with self._lock:
            self._subs.append(q)
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            if q in self._subs:
                self._subs.remove(q)

    def broadcast(self, event: str, data: Any) -> None:
        with self._lock:
            subs = list(self._subs)
        for q in subs:
            try:
                q.put_nowait({"event": event, "data": data, "ts": time.time()})
            except queue.Full:
                pass


class Generator:
    """Abstract generation backend (ref: generator_cgo.go / generator_yzma.go)."""

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        raise NotImplementedError

    def generate_stream(self, prompt: str, max_tokens: int = 128) -> Iterator[str]:
        yield self.generate(prompt, max_tokens)

    def generate_many(self, prompts: list[str],
                      max_tokens: int = 128) -> list[str]:
        """Batch generation.  The base fallback is sequential; backends
        with a serving engine (EngineGenerator) overlap the whole batch
        through continuous batching — Heimdall QC rides this."""
        return [self.generate(p, max_tokens) for p in prompts]


def _trim_prompt_ids(tokenizer, prompt: str, max_context: int) -> list[int]:
    """Shared weights-backed prompt policy: keep the prompt TAIL within
    the model's trained window — for in-image checkpoints rope positions
    beyond it were never seen in training."""
    return tokenizer.encode(prompt, add_special=False)[-max_context:] or [1]


def _cap_new_tokens(max_tokens: int, max_context: int) -> int:
    """Bound decode length to one trained window beyond the prompt:
    positions past 2x max_context are deep rope extrapolation for an
    in-image from-scratch model (held-out action rates were measured at
    prompt<=window + window new tokens).  ONE implementation for both
    weights-backed generators (QwenGenerator, EngineGenerator) so the
    window policy can never diverge between the sync and engine paths."""
    return max(1, min(max_tokens, max_context))


class QwenGenerator(Generator):
    """Qwen2-on-TPU backend (replaces llama.cpp generation)."""

    def __init__(self, cfg=None, params=None, tokenizer=None, seed: int = 0,
                 max_context: int = 256):
        import jax

        from nornicdb_tpu.models import qwen2
        from nornicdb_tpu.models.tokenizer import HashTokenizer

        self.cfg = cfg if cfg is not None else qwen2.QWEN_SMALL
        self.params = (
            params if params is not None
            else qwen2.init_params(self.cfg, jax.random.PRNGKey(seed))
        )
        self.tokenizer = tokenizer or HashTokenizer(self.cfg.vocab_size)
        self.qwen2 = qwen2
        # prompts are trimmed to the model's trained window: for in-image
        # checkpoints rope positions beyond it were never seen in training
        self.max_context = max_context

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        ids = _trim_prompt_ids(self.tokenizer, prompt, self.max_context)
        out = self.qwen2.generate(
            self.params, self.cfg, ids,
            max_new_tokens=self._cap_new_tokens(max_tokens),
            eos_id=getattr(self.tokenizer, "eos_id", -1),
        )
        return self.tokenizer.decode(out)

    def _cap_new_tokens(self, max_tokens: int) -> int:
        return _cap_new_tokens(max_tokens, self.max_context)

    def generate_stream(self, prompt: str, max_tokens: int = 128):
        """TRUE incremental decode (ref: GenerationModel streaming,
        llama.go:748 + generate.go): prefill once, then one jitted
        decode_step per yielded delta. Deltas are text diffs of the running
        decode so any tokenizer's spacing/punctuation rules hold."""
        import jax.numpy as jnp

        ids = _trim_prompt_ids(self.tokenizer, prompt, self.max_context)
        max_tokens = self._cap_new_tokens(max_tokens)
        # bucketed cache length: one compiled program per power-of-two
        # bucket instead of one per distinct prompt length
        max_len = self.qwen2.round_up_pow2(len(ids) + max_tokens)
        logits, caches = self.qwen2.prefill(
            self.params, self.cfg, jnp.asarray([ids], jnp.int32), max_len
        )
        eos = getattr(self.tokenizer, "eos_id", -1)
        tok = int(jnp.argmax(logits, axis=-1)[0])
        out: list[int] = []
        prev_text = ""
        pos = len(ids)
        while len(out) < max_tokens and tok != eos:
            out.append(tok)
            text = self.tokenizer.decode(out)
            if text != prev_text:
                yield text[len(prev_text):]
                prev_text = text
            if len(out) >= max_tokens:
                break
            logits, caches = self.qwen2.decode_step(
                self.params, self.cfg, jnp.asarray([tok], jnp.int32),
                caches, jnp.asarray(pos),
            )
            tok = int(jnp.argmax(logits, axis=-1)[0])
            pos += 1


class EngineGenerator(Generator):
    """Generator served by the genserve continuous-batching engine.

    Replaces the synchronous QwenGenerator path when genserve is enabled:
    every chat/QC generation becomes a submit into the shared paged-KV
    engine, so concurrent requests decode in ONE running batch instead of
    serializing, and admission control / deadline shedding apply
    (ResourceExhausted surfaces as HTTP 429 / Bolt transient at the
    edges).  Streaming is native: tokens are yielded as the scheduler
    produces them."""

    def __init__(self, engine, max_context: int = 256):
        self.engine = engine
        self.tokenizer = engine.tokenizer
        # same trained-window recency trim as QwenGenerator
        self.max_context = max_context
        # expose the backing model like QwenGenerator (pretrain tooling
        # and the model registry read these)
        self.cfg = engine.cfg
        self.params = engine.params

    def _ids(self, prompt: str) -> list[int]:
        return _trim_prompt_ids(self.tokenizer, prompt, self.max_context)

    def _cap(self, max_tokens: int) -> int:
        return _cap_new_tokens(max_tokens, self.max_context)

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        return self.tokenizer.decode(self.engine.generate(
            self._ids(prompt), max_new_tokens=self._cap(max_tokens)))

    def generate_stream(self, prompt: str, max_tokens: int = 128):
        handle = self.engine.submit(
            self._ids(prompt), max_new_tokens=self._cap(max_tokens))
        yield from handle.stream_text()

    def generate_many(self, prompts: list[str],
                      max_tokens: int = 128) -> list[str]:
        """Submit the whole batch up front: the engine's scheduler decodes
        every prompt in one continuous batch (this is the Heimdall QC
        path — previously one synchronous generate() per suggested
        edge)."""
        cap = self._cap(max_tokens)
        handles = [self.engine.submit(self._ids(p), max_new_tokens=cap)
                   for p in prompts]
        return [self.tokenizer.decode(h.result()) for h in handles]


class TemplateGenerator(Generator):
    """Deterministic fallback when no trained weights are mounted: answers
    from DB context using templates (keeps the assistant functional in
    headless/test environments, like the reference's stub builds)."""

    def __init__(self, db=None):
        self.db = db

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        low = prompt.lower()
        if self.db is not None:
            if "how many" in low and "node" in low:
                return f"The graph currently holds {self.db.storage.node_count()} nodes."
            if "how many" in low and ("edge" in low or "relationship" in low):
                return (
                    f"The graph currently holds {self.db.storage.edge_count()} "
                    "relationships."
                )
            m = re.search(r"(?:search|find|recall)\s+(?:for\s+)?(.+)", low)
            if m:
                results = self.db.recall(m.group(1).strip(" ?.!"), limit=3)
                if results:
                    lines = [f"- {r['content'][:80]}" for r in results]
                    return "Here is what I found:\n" + "\n".join(lines)
                return "I could not find matching memories."
            if "status" in low or "health" in low:
                return json.dumps(
                    {"action": "status", "params": {}}
                )
        return "I am Heimdall, the NornicDB assistant. Ask me about the graph."


ActionFn = Callable[[dict[str, Any]], Any]


def _brief(v: Any, limit: int = 200) -> Any:
    """Row values trimmed for chat-sized payloads — including property
    values inside nodes/edges (a 10MB document property must not balloon
    the chat JSON)."""
    if isinstance(v, str) and len(v) > limit:
        return v[:limit] + "…"
    if hasattr(v, "id") and hasattr(v, "properties"):
        return {
            "id": v.id,
            "properties": {
                k: _brief(p, limit) for k, p in dict(v.properties).items()
            },
        }
    if isinstance(v, dict):
        return {k: _brief(x, limit) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_brief(x, limit) for x in list(v)[:20]]
    return v


class HeimdallManager:
    """(ref: heimdall.Manager scheduler.go:178). The system prompt is
    assembled per-request by PromptContext.build_final_prompt()."""

    def __init__(self, generator: Generator, db=None):
        self.generator = generator
        self.db = db
        self.bifrost = Bifrost()
        self.metrics = HeimdallMetrics()
        # named-metrics registry with Prometheus rendering
        # (ref: pkg/heimdall/metrics.go)
        self.metrics_registry = MetricsRegistry()
        # model registry; the construction generator is the default
        # reasoning model (ref: ModelInfo types.go:32, scheduler model pick)
        self.models = ModelRegistry()
        self.models.register(
            ModelInfo(name="heimdall", type=MODEL_REASONING,
                      backend=generator, loaded=True),
            default=True,
        )
        # async DB-event fan-out to plugins (ref: plugin.go:1345
        # dbEventDispatcher — bounded queue + background thread)
        self.events = EventDispatcher()
        self._actions: dict[str, ActionFn] = {}
        self._action_descriptions: dict[str, str] = {}
        # plugin-installed hooks that mutate the per-request PromptContext
        # (ref: PrePrompt receiving *PromptContext, plugin.go)
        self.context_hooks: list[Callable[[PromptContext], None]] = []
        # default few-shot examples (ref: handler.go:324 example injection)
        self.default_examples: list[PromptExample] = [
            PromptExample("how many nodes are there?",
                          '{"action": "query", "params": {"cypher": '
                          '"MATCH (n) RETURN count(n)"}}'),
            PromptExample("is the database healthy?",
                          '{"action": "status", "params": {}}'),
        ]
        # a PluginHost installs itself here so chat-path actions run through
        # the pre/post-execute hooks (incl. veto)
        self.action_dispatcher: Optional[Callable[[dict], Any]] = None
        # identity until a PluginHost installs pre_prompt hooks; the
        # streaming path routes prompts through this so stream=true cannot
        # evade plugin redaction/veto guards
        self.pre_prompt_transform: Callable[[str], str] = lambda p: p
        self.plugin_host = None  # set by PluginHost.__init__
        self._lock = threading.Lock()
        # built-in actions (ref: plugins/heimdall reference plugin actions)
        self.register_action("status", self._action_status,
                             "Report database health and entity counts")
        self.register_action(
            "hello", lambda p: {"message": "Heimdall online"},
            "Liveness check",
        )
        self.register_action("query", self._action_query,
                             "Run a Cypher query: params {cypher: string}")

    # -- actions (ref: plugin.go ActionFunc) ---------------------------------
    def register_action(
        self, name: str, fn: ActionFn, description: str = ""
    ) -> None:
        with self._lock:
            self._actions[name] = fn
            if description:
                self._action_descriptions[name] = description

    def action_prompt(self) -> str:
        """Registered-action catalog injected (immutably) into every
        prompt (ref: PromptContext.ActionPrompt types.go:294)."""
        with self._lock:
            lines = []
            for name in sorted(self._actions):
                desc = self._action_descriptions.get(name, "")
                lines.append(f"- {name}: {desc}" if desc else f"- {name}")
        return "\n".join(lines)

    def _action_status(self, params: dict) -> dict:
        out = {"status": "ok"}
        if self.db is not None:
            out["nodes"] = self.db.storage.node_count()
            out["edges"] = self.db.storage.edge_count()
        return out

    def _action_query(self, params: dict) -> dict:
        """Cypher pass-through action (ref: heimdall.watcher.query in the
        reference plugin + the CypherPrimer ACTION MODE examples).

        Read-only: the chat endpoint is gated at read scope
        (http.py h._auth("read")), so a model steered into emitting a
        write statement must not become a privilege escalation — write-
        classified Cypher is refused here, mirroring the per-statement
        gate on /db/{db}/tx/commit."""
        if self.db is None:
            return {"error": "no database attached"}
        cypher = str(params.get("cypher", "")).strip()
        if not cypher:
            return {"error": "params.cypher required"}
        from nornicdb_tpu.cypher.executor import classify_query_text

        if classify_query_text(cypher) == "write":
            return {"error": "query action is read-only; use the Cypher "
                             "API for writes"}
        result = self.db.cypher(cypher)
        rows = [[_brief(v) for v in row] for row in result.rows[:50]]
        return {"columns": result.columns, "rows": rows,
                "row_count": len(result.rows)}

    @staticmethod
    def try_parse_action(text: str) -> Optional[dict[str, Any]]:
        """Extract a JSON action from model output (ref: tryParseAction
        handler.go:516).

        In-image generators decode through a word-level tokenizer that
        spaces out punctuation ('{ " action " : ...'), so when the direct
        scan finds nothing, retry with quote-adjacent whitespace collapsed —
        interior spaces (e.g. inside a cypher string) are preserved."""
        out = HeimdallManager._try_parse_action_exact(text)
        if out is not None:
            return out
        normalized = re.sub(r'\s+"', '"', re.sub(r'"\s+', '"', text))
        if normalized != text:
            return HeimdallManager._try_parse_action_exact(normalized)
        return None

    @staticmethod
    def _try_parse_action_exact(text: str) -> Optional[dict[str, Any]]:
        marker = text.find('"action"')
        if marker == -1:
            return None
        # try every opening brace before the marker, outermost first, so a
        # nested object preceding "action" (key order is unguaranteed) still
        # resolves to the enclosing action object
        starts = [i for i, ch in enumerate(text[: marker + 1]) if ch == "{"]
        for start in starts:
            depth = 0
            for i in range(start, len(text)):
                if text[i] == "{":
                    depth += 1
                elif text[i] == "}":
                    depth -= 1
                    if depth == 0:
                        if i < marker:
                            break  # object closed before "action": not it
                        try:
                            obj = json.loads(text[start : i + 1])
                        except json.JSONDecodeError:
                            break
                        if isinstance(obj, dict) and "action" in obj:
                            return obj
                        break
        return None

    # -- generation (ref: Generate scheduler.go:178) ---------------------------
    def generate(self, prompt: str, max_tokens: int = 128,
                 generator: Optional[Generator] = None) -> str:
        """One generation with metric/error accounting. PluginHost wraps
        this method to apply pre_prompt hooks — any alternate-model path
        must also flow through here, never call a backend directly, or
        plugin guards (redaction, veto) become evadable by picking a
        different registered model."""
        t0 = time.perf_counter()
        backend = generator if generator is not None else self.generator
        try:
            out = backend.generate(prompt, max_tokens)
            self.metrics.generations += 1
            self.metrics.tokens_generated += len(out.split())
            return out
        except Exception:
            self.metrics.errors += 1
            raise
        finally:
            self.metrics.total_latency += time.perf_counter() - t0

    def generate_many(self, prompts: list[str], max_tokens: int = 128,
                      generator: Optional[Generator] = None) -> list[str]:
        """Batch generation with the same guard + metric contract as
        :meth:`generate`.  PluginHost wraps ``generate`` (not this), so
        pre_prompt guards are applied here explicitly via
        ``pre_prompt_transform`` — a batch path must never evade plugin
        redaction/veto.  Backends with a serving engine overlap the whole
        batch through continuous batching."""
        if not prompts:
            return []
        t0 = time.perf_counter()
        backend = generator if generator is not None else self.generator
        guarded = [self.pre_prompt_transform(p) for p in prompts]
        try:
            outs = backend.generate_many(guarded, max_tokens)
            self.metrics.generations += len(outs)
            self.metrics.tokens_generated += sum(
                len(o.split()) for o in outs)
            return outs
        except Exception:
            self.metrics.errors += 1
            raise
        finally:
            self.metrics.total_latency += time.perf_counter() - t0

    def build_context(
        self, messages: list[dict[str, str]]
    ) -> PromptContext:
        """Assemble the per-request PromptContext: immutable action
        catalog, default examples, DB context, then plugin hooks
        (ref: handler.go:207-340 prompt assembly + PrePrompt)."""
        user_message = ""
        for m in reversed(messages):
            if m.get("role", "user") == "user":
                user_message = m.get("content", "")
                break
        ctx = PromptContext(
            user_message=user_message,
            messages=messages,
            action_prompt=self.action_prompt(),
        )
        ctx.bifrost = self.bifrost
        ctx.examples.extend(self.default_examples)
        if self.db is not None:
            # DB context injection (ref: handler.go DatabaseReader):
            # schema-level summary the model can ground answers in
            try:
                ctx.additional_instructions = (
                    f"Current graph: {self.db.storage.node_count()} nodes, "
                    f"{self.db.storage.edge_count()} relationships."
                )
            except Exception:
                # context enrichment is best-effort, but a storage engine
                # that can't count is worth surfacing
                log.warning("heimdall DB-context injection failed",
                            exc_info=True)
                _count_error("heimdall.context")
        for hook in list(self.context_hooks):
            try:
                hook(ctx)
            except Exception:
                log.warning("heimdall context hook %r failed", hook,
                            exc_info=True)
                _count_error("heimdall.context_hook")
            if ctx.cancelled:
                break
        return ctx

    def _build_prompt(self, ctx, messages: list[dict[str, str]]) -> str:
        """One prompt assembly for streamed AND non-streamed chat — the two
        paths must never drift in format."""
        prompt_parts = [ctx.build_final_prompt()]
        for m in messages:
            prompt_parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
        prompt_parts.append("assistant:")
        return "\n".join(prompt_parts)

    def _dispatch_action(self, action: dict):
        """Shared action dispatch; returns the raw result (or error dict)."""
        if self.action_dispatcher is not None:
            try:
                result = self.action_dispatcher(action)
                self.metrics.actions_executed += 1
                return result
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                return {"error": str(e)}
        fn = self._actions.get(str(action.get("action")))
        if fn is None:
            return None
        try:
            result = fn(action.get("params") or {})
            self.metrics.actions_executed += 1
            return result
        except Exception as e:  # noqa: BLE001
            return {"error": str(e)}

    def chat(
        self,
        messages: list[dict[str, str]],
        max_tokens: int = 128,
        model: Optional[str] = None,
        temperature: Optional[float] = None,
    ) -> dict:
        """OpenAI-compatible chat completion (ref: handleChatCompletions
        handler.go:207) + action execution."""
        ctx = self.build_context(messages)
        if ctx.cancelled:
            # a PrePrompt hook aborted the request (ref: Cancel types.go:343)
            self.metrics_registry.inc("requests_cancelled")
            return {
                "id": f"chatcmpl-{ctx.request_id}",
                "object": "chat.completion",
                "model": model or "heimdall",
                "choices": [{
                    "index": 0,
                    "message": {
                        "role": "assistant",
                        "content": f"Request cancelled: {ctx.cancel_reason}",
                    },
                    "finish_reason": "cancelled",
                }],
                "cancelled_by": ctx.cancelled_by,
            }
        prompt = self._build_prompt(ctx, messages)
        # model selection through the registry (ref: ChatRequest.Model)
        generator = self.generator
        if model and model not in ("heimdall", ""):
            info = self.models.get(model)
            if info is None:
                return {"error": {
                    "message": f"model {model!r} not found",
                    "type": "invalid_request_error",
                }}
            generator = self.models.acquire(model)
            if generator is None:
                return {"error": {
                    "message": f"model {model!r} has no loaded backend",
                    "type": "invalid_request_error",
                }}
        else:
            self.models.acquire("heimdall")
        text = self._generate_with(generator, prompt, max_tokens)
        prompt_toks = estimate_tokens(prompt)
        completion_toks = estimate_tokens(text)
        self.metrics_registry.inc("chat_requests")
        self.metrics_registry.inc("prompt_tokens", prompt_toks)
        self.metrics_registry.inc("completion_tokens", completion_toks)
        action_result = None
        action = self.try_parse_action(text)
        if action is not None:
            action_result = self._dispatch_action(action)
        self.bifrost.broadcast("chat", {"content": text[:200]})
        response = {
            "id": f"chatcmpl-{ctx.request_id}",
            "object": "chat.completion",
            "model": model or "heimdall",
            "created": int(ctx.request_time),
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }
            ],
            # (ref: ChatUsage types.go:80)
            "usage": {
                "prompt_tokens": prompt_toks,
                "completion_tokens": completion_toks,
                "total_tokens": prompt_toks + completion_toks,
            },
        }
        notes = ctx.drain_notifications()
        if notes:
            response["notifications"] = [vars(n) for n in notes]
        if action_result is not None:
            response["action_result"] = action_result
        return response

    def _generate_with(self, generator, prompt: str, max_tokens: int) -> str:
        """Dispatch through self.generate so the PluginHost wrapper (and
        its pre_prompt hooks) applies to every backend."""
        if generator is self.generator:
            return self.generate(prompt, max_tokens)
        return self.generate(prompt, max_tokens, generator=generator)

    def chat_stream(self, messages: list[dict[str, str]],
                    max_tokens: int = 128, model: Optional[str] = None,
                    ) -> Iterator[dict]:
        """Streaming chunks (ref: streaming handler.go:561; queued
        notifications are flushed ahead of content chunks to preserve
        ordering, ref: notificationQueue types.go:321-324).

        Generators that implement a REAL generate_stream (the Qwen decode
        loop) stream token deltas as produced; the accumulated text is
        action-sniffed at the end like the reference's buffered streaming
        handler. Template/backoff generators fall back to word-chunking
        the full response."""
        generator = self.generator
        if model and model not in ("heimdall", ""):
            try:
                maybe = self.models.acquire(model)
                msg = f"model {model!r} has no loaded backend"
            except KeyError:
                maybe = None
                msg = f"model {model!r} not found"
            if maybe is None:
                # unknown or unloaded model: same error contract as chat(),
                # never a silent fallback to the default backend
                yield {
                    "object": "chat.completion.chunk",
                    "choices": [],
                    "error": {"message": msg,
                              "type": "invalid_request_error"},
                }
                return
            generator = maybe
        else:
            self.models.acquire("heimdall")  # last_used bookkeeping
        streams_natively = (
            type(generator).generate_stream is not Generator.generate_stream
        )
        if streams_natively:
            yield from self._chat_stream_native(
                generator, messages, max_tokens, model)
            return
        try:
            full = self.chat(messages, max_tokens, model=model)
        except Exception as e:  # noqa: BLE001 — SSE headers already sent:
            # the client must get a terminal error event, matching the
            # native path's contract
            self.metrics.errors += 1
            yield {"object": "chat.completion.chunk", "choices": [],
                   "error": {"message": str(e)}}
            yield {"object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {},
                                "finish_reason": "error"}]}
            return
        if "choices" not in full:
            # error response (unknown model etc.): one error event, done
            yield {
                "object": "chat.completion.chunk",
                "choices": [],
                "error": full.get("error",
                                  {"message": "generation failed"}),
            }
            return
        for note in full.pop("notifications", []):
            yield {
                "object": "chat.completion.chunk",
                "choices": [],
                "notification": note,
            }
        content = full["choices"][0]["message"]["content"]
        words = content.split(" ")
        for i, w in enumerate(words):
            yield {
                "object": "chat.completion.chunk",
                "choices": [
                    {
                        "index": 0,
                        "delta": {"content": w + (" " if i < len(words) - 1 else "")},
                        "finish_reason": None,
                    }
                ],
            }
        yield {
            "object": "chat.completion.chunk",
            "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
        }

    def _chat_stream_native(self, generator, messages, max_tokens, model
                            ) -> Iterator[dict]:
        ctx = self.build_context(messages)
        if ctx.cancelled:
            self.metrics_registry.inc("requests_cancelled")
            yield {
                "object": "chat.completion.chunk",
                "choices": [],
                "error": {"message": f"Request cancelled: {ctx.cancel_reason}"},
            }
            yield {"object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {},
                                "finish_reason": "cancelled"}]}
            return
        for note in [vars(n) for n in ctx.drain_notifications()]:
            yield {"object": "chat.completion.chunk", "choices": [],
                   "notification": note}
        # plugin guards (redaction, veto) apply to streamed prompts too
        prompt = self.pre_prompt_transform(
            self._build_prompt(ctx, messages))
        pieces: list[str] = []
        t0 = time.perf_counter()
        try:
            for delta in generator.generate_stream(prompt, max_tokens):
                pieces.append(delta)
                yield {
                    "object": "chat.completion.chunk",
                    "choices": [{"index": 0, "delta": {"content": delta},
                                 "finish_reason": None}],
                }
        except Exception as e:  # noqa: BLE001 — headers are already sent;
            # the client must see a terminal error event, not a cut stream
            self.metrics.errors += 1
            yield {"object": "chat.completion.chunk", "choices": [],
                   "error": {"message": str(e)}}
            yield {"object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {},
                                "finish_reason": "error"}]}
            return
        text = "".join(pieces)
        self.metrics.generations += 1
        # same unit as generate() (word count) so the counter stays summable
        self.metrics.tokens_generated += len(text.split())
        self.metrics.total_latency += time.perf_counter() - t0
        self.metrics_registry.inc("chat_requests")
        self.metrics_registry.inc("prompt_tokens", estimate_tokens(prompt))
        self.metrics_registry.inc("completion_tokens", estimate_tokens(text))
        self.bifrost.broadcast("chat", {"content": text[:200]})
        # buffered action sniff over the COMPLETE text, like the reference's
        # streaming handler (tryParseAction handler.go:516)
        action = self.try_parse_action(text)
        if action is not None:
            result = self._dispatch_action(action)
            if result is not None:
                yield {"object": "chat.completion.chunk", "choices": [],
                       "action_result": _brief(result, 2000)}
        yield {
            "object": "chat.completion.chunk",
            "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
        }
