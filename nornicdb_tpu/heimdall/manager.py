"""Heimdall: the in-process AI assistant (TPU SLM).

Behavioral reference: /root/reference/pkg/heimdall/ —
Manager.Generate (scheduler.go:178), Handler.handleChatCompletions
(handler.go:207), action parsing from model output (tryParseAction :516),
streaming (:561), Bifrost SSE notification bus (bifrost.go:15), model
registry (types.go:20-37), plugin actions (plugin.go), metrics
(metrics.go).

The generation backend is the Qwen2 decoder on TPU
(nornicdb_tpu.models.qwen2 — replaces pkg/localllm llama.cpp), with a
deterministic template fallback when no weights are mounted.
"""

from __future__ import annotations

import json
import queue
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

# model kinds (ref: types.go:20-37)
MODEL_EMBEDDING = "embedding"
MODEL_REASONING = "reasoning"
MODEL_CLASSIFICATION = "classification"


@dataclass
class HeimdallMetrics:
    generations: int = 0
    tokens_generated: int = 0
    actions_executed: int = 0
    errors: int = 0
    total_latency: float = 0.0


class Bifrost:
    """Notification bus to UI subscribers (ref: bifrost.go:15 — SSE bus)."""

    def __init__(self) -> None:
        self._subs: list[queue.Queue] = []
        self._lock = threading.Lock()

    def subscribe(self) -> queue.Queue:
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._subs.append(q)
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            if q in self._subs:
                self._subs.remove(q)

    def broadcast(self, event: str, data: Any) -> None:
        with self._lock:
            subs = list(self._subs)
        for q in subs:
            try:
                q.put_nowait({"event": event, "data": data, "ts": time.time()})
            except queue.Full:
                pass


class Generator:
    """Abstract generation backend (ref: generator_cgo.go / generator_yzma.go)."""

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        raise NotImplementedError

    def generate_stream(self, prompt: str, max_tokens: int = 128) -> Iterator[str]:
        yield self.generate(prompt, max_tokens)


class QwenGenerator(Generator):
    """Qwen2-on-TPU backend (replaces llama.cpp generation)."""

    def __init__(self, cfg=None, params=None, tokenizer=None, seed: int = 0):
        import jax

        from nornicdb_tpu.models import qwen2
        from nornicdb_tpu.models.tokenizer import HashTokenizer

        self.cfg = cfg if cfg is not None else qwen2.QWEN_SMALL
        self.params = (
            params if params is not None
            else qwen2.init_params(self.cfg, jax.random.PRNGKey(seed))
        )
        self.tokenizer = tokenizer or HashTokenizer(self.cfg.vocab_size)
        self.qwen2 = qwen2

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        ids = self.tokenizer.encode(prompt, add_special=False)[-256:] or [1]
        out = self.qwen2.generate(
            self.params, self.cfg, ids, max_new_tokens=max_tokens,
        )
        return self.tokenizer.decode(out)


class TemplateGenerator(Generator):
    """Deterministic fallback when no trained weights are mounted: answers
    from DB context using templates (keeps the assistant functional in
    headless/test environments, like the reference's stub builds)."""

    def __init__(self, db=None):
        self.db = db

    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        low = prompt.lower()
        if self.db is not None:
            if "how many" in low and "node" in low:
                return f"The graph currently holds {self.db.storage.node_count()} nodes."
            if "how many" in low and ("edge" in low or "relationship" in low):
                return (
                    f"The graph currently holds {self.db.storage.edge_count()} "
                    "relationships."
                )
            m = re.search(r"(?:search|find|recall)\s+(?:for\s+)?(.+)", low)
            if m:
                results = self.db.recall(m.group(1).strip(" ?.!"), limit=3)
                if results:
                    lines = [f"- {r['content'][:80]}" for r in results]
                    return "Here is what I found:\n" + "\n".join(lines)
                return "I could not find matching memories."
            if "status" in low or "health" in low:
                return json.dumps(
                    {"action": "status", "params": {}}
                )
        return "I am Heimdall, the NornicDB assistant. Ask me about the graph."


ActionFn = Callable[[dict[str, Any]], Any]


class HeimdallManager:
    """(ref: heimdall.Manager scheduler.go:178)"""

    SYSTEM_PROMPT = (
        "You are Heimdall, the NornicDB graph assistant. Answer questions "
        "about the graph; when an operation is needed reply with JSON "
        '{"action": name, "params": {...}}.'
    )

    def __init__(self, generator: Generator, db=None):
        self.generator = generator
        self.db = db
        self.bifrost = Bifrost()
        self.metrics = HeimdallMetrics()
        self._actions: dict[str, ActionFn] = {}
        # a PluginHost installs itself here so chat-path actions run through
        # the pre/post-execute hooks (incl. veto)
        self.action_dispatcher: Optional[Callable[[dict], Any]] = None
        self._lock = threading.Lock()
        # built-in actions (ref: plugins/heimdall reference plugin actions)
        self.register_action("status", self._action_status)
        self.register_action("hello", lambda p: {"message": "Heimdall online"})

    # -- actions (ref: plugin.go ActionFunc) ---------------------------------
    def register_action(self, name: str, fn: ActionFn) -> None:
        with self._lock:
            self._actions[name] = fn

    def _action_status(self, params: dict) -> dict:
        out = {"status": "ok"}
        if self.db is not None:
            out["nodes"] = self.db.storage.node_count()
            out["edges"] = self.db.storage.edge_count()
        return out

    @staticmethod
    def try_parse_action(text: str) -> Optional[dict[str, Any]]:
        """Extract a JSON action from model output (ref: tryParseAction
        handler.go:516)."""
        marker = text.find('"action"')
        if marker == -1:
            return None
        # try every opening brace before the marker, outermost first, so a
        # nested object preceding "action" (key order is unguaranteed) still
        # resolves to the enclosing action object
        starts = [i for i, ch in enumerate(text[: marker + 1]) if ch == "{"]
        for start in starts:
            depth = 0
            for i in range(start, len(text)):
                if text[i] == "{":
                    depth += 1
                elif text[i] == "}":
                    depth -= 1
                    if depth == 0:
                        if i < marker:
                            break  # object closed before "action": not it
                        try:
                            obj = json.loads(text[start : i + 1])
                        except json.JSONDecodeError:
                            break
                        if isinstance(obj, dict) and "action" in obj:
                            return obj
                        break
        return None

    # -- generation (ref: Generate scheduler.go:178) ---------------------------
    def generate(self, prompt: str, max_tokens: int = 128) -> str:
        t0 = time.time()
        try:
            out = self.generator.generate(prompt, max_tokens)
            self.metrics.generations += 1
            self.metrics.tokens_generated += len(out.split())
            return out
        except Exception:
            self.metrics.errors += 1
            raise
        finally:
            self.metrics.total_latency += time.time() - t0

    def chat(self, messages: list[dict[str, str]], max_tokens: int = 128) -> dict:
        """OpenAI-compatible chat completion (ref: handleChatCompletions
        handler.go:207) + action execution."""
        prompt_parts = [self.SYSTEM_PROMPT]
        for m in messages:
            prompt_parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
        prompt_parts.append("assistant:")
        text = self.generate("\n".join(prompt_parts), max_tokens)
        action_result = None
        action = self.try_parse_action(text)
        if action is not None:
            if self.action_dispatcher is not None:
                try:
                    action_result = self.action_dispatcher(action)
                    self.metrics.actions_executed += 1
                except Exception as e:
                    action_result = {"error": str(e)}
            else:
                fn = self._actions.get(str(action.get("action")))
                if fn is not None:
                    try:
                        action_result = fn(action.get("params") or {})
                        self.metrics.actions_executed += 1
                    except Exception as e:
                        action_result = {"error": str(e)}
        self.bifrost.broadcast("chat", {"content": text[:200]})
        response = {
            "id": f"chatcmpl-{int(time.time() * 1000)}",
            "object": "chat.completion",
            "model": "heimdall",
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }
            ],
        }
        if action_result is not None:
            response["action_result"] = action_result
        return response

    def chat_stream(self, messages: list[dict[str, str]],
                    max_tokens: int = 128) -> Iterator[dict]:
        """Streaming chunks (ref: streaming handler.go:561)."""
        full = self.chat(messages, max_tokens)
        content = full["choices"][0]["message"]["content"]
        words = content.split(" ")
        for i, w in enumerate(words):
            yield {
                "object": "chat.completion.chunk",
                "choices": [
                    {
                        "index": 0,
                        "delta": {"content": w + (" " if i < len(words) - 1 else "")},
                        "finish_reason": None,
                    }
                ],
            }
        yield {
            "object": "chat.completion.chunk",
            "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
        }
