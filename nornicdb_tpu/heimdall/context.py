"""Heimdall prompt/context machinery: token budgets, prompt building,
examples, per-request context with notifications and cancellation.

Behavioral reference: /root/reference/pkg/heimdall/types.go —
PromptContext (:284, immutable ActionPrompt + plugin-mutable
AdditionalInstructions/Examples/PluginData, notification queue, Cancel),
PromptExample (:429), token budget (:456-511, env-overridable
NORNICDB_HEIMDALL_MAX_{CONTEXT,SYSTEM,USER}_TOKENS, ~4 chars/token
estimate), BuildFinalPrompt full→minimal fallback (:513-648) with the
embedded CypherPrimer, and GenerateParams defaults (:93-111).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

# token budget defaults (ref: types.go:436-448)
DEFAULT_MAX_CONTEXT_TOKENS = 8192
DEFAULT_MAX_SYSTEM_TOKENS = 6000
DEFAULT_MAX_USER_TOKENS = 2000
TOKENS_PER_CHAR = 0.25


@dataclass
class TokenBudget:
    max_context: int = DEFAULT_MAX_CONTEXT_TOKENS
    max_system: int = DEFAULT_MAX_SYSTEM_TOKENS
    max_user: int = DEFAULT_MAX_USER_TOKENS

    @classmethod
    def from_env(cls) -> "TokenBudget":
        def _get(name: str, default: int) -> int:
            try:
                v = int(os.environ.get(name, ""))
                return v if v > 0 else default
            except ValueError:
                return default

        return cls(
            _get("NORNICDB_HEIMDALL_MAX_CONTEXT_TOKENS",
                 DEFAULT_MAX_CONTEXT_TOKENS),
            _get("NORNICDB_HEIMDALL_MAX_SYSTEM_TOKENS",
                 DEFAULT_MAX_SYSTEM_TOKENS),
            _get("NORNICDB_HEIMDALL_MAX_USER_TOKENS",
                 DEFAULT_MAX_USER_TOKENS),
        )


def estimate_tokens(text: str) -> int:
    """~4 chars per token (ref: EstimateTokens types.go:506)."""
    return int(len(text) * TOKENS_PER_CHAR)


@dataclass
class PromptExample:
    """(ref: PromptExample types.go:429)"""

    user_says: str
    action_json: str


@dataclass
class GenerateParams:
    """(ref: GenerateParams types.go:93 + DefaultGenerateParams)"""

    max_tokens: int = 512
    temperature: float = 0.1  # low → deterministic JSON output
    top_p: float = 0.9
    top_k: int = 40
    stop_tokens: tuple = ("<|im_end|>", "<|endoftext|>", "</s>")


# a compact Cypher reference injected into the full prompt
# (ref: CypherPrimer types.go — trimmed to the same sections)
CYPHER_PRIMER = """CYPHER QUERY REFERENCE:
Patterns: MATCH (n) | MATCH (n:Label) | MATCH (n {prop: v}) | MATCH (n)-[r:TYPE]->(m)
Common: MATCH (n) RETURN count(n) | MATCH (n:L) RETURN n LIMIT 10 | MATCH ()-[r]->() RETURN type(r), count(r)
Filters: WHERE n.p = 'v' | CONTAINS | STARTS WITH | IS NOT NULL | n.p > 10
Aggregates: count, collect, sum, avg, min, max
Paths: MATCH p = (a)-[*1..3]->(b) | shortestPath((a)-[*]->(b))
Writes: CREATE (n:L {p: 'v'}) | SET n.p = 'v' | DETACH DELETE n
Vector: CALL db.index.vector.queryNodes('idx', 50, 'QUERY') YIELD node, score
"""


@dataclass
class QueuedNotification:
    """(ref: QueuedNotification types.go:334)"""

    type: str  # info/warning/error/success/progress
    title: str
    message: str


class PromptContext:
    """Per-request context threaded through plugin PrePrompt hooks.

    `action_prompt` is immutable (set from the registered-action catalog
    before hooks run); `additional_instructions`, `examples`, and
    `plugin_data` are plugin-mutable (ref: types.go:284-331).
    """

    def __init__(
        self,
        user_message: str,
        messages: Optional[list[dict[str, str]]] = None,
        action_prompt: str = "",
        budget: Optional[TokenBudget] = None,
    ):
        self.request_id = uuid.uuid4().hex[:16]
        self.request_time = time.time()
        self._action_prompt = action_prompt  # immutable
        self.user_message = user_message
        self.messages = list(messages or [])
        self.additional_instructions = ""
        self.examples: list[PromptExample] = []
        self.plugin_data: dict[str, Any] = {}
        self.budget = budget or TokenBudget.from_env()
        self._notifications: list[QueuedNotification] = []
        self._note_lock = threading.Lock()
        self._cancelled = False
        self._cancel_reason = ""
        self._cancelled_by = ""
        self.bifrost = None  # set by the manager

    @property
    def action_prompt(self) -> str:
        return self._action_prompt

    # -- cancellation (ref: Cancel types.go:343) ---------------------------
    def cancel(self, reason: str, cancelled_by: str = "") -> None:
        self._cancelled = True
        self._cancel_reason = reason
        self._cancelled_by = cancelled_by

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def cancel_reason(self) -> str:
        return self._cancel_reason

    @property
    def cancelled_by(self) -> str:
        return self._cancelled_by

    # -- notifications (ref: Notify/DrainNotifications types.go:372-412) --
    def notify(self, type_: str, title: str, message: str) -> None:
        with self._note_lock:
            self._notifications.append(
                QueuedNotification(type_, title, message)
            )
        if self.bifrost is not None:
            self.bifrost.broadcast(
                "notification",
                {"type": type_, "title": title, "message": message},
            )

    def notify_info(self, title: str, message: str) -> None:
        self.notify("info", title, message)

    def notify_warning(self, title: str, message: str) -> None:
        self.notify("warning", title, message)

    def notify_error(self, title: str, message: str) -> None:
        self.notify("error", title, message)

    def notify_progress(self, title: str, message: str) -> None:
        self.notify("progress", title, message)

    def drain_notifications(self) -> list[QueuedNotification]:
        with self._note_lock:
            out = self._notifications
            self._notifications = []
        return out

    # -- prompt building (ref: BuildFinalPrompt types.go:513) --------------
    def build_final_prompt(self) -> str:
        full = self._build_full_prompt()
        if estimate_tokens(full) <= self.budget.max_system:
            return full
        return self._build_minimal_prompt()

    def _build_full_prompt(self) -> str:
        parts = [
            "You are Heimdall, the AI assistant for NornicDB - a "
            "high-performance graph database.\n"
            "Your role is to help users manage the database by executing "
            "actions and running Cypher queries.\n",
        ]
        if self._action_prompt:
            parts.append("AVAILABLE ACTIONS:\n" + self._action_prompt + "\n")
        parts.append(CYPHER_PRIMER)
        parts.append(
            "RESPONSE MODES:\n"
            "1. ACTION MODE - For database operations, respond with JSON:\n"
            '   {"action": "status", "params": {}}\n'
            '   {"action": "query", "params": {"cypher": "MATCH (n) RETURN '
            'count(n)"}}\n'
            "2. HELP MODE - For Cypher questions, explain with examples.\n"
            "IMPORTANT: Always complete your JSON responses with proper "
            "closing braces.\n"
        )
        if self.additional_instructions:
            parts.append(
                "ADDITIONAL CONTEXT:\n" + self.additional_instructions + "\n"
            )
        if self.examples:
            ex_lines = ["EXAMPLES:"]
            for ex in self.examples:
                ex_lines.append(f'User: "{ex.user_says}"\n-> {ex.action_json}')
            parts.append("\n".join(ex_lines) + "\n")
        parts.append(
            "Respond with JSON action command only. No explanations, "
            "no markdown.\n"
        )
        return "\n".join(parts)

    def _build_minimal_prompt(self) -> str:
        """(ref: buildMinimalPrompt types.go:581 — actions only)"""
        return (
            "You are Heimdall, AI assistant for NornicDB graph database.\n\n"
            "ACTIONS:\n" + self._action_prompt + "\n"
            'For queries: {"action": "query", "params": {"cypher": "..."}}\n'
            "Respond with JSON only.\n"
        )

    # -- budget info (ref: GetBudgetInfo types.go:688) ---------------------
    def estimated_system_tokens(self) -> int:
        return estimate_tokens(self.build_final_prompt())

    def validate_token_budget(self) -> Optional[str]:
        """Returns an error string when over budget, else None."""
        sys_tokens = self.estimated_system_tokens()
        if sys_tokens > self.budget.max_system:
            return (
                f"system prompt {sys_tokens} tokens exceeds budget "
                f"{self.budget.max_system}"
            )
        user_tokens = estimate_tokens(self.user_message)
        if user_tokens > self.budget.max_user:
            return (
                f"user message {user_tokens} tokens exceeds budget "
                f"{self.budget.max_user}"
            )
        return None

    def budget_info(self) -> dict[str, int]:
        return {
            "max_context": self.budget.max_context,
            "max_system": self.budget.max_system,
            "max_user": self.budget.max_user,
            "estimated_system": self.estimated_system_tokens(),
            "estimated_user": estimate_tokens(self.user_message),
        }
