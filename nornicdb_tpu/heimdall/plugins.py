"""Heimdall plugin system: lifecycle hooks, actions, health, DB monitoring.

Behavioral reference: /root/reference/pkg/heimdall/plugin.go (1,488 LoC —
PrePrompt/PreExecute/PostExecute hooks, plugin lifecycle, health, config
schema) and plugins/heimdall/plugin.go:62-424 (the "Watcher" reference
plugin: hello/status/health/config actions); directory loading mirrors
pkg/nornicdb/plugins.go:56 (Python modules instead of Go .so files).
"""

from __future__ import annotations

import importlib.util
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.telemetry.metrics import count_error as _count_error

log = logging.getLogger(__name__)


@dataclass
class PluginInfo:
    name: str
    version: str = "0.0.1"
    description: str = ""
    healthy: bool = True
    started_at: float = 0.0


class HeimdallPlugin:
    """Base class for plugins (ref: plugin.go lifecycle interface)."""

    name = "plugin"
    version = "0.0.1"
    description = ""

    # lifecycle ------------------------------------------------------------
    def on_start(self, manager) -> None:
        pass

    def on_stop(self) -> None:
        pass

    def health(self) -> bool:
        return True

    # generation hooks (ref: PrePrompt/PreExecute/PostExecute) -------------
    def pre_prompt(self, prompt: str) -> str:
        return prompt

    def pre_prompt_context(self, ctx) -> None:
        """Mutate the per-request PromptContext: add examples, additional
        instructions, plugin_data; call ctx.cancel() to veto the request
        (ref: PrePrompt receiving *PromptContext, types.go:284)."""

    # observability (ref: Summary/RecentEvents in the plugin interface,
    # plugin.go:162-164, SubsystemEvent :485)
    def summary(self) -> str:
        return self.description

    def recent_events(self, limit: int = 10) -> list[dict]:
        return []

    def pre_execute(self, action: dict[str, Any]) -> Optional[dict[str, Any]]:
        """Return modified action, or None to veto execution."""
        return action

    def post_execute(self, action: dict[str, Any], result: Any) -> Any:
        return result

    # actions --------------------------------------------------------------
    def actions(self) -> dict[str, Callable[[dict], Any]]:
        return {}

    # storage event monitoring (ref: DB event monitoring) ------------------
    def on_db_event(self, kind: str, entity: Any) -> None:
        pass


class PluginHost:
    """Plugin lifecycle manager wired into a HeimdallManager + DB."""

    def __init__(self, manager, db=None):
        self.manager = manager
        manager.plugin_host = self  # surfaced in /api/bifrost/status
        self.db = db
        self._lock = threading.Lock()
        self._plugins: dict[str, HeimdallPlugin] = {}
        self._info: dict[str, PluginInfo] = {}
        if db is not None:
            # storage events flow through the manager's async dispatcher
            # (bounded queue + worker thread — ref: plugin.go:1345
            # dbEventDispatcher), never synchronously in the write path
            dispatcher = getattr(manager, "events", None)
            if dispatcher is not None:
                dispatcher.subscribe(self._deliver_db_event)
                dispatcher.start()
                db.storage.on_event(self._emit_storage_event)
            else:
                db.storage.on_event(self._on_db_event)
        self._install_hooks()

    # -- registration -------------------------------------------------------
    def register(self, plugin: HeimdallPlugin) -> PluginInfo:
        with self._lock:
            self._plugins[plugin.name] = plugin
            info = PluginInfo(
                plugin.name, plugin.version, plugin.description,
                started_at=time.time(),
            )
            self._info[plugin.name] = info
        plugin.on_start(self.manager)
        registered = []
        for action, fn in plugin.actions().items():
            # namespaced always; bare name only when it doesn't clobber a
            # built-in or another plugin's action
            namespaced = f"{plugin.name}.{action}"
            self.manager.register_action(namespaced, fn)
            registered.append(namespaced)
            if action not in self.manager._actions:
                self.manager.register_action(action, fn)
                registered.append(action)
        with self._lock:
            self._registered_actions = getattr(self, "_registered_actions", {})
            self._registered_actions[plugin.name] = registered
        return info

    def unregister(self, name: str) -> None:
        with self._lock:
            plugin = self._plugins.pop(name, None)
            self._info.pop(name, None)
            actions = getattr(self, "_registered_actions", {}).pop(name, [])
        for a in actions:
            self.manager._actions.pop(a, None)
        if plugin is not None:
            plugin.on_stop()

    def load_directory(self, path: str) -> list[PluginInfo]:
        """Load every *.py module exposing PLUGIN (ref: LoadPluginsFromDir
        pkg/nornicdb/plugins.go:56 — Python modules instead of .so)."""
        out = []
        if not os.path.isdir(path):
            return out
        for fname in sorted(os.listdir(path)):
            if not fname.endswith(".py") or fname.startswith("_"):
                continue
            mod_path = os.path.join(path, fname)
            spec = importlib.util.spec_from_file_location(
                f"heimdall_plugin_{fname[:-3]}", mod_path
            )
            try:
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)  # type: ignore[union-attr]
                plugin = getattr(mod, "PLUGIN", None)
                if isinstance(plugin, HeimdallPlugin):
                    out.append(self.register(plugin))
            except Exception:
                # a broken plugin must not break the host — but a plugin
                # that silently never loads is an operator mystery
                log.warning("heimdall plugin %s failed to load", mod_path,
                            exc_info=True)
                _count_error("heimdall.plugin_load")
                continue
        return out

    # -- status ------------------------------------------------------------
    def plugins(self) -> list[PluginInfo]:
        with self._lock:
            infos = list(self._info.values())
        for info in infos:
            plugin = self._plugins.get(info.name)
            if plugin is not None:
                try:
                    info.healthy = bool(plugin.health())
                except Exception:
                    log.warning("heimdall plugin %s health check failed",
                                info.name, exc_info=True)
                    info.healthy = False
        return infos

    # -- hook plumbing ------------------------------------------------------
    def _install_hooks(self) -> None:
        mgr = self.manager
        mgr.action_dispatcher = self.run_action  # chat-path actions get hooks
        original_generate = mgr.generate

        def apply_pre_prompt(prompt: str) -> str:
            with self._lock:
                plugins = list(self._plugins.values())
            for p in plugins:
                try:
                    prompt = p.pre_prompt(prompt)
                except Exception:
                    # a failing guard plugin falls back to the unmodified
                    # prompt; log it — redaction silently not applying is
                    # exactly what an operator needs to know
                    log.warning("heimdall plugin %s pre_prompt failed",
                                p.name, exc_info=True)
                    _count_error("heimdall.plugin_hook")
            return prompt

        def generate_with_hooks(prompt: str, max_tokens: int = 128,
                                **kwargs) -> str:
            return original_generate(apply_pre_prompt(prompt), max_tokens,
                                     **kwargs)

        mgr.generate = generate_with_hooks  # type: ignore[method-assign]
        # the streaming path builds its own prompt and calls the backend's
        # generate_stream — it must apply the SAME guards (redaction, veto)
        # or stream=true would evade them
        mgr.pre_prompt_transform = apply_pre_prompt

        # PromptContext hooks (ref: PrePrompt with *PromptContext):
        # every plugin gets a chance to mutate/cancel the request context
        def context_hook(ctx) -> None:
            with self._lock:
                plugins = list(self._plugins.values())
            for p in plugins:
                try:
                    p.pre_prompt_context(ctx)
                except Exception:
                    log.warning("heimdall plugin %s pre_prompt_context "
                                "failed", p.name, exc_info=True)
                    _count_error("heimdall.plugin_hook")
                if ctx.cancelled:
                    if not ctx.cancelled_by:
                        ctx.cancel(ctx.cancel_reason, p.name)
                    return

        if hasattr(mgr, "context_hooks"):
            mgr.context_hooks.append(context_hook)

    def run_action(self, action: dict[str, Any]) -> Any:
        """Execute an action through pre/post hooks."""
        with self._lock:
            plugins = list(self._plugins.values())
        for p in plugins:
            try:
                modified = p.pre_execute(action)
            except Exception:
                log.warning("heimdall plugin %s pre_execute failed",
                            p.name, exc_info=True)
                _count_error("heimdall.plugin_hook")
                continue
            if modified is None:
                return {"vetoed_by": p.name}
            action = modified
        fn = self.manager._actions.get(str(action.get("action")))
        result = fn(action.get("params") or {}) if fn else None
        for p in plugins:
            try:
                result = p.post_execute(action, result)
            except Exception:
                log.warning("heimdall plugin %s post_execute failed",
                            p.name, exc_info=True)
                _count_error("heimdall.plugin_hook")
        return result

    def _emit_storage_event(self, kind: str, entity: Any) -> None:
        """Storage callback → typed DatabaseEvent on the async queue
        (non-blocking; drop-on-full matches the reference)."""
        dispatcher = self.manager.events
        if hasattr(entity, "type") and hasattr(entity, "start_node"):
            dispatcher.emit_relationship_event(
                kind, getattr(entity, "id", ""), entity.type,
                entity.start_node, entity.end_node,
            )
        else:
            dispatcher.emit_node_event(
                kind, getattr(entity, "id", ""),
                list(getattr(entity, "labels", []) or []),
            )

    def _deliver_db_event(self, event) -> None:
        """Dispatcher worker → plugin on_db_event(kind, event). Existing
        plugins that only inspect `kind` are unaffected; the payload is
        the typed DatabaseEvent rather than the raw Node/Edge (the async
        boundary must not retain live storage objects)."""
        with self._lock:
            plugins = list(self._plugins.values())
        for p in plugins:
            try:
                p.on_db_event(event.type, event)
            except Exception:
                log.warning("heimdall plugin %s on_db_event failed",
                            p.name, exc_info=True)
                _count_error("heimdall.plugin_event")

    def _on_db_event(self, kind: str, entity: Any) -> None:
        with self._lock:
            plugins = list(self._plugins.values())
        for p in plugins:
            try:
                p.on_db_event(kind, entity)
            except Exception:
                log.warning("heimdall plugin %s on_db_event failed",
                            p.name, exc_info=True)
                _count_error("heimdall.plugin_event")


class WatcherPlugin(HeimdallPlugin):
    """Reference plugin (ref: plugins/heimdall/plugin.go:62-424 'Watcher'):
    hello/status/health/config actions + db event counting."""

    name = "watcher"
    version = "1.0.0"
    description = "Counts DB events and answers hello/status/health/config"

    def __init__(self) -> None:
        self.events: dict[str, int] = {}
        self.config: dict[str, Any] = {"verbose": False}
        self._manager = None

    def on_start(self, manager) -> None:
        self._manager = manager

    def actions(self):
        return {
            "hello": lambda p: {"message": f"hello from {self.name}"},
            "status": lambda p: {"events": dict(self.events)},
            "health": lambda p: {"healthy": self.health()},
            "config": lambda p: (
                self.config.update(p or {}) or dict(self.config)
            ),
        }

    def on_db_event(self, kind: str, entity) -> None:
        self.events[kind] = self.events.get(kind, 0) + 1
