"""Heimdall model registry, metrics registry, and async DB-event
dispatcher.

Behavioral reference: /root/reference/pkg/heimdall/ —
ModelInfo/ModelType registry (types.go:23-42: name/path/type/size/
quantization/loaded/last_used/VRAM estimate), the metrics registry
(metrics.go: named counters/gauges with Prometheus text rendering), and
the database event dispatcher (plugin.go:1345-1488: bounded 1000-event
queue, background delivery thread, non-blocking emit with drop-on-full,
per-plugin panic isolation).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)

# model kinds (ref: types.go:23-29)
MODEL_EMBEDDING = "embedding"
MODEL_REASONING = "reasoning"
MODEL_CLASSIFICATION = "classification"

_MODEL_TYPES = {MODEL_EMBEDDING, MODEL_REASONING, MODEL_CLASSIFICATION}


@dataclass
class ModelInfo:
    """(ref: ModelInfo types.go:32)"""

    name: str
    type: str
    path: str = ""
    size_bytes: int = 0
    quantization: str = ""
    loaded: bool = False
    last_used: float = 0.0
    vram_estimate_bytes: int = 0
    # the in-process backend (a Generator or an Embedder); None = metadata
    # entry only, loaded lazily via the loader callable
    backend: Any = None
    loader: Optional[Callable[[], Any]] = None

    def as_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "type": self.type,
            "path": self.path,
            "size_bytes": self.size_bytes,
            "quantization": self.quantization,
            "loaded": self.loaded,
            "last_used": self.last_used,
            "vram_estimate_bytes": self.vram_estimate_bytes,
        }


class ModelRegistry:
    """Named models by type, with lazy loading + LRU-style last_used
    tracking (ref: the registry the scheduler consults to pick the
    generation model; generator_cgo.go loads on demand)."""

    def __init__(self) -> None:
        self._models: dict[str, ModelInfo] = {}
        self._default: dict[str, str] = {}  # type -> model name
        self._lock = threading.Lock()
        # per-model load locks so two threads never run the same (large)
        # loader concurrently, without serializing unrelated loads
        self._load_locks: dict[str, threading.Lock] = {}

    def register(self, info: ModelInfo, default: bool = False) -> None:
        if info.type not in _MODEL_TYPES:
            raise ValueError(f"unknown model type {info.type!r}")
        with self._lock:
            self._models[info.name] = info
            if default or info.type not in self._default:
                self._default[info.type] = info.name

    def get(self, name: str) -> Optional[ModelInfo]:
        with self._lock:
            return self._models.get(name)

    def list(self, type_: Optional[str] = None) -> list[ModelInfo]:
        with self._lock:
            models = list(self._models.values())
        if type_ is not None:
            models = [m for m in models if m.type == type_]
        return sorted(models, key=lambda m: m.name)

    def default_for(self, type_: str) -> Optional[ModelInfo]:
        with self._lock:
            name = self._default.get(type_)
            return self._models.get(name) if name else None

    def set_default(self, type_: str, name: str) -> None:
        with self._lock:
            if name not in self._models:
                raise KeyError(name)
            self._default[type_] = name

    def acquire(self, name: str) -> Any:
        """Returns the model backend, loading it on first use and
        stamping last_used (ref: Loaded/LastUsed bookkeeping)."""
        with self._lock:
            info = self._models.get(name)
            if info is None:
                raise KeyError(f"model {name!r} not registered")
            load_lock = self._load_locks.setdefault(name, threading.Lock())
        if info.backend is None and info.loader is not None:
            # the expensive load runs under the per-model lock so a
            # concurrent request for the same model waits instead of
            # double-loading a multi-GB backend
            with load_lock:
                if info.backend is None:
                    info.backend = info.loader()
        with self._lock:
            info.loaded = info.backend is not None
            info.last_used = time.time()
            return info.backend

    def unload(self, name: str) -> bool:
        """Drop the backend reference (memory reclaim on next GC)."""
        with self._lock:
            info = self._models.get(name)
            if info is None or info.backend is None:
                return False
            info.backend = None
            info.loaded = False
            return True


class MetricsRegistry:
    """Named counters/gauges with Prometheus text rendering
    (ref: pkg/heimdall/metrics.go)."""

    def __init__(self, prefix: str = "heimdall") -> None:
        self.prefix = prefix
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0.0))

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {**self._counters, **self._gauges}

    def prometheus_families(self) -> list[tuple[str, str, str, float]]:
        """Typed samples for the telemetry registry's families_callback:
        [(metric_name, kind, help, value)] — keeps counter/gauge typing
        when the unified /metrics exposition renders these."""
        out: list[tuple[str, str, str, float]] = []
        with self._lock:
            for name, v in sorted(self._counters.items()):
                out.append((f"{self.prefix}_{name}", "counter", "", v))
            for name, v in sorted(self._gauges.items()):
                out.append((f"{self.prefix}_{name}", "gauge", "", v))
        return out

    def render_prometheus(self) -> str:
        lines = []
        for full, kind, _help, v in self.prometheus_families():
            lines.append(f"# TYPE {full} {kind}")
            lines.append(f"{full} {v:g}")
        return "\n".join(lines) + ("\n" if lines else "")


@dataclass
class DatabaseEvent:
    """(ref: DatabaseEvent plugin.go — node/relationship/query events)"""

    type: str
    node_id: str = ""
    node_labels: list[str] = field(default_factory=list)
    relationship_id: str = ""
    relationship_type: str = ""
    source_node_id: str = ""
    target_node_id: str = ""
    properties: dict[str, Any] = field(default_factory=dict)
    query: str = ""
    duration: float = 0.0
    rows_affected: int = 0
    error: str = ""
    timestamp: float = 0.0


class EventDispatcher:
    """Async delivery of database events to subscribers: bounded queue,
    one background thread, non-blocking emit with drop-on-full, per-
    subscriber error isolation (ref: dbEventDispatcher plugin.go:1349,
    1000-event buffer, fire-and-forget with panic recovery)."""

    QUEUE_SIZE = 1000

    def __init__(self) -> None:
        self._queue: queue.Queue = queue.Queue(maxsize=self.QUEUE_SIZE)
        self._subscribers: list[Callable[[DatabaseEvent], None]] = []
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0
        self.delivered = 0

    def subscribe(self, fn: Callable[[DatabaseEvent], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="heimdall-events"
        )
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if not self._running:
                return
            self._running = False
        try:
            # non-blocking wake: a full queue means the worker is active
            # and will observe _running on its own — a blocking put here
            # could hang stop() behind a wedged subscriber
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def emit(self, event: DatabaseEvent) -> bool:
        """Non-blocking; returns False when the queue is full and the
        event was dropped (ref: EmitDatabaseEvent drop-on-full)."""
        if not self._running:
            return False
        if not event.timestamp:
            event.timestamp = time.time()
        try:
            self._queue.put_nowait(event)
            return True
        except queue.Full:
            self.dropped += 1
            return False

    # convenience emitters (ref: EmitNodeEvent/EmitRelationshipEvent/
    # EmitQueryEvent plugin.go:1455-1488)
    def emit_node_event(self, type_: str, node_id: str,
                        labels: Optional[list[str]] = None,
                        properties: Optional[dict] = None) -> bool:
        return self.emit(DatabaseEvent(
            type=type_, node_id=node_id, node_labels=list(labels or []),
            properties=dict(properties or {}),
        ))

    def emit_relationship_event(self, type_: str, rel_id: str,
                                rel_type: str, source_id: str,
                                target_id: str) -> bool:
        return self.emit(DatabaseEvent(
            type=type_, relationship_id=rel_id, relationship_type=rel_type,
            source_node_id=source_id, target_node_id=target_id,
        ))

    def emit_query_event(self, type_: str, query_text: str,
                         duration: float, rows: int = 0,
                         error: str = "") -> bool:
        return self.emit(DatabaseEvent(
            type=type_, query=query_text, duration=duration,
            rows_affected=rows, error=error,
        ))

    def _run(self) -> None:
        while True:
            try:
                # bounded wait so the worker re-checks _running even when
                # stop()'s wake sentinel couldn't be enqueued (full queue)
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                with self._lock:
                    if not self._running:
                        return
                continue
            if event is None:
                with self._lock:
                    if not self._running:
                        return
                continue
            with self._lock:
                subs = list(self._subscribers)
            for fn in subs:
                try:
                    fn(event)
                except Exception:
                    # a broken subscriber must not stall delivery, but a
                    # permanently crashing one should be visible
                    log.warning("event subscriber %r failed",
                                getattr(fn, "__name__", fn), exc_info=True)
                    count_error("heimdall.event_subscriber")
            self.delivered += 1
