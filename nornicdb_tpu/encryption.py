"""At-rest encryption helpers: AES-256-GCM with PBKDF2 key derivation.

Behavioral reference: /root/reference/pkg/encryption/encryption.go
(DeriveKey et al.); PBKDF2 with 600k iterations matching
pkg/nornicdb/db.go:805; at-rest encryption applied to WAL payloads and
snapshots (the reference delegates to BadgerDB's built-in encryption with
the derived key, db.go:781-809 — here the WAL layer is the storage of
record so it encrypts its own records).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

PBKDF2_ITERATIONS = 600_000  # (ref: db.go:805)
KEY_BYTES = 32  # AES-256
NONCE_BYTES = 12


def derive_key(passphrase: str, salt: bytes, iterations: int = PBKDF2_ITERATIONS) -> bytes:
    """(ref: encryption.DeriveKey)"""
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt, iterations,
                               dklen=KEY_BYTES)


def new_salt() -> bytes:
    return os.urandom(16)


class Encryptor:
    """AES-256-GCM payload encryption."""

    def __init__(self, key: bytes):
        if len(key) != KEY_BYTES:
            raise ValueError(f"key must be {KEY_BYTES} bytes")
        self._aead = AESGCM(key)

    @classmethod
    def from_passphrase(cls, passphrase: str, salt: bytes,
                        iterations: int = PBKDF2_ITERATIONS) -> "Encryptor":
        return cls(derive_key(passphrase, salt, iterations))

    def encrypt(self, plaintext: bytes, aad: Optional[bytes] = None) -> bytes:
        nonce = os.urandom(NONCE_BYTES)
        return nonce + self._aead.encrypt(nonce, plaintext, aad)

    def decrypt(self, blob: bytes, aad: Optional[bytes] = None) -> bytes:
        nonce, ct = blob[:NONCE_BYTES], blob[NONCE_BYTES:]
        return self._aead.decrypt(nonce, ct, aad)


def load_or_create_salt(path: str) -> bytes:
    """Persist-or-load a PBKDF2 salt file, shared by every at-rest layer
    (WAL, segment store) so salt handling can't silently diverge. An empty
    or short file (crash mid-write) is treated as absent and regenerated —
    safe because a salt only matters once records encrypted under it exist,
    and those are written strictly after the salt file."""
    if os.path.exists(path):
        with open(path, "rb") as f:
            salt = f.read()
        if len(salt) >= 16:
            return salt
    salt = new_salt()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(salt)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return salt
