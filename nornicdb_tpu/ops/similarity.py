"""Batch vector similarity + top-k on TPU via XLA.

Replaces the reference's CUDA/Metal kernels
(/root/reference/pkg/gpu/cuda/cuda_kernels.cu: kernel_compute_norms :185,
kernel_normalize_vectors :206, kernel_cosine_similarity :284,
kernel_topk_simple :384; pkg/simd/simd.go:38-240).

TPU-first design notes:
  - Cosine scoring IS a matmul: normalize once, then Q @ C^T rides the MXU.
    We keep corpora normalized at insert time so the query path is one GEMM.
  - Scores + top-k are computed under one jit so XLA fuses the epilogue and
    never round-trips the (Q, N) score matrix through HBM when chunked.
  - Static shapes: corpora are padded to lane multiples (128) and masked with
    -inf; jit caches per padded shape bucket, not per exact N.
  - bf16 matmul with f32 accumulation (preferred_element_type) matches MXU
    native precision.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu import backend as _backend
from nornicdb_tpu.errors import DeviceUnavailable
from nornicdb_tpu.ops.host_search import (
    format_topk_results,
    host_score_rows,
    host_topk,
)

logger = logging.getLogger(__name__)

LANE = 128  # TPU lane width; min tile second dim


def pad_to_multiple(n: int, m: int = LANE) -> int:
    return ((n + m - 1) // m) * m


@jax.jit
def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-wise L2 normalization (ref: kernel_normalize_vectors cuda_kernels.cu:206)."""
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True))
    return (x / jnp.maximum(norm, eps)).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def dot_scores(
    queries: jax.Array, corpus: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """(Q, D) x (N, D) -> (Q, N) dot-product scores on the MXU."""
    if use_bf16:
        queries = queries.astype(jnp.bfloat16)
        corpus = corpus.astype(jnp.bfloat16)
    return jax.lax.dot_general(
        queries,
        corpus,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def cosine_scores(
    queries: jax.Array, corpus: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """Full cosine similarity: normalizes both sides then one GEMM
    (ref: kernel_cosine_similarity cuda_kernels.cu:284)."""
    return dot_scores(l2_normalize(queries), l2_normalize(corpus), use_bf16)


@functools.partial(
    jax.jit, static_argnames=("k", "normalized", "use_bf16", "exact", "recall_target")
)
def cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    normalized: bool = True,
    use_bf16: bool = True,
    exact: bool = False,
    recall_target: float = 0.95,
) -> tuple[jax.Array, jax.Array]:
    """Fused cosine scoring + top-k.

    queries: (Q, D); corpus: (Np, D) padded to a lane multiple;
    valid:   (Np,) bool mask — False rows (padding / tombstones) score -inf.
    Returns (values (Q, k), indices (Q, k)).

    By default top-k uses lax.approx_max_k, the TPU-native partial-reduction
    top-k (fuses into the GEMM epilogue; measured ~4x faster end-to-end at
    N=1M than exact lax.top_k, which adds a full-sort pass). Scores of the
    returned candidates are exact; only set membership is approximate
    (recall_target, default 0.95 — same contract as the reference's HNSW
    path, pkg/search/hnsw_index.go). exact=True restores full sort.
    """
    q = queries if normalized else l2_normalize(queries)
    c = corpus if normalized else l2_normalize(corpus)
    scores = dot_scores(q, c, use_bf16)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    if exact:
        return jax.lax.top_k(scores, k)
    return jax.lax.approx_max_k(scores, k, recall_target=recall_target)


@functools.partial(jax.jit, static_argnames=("k",))
def masked_dot_topk(
    query: jax.Array, corpus: jax.Array, valid: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Graph-filtered top-k for the Cypher ``VectorTopK`` operator: one
    (1, D) x (Np, D) GEMM over a pre-normalized corpus with the surviving
    graph-predicate rows as a validity mask (False -> -inf, covering both
    pad rows and mask-rejected rows), plus the exact k largest masked
    scores for the rescore boundary.

    Returns ``(scores (Np,), top_vals (k,))``.  f32 end to end — the
    caller's widened-boundary rescore contract budgets for f32 GEMM
    rounding only, not bf16.
    """
    s = dot_scores(query[None, :], corpus, use_bf16=False)[0]
    s = jnp.where(valid, s, -jnp.inf)
    return s, jax.lax.top_k(s, k)[0]


# streaming Pallas top-k engages above this corpus size; below it the (Q, N)
# score matrix is small enough that the XLA GEMM+approx_max_k path wins on
# dispatch overhead
STREAMING_MIN_ROWS = 65_536

# bin-reduction strategy for the streaming kernels ("sort" | "approx" |
# "pallas", see pallas_kernels._topk_bins). Overridable per-deployment while
# autotune data accumulates (benchmarks/kernel_autotune.py). Validated here
# so a config typo fails at import, not inside the first jitted query.
TOPK_EPILOGUE = os.environ.get("NORNICDB_TOPK_EPILOGUE", "sort")
if TOPK_EPILOGUE not in ("sort", "approx", "pallas"):
    raise ValueError(
        f"NORNICDB_TOPK_EPILOGUE={TOPK_EPILOGUE!r}: "
        "must be one of sort|approx|pallas"
    )


def topk_backend(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    exact: bool = False,
    use_bf16: bool = True,
    streaming: Optional[bool] = None,
    quantized: Optional[tuple[jax.Array, jax.Array]] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k dispatch for normalized inputs: the streaming Pallas kernel
    (ops.pallas_kernels.streaming_cosine_topk — one corpus read, no (Q, N)
    materialization) on TPU for large corpora, else the XLA
    GEMM+approx_max_k path. `streaming=None` auto-selects; tests force it on
    small corpora (interpret mode runs the same kernel off-TPU). The kernel
    scores in bf16, so an explicit use_bf16=False keeps the XLA f32 path.
    `quantized=(c_i8, c_scale)` (quantize_rows of the same corpus) engages
    the int8 MXU kernel — 2x the bf16 MXU rate, half the corpus HBM read."""
    from nornicdb_tpu.ops.pallas_kernels import (
        _on_tpu,
        pick_tile_n,
        quantize_rows,
        streaming_cosine_topk,
        streaming_cosine_topk_int8,
        streaming_rows_for,
    )

    n = int(corpus.shape[0])
    on_tpu = _on_tpu()
    if streaming is None:
        streaming = (
            (not exact) and use_bf16 and on_tpu and n >= STREAMING_MIN_ROWS
        )
    if streaming and not exact:
        tile = pick_tile_n(n)
        rows = min(streaming_rows_for(k, tile), max(n // tile, 1))
        # tile must divide n (corpus capacities are 128-multiples, but a
        # sharded slice need not be) and the bins must hold a full top-k;
        # otherwise fall through to the XLA path instead of crashing
        if n % tile == 0 and rows * tile >= k:
            if quantized is not None:
                q_i8, q_scale = quantize_rows(queries)
                return streaming_cosine_topk_int8(
                    q_i8, q_scale, quantized[0], quantized[1], valid,
                    min(k, n), tile_n=tile, rows=rows,
                    interpret=not on_tpu, epilogue=TOPK_EPILOGUE,
                )
            return streaming_cosine_topk(
                queries, corpus, valid, min(k, n),
                tile_n=tile, rows=rows, interpret=not on_tpu,
                epilogue=TOPK_EPILOGUE,
            )
    return cosine_topk(
        queries, corpus, valid, k, normalized=True, use_bf16=use_bf16,
        exact=exact,
    )


@functools.partial(jax.jit, static_argnames=("k",))
def cosine_topk_int8_xla(
    queries: jax.Array,
    c_i8: jax.Array,
    c_scale: jax.Array,
    valid: jax.Array,
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """XLA fallback scoring over an int8-resident corpus: dequantize the
    codes into the bf16 GEMM (int8 values are exactly representable in
    bf16), apply the per-row dequant multiplier in the f32 epilogue.

    Engages where the streaming int8 Pallas kernel doesn't (non-TPU
    backends, small corpora, tile-indivisible shard slices). Queries stay
    f32/bf16 — only the CORPUS is quantized, so candidate membership is at
    least as accurate as the both-sides-int8 kernel. Always approximate:
    there is deliberately NO exact int8 device mode — the recall-1.0
    contract is served from the host f32 mirror, and served scores come
    from the caller's exact f32 host rescore either way."""
    scores = jax.lax.dot_general(
        queries.astype(jnp.bfloat16),
        c_i8.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / jnp.maximum(c_scale, 1e-9)[None, :]
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.approx_max_k(scores, k, recall_target=0.95)


def topk_backend_int8(
    queries: jax.Array,
    c_i8: jax.Array,
    c_scale: jax.Array,
    valid: jax.Array,
    k: int,
    streaming: Optional[bool] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k dispatch for an int8-RESIDENT corpus (no f32/bf16 device copy
    exists — compressed residency, 4x the rows per HBM byte). On TPU at
    scale the streaming int8 Pallas bin-reduce kernel runs the MXU at the
    int8 rate over the codes; elsewhere the XLA dequant-GEMM fallback.
    ``c_scale`` follows the quantize_rows convention (x ~= int8 / scale).
    Candidate scores are approximate (int8 + bf16 noise); callers rescore
    the candidate set exactly from the host f32 mirror."""
    from nornicdb_tpu.ops.pallas_kernels import (
        _on_tpu,
        pick_tile_n,
        quantize_rows,
        streaming_cosine_topk_int8,
        streaming_rows_for,
    )

    n = int(c_i8.shape[0])
    on_tpu = _on_tpu()
    if streaming is None:
        streaming = on_tpu and n >= STREAMING_MIN_ROWS
    if streaming:
        tile = pick_tile_n(n)
        rows = min(streaming_rows_for(k, tile), max(n // tile, 1))
        if n % tile == 0 and rows * tile >= k:
            q_i8, q_scale = quantize_rows(queries)
            return streaming_cosine_topk_int8(
                q_i8, q_scale, c_i8, c_scale, valid,
                min(k, n), tile_n=tile, rows=rows,
                interpret=not on_tpu, epilogue=TOPK_EPILOGUE,
            )
    return cosine_topk_int8_xla(queries, c_i8, c_scale, valid, min(k, n))


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def score_subset(
    query: jax.Array, corpus: jax.Array, indices: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """Exact re-score of candidate rows (ref: EmbeddingIndex.ScoreSubset
    pkg/gpu/gpu.go:1554): gather candidates then one small GEMV."""
    cand = corpus[indices]  # (C, D)
    q = query.reshape(1, -1)
    return dot_scores(q, cand, use_bf16)[0]


@jax.jit
def euclidean_scores(queries: jax.Array, corpus: jax.Array) -> jax.Array:
    """Squared euclidean distances via the |x|^2 - 2xy + |y|^2 expansion so the
    cross term rides the MXU (ref: euclidean_distance shaders_darwin.metal:333)."""
    qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
    cn = jnp.sum(corpus.astype(jnp.float32) ** 2, axis=1)[None, :]
    cross = dot_scores(queries, corpus, use_bf16=False)
    return jnp.maximum(qn - 2.0 * cross + cn, 0.0)


def merge_topk(
    values: jax.Array, indices: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Merge per-shard/per-chunk top-k lists into a global top-k.

    values/indices: (S, Q, k) stacked partial results with GLOBAL indices.
    Returns (Q, k). Used for the ICI all-gather merge of sharded search.

    Sentinel contract: any merged entry whose value is not finite (the
    -inf padding a near-empty shard emits when ``k`` exceeds its live
    rows) gets index -1, so a padding slot's index can NEVER surface as
    a candidate — even through a caller that forgets to filter by score.
    (Before this guard a -inf entry kept whatever index the per-shard
    top-k happened to assign it, and ``ids[idx]`` on a negative or
    recycled index could attribute a live id to a sentinel score.)

    Tie-breaking is stable vs the single-device path: the flattened
    candidate axis is shard-major (shard s, rank j -> s*k + j), and
    lax.top_k breaks value ties by the lowest flattened position — i.e.
    lowest shard first, then best per-shard rank. Because row slots are
    laid out contiguously per shard, that is exactly ascending global
    row index, the same order lax.top_k yields on one device.
    """
    s, q, kk = values.shape
    flat_v = jnp.transpose(values, (1, 0, 2)).reshape(q, s * kk)
    flat_i = jnp.transpose(indices, (1, 0, 2)).reshape(q, s * kk)
    best_v, pos = jax.lax.top_k(flat_v, k)
    best_i = jnp.take_along_axis(flat_i, pos, axis=1)
    best_i = jnp.where(jnp.isfinite(best_v), best_i, -1)
    return best_v, best_i


# ------------------------------------------------------------- device sync
# dirty-tracking granularity: one block = one LANE-aligned row group. Writes
# mark only the blocks they touch; sync patches only dirty blocks.
BLOCK_ROWS = LANE

# H2D sync telemetry: duration + bytes histograms by mode (patch vs full
# upload), plus a `device.sync` span when a traced request pays the sync
from nornicdb_tpu.telemetry.metrics import (  # noqa: E402
    BYTE_BUCKETS as _BYTE_BUCKETS,
    REGISTRY as _REGISTRY,
)
from nornicdb_tpu.telemetry.tracing import tracer as _tracer  # noqa: E402

_SYNC_HIST = _REGISTRY.histogram(
    "nornicdb_device_sync_seconds",
    "Host-to-device corpus sync duration by mode",
    labels=("mode",),
)
_SYNC_PATCH_CELL = _SYNC_HIST.labels("patch")
_SYNC_FULL_CELL = _SYNC_HIST.labels("full")
_SYNC_BYTES_HIST = _REGISTRY.histogram(
    "nornicdb_device_sync_transfer_bytes",
    "Bytes shipped per host-to-device sync by mode",
    labels=("mode",),
    buckets=_BYTE_BUCKETS,
)
_SYNC_PATCH_BYTES_CELL = _SYNC_BYTES_HIST.labels("patch")
_SYNC_FULL_BYTES_CELL = _SYNC_BYTES_HIST.labels("full")

# mesh-sharded serving telemetry (parallel.ShardedCorpus): registered here —
# not in parallel/ — so the families render in the /metrics catalog of every
# process (the sharded module imports lazily, only when a mesh exists)
_SHARDED_SEARCH_HIST = _REGISTRY.histogram(
    "nornicdb_sharded_search_seconds",
    "Fused per-shard scoring + local top-k + ICI all-gather merge: one "
    "device dispatch per (possibly batched) sharded search",
)
_SHARDED_MERGE_HIST = _REGISTRY.histogram(
    "nornicdb_sharded_merge_seconds",
    "Host-side merge epilogue of a sharded search (sentinel filtering, "
    "id resolution, IVF block+residual candidate merge)",
)
_SHARD_REBALANCES = _REGISTRY.counter(
    "nornicdb_shard_rebalances_total",
    "Shard-boundary remaps (grow/compact/recovery) that forced a full "
    "re-shard re-upload of the mesh corpus",
)
_SHARD_LOCALK_OVERFLOWS = _REGISTRY.counter(
    "nornicdb_shard_local_k_overflows_total",
    "Approx sharded searches where one shard's local_k candidate list "
    "saturated the merged top-k (raise local_k to recover recall)",
)
_SHARD_ROWS_GAUGE = _REGISTRY.gauge(
    "nornicdb_shard_rows",
    "Live corpus rows resident on each mesh shard",
    labels=("shard",),
)

# above this fraction of dirty blocks, one contiguous full transfer beats
# many small patch dispatches (each patch pays launch + slice overhead and
# the runs re-upload their padding rows)
FULL_SYNC_DIRTY_FRACTION = 0.5


@dataclass
class SyncStats:
    """H2D sync accounting for one corpus (exposed via stats()["sync"] and
    the server's /admin/stats + /metrics)."""

    patches: int = 0          # incremental patch syncs (1 per sync pass)
    full_uploads: int = 0     # whole-corpus transfers (first sync/grow/…)
    bytes_uploaded: int = 0   # total host bytes shipped to the device
    patch_bytes: int = 0      # subset of bytes_uploaded moved by patching
    rows_patched: int = 0
    uploader_runs: int = 0    # write-behind background sync cycles
    query_stall_s: float = 0.0  # time the query path spent blocked in sync
    # device search programs launched (one per fused batch when queries go
    # through the QueryBatcher) — the counter the multi-process bench's
    # one-program-per-fused-batch invariant is asserted against
    device_dispatches: int = 0

    def as_dict(self) -> dict:
        return asdict(self)


def _coalesce_runs(
    blocks: Sequence[int], cap_blocks: int
) -> list[tuple[int, int]]:
    """Coalesce sorted dirty block ids into (start_block, n_blocks) upload
    runs. Blocks separated by <= 2 clean blocks merge into one run (a couple
    of redundant blocks cost less than another dispatch), and run lengths
    round up to powers of two so the jitted patch program caches O(log N)
    shapes instead of one per burst size; the start shifts back when the
    padding would overrun capacity. Padding rows rewrite identical host
    bytes, so overlap between padded runs is harmless."""
    runs: list[tuple[int, int]] = []
    i = 0
    while i < len(blocks):
        j = i
        while j + 1 < len(blocks) and blocks[j + 1] - blocks[j] <= 3:
            j += 1
        start, n = blocks[i], blocks[j] - blocks[i] + 1
        n = min(1 << (n - 1).bit_length(), cap_blocks)
        runs.append((min(start, cap_blocks - n), n))
        i = j + 1
    return runs


def _patch_rows_impl(dev: jax.Array, rows: jax.Array, start) -> jax.Array:
    return jax.lax.dynamic_update_slice(dev, rows, (start, 0))


def _patch_valid_impl(dev_valid: jax.Array, rows: jax.Array, start) -> jax.Array:
    return jax.lax.dynamic_update_slice(dev_valid, rows, (start,))


def _patch_i8_impl(dev_i8, dev_scale, rows, start):
    """Requantize ONLY the patched rows: quantization is per-row symmetric
    (ops.pallas_kernels.quantize_rows), so block-local requantization
    matches requantizing the whole corpus (int8 codes exactly; scales to
    within a float ulp of XLA codegen variance)."""
    from nornicdb_tpu.ops.pallas_kernels import quantize_rows

    i8, s = quantize_rows(rows)
    return (
        jax.lax.dynamic_update_slice(dev_i8, i8, (start, 0)),
        jax.lax.dynamic_update_slice(dev_scale, s, (start,)),
    )


# donated variants update the resident buffer in place on TPU (no 2x HBM
# spike during the patch); the non-donated twins run while a search still
# borrows the buffer (HostCorpus._borrow_device reader guard)
_patch_rows = jax.jit(_patch_rows_impl)
_patch_rows_donated = jax.jit(_patch_rows_impl, donate_argnums=(0,))
_patch_valid = jax.jit(_patch_valid_impl)
_patch_valid_donated = jax.jit(_patch_valid_impl, donate_argnums=(0,))
_patch_i8 = jax.jit(_patch_i8_impl)
_patch_i8_donated = jax.jit(_patch_i8_impl, donate_argnums=(0, 1))


# ----------------------------------------------------------------- host API
class HostCorpus:
    """Host-side state machine shared by DeviceCorpus (single chip) and
    parallel.ShardedCorpus (mesh): id->slot map, padded row matrix, tombstone
    removal, deferred ratio-triggered compaction, capacity growth, plus the
    block-granular dirty tracking + incremental H2D sync driver (subclasses
    supply _upload_full/_apply_patch for their device layout) and the
    write-behind uploader thread.

    Mirrors gpu.EmbeddingIndex host bookkeeping (ref: pkg/gpu/gpu.go:1224,
    Add/Remove :1378-1460; the reference's HNSW uses the same
    tombstone-then-rebuild idea, search.go:1215). `align` keeps the row count
    a multiple of the hardware tile / shard granularity.
    """

    def __init__(
        self,
        dims: int,
        align: int = LANE,
        capacity: int = 0,
        compact_ratio: float = 0.3,
        backend=None,
    ):
        self.dims = dims
        self.align = align
        self.compact_ratio = compact_ratio
        # device lifecycle manager (nornicdb_tpu.backend): every device
        # path gates through it BEFORE taking any lock, and serves from
        # the host arrays while it reports DEGRADED_CPU. None -> the
        # process-default manager, resolved lazily on first device use.
        self._backend = backend
        self._backend_registered = False
        cap = max(capacity, align)
        cap = ((cap + align - 1) // align) * align
        self._ids: list[Optional[str]] = []
        self._slot_of: dict[str, int] = {}
        self._host = np.zeros((cap, dims), np.float32)
        self._valid = np.zeros(cap, bool)
        self._tombstones = 0
        # dirty tracking is block-granular: mutators mark only the
        # BLOCK_ROWS-row blocks they touch; _full_dirty forces a whole-corpus
        # upload (first sync, grow/compact/clear, dtype change)
        self._dirty_blocks: set[int] = set()
        self._full_dirty = True
        self._compact_pending = False
        # guards host arrays + dirty sets + device-buffer swaps against the
        # write-behind uploader thread and concurrent searchers
        self._sync_lock = threading.RLock()
        # searches borrowing the device buffer; while > 0 the patcher must
        # not donate (free) the buffer they hold. device_arrays() leaks an
        # unscoped reference and clears _donation_ok for good.
        self._readers = 0
        self._donation_ok = True
        self.sync_stats = SyncStats()
        # mutation epoch: bumps on every write (stats / cache invalidation)
        self._epoch = 0
        # layout epoch: bumps ONLY when a mutation invalidates derived
        # layouts (IVF blocks hold row copies) — i.e. in-place overwrite of
        # a covered slot, or any slot-space remap (grow/compact/clear). New
        # ids and removals leave fitted layouts valid: fresh slots are in no
        # block, and removed slots filter out host-side at result time.
        self._layout_epoch = 0
        self._layout_slots: Optional[np.ndarray] = None  # bool per slot
        # write-behind uploader (start_uploader): coalesces dirty blocks in
        # the background so the query path usually finds a clean buffer
        self._uploader: Optional[threading.Thread] = None
        self._uploader_stop = threading.Event()
        self._uploader_wake = threading.Event()
        self._uploader_interval = 0.002

    def __len__(self) -> int:
        return len(self._slot_of)

    @property
    def capacity(self) -> int:
        return self._host.shape[0]

    # -- dirty-block bookkeeping (all called under _sync_lock) -------------
    def _mark_rows_dirty(self, start: int, stop: int) -> None:
        self._dirty_blocks.update(
            range(start // BLOCK_ROWS, (stop - 1) // BLOCK_ROWS + 1)
        )

    def _mark_all_dirty(self) -> None:
        self._full_dirty = True
        self._dirty_blocks.clear()

    def _note_overwrite(self, slot: int) -> None:
        """In-place update of a slot covered by a derived layout: the IVF
        blocks hold a COPY of the row, so the layout would serve the stale
        vector — it must rebuild (layout epoch bump)."""
        ls = self._layout_slots
        if ls is not None and slot < ls.size and ls[slot]:
            self._layout_epoch += 1

    def add(self, id_: str, vector: np.ndarray) -> None:
        v = np.asarray(vector, np.float32)
        norm = float(np.linalg.norm(v))
        if norm > 1e-12:
            v = v / norm
        with self._sync_lock:
            slot = self._slot_of.get(id_)
            if slot is None:
                if len(self._ids) >= self.capacity and self._compact_pending:
                    # reclaim tombstoned slots before paying for a capacity
                    # doubling: a write-only churn workload (no searches to
                    # trigger the deferred compaction) must stay bounded
                    self._compact()
                slot = len(self._ids)
                if slot >= self.capacity:
                    self._grow()
                self._ids.append(id_)
                self._slot_of[id_] = slot
            else:
                self._note_overwrite(slot)
            self._host[slot] = v
            self._valid[slot] = True
            self._mark_rows_dirty(slot, slot + 1)
            self._epoch += 1
        self._wake_uploader()

    def add_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        if not ids:
            return
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-12)
        with self._sync_lock:
            all_new = len(set(ids)) == len(ids) and not any(
                i in self._slot_of for i in ids
            )
            if all_new:
                # bulk-ingest fast path: one slice assignment into the slot
                # tail instead of a Python loop per row
                if (
                    len(self._ids) + len(ids) > self.capacity
                    and self._compact_pending
                ):
                    self._compact()  # reclaim tombstones before growing
                start = len(self._ids)
                end = start + len(ids)
                if end > self.capacity:
                    self._grow(min_capacity=end)
                self._host[start:end] = vectors
                self._valid[start:end] = True
                self._ids.extend(ids)
                self._slot_of.update(
                    (id_, start + i) for i, id_ in enumerate(ids)
                )
                self._mark_rows_dirty(start, end)
            else:
                for i, id_ in enumerate(ids):
                    slot = self._slot_of.get(id_)
                    if slot is None:
                        if (
                            len(self._ids) >= self.capacity
                            and self._compact_pending
                        ):
                            self._compact()
                        slot = len(self._ids)
                        if slot >= self.capacity:
                            self._grow(min_capacity=slot + len(ids) - i)
                        self._ids.append(id_)
                        self._slot_of[id_] = slot
                    else:
                        self._note_overwrite(slot)
                    self._host[slot] = vectors[i]
                    self._valid[slot] = True
                    self._mark_rows_dirty(slot, slot + 1)
            self._epoch += 1
        self._wake_uploader()

    def remove(self, id_: str) -> bool:
        with self._sync_lock:
            slot = self._slot_of.pop(id_, None)
            if slot is None:
                return False
            self._ids[slot] = None
            self._valid[slot] = False
            self._tombstones += 1
            self._mark_rows_dirty(slot, slot + 1)
            self._epoch += 1
            if (
                self._ids
                and self._tombstones / len(self._ids) > self.compact_ratio
            ):
                # deferred: the full rewrite + full re-upload runs coalesced
                # on the write-behind uploader (or the next sync), never on
                # the caller's write path
                self._compact_pending = True
        self._wake_uploader()
        return True

    # -- inspection / lifecycle (ref: EmbeddingIndex Has/Get/Clear/Stats/
    # MemoryUsage/Serialize, pkg/gpu/gpu.go + gpu_test.go:630-800) ---------
    def has(self, id_: str) -> bool:
        with self._sync_lock:
            return id_ in self._slot_of

    def get(self, id_: str) -> Optional[np.ndarray]:
        """The stored (normalized) vector, or None when absent."""
        # slot lookup and row read must be one atomic view: the write-behind
        # uploader thread's deferred _compact() rebinds _slot_of/_host with a
        # remapped slot space, and a stale slot indexed into the new _host
        # would silently return another id's vector
        with self._sync_lock:
            slot = self._slot_of.get(id_)
            if slot is None:
                return None
            return self._host[slot].copy()

    def clear(self) -> None:
        with self._sync_lock:
            cap = self.capacity
            self._ids = []
            self._slot_of = {}
            self._host = np.zeros((cap, self.dims), np.float32)
            self._valid = np.zeros(cap, bool)
            self._tombstones = 0
            self._compact_pending = False
            self._mark_all_dirty()
            self._epoch += 1
            self._layout_epoch += 1
            # slot space was remapped: derived cluster layouts (DeviceCorpus
            # _assignments/IVF blocks) would index the wrong rows — same
            # reason _grow/_compact invalidate them
            clear_clusters = getattr(self, "clear_clusters", None)
            if callable(clear_clusters):
                clear_clusters()

    def stats(self) -> dict:
        return {
            "count": len(self._slot_of),
            "capacity": self.capacity,
            "dims": self.dims,
            "tombstones": self._tombstones,
            "epoch": self._epoch,
            "layout_epoch": self._layout_epoch,
            "dirty_blocks": len(self._dirty_blocks),
            "memory_bytes": self.memory_usage(),
            "sync": self.sync_stats.as_dict(),
        }

    def memory_usage(self) -> int:
        return int(self._host.nbytes + self._valid.nbytes)

    def export_host_state(self) -> dict:
        """Consistent copies of the host arrays + slot map for the
        cross-process shared-memory read plane (server/readplane.py):
        ``{"rows", "valid", "ids", "epoch", "count", "dims"}``.  The copy
        runs under _sync_lock so a racing in-place row overwrite can never
        tear an exported vector; the slot layout is exported AS IS (no
        forced compaction) so exported indices mean the same thing they
        mean to the in-process host/device search paths."""
        with self._sync_lock:
            return {
                "rows": self._host.copy(),
                "valid": self._valid.copy(),
                "ids": list(self._ids),
                "epoch": self._epoch,
                "count": len(self._slot_of),
                "dims": self.dims,
            }

    def save(self, path: str) -> None:
        """Persist live ids + vectors (tombstones are not serialized —
        matches the reference's compact-on-serialize behavior)."""
        # same atomic-view contract as get(): the uploader thread's deferred
        # _compact() rebinds _ids/_host with remapped slots, and a snapshot
        # torn across that rebind would checkpoint ids against other rows
        with self._sync_lock:
            live = [(i, id_) for i, id_ in enumerate(self._ids)
                    if id_ is not None]
            ids = np.asarray([id_ for _, id_ in live])
            vecs = (self._host[[i for i, _ in live]].copy()
                    if live else np.zeros((0, self.dims), np.float32))
        np.savez_compressed(path, ids=ids, vectors=vecs,
                            dims=np.asarray(self.dims))

    @classmethod
    def load(cls, path: str, **kwargs) -> "HostCorpus":
        with np.load(path, allow_pickle=False) as data:
            if any(k not in data for k in ("vectors", "ids", "dims")):
                raise ValueError(f"{path} is not a corpus checkpoint")
            dims = int(data["dims"])
            out = cls(dims=dims, **kwargs)
            vecs = data["vectors"]
            ids = [str(i) for i in data["ids"]]
            if ids:
                out.add_batch(ids, vecs)
        return out

    def _grow(self, min_capacity: int = 0) -> None:
        need = max(self.capacity * 2, min_capacity, self.align)
        new_cap = ((need + self.align - 1) // self.align) * self.align
        host = np.zeros((new_cap, self.dims), np.float32)
        valid = np.zeros(new_cap, bool)
        host[: self._host.shape[0]] = self._host
        valid[: self._valid.shape[0]] = self._valid
        self._host, self._valid = host, valid
        # shape change: the resident device buffer cannot be patched in place
        self._mark_all_dirty()
        self._layout_epoch += 1

    def _compact(self) -> None:
        live = [(i, id_) for i, id_ in enumerate(self._ids) if id_ is not None]
        host = np.zeros_like(self._host)
        valid = np.zeros_like(self._valid)
        ids: list[Optional[str]] = []
        slot_of: dict[str, int] = {}
        for new_slot, (old_slot, id_) in enumerate(live):
            host[new_slot] = self._host[old_slot]
            valid[new_slot] = True
            ids.append(id_)
            slot_of[id_] = new_slot
        self._host, self._valid = host, valid
        self._ids, self._slot_of = ids, slot_of
        self._tombstones = 0
        self._compact_pending = False
        self._mark_all_dirty()
        self._epoch += 1
        self._layout_epoch += 1

    # -- backend lifecycle gate --------------------------------------------
    def _backend_mgr(self):
        """This corpus's BackendManager (process default unless injected),
        registered for recovery re-upload on first resolution."""
        mgr = self._backend
        if mgr is None:
            mgr = self._backend = _backend.manager()
        if not self._backend_registered:
            self._backend_registered = True
            mgr.register_corpus(self)
        return mgr

    def _device_ok_nowait(self) -> bool:
        """Non-blocking state read for code already inside a lock
        (``_sync``), where *waiting* on acquisition is exactly the bug
        NL-DEV01 bans."""
        return self._backend_mgr().ready()

    def _device_gate(self) -> bool:
        """Is the device serving?  The cold entry of a search: blocks —
        bounded by the manager's acquire timeout, on the manager's worker
        thread, with NO caller lock held — and honors the fallback policy
        (raises DeviceUnavailable under "fail")."""
        mgr = self._backend_mgr()
        mgr.require_ready()
        return mgr.ready()

    def _on_backend_recovered(self, mode: str) -> None:
        """The manager re-acquired the device: schedule the re-upload.
        ``mode="full"`` assumes device memory was lost — drop the resident
        buffers and mark everything dirty (next sync is a whole-corpus
        transfer).  ``mode="dirty"`` trusts a surviving resident buffer
        (transient hang) and only patches the blocks written while
        degraded, which the dirty tracking already holds."""
        with self._sync_lock:
            if not (mode == "dirty" and self._device_ready()):
                self._dev = None
                self._dev_valid = None
                if getattr(self, "_dev_i8", None) is not None:
                    self._dev_i8 = None
                self._mark_all_dirty()
                # device-resident cluster state (IVF blocks, centroids)
                # died with the device too — a post-recovery pruned search
                # must not dereference buffers of the lost incarnation.
                # Drop it and queue the last fit's HOST copy (id-based, so
                # it survives slot remaps) for re-install once READY.
                clear = getattr(self, "clear_clusters", None)
                if callable(clear):
                    last_fit = getattr(self, "_last_fit_host", None)
                    clear()
                    self._pending_clusters = last_fit
        self._wake_uploader()

    def _on_backend_ready(self) -> None:
        """Called by the manager AFTER the READY transition lands (the
        _on_backend_recovered wake can be consumed by an uploader that
        still observed RECOVERING): guarantees the re-upload runs in the
        background instead of inline on the first post-recovery query."""
        self._wake_uploader()

    def _search_host(
        self, q: np.ndarray, k: int, min_similarity: float
    ) -> list[list[tuple[str, float]]]:
        """DEGRADED_CPU serving: exact NumPy top-k over the host arrays.

        Scoring holds _sync_lock: writers mutate _host rows IN PLACE, and
        a scan racing an overwrite would read torn vectors (half-old,
        half-new — the atomic-view contract get()/save() keep for the
        same reason; the device path reads immutable buffers instead).
        Writers briefly queue behind a degraded-mode scan — correctness
        over throughput while the accelerator is down."""
        self._backend_mgr().note_fallback("search")
        return self._host_exact_topk(q, k, min_similarity)

    def _host_exact_topk(
        self, q: np.ndarray, k: int, min_similarity: float
    ) -> list[list[tuple[str, float]]]:
        """Exact f32 top-k over the host arrays — the scoring core of
        ``_search_host``, reusable without the degraded-fallback accounting
        (the int8-resident corpus serves its ``exact=True`` contract here:
        quantized device membership can't be exact, the host mirror is)."""
        norms = np.linalg.norm(q, axis=1, keepdims=True)
        qn = q / np.maximum(norms, 1e-12)
        with self._sync_lock:
            if self._compact_pending:
                self._compact()
            vals, idx = host_topk(
                qn, self._host, self._valid, min(k, self.capacity)
            )
            ids = self._ids
        return self._format_results(
            vals, idx, q.shape[0], k, min_similarity, ids=ids,
        )

    # -- device sync engine ------------------------------------------------
    # Subclasses provide the actual device buffers through three hooks:
    # _device_ready (is there a patchable resident buffer), _upload_full
    # (whole-corpus transfer) and _apply_patch (jitted dynamic_update_slice
    # of one contiguous row run). The driver below owns the policy: deferred
    # compaction, patch-vs-full choice, run coalescing, stats.
    def _device_ready(self) -> bool:
        dev = getattr(self, "_dev", None)
        return dev is not None and int(dev.shape[0]) == self.capacity

    def _upload_full(self) -> None:
        raise NotImplementedError

    def _apply_patch(
        self, start_row: int, rows: np.ndarray, valid_rows: np.ndarray,
        donate: bool,
    ) -> None:
        raise NotImplementedError

    def _sync(self, _record_stall: bool = True) -> None:
        """Bring the resident device buffer up to date with the host.

        Incremental path: dirty BLOCK_ROWS-row blocks coalesce into
        contiguous runs patched into the resident buffer via jitted
        dynamic_update_slice — O(dirty rows) transferred, not O(capacity).
        Full upload only on first sync, grow/compact/clear, or when most of
        the corpus is dirty. In-flight searches always see either the
        pre-patch or post-patch buffer, never a half-patched one: a patch
        builds a new (immutable) array while borrowers hold the old one, and
        the old buffer is donated back to the allocator only when nobody
        borrows it (ref: shouldAutoSync gpu.go:1473 — which re-uploaded the
        whole corpus on any write)."""
        if not self._device_ok_nowait():
            # backend degraded: keep accumulating dirty state on the host;
            # the manager's recovery notification re-uploads when the
            # device comes back. NEVER wait here — this runs under
            # _sync_lock, the exact shape of the round-5 deadlock.
            return
        with self._sync_lock:
            if self._compact_pending:
                self._compact()  # coalesced: one rewrite for the whole burst
            needs_full = self._full_dirty or not self._device_ready()
            if not needs_full and not self._dirty_blocks:
                return
            t0 = time.perf_counter()
            s = self.sync_stats
            cap_blocks = max(1, self.capacity // BLOCK_ROWS)
            if (
                not needs_full
                and len(self._dirty_blocks)
                > cap_blocks * FULL_SYNC_DIRTY_FRACTION
            ):
                needs_full = True
            if needs_full:
                with _tracer.span("device.sync", {"mode": "full"}):
                    self._upload_full()
                s.full_uploads += 1
                nbytes = int(self._host.nbytes + self._valid.nbytes)
                s.bytes_uploaded += nbytes
                _SYNC_FULL_CELL.observe(time.perf_counter() - t0)
                _SYNC_FULL_BYTES_CELL.observe(nbytes)
            else:
                donate = self._readers == 0 and self._donation_ok
                patch_bytes = 0
                with _tracer.span("device.sync", {"mode": "patch"}) as sp:
                    for start_b, n_b in _coalesce_runs(
                        sorted(self._dirty_blocks), cap_blocks
                    ):
                        r0 = start_b * BLOCK_ROWS
                        r1 = min((start_b + n_b) * BLOCK_ROWS, self.capacity)
                        rows, vrows = self._host[r0:r1], self._valid[r0:r1]
                        self._apply_patch(r0, rows, vrows, donate)
                        nbytes = int(rows.nbytes + vrows.nbytes)
                        patch_bytes += nbytes
                        s.patch_bytes += nbytes
                        s.bytes_uploaded += nbytes
                        s.rows_patched += r1 - r0
                    sp.set_attr("bytes", patch_bytes)
                s.patches += 1
                _SYNC_PATCH_CELL.observe(time.perf_counter() - t0)
                _SYNC_PATCH_BYTES_CELL.observe(patch_bytes)
            self._full_dirty = False
            self._dirty_blocks.clear()
            if _record_stall:
                s.query_stall_s += time.perf_counter() - t0

    @contextlib.contextmanager
    def _borrow_device(self):
        """Sync, then pin the serving buffer for the duration of a search.
        While any borrower is active the patcher will not donate the buffer
        out from under it — this is what lets the write-behind uploader
        double-buffer: readers keep the old snapshot, the patch lands in a
        new one.

        Yields (dev, valid, i8, ids, slot_of). ids/slot_of are the host
        mappings captured under the lock: compaction/clear REBIND them (new
        list/dict), so a borrower resolving slots of the borrowed buffer
        through these references can never see a background compaction's
        remapped slot space mid-search. In-place mutations (remove's
        tombstone, add's append) remain visible, which only ever hides
        just-removed ids — never misattributes."""
        with self._sync_lock:
            self._sync()
            self._readers += 1
            dev, valid = self._dev, self._dev_valid
            i8 = getattr(self, "_dev_i8", None)
            ids, slot_of = self._ids, self._slot_of
        if dev is None:
            # the backend degraded between the caller's gate and the sync
            # (or was never acquired): there is no resident buffer to
            # borrow — callers catch this and serve the host path
            with self._sync_lock:
                self._readers -= 1
            raise DeviceUnavailable("no resident device buffer (degraded)")
        try:
            yield dev, valid, i8, ids, slot_of
        finally:
            with self._sync_lock:
                self._readers -= 1

    # -- write-behind uploader ---------------------------------------------
    def start_uploader(self, interval: float = 0.002) -> None:
        """Start the write-behind H2D sync thread: it coalesces dirty blocks
        and stages them between queries, so a query arriving after a write
        burst waits only for whatever the uploader has not staged yet (a
        bounded patch), never a full transfer. `interval` is the coalescing
        window after the first write of a burst."""
        with self._sync_lock:
            if self._uploader is not None:
                return
            self._uploader_interval = interval
            self._uploader_stop = threading.Event()
            self._uploader_wake = threading.Event()
            self._uploader = threading.Thread(
                target=self._uploader_loop, name="nornicdb-uploader",
                daemon=True,
            )
            self._uploader.start()

    def stop_uploader(self) -> None:
        with self._sync_lock:
            t, self._uploader = self._uploader, None
            # capture THIS thread's events under the lock: a concurrent
            # start_uploader() swaps in fresh ones, and signalling those
            # would kill the new thread while the old one runs forever
            stop, wake = self._uploader_stop, self._uploader_wake
        if t is None:
            return
        stop.set()
        wake.set()
        t.join(timeout=5.0)

    def _wake_uploader(self) -> None:
        if self._uploader is not None:
            self._uploader_wake.set()

    def _uploader_loop(self) -> None:
        stop, wake = self._uploader_stop, self._uploader_wake
        while not stop.is_set():
            if not wake.wait(timeout=0.25):
                continue
            wake.clear()
            # coalescing window: let the write burst accumulate so one patch
            # covers it, instead of one dispatch per row
            if stop.wait(self._uploader_interval):
                break
            try:
                self._sync(_record_stall=False)
                self.sync_stats.uploader_runs += 1
            except Exception:
                logger.exception("write-behind device sync failed")

    def _format_results(
        self,
        vals: np.ndarray,
        idx: np.ndarray,
        n_queries: int,
        k: int,
        min_similarity: float,
        ids: Optional[list[Optional[str]]] = None,
    ) -> list[list[tuple[str, float]]]:
        """Resolve slot indices to ids. `ids` must be the slot map captured
        with the buffer the indices came from (_borrow_device) — resolving
        against live self._ids would misattribute results if a background
        compaction remapped the slot space mid-search. Delegates to the
        shared epilogue (ops.host_search.format_topk_results) so the
        cross-process read plane resolves identically by construction."""
        ids = self._ids if ids is None else ids
        return format_topk_results(
            vals, idx, n_queries, k, min_similarity, ids
        )


class DeviceCorpus(HostCorpus):
    """Single-device resident, padded, normalized embedding matrix with
    incremental dirty-block host sync: writes patch only the 128-row blocks
    they touched into the resident buffer (ref: gpu.EmbeddingIndex
    pkg/gpu/gpu.go:1224 — flat buffer, shouldAutoSync :1473 which re-uploads
    everything, Search :1519, ScoreSubset :1554).

    Optional IVF-style cluster pruning (ref: ClusterIndex kmeans.go:144,
    SearchWithClusters :816, search-side candidate gen
    kmeans_candidate_gen.go): after cluster() the search scores only the
    rows assigned to the n_probe nearest centroids, cutting FLOPs ~K/n_probe
    at a small recall cost. Stale assignments degrade recall, never
    correctness (scores stay exact); recluster on the embed queue's
    debounced trigger.
    """

    def __init__(
        self,
        dims: int,
        capacity: int = LANE,
        dtype=jnp.float32,
        compact_ratio: float = 0.3,
        quantize: bool = False,
        backend=None,
    ):
        super().__init__(dims, align=LANE, capacity=capacity,
                         compact_ratio=compact_ratio, backend=backend)
        self.dtype = dtype
        # int8 serving mirror (ref: the CUDA path's fp16 storage trade-off,
        # gpu-acceleration.md — here int8 runs the MXU at 2x the bf16 rate)
        self.quantize = quantize
        self._dev: Optional[jax.Array] = None
        self._dev_valid: Optional[jax.Array] = None
        self._dev_i8: Optional[tuple[jax.Array, jax.Array]] = None
        # IVF state: (K, D) centroids + per-slot assignment (-1 = unassigned)
        self._centroids: Optional[jax.Array] = None
        self._assignments: Optional[np.ndarray] = None
        # fused cluster-contiguous layout (ops/ivf.py); valid only while
        # its epoch matches the corpus mutation epoch
        self._ivf = None
        # cluster fit delivered while DEGRADED_CPU: the device install is
        # deferred, not dropped — applied by _on_backend_ready on recovery
        self._pending_clusters: Optional[tuple] = None
        # host copy (centroids ndarray, id->cluster map) of the last
        # installed fit: full-mode recovery re-installs from this after
        # dropping the device-resident cluster buffers
        self._last_fit_host: Optional[tuple] = None
        # fleet telemetry: HBM residency provider (weakref'd; summed per
        # component at /metrics render — telemetry/deviceprof.py)
        from nornicdb_tpu.telemetry import deviceprof as _deviceprof

        _deviceprof.register_hbm(self, DeviceCorpus._hbm_bytes)

    @staticmethod
    def _hbm_bytes(self) -> dict:
        """Lock-free device-resident byte accounting (scrape thread)."""
        out = {"corpus_f32": 0, "corpus_int8": 0, "ivf": 0}
        dev, valid, i8, ivf = (self._dev, self._dev_valid, self._dev_i8,
                               self._ivf)
        for arr in (dev, valid):
            if arr is not None:
                out["corpus_f32"] += int(arr.size) * arr.dtype.itemsize
        if i8 is not None:
            for arr in i8:
                out["corpus_int8"] += int(arr.size) * arr.dtype.itemsize
        if ivf is not None:
            for name in ("blocks", "counts", "slotmap", "centroids",
                         "residual", "residual_slots", "residual_valid",
                         "block_scales", "residual_scales"):
                arr = getattr(ivf, name, None)
                # host-side layout fields (np slotmaps) are not HBM
                if arr is not None and not isinstance(arr, np.ndarray):
                    out["ivf"] += int(arr.size) * arr.dtype.itemsize
        return out

    # -- cluster pruning ----------------------------------------------------
    def cluster(self, k: int = 0, iters: int = 10, seed: int = 0,
                sample: int = 0) -> int:
        """Fit k-means over live rows (ref: ClusterIndex.Cluster kmeans.go:232).
        Returns the cluster count; 0 when nothing was installed (too few
        rows, or the corpus mutated underneath the fit).  ``sample`` caps
        the Lloyd fit (ops.kmeans.kmeans_fit) for very large corpora.

        The fit itself runs outside the lock (it can take seconds at
        scale); install is optimistic: snapshot the rows + layout epoch
        under the lock, and install only if the epoch is unchanged — a
        background compaction (write-behind uploader) or an overwrite of a
        snapshot row during the fit would otherwise stamp a layout built
        from stale slots as current."""
        from nornicdb_tpu.ops.kmeans import kmeans_fit

        if not self._device_gate():
            return 0  # degraded: pruning is a device-path optimization
        with self._sync_lock:
            live = [i for i, id_ in enumerate(self._ids) if id_ is not None]
            if len(live) < 2:
                return 0
            data = self._host[live]  # fancy indexing copies: stable snapshot
            epoch_at_read = self._layout_epoch
            # widen the overwrite guard to the snapshot rows so an in-place
            # update during the fit bumps the epoch and voids the install
            mask = np.zeros(self.capacity, bool)
            mask[live] = True
            if (
                self._layout_slots is not None
                and self._layout_slots.size == self.capacity
            ):
                mask |= self._layout_slots
            self._layout_slots = mask
        res = kmeans_fit(data, k=k, iters=iters, seed=seed, sample=sample)
        # H2D transfer OUTSIDE the lock (NL-DEV01): only the pointer
        # install runs in the critical section
        centroids_dev = jnp.asarray(res.centroids, dtype=self.dtype)
        with self._sync_lock:
            if self._layout_epoch != epoch_at_read:
                return 0  # slot space moved mid-fit: caller may recluster
            assignments = np.full(self.capacity, -1, np.int32)
            for row, slot in enumerate(live):
                assignments[slot] = res.assignments[row]
            self._centroids = centroids_dev
            self._assignments = assignments
            # id-based host copy: full-mode recovery re-installs from this
            self._last_fit_host = (
                np.asarray(res.centroids, np.float32),
                {
                    self._ids[slot]: int(res.assignments[row])
                    for row, slot in enumerate(live)
                    if slot < len(self._ids) and self._ids[slot] is not None
                },
            )
        self._build_ivf_layout(np.asarray(live), res.assignments,
                               res.centroids, expect_epoch=epoch_at_read)
        return res.k

    def _build_ivf_layout(self, live_slots: np.ndarray,
                          live_assignments: np.ndarray,
                          centroids: np.ndarray,
                          expect_epoch: Optional[int] = None) -> None:
        """Cluster-contiguous block layout for the fused one-program IVF
        path (ops/ivf.py). Invalidated by any corpus mutation.

        The build (and its H2D transfers) runs OUTSIDE the lock
        (NL-DEV01); install is optimistic: the row snapshot pins the
        layout epoch, and the built layout installs only if the epoch is
        unchanged — an overwrite/compaction during the build voids it
        (the widened ``_layout_slots`` mask makes covered-row overwrites
        bump the epoch, same contract as ``cluster()``)."""
        from nornicdb_tpu.ops.ivf import build_ivf_layout

        with self._sync_lock:
            if expect_epoch is not None and self._layout_epoch != expect_epoch:
                return  # slot space moved since the caller resolved slots
            epoch_at_read = self._layout_epoch
            rows = self._host[live_slots]  # fancy indexing copies: snapshot
            # slots the layout copies rows from: an in-place overwrite of
            # any of these bumps _layout_epoch (invalidates the layout);
            # writes to OTHER slots leave it serving correct vectors
            mask = np.zeros(self.capacity, bool)
            mask[live_slots] = True
            self._layout_slots = mask
        layout = build_ivf_layout(
            rows, live_slots, live_assignments, centroids,
            dtype=self.dtype, epoch=epoch_at_read,
        )
        with self._sync_lock:
            if self._layout_epoch != epoch_at_read:
                return  # mutated mid-build: discard the stale layout
            self._ivf = layout

    def clear_clusters(self) -> None:
        self._centroids = None
        self._assignments = None
        self._ivf = None
        self._layout_slots = None
        self._pending_clusters = None

    def _on_backend_ready(self) -> None:
        """Post-recovery: wake the uploader (base) and install any cluster
        fit that arrived while degraded.  The install's device transfers
        run on a throwaway thread, NEVER on the manager's probe thread —
        if the flaky device hangs again mid-install, the watchdog that
        detects hangs must not be the thread that hung (the install
        thread strands harmlessly: set_clusters holds no lock across its
        device ops)."""
        super()._on_backend_ready()
        with self._sync_lock:
            pending, self._pending_clusters = self._pending_clusters, None
            if pending is None and self._ivf is None:
                # a degraded-era grow/compact ran clear_clusters(), which
                # drops the stash along with the layout — but the id-based
                # host copy survives slot remaps and still describes the
                # newest fit. Reinstall it instead of serving full scans
                # until the next periodic recluster (the set_clusters
                # contract: a degraded-era fit is NOT discarded).
                pending = self._last_fit_host
        if pending is None:
            return

        def _install() -> None:
            try:
                self.set_clusters(pending[0], pending[1])
            except Exception:
                logger.exception("post-recovery cluster install failed")

        threading.Thread(
            target=_install, name="nornicdb-cluster-reinstall", daemon=True,
        ).start()

    def set_clusters(
        self, centroids: np.ndarray, assignments_by_id: dict[str, int]
    ) -> None:
        """Install externally computed clusters (e.g. the search service's
        fit) without re-running k-means. The id->slot resolution sees one
        consistent slot space under the sync lock; the H2D transfer and
        layout build run OUTSIDE it (NL-DEV01) with an optimistic
        epoch-checked install (the write-behind uploader may compact
        concurrently — a remap voids the stale layout)."""
        if not self._device_ok_nowait():
            # degraded: the fit is NOT discarded — stash it host-side and
            # install on recovery (_on_backend_ready), so pruned search
            # comes back with the device instead of waiting for the next
            # periodic re-cluster. Full scan keeps serving meanwhile.
            with self._sync_lock:
                self._pending_clusters = (
                    np.asarray(centroids, np.float32),
                    dict(assignments_by_id),
                )
                self._last_fit_host = self._pending_clusters
            return
        fit_host = (np.asarray(centroids, np.float32), dict(assignments_by_id))
        centroids_dev = jnp.asarray(centroids, dtype=self.dtype)
        with self._sync_lock:
            self._last_fit_host = fit_host
            slot_assignments = np.full(self.capacity, -1, np.int32)
            for id_, c in assignments_by_id.items():
                slot = self._slot_of.get(id_)
                if slot is not None:
                    slot_assignments[slot] = c
            self._centroids = centroids_dev
            self._assignments = slot_assignments
            # the old layout describes the replaced clustering — drop it
            # even when no live rows match (else the epoch guard keeps
            # serving it); a stashed degraded-era fit is superseded too
            self._ivf = None
            self._layout_slots = None
            self._pending_clusters = None
            live = np.nonzero((slot_assignments >= 0) & self._valid)[0]
            epoch_at_read = self._layout_epoch
        if live.size:
            self._build_ivf_layout(live, slot_assignments[live],
                                   np.asarray(centroids, np.float32),
                                   expect_epoch=epoch_at_read)

    def _grow(self, min_capacity: int = 0) -> None:
        super()._grow(min_capacity)
        # slot space changed shape: stale cluster state would crash/corrupt
        # pruned search — drop it until the next recluster
        self.clear_clusters()

    def _compact(self) -> None:
        super()._compact()
        # compaction remaps slots: old assignments index the wrong rows
        self.clear_clusters()

    def _pruned_search(
        self, q: np.ndarray, k: int, min_similarity: float, n_probe: int,
        exact: bool,
    ) -> Optional[list[list[tuple[str, float]]]]:
        """Score only rows in the n_probe nearest clusters; None when no
        cluster index is fitted (caller falls back to the full scan).

        Buffer, id map, cluster state and the layout-epoch check are all
        captured under ONE lock hold (and the sync — including any pending
        compaction — runs first), so a background compaction racing this
        search can only ever rebind state we no longer read: everything
        below resolves against the captured snapshot."""
        with self._sync_lock:
            self._sync()
            self._readers += 1
            corpus = self._dev
            ids, valid_host = self._ids, self._valid
            centroids, assignments = self._centroids, self._assignments
            layout = self._ivf
            layout_ok = (
                layout is not None and layout.epoch == self._layout_epoch
            )
        try:
            if corpus is None or centroids is None or assignments is None:
                return None
            # fused one-program path: valid while the layout matches the
            # LAYOUT epoch, which bumps only when a covered row was
            # overwritten in place or the slot space remapped
            # (grow/compact/clear). Plain adds and removes keep the layout
            # serving: new rows are merely invisible to pruned search until
            # the next recluster (recall, not correctness) and removed rows
            # filter out through the captured id map below.
            if layout_ok:
                from nornicdb_tpu.ops.ivf import ivf_search

                vals, slots = ivf_search(layout, q, k, n_probe)
                out: list[list[tuple[str, float]]] = []
                for qi in range(vals.shape[0]):
                    row: list[tuple[str, float]] = []
                    for s, slot in zip(vals[qi], slots[qi]):
                        if (
                            slot < 0 or not np.isfinite(s)
                            or s < min_similarity
                        ):
                            continue
                        id_ = ids[slot] if slot < len(ids) else None
                        if id_ is not None:
                            row.append((id_, float(s)))
                    out.append(row[:k])
                return out
            n_probe = min(n_probe, int(centroids.shape[0]))
            return self._pruned_scan(
                q, k, min_similarity, n_probe, corpus, ids, valid_host,
                centroids, assignments,
            )
        finally:
            with self._sync_lock:
                self._readers -= 1

    def _pruned_scan(
        self, q: np.ndarray, k: int, min_similarity: float, n_probe: int,
        corpus: jax.Array, ids: list[Optional[str]], valid_host: np.ndarray,
        centroids: jax.Array, assignments: np.ndarray,
    ) -> list[list[tuple[str, float]]]:
        """Assignment-mask fallback pruning over the synced device corpus.
        All host state comes in as the snapshot captured with the buffer."""
        from nornicdb_tpu.ops.kmeans import nearest_clusters

        out: list[list[tuple[str, float]]] = []
        for qi in range(q.shape[0]):
            probes = np.asarray(
                nearest_clusters(
                    jnp.asarray(q[qi], dtype=self.dtype), centroids, n_probe
                )
            )
            mask = np.isin(assignments, probes) & valid_host
            slots = np.nonzero(mask)[0]
            if slots.size == 0:
                out.append([])
                continue
            # pad the candidate set to a power-of-two bucket so the jitted
            # score program caches a handful of shapes instead of recompiling
            # per query (dynamic shapes were 6x slower than the full scan)
            bucket = max(1024, 1 << (int(slots.size) - 1).bit_length())
            padded = np.zeros(bucket, np.int64)
            padded[: slots.size] = slots
            qd = l2_normalize(jnp.asarray(q[qi], dtype=self.dtype).reshape(-1))
            scores = np.asarray(
                score_subset(qd, corpus, jnp.asarray(padded)), np.float32
            )[: slots.size]
            order = np.argsort(-scores)[:k]
            row = []
            for j in order:
                s = float(scores[j])
                if s < min_similarity:
                    continue
                id_ = ids[slots[j]]
                if id_ is not None:
                    row.append((id_, s))
            out.append(row)
        return out

    def _upload_full(self) -> None:
        """Whole-corpus H2D transfer (first sync / grow / compact / clear).

        NL-DEV01 suppressions: these transfers run under _sync_lock by
        design — they must see the host arrays and dirty bookkeeping as
        one atomic view. They are WARM, never cold: _sync gates on
        _device_ok_nowait() first, so the backend was acquired by the
        manager's worker thread before any of these can execute."""
        self._dev = jnp.asarray(  # nornlint: disable=NL-DEV01
            self._host, dtype=self.dtype)
        self._dev_valid = jnp.asarray(self._valid)  # nornlint: disable=NL-DEV01
        if self.quantize:
            from nornicdb_tpu.ops.pallas_kernels import quantize_rows

            self._dev_i8 = quantize_rows(self._dev)

    def _apply_patch(
        self, start_row: int, rows: np.ndarray, valid_rows: np.ndarray,
        donate: bool,
    ) -> None:
        """Patch one contiguous dirty run into the resident buffers; the
        int8 serving mirror requantizes only the patched rows.

        NL-DEV01 suppressions: warm transfers under _sync_lock by design
        (same rationale as _upload_full — gated upstream, atomic view)."""
        start = np.int32(start_row)
        # one H2D conversion feeds both the f32/bf16 patch and the int8
        # requantization — the rows transfer once, not per consumer
        rows_dev = jnp.asarray(  # nornlint: disable=NL-DEV01
            rows, dtype=self.dtype)
        try:
            patch = _patch_rows_donated if donate else _patch_rows
            self._dev = patch(self._dev, rows_dev, start)
            vpatch = _patch_valid_donated if donate else _patch_valid
            self._dev_valid = vpatch(
                self._dev_valid,
                jnp.asarray(valid_rows),  # nornlint: disable=NL-DEV01
                start,
            )
            if self.quantize and self._dev_i8 is not None:
                qpatch = _patch_i8_donated if donate else _patch_i8
                self._dev_i8 = qpatch(
                    self._dev_i8[0], self._dev_i8[1], rows_dev, start,
                )
        except Exception:
            # a failing donated patch has CONSUMED an unknown subset of
            # the resident buffers — drop them all so _device_ready()
            # reports false and the next _sync rebuilds via _upload_full
            # instead of patching a poisoned buffer (NL-JAX04)
            self._dev = None
            self._dev_valid = None
            self._dev_i8 = None
            raise

    def device_arrays(self) -> tuple[jax.Array, jax.Array]:
        """Legacy unguarded access to the resident buffers. Callers may hold
        the returned arrays indefinitely, so donation is permanently
        disabled for this corpus the moment anyone uses this — otherwise a
        later patch would free a buffer the caller still reads. Prefer
        _borrow_device, which scopes the pin to the search."""
        self._device_gate()  # cold acquisition happens HERE, not under lock
        with self._sync_lock:
            self._donation_ok = False
            self._sync()
            if self._dev is None:
                raise DeviceUnavailable(
                    "backend degraded: no resident device buffer"
                )
            return self._dev, self._dev_valid

    def search(
        self,
        queries: np.ndarray,
        k: int,
        min_similarity: float = -1.0,
        exact: bool = False,
        n_probe: int = 0,
        streaming: Optional[bool] = None,
    ) -> list[list[tuple[str, float]]]:
        """Brute-force cosine top-k. Returned scores are exact; with the
        default exact=False, candidate membership uses the TPU-native
        approx_max_k or (on TPU at scale, the default serving path) the
        streaming Pallas kernel — both honoring the ~0.95 recall contract of
        the reference's HNSW ANN path; exact=True gives recall 1.0 at the
        cost of a full sort. With n_probe > 0 and a fitted cluster index,
        only the n_probe nearest clusters are scored (IVF pruning,
        ref: SearchWithClusters kmeans.go:816). Returns per-query
        [(id, score)] filtered by min_similarity (ref: Search gpu.go:1519,
        MinSimilarity semantics search.go:157-205)."""
        if len(self._slot_of) == 0:
            return [[] for _ in range(np.atleast_2d(queries).shape[0])]
        q = np.atleast_2d(np.asarray(queries, np.float32))
        # lifecycle gate FIRST, before any lock: a cold backend acquires on
        # the manager's worker thread (bounded by the config timeout), a
        # degraded one routes this search to the exact host path
        if not self._device_gate():
            return self._search_host(q, k, min_similarity)
        from nornicdb_tpu.telemetry import deviceprof as _deviceprof

        try:
            if n_probe > 0:
                t0 = time.perf_counter()
                pruned = self._pruned_search(
                    q, k, min_similarity, n_probe, exact
                )
                if pruned is not None:
                    self.sync_stats.device_dispatches += 1
                    # unified program ledger (fleet telemetry plane):
                    # shape class = pow2 batch, bounded like the jit
                    # shape classes themselves
                    _deviceprof.record_execute(
                        "search", "ivf",
                        _deviceprof.pow2_class(q.shape[0], "b"),
                        time.perf_counter() - t0,
                    )
                    return pruned
            t0 = time.perf_counter()
            with self._borrow_device() as (corpus, valid, dev_i8, ids, _):
                kk = min(k, self.capacity)
                vals, idx = topk_backend(
                    l2_normalize(jnp.asarray(q, dtype=self.dtype)), corpus,
                    valid, kk, exact=exact, streaming=streaming,
                    quantized=dev_i8 if self.quantize else None,
                )
                # materialize INSIDE the borrow: the computation must
                # finish before the patcher may donate the buffer it reads
                vals_np = np.asarray(vals, np.float32)
                idx_np = np.asarray(idx)
            self.sync_stats.device_dispatches += 1
            _deviceprof.record_execute(
                "search", "dense", _deviceprof.pow2_class(q.shape[0], "b"),
                time.perf_counter() - t0,
            )
        except DeviceUnavailable:
            # degraded between the gate and the borrow
            return self._search_host(q, k, min_similarity)
        return self._format_results(
            vals_np, idx_np, q.shape[0], k, min_similarity, ids=ids,
        )

    def score_subset(
        self, query: np.ndarray, ids: list[str]
    ) -> list[tuple[str, float]]:
        """Exact re-score of the given ids; unknown/removed ids are omitted
        from the returned (id, score) pairs so results stay attributable."""
        if not self._device_gate():
            return self._score_subset_host(query, ids)
        try:
            with self._borrow_device() as (corpus, _, _i8, _ids, slot_of):
                # slot_of is the snapshot consistent with the borrowed
                # buffer — a racing background compaction rebinds, never
                # mutates, it
                present = [(i, slot_of[i]) for i in ids if i in slot_of]
                if not present:
                    return []
                q = l2_normalize(
                    jnp.asarray(query, dtype=self.dtype).reshape(-1)
                )
                slots = jnp.asarray([s for _, s in present])
                scores = np.asarray(score_subset(q, corpus, slots), np.float32)
        except DeviceUnavailable:
            return self._score_subset_host(query, ids)
        return [(id_, float(s)) for (id_, _), s in zip(present, scores)]

    def _score_subset_host(
        self, query: np.ndarray, ids: list[str]
    ) -> list[tuple[str, float]]:
        """DEGRADED_CPU twin of score_subset over the host arrays."""
        self._backend_mgr().note_fallback("search")
        with self._sync_lock:
            present = [(i, self._slot_of[i]) for i in ids if i in self._slot_of]
            if not present:
                return []
            scores = host_score_rows(
                query, self._host, np.asarray([s for _, s in present])
            )
        return [(id_, float(s)) for (id_, _), s in zip(present, scores)]
