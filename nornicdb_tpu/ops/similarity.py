"""Batch vector similarity + top-k on TPU via XLA.

Replaces the reference's CUDA/Metal kernels
(/root/reference/pkg/gpu/cuda/cuda_kernels.cu: kernel_compute_norms :185,
kernel_normalize_vectors :206, kernel_cosine_similarity :284,
kernel_topk_simple :384; pkg/simd/simd.go:38-240).

TPU-first design notes:
  - Cosine scoring IS a matmul: normalize once, then Q @ C^T rides the MXU.
    We keep corpora normalized at insert time so the query path is one GEMM.
  - Scores + top-k are computed under one jit so XLA fuses the epilogue and
    never round-trips the (Q, N) score matrix through HBM when chunked.
  - Static shapes: corpora are padded to lane multiples (128) and masked with
    -inf; jit caches per padded shape bucket, not per exact N.
  - bf16 matmul with f32 accumulation (preferred_element_type) matches MXU
    native precision.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

LANE = 128  # TPU lane width; min tile second dim


def pad_to_multiple(n: int, m: int = LANE) -> int:
    return ((n + m - 1) // m) * m


@jax.jit
def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-wise L2 normalization (ref: kernel_normalize_vectors cuda_kernels.cu:206)."""
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True))
    return (x / jnp.maximum(norm, eps)).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def dot_scores(
    queries: jax.Array, corpus: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """(Q, D) x (N, D) -> (Q, N) dot-product scores on the MXU."""
    if use_bf16:
        queries = queries.astype(jnp.bfloat16)
        corpus = corpus.astype(jnp.bfloat16)
    return jax.lax.dot_general(
        queries,
        corpus,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def cosine_scores(
    queries: jax.Array, corpus: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """Full cosine similarity: normalizes both sides then one GEMM
    (ref: kernel_cosine_similarity cuda_kernels.cu:284)."""
    return dot_scores(l2_normalize(queries), l2_normalize(corpus), use_bf16)


@functools.partial(
    jax.jit, static_argnames=("k", "normalized", "use_bf16", "exact", "recall_target")
)
def cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    normalized: bool = True,
    use_bf16: bool = True,
    exact: bool = False,
    recall_target: float = 0.95,
) -> tuple[jax.Array, jax.Array]:
    """Fused cosine scoring + top-k.

    queries: (Q, D); corpus: (Np, D) padded to a lane multiple;
    valid:   (Np,) bool mask — False rows (padding / tombstones) score -inf.
    Returns (values (Q, k), indices (Q, k)).

    By default top-k uses lax.approx_max_k, the TPU-native partial-reduction
    top-k (fuses into the GEMM epilogue; measured ~4x faster end-to-end at
    N=1M than exact lax.top_k, which adds a full-sort pass). Scores of the
    returned candidates are exact; only set membership is approximate
    (recall_target, default 0.95 — same contract as the reference's HNSW
    path, pkg/search/hnsw_index.go). exact=True restores full sort.
    """
    q = queries if normalized else l2_normalize(queries)
    c = corpus if normalized else l2_normalize(corpus)
    scores = dot_scores(q, c, use_bf16)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    if exact:
        return jax.lax.top_k(scores, k)
    return jax.lax.approx_max_k(scores, k, recall_target=recall_target)


# streaming Pallas top-k engages above this corpus size; below it the (Q, N)
# score matrix is small enough that the XLA GEMM+approx_max_k path wins on
# dispatch overhead
STREAMING_MIN_ROWS = 65_536

# bin-reduction strategy for the streaming kernels ("sort" | "approx" |
# "pallas", see pallas_kernels._topk_bins). Overridable per-deployment while
# autotune data accumulates (benchmarks/kernel_autotune.py). Validated here
# so a config typo fails at import, not inside the first jitted query.
TOPK_EPILOGUE = os.environ.get("NORNICDB_TOPK_EPILOGUE", "sort")
if TOPK_EPILOGUE not in ("sort", "approx", "pallas"):
    raise ValueError(
        f"NORNICDB_TOPK_EPILOGUE={TOPK_EPILOGUE!r}: "
        "must be one of sort|approx|pallas"
    )


def topk_backend(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    exact: bool = False,
    use_bf16: bool = True,
    streaming: Optional[bool] = None,
    quantized: Optional[tuple[jax.Array, jax.Array]] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k dispatch for normalized inputs: the streaming Pallas kernel
    (ops.pallas_kernels.streaming_cosine_topk — one corpus read, no (Q, N)
    materialization) on TPU for large corpora, else the XLA
    GEMM+approx_max_k path. `streaming=None` auto-selects; tests force it on
    small corpora (interpret mode runs the same kernel off-TPU). The kernel
    scores in bf16, so an explicit use_bf16=False keeps the XLA f32 path.
    `quantized=(c_i8, c_scale)` (quantize_rows of the same corpus) engages
    the int8 MXU kernel — 2x the bf16 MXU rate, half the corpus HBM read."""
    from nornicdb_tpu.ops.pallas_kernels import (
        _on_tpu,
        pick_tile_n,
        quantize_rows,
        streaming_cosine_topk,
        streaming_cosine_topk_int8,
        streaming_rows_for,
    )

    n = int(corpus.shape[0])
    on_tpu = _on_tpu()
    if streaming is None:
        streaming = (
            (not exact) and use_bf16 and on_tpu and n >= STREAMING_MIN_ROWS
        )
    if streaming and not exact:
        tile = pick_tile_n(n)
        rows = min(streaming_rows_for(k, tile), max(n // tile, 1))
        # tile must divide n (corpus capacities are 128-multiples, but a
        # sharded slice need not be) and the bins must hold a full top-k;
        # otherwise fall through to the XLA path instead of crashing
        if n % tile == 0 and rows * tile >= k:
            if quantized is not None:
                q_i8, q_scale = quantize_rows(queries)
                return streaming_cosine_topk_int8(
                    q_i8, q_scale, quantized[0], quantized[1], valid,
                    min(k, n), tile_n=tile, rows=rows,
                    interpret=not on_tpu, epilogue=TOPK_EPILOGUE,
                )
            return streaming_cosine_topk(
                queries, corpus, valid, min(k, n),
                tile_n=tile, rows=rows, interpret=not on_tpu,
                epilogue=TOPK_EPILOGUE,
            )
    return cosine_topk(
        queries, corpus, valid, k, normalized=True, use_bf16=use_bf16,
        exact=exact,
    )


@functools.partial(jax.jit, static_argnames=("use_bf16",))
def score_subset(
    query: jax.Array, corpus: jax.Array, indices: jax.Array, use_bf16: bool = True
) -> jax.Array:
    """Exact re-score of candidate rows (ref: EmbeddingIndex.ScoreSubset
    pkg/gpu/gpu.go:1554): gather candidates then one small GEMV."""
    cand = corpus[indices]  # (C, D)
    q = query.reshape(1, -1)
    return dot_scores(q, cand, use_bf16)[0]


@jax.jit
def euclidean_scores(queries: jax.Array, corpus: jax.Array) -> jax.Array:
    """Squared euclidean distances via the |x|^2 - 2xy + |y|^2 expansion so the
    cross term rides the MXU (ref: euclidean_distance shaders_darwin.metal:333)."""
    qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
    cn = jnp.sum(corpus.astype(jnp.float32) ** 2, axis=1)[None, :]
    cross = dot_scores(queries, corpus, use_bf16=False)
    return jnp.maximum(qn - 2.0 * cross + cn, 0.0)


def merge_topk(
    values: jax.Array, indices: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Merge per-shard/per-chunk top-k lists into a global top-k.

    values/indices: (S, Q, k) stacked partial results with GLOBAL indices.
    Returns (Q, k). Used for the ICI all-gather merge of sharded search.
    """
    s, q, kk = values.shape
    flat_v = jnp.transpose(values, (1, 0, 2)).reshape(q, s * kk)
    flat_i = jnp.transpose(indices, (1, 0, 2)).reshape(q, s * kk)
    best_v, pos = jax.lax.top_k(flat_v, k)
    best_i = jnp.take_along_axis(flat_i, pos, axis=1)
    return best_v, best_i


# ----------------------------------------------------------------- host API
class HostCorpus:
    """Host-side state machine shared by DeviceCorpus (single chip) and
    parallel.ShardedCorpus (mesh): id->slot map, padded row matrix, tombstone
    removal, ratio-triggered compaction, capacity growth.

    Mirrors gpu.EmbeddingIndex host bookkeeping (ref: pkg/gpu/gpu.go:1224,
    Add/Remove :1378-1460; the reference's HNSW uses the same
    tombstone-then-rebuild idea, search.go:1215). `align` keeps the row count
    a multiple of the hardware tile / shard granularity.
    """

    def __init__(
        self,
        dims: int,
        align: int = LANE,
        capacity: int = 0,
        compact_ratio: float = 0.3,
    ):
        self.dims = dims
        self.align = align
        self.compact_ratio = compact_ratio
        cap = max(capacity, align)
        cap = ((cap + align - 1) // align) * align
        self._ids: list[Optional[str]] = []
        self._slot_of: dict[str, int] = {}
        self._host = np.zeros((cap, dims), np.float32)
        self._valid = np.zeros(cap, bool)
        self._tombstones = 0
        self._dirty = True
        # mutation epoch: consumers holding derived layouts (IVF blocks)
        # compare epochs to detect staleness (stale layout would serve
        # stale vectors, not just degraded recall)
        self._epoch = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    @property
    def capacity(self) -> int:
        return self._host.shape[0]

    def add(self, id_: str, vector: np.ndarray) -> None:
        v = np.asarray(vector, np.float32)
        norm = float(np.linalg.norm(v))
        if norm > 1e-12:
            v = v / norm
        slot = self._slot_of.get(id_)
        if slot is None:
            slot = len(self._ids)
            if slot >= self.capacity:
                self._grow()
            self._ids.append(id_)
            self._slot_of[id_] = slot
        self._host[slot] = v
        self._valid[slot] = True
        self._dirty = True
        self._epoch += 1

    def add_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        vectors = np.asarray(vectors, np.float32)
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-12)
        for i, id_ in enumerate(ids):
            slot = self._slot_of.get(id_)
            if slot is None:
                slot = len(self._ids)
                if slot >= self.capacity:
                    self._grow(min_capacity=slot + len(ids) - i)
                self._ids.append(id_)
                self._slot_of[id_] = slot
            self._host[slot] = vectors[i]
            self._valid[slot] = True
        self._dirty = True
        self._epoch += 1

    def remove(self, id_: str) -> bool:
        slot = self._slot_of.pop(id_, None)
        if slot is None:
            return False
        self._ids[slot] = None
        self._valid[slot] = False
        self._tombstones += 1
        self._dirty = True
        self._epoch += 1
        if self._ids and self._tombstones / len(self._ids) > self.compact_ratio:
            self._compact()
        return True

    # -- inspection / lifecycle (ref: EmbeddingIndex Has/Get/Clear/Stats/
    # MemoryUsage/Serialize, pkg/gpu/gpu.go + gpu_test.go:630-800) ---------
    def has(self, id_: str) -> bool:
        return id_ in self._slot_of

    def get(self, id_: str) -> Optional[np.ndarray]:
        """The stored (normalized) vector, or None when absent."""
        slot = self._slot_of.get(id_)
        if slot is None:
            return None
        return self._host[slot].copy()

    def clear(self) -> None:
        cap = self.capacity
        self._ids = []
        self._slot_of = {}
        self._host = np.zeros((cap, self.dims), np.float32)
        self._valid = np.zeros(cap, bool)
        self._tombstones = 0
        self._dirty = True
        self._epoch += 1
        # slot space was remapped: derived cluster layouts (DeviceCorpus
        # _assignments/IVF blocks) would index the wrong rows — same reason
        # _grow/_compact invalidate them
        clear_clusters = getattr(self, "clear_clusters", None)
        if callable(clear_clusters):
            clear_clusters()

    def stats(self) -> dict:
        return {
            "count": len(self._slot_of),
            "capacity": self.capacity,
            "dims": self.dims,
            "tombstones": self._tombstones,
            "epoch": self._epoch,
            "memory_bytes": self.memory_usage(),
        }

    def memory_usage(self) -> int:
        return int(self._host.nbytes + self._valid.nbytes)

    def save(self, path: str) -> None:
        """Persist live ids + vectors (tombstones are not serialized —
        matches the reference's compact-on-serialize behavior)."""
        live = [(i, id_) for i, id_ in enumerate(self._ids)
                if id_ is not None]
        ids = np.asarray([id_ for _, id_ in live])
        vecs = (self._host[[i for i, _ in live]]
                if live else np.zeros((0, self.dims), np.float32))
        np.savez_compressed(path, ids=ids, vectors=vecs,
                            dims=np.asarray(self.dims))

    @classmethod
    def load(cls, path: str, **kwargs) -> "HostCorpus":
        with np.load(path, allow_pickle=False) as data:
            if any(k not in data for k in ("vectors", "ids", "dims")):
                raise ValueError(f"{path} is not a corpus checkpoint")
            dims = int(data["dims"])
            out = cls(dims=dims, **kwargs)
            vecs = data["vectors"]
            ids = [str(i) for i in data["ids"]]
            if ids:
                out.add_batch(ids, vecs)
        return out

    def _grow(self, min_capacity: int = 0) -> None:
        need = max(self.capacity * 2, min_capacity, self.align)
        new_cap = ((need + self.align - 1) // self.align) * self.align
        host = np.zeros((new_cap, self.dims), np.float32)
        valid = np.zeros(new_cap, bool)
        host[: self._host.shape[0]] = self._host
        valid[: self._valid.shape[0]] = self._valid
        self._host, self._valid = host, valid

    def _compact(self) -> None:
        live = [(i, id_) for i, id_ in enumerate(self._ids) if id_ is not None]
        host = np.zeros_like(self._host)
        valid = np.zeros_like(self._valid)
        ids: list[Optional[str]] = []
        slot_of: dict[str, int] = {}
        for new_slot, (old_slot, id_) in enumerate(live):
            host[new_slot] = self._host[old_slot]
            valid[new_slot] = True
            ids.append(id_)
            slot_of[id_] = new_slot
        self._host, self._valid = host, valid
        self._ids, self._slot_of = ids, slot_of
        self._tombstones = 0
        self._dirty = True
        self._epoch += 1

    def _format_results(
        self,
        vals: np.ndarray,
        idx: np.ndarray,
        n_queries: int,
        k: int,
        min_similarity: float,
    ) -> list[list[tuple[str, float]]]:
        out: list[list[tuple[str, float]]] = []
        for qi in range(n_queries):
            row: list[tuple[str, float]] = []
            for v, i in zip(vals[qi], idx[qi]):
                if not np.isfinite(v) or v < min_similarity:
                    continue
                id_ = self._ids[i] if i < len(self._ids) else None
                if id_ is not None:
                    row.append((id_, float(v)))
            out.append(row[:k])
        return out


class DeviceCorpus(HostCorpus):
    """Single-device resident, padded, normalized embedding matrix with
    dirty-tracking host sync (ref: gpu.EmbeddingIndex pkg/gpu/gpu.go:1224 —
    flat buffer, shouldAutoSync :1473, Search :1519, ScoreSubset :1554).

    Optional IVF-style cluster pruning (ref: ClusterIndex kmeans.go:144,
    SearchWithClusters :816, search-side candidate gen
    kmeans_candidate_gen.go): after cluster() the search scores only the
    rows assigned to the n_probe nearest centroids, cutting FLOPs ~K/n_probe
    at a small recall cost. Stale assignments degrade recall, never
    correctness (scores stay exact); recluster on the embed queue's
    debounced trigger.
    """

    def __init__(
        self,
        dims: int,
        capacity: int = LANE,
        dtype=jnp.float32,
        compact_ratio: float = 0.3,
        quantize: bool = False,
    ):
        super().__init__(dims, align=LANE, capacity=capacity,
                         compact_ratio=compact_ratio)
        self.dtype = dtype
        # int8 serving mirror (ref: the CUDA path's fp16 storage trade-off,
        # gpu-acceleration.md — here int8 runs the MXU at 2x the bf16 rate)
        self.quantize = quantize
        self._dev: Optional[jax.Array] = None
        self._dev_valid: Optional[jax.Array] = None
        self._dev_i8: Optional[tuple[jax.Array, jax.Array]] = None
        # IVF state: (K, D) centroids + per-slot assignment (-1 = unassigned)
        self._centroids: Optional[jax.Array] = None
        self._assignments: Optional[np.ndarray] = None
        # fused cluster-contiguous layout (ops/ivf.py); valid only while
        # its epoch matches the corpus mutation epoch
        self._ivf = None

    # -- cluster pruning ----------------------------------------------------
    def cluster(self, k: int = 0, iters: int = 10, seed: int = 0) -> int:
        """Fit k-means over live rows (ref: ClusterIndex.Cluster kmeans.go:232).
        Returns the cluster count."""
        from nornicdb_tpu.ops.kmeans import kmeans_fit

        live = [i for i, id_ in enumerate(self._ids) if id_ is not None]
        if len(live) < 2:
            return 0
        data = self._host[live]
        res = kmeans_fit(data, k=k, iters=iters, seed=seed)
        assignments = np.full(self.capacity, -1, np.int32)
        for row, slot in enumerate(live):
            assignments[slot] = res.assignments[row]
        self._centroids = jnp.asarray(res.centroids, dtype=self.dtype)
        self._assignments = assignments
        self._build_ivf_layout(np.asarray(live), res.assignments,
                               res.centroids)
        return res.k

    def _build_ivf_layout(self, live_slots: np.ndarray,
                          live_assignments: np.ndarray,
                          centroids: np.ndarray) -> None:
        """Cluster-contiguous block layout for the fused one-program IVF
        path (ops/ivf.py). Invalidated by any corpus mutation."""
        from nornicdb_tpu.ops.ivf import build_ivf_layout

        self._ivf = build_ivf_layout(
            self._host[live_slots], live_slots, live_assignments,
            centroids, dtype=self.dtype, epoch=self._epoch,
        )

    def clear_clusters(self) -> None:
        self._centroids = None
        self._assignments = None
        self._ivf = None

    def set_clusters(
        self, centroids: np.ndarray, assignments_by_id: dict[str, int]
    ) -> None:
        """Install externally computed clusters (e.g. the search service's
        fit) without re-running k-means."""
        slot_assignments = np.full(self.capacity, -1, np.int32)
        for id_, c in assignments_by_id.items():
            slot = self._slot_of.get(id_)
            if slot is not None:
                slot_assignments[slot] = c
        self._centroids = jnp.asarray(centroids, dtype=self.dtype)
        self._assignments = slot_assignments
        # the old layout describes the replaced clustering — drop it even
        # when no live rows match (else the epoch guard keeps serving it)
        self._ivf = None
        live = np.nonzero((slot_assignments >= 0) & self._valid)[0]
        if live.size:
            self._build_ivf_layout(live, slot_assignments[live],
                                   np.asarray(centroids, np.float32))

    def _grow(self, min_capacity: int = 0) -> None:
        super()._grow(min_capacity)
        # slot space changed shape: stale cluster state would crash/corrupt
        # pruned search — drop it until the next recluster
        self.clear_clusters()

    def _compact(self) -> None:
        super()._compact()
        # compaction remaps slots: old assignments index the wrong rows
        self.clear_clusters()

    def _pruned_search(
        self, q: np.ndarray, k: int, min_similarity: float, n_probe: int,
        exact: bool,
    ) -> Optional[list[list[tuple[str, float]]]]:
        """Score only rows in the n_probe nearest clusters; None when the
        candidate set is too small to be worth it."""
        from nornicdb_tpu.ops.kmeans import nearest_clusters

        if self._centroids is None or self._assignments is None:
            return None
        # fused one-program path: valid only while the layout matches the
        # corpus epoch (a stale layout would serve stale VECTORS — worse
        # than stale assignments, which only degrade recall)
        if self._ivf is not None and self._ivf.epoch == self._epoch:
            from nornicdb_tpu.ops.ivf import ivf_search

            vals, slots = ivf_search(self._ivf, q, k, n_probe)
            out: list[list[tuple[str, float]]] = []
            for qi in range(vals.shape[0]):
                row: list[tuple[str, float]] = []
                for s, slot in zip(vals[qi], slots[qi]):
                    if slot < 0 or not np.isfinite(s) or s < min_similarity:
                        continue
                    id_ = self._ids[slot] if slot < len(self._ids) else None
                    if id_ is not None:
                        row.append((id_, float(s)))
                out.append(row[:k])
            return out
        n_probe = min(n_probe, int(self._centroids.shape[0]))
        out: list[list[tuple[str, float]]] = []
        corpus, _ = self.device_arrays()
        for qi in range(q.shape[0]):
            probes = np.asarray(
                nearest_clusters(
                    jnp.asarray(q[qi], dtype=self.dtype), self._centroids, n_probe
                )
            )
            mask = np.isin(self._assignments, probes) & self._valid
            slots = np.nonzero(mask)[0]
            if slots.size == 0:
                out.append([])
                continue
            # pad the candidate set to a power-of-two bucket so the jitted
            # score program caches a handful of shapes instead of recompiling
            # per query (dynamic shapes were 6x slower than the full scan)
            bucket = max(1024, 1 << (int(slots.size) - 1).bit_length())
            padded = np.zeros(bucket, np.int64)
            padded[: slots.size] = slots
            qd = l2_normalize(jnp.asarray(q[qi], dtype=self.dtype).reshape(-1))
            scores = np.asarray(
                score_subset(qd, corpus, jnp.asarray(padded)), np.float32
            )[: slots.size]
            order = np.argsort(-scores)[:k]
            row = []
            for j in order:
                s = float(scores[j])
                if s < min_similarity:
                    continue
                id_ = self._ids[slots[j]]
                if id_ is not None:
                    row.append((id_, s))
            out.append(row)
        return out

    def _sync(self) -> None:
        """H2D upload when dirty (ref: shouldAutoSync gpu.go:1473)."""
        if self._dirty or self._dev is None:
            self._dev = jnp.asarray(self._host, dtype=self.dtype)
            self._dev_valid = jnp.asarray(self._valid)
            if self.quantize:
                from nornicdb_tpu.ops.pallas_kernels import quantize_rows

                self._dev_i8 = quantize_rows(self._dev)
            self._dirty = False

    def device_arrays(self) -> tuple[jax.Array, jax.Array]:
        self._sync()
        return self._dev, self._dev_valid

    def search(
        self,
        queries: np.ndarray,
        k: int,
        min_similarity: float = -1.0,
        exact: bool = False,
        n_probe: int = 0,
        streaming: Optional[bool] = None,
    ) -> list[list[tuple[str, float]]]:
        """Brute-force cosine top-k. Returned scores are exact; with the
        default exact=False, candidate membership uses the TPU-native
        approx_max_k or (on TPU at scale, the default serving path) the
        streaming Pallas kernel — both honoring the ~0.95 recall contract of
        the reference's HNSW ANN path; exact=True gives recall 1.0 at the
        cost of a full sort. With n_probe > 0 and a fitted cluster index,
        only the n_probe nearest clusters are scored (IVF pruning,
        ref: SearchWithClusters kmeans.go:816). Returns per-query
        [(id, score)] filtered by min_similarity (ref: Search gpu.go:1519,
        MinSimilarity semantics search.go:157-205)."""
        if len(self._slot_of) == 0:
            return [[] for _ in range(np.atleast_2d(queries).shape[0])]
        q = np.atleast_2d(np.asarray(queries, np.float32))
        if n_probe > 0:
            pruned = self._pruned_search(q, k, min_similarity, n_probe, exact)
            if pruned is not None:
                return pruned
        corpus, valid = self.device_arrays()
        kk = min(k, self.capacity)
        vals, idx = topk_backend(
            l2_normalize(jnp.asarray(q, dtype=self.dtype)), corpus, valid, kk,
            exact=exact, streaming=streaming,
            quantized=self._dev_i8 if self.quantize else None,
        )
        return self._format_results(
            np.asarray(vals, np.float32), np.asarray(idx), q.shape[0], k,
            min_similarity,
        )

    def score_subset(
        self, query: np.ndarray, ids: list[str]
    ) -> list[tuple[str, float]]:
        """Exact re-score of the given ids; unknown/removed ids are omitted
        from the returned (id, score) pairs so results stay attributable."""
        corpus, _ = self.device_arrays()
        present = [(i, self._slot_of[i]) for i in ids if i in self._slot_of]
        if not present:
            return []
        q = l2_normalize(jnp.asarray(query, dtype=self.dtype).reshape(-1))
        slots = jnp.asarray([s for _, s in present])
        scores = score_subset(q, corpus, slots)
        return [
            (id_, float(s))
            for (id_, _), s in zip(present, np.asarray(scores, np.float32))
        ]
