"""Fused one-program IVF search: cluster-contiguous layout + single-jit
probe→gather→score→top-k.

Behavioral reference: /root/reference/pkg/gpu/kmeans.go —
ClusterIndex.SearchWithClusters (:816) probes the n_probe nearest
centroids and scores only their member rows; kmeans_candidate_gen.go
feeds the same candidates to the search pipeline.

TPU-first design (replaces the round-1 per-query host loop, which paid
one device round-trip per query and never beat the full scan through the
relay):
  - The corpus is re-laid out cluster-contiguous: one (K, Cmax, D) block
    array, each cluster's rows contiguous and zero-padded to a shared
    power-of-two Cmax. Block gathers are coarse contiguous HBM reads —
    the row-gather pattern the TPU punishes never appears.
  - Oversized clusters spill their overflow rows into a residual segment
    that every query scans (brute force), so a pathological k-means
    imbalance degrades speed, never recall, and the block array is at
    most ~2x the live corpus.
  - One jit per (B, n_probe, Cmax) shape class does everything: centroid
    GEMM probe, block gather, bf16 scoring with f32 accumulation,
    validity masking, residual concat, top-k. No host round-trips inside
    the batch.

FLOP math at N=1M, D=1024, K=~707: a full scan is B·N·D MACs; probing
P=8 of ~707 clusters scores ~P/K of the corpus (~1.1%) plus residual —
the HBM read per query batch drops by the same factor, which is what
matters at small B where the scan is bandwidth-bound.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.ops.similarity import LANE, dot_scores, l2_normalize


@dataclass
class IVFLayout:
    """Cluster-contiguous device layout built by build_ivf_layout."""

    blocks: jax.Array        # (K, Cmax, D) zero-padded cluster blocks
    counts: jax.Array        # (K,) int32 live rows per block
    centroids: jax.Array     # (K, D)
    slotmap: np.ndarray      # (K, Cmax) int32 -> corpus slot, -1 = pad
    residual: Optional[jax.Array]   # (Rp, D) spilled rows (None if none)
    residual_slots: np.ndarray      # (Rp,) int32 -> corpus slot, -1 = pad
    residual_valid: Optional[jax.Array]  # (Rp,) device mask, built once
    cmax: int
    k: int
    # corpus LAYOUT epoch at build time: the layout serves while this
    # matches HostCorpus._layout_epoch, which bumps only when a covered row
    # is overwritten in place or the slot space remaps (grow/compact/clear)
    # — plain adds/removes leave a fitted layout valid
    epoch: int

    @property
    def n_rows(self) -> int:
        return int((self.slotmap >= 0).sum() + (self.residual_slots >= 0).sum())


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def build_ivf_layout(
    rows: np.ndarray,
    slots: np.ndarray,
    assignments: np.ndarray,
    centroids: np.ndarray,
    dtype=jnp.float32,
    epoch: int = 0,
    max_block_factor: float = 2.0,
) -> IVFLayout:
    """Builds the block layout from live rows.

    rows:        (N, D) float32, already L2-normalized (corpus invariant)
    slots:       (N,) original corpus slot per row
    assignments: (N,) cluster id per row
    centroids:   (K, D)
    max_block_factor: Cmax is capped at ~factor x mean cluster size;
        overflow rows spill to the residual segment.
    """
    n, d = rows.shape
    k = centroids.shape[0]
    mean = max(1, n // max(1, k))
    cmax = _next_pow2(min(max(int(mean * max_block_factor), 8), n))
    # fully vectorized scatter: sort by cluster, compute each row's rank
    # within its cluster, rows with rank < Cmax land in the block array,
    # the rest spill (an O(N) Python loop here cost tens of seconds per
    # recluster at N=1M)
    in_range = (assignments >= 0) & (assignments < k)
    rows_v, slots_v, assign_v = rows[in_range], slots[in_range], assignments[in_range]
    order = np.argsort(assign_v, kind="stable")
    sorted_assign = assign_v[order]
    counts_all = np.bincount(sorted_assign, minlength=k)
    starts = np.concatenate(([0], np.cumsum(counts_all)[:-1]))
    rank = np.arange(sorted_assign.size) - starts[sorted_assign]
    in_block = rank < cmax
    blocks = np.zeros((k, cmax, d), np.float32)
    slotmap = np.full((k, cmax), -1, np.int32)
    c_idx = sorted_assign[in_block]
    p_idx = rank[in_block]
    blocks[c_idx, p_idx] = rows_v[order][in_block]
    slotmap[c_idx, p_idx] = slots_v[order][in_block]
    counts = np.minimum(counts_all, cmax).astype(np.int32)
    spill_rows = rows_v[order][~in_block]
    spill_slot_arr = slots_v[order][~in_block]
    if spill_rows.shape[0]:
        rp = ((spill_rows.shape[0] + LANE - 1) // LANE) * LANE
        residual = np.zeros((rp, d), np.float32)
        residual[: spill_rows.shape[0]] = spill_rows
        residual_slots = np.full(rp, -1, np.int32)
        residual_slots[: spill_slot_arr.shape[0]] = spill_slot_arr
        residual_dev = jnp.asarray(residual, dtype=dtype)
        residual_valid = jnp.asarray(residual_slots >= 0)
    else:
        residual_dev = None
        residual_slots = np.empty(0, np.int32)
        residual_valid = None
    return IVFLayout(
        blocks=jnp.asarray(blocks, dtype=dtype),
        counts=jnp.asarray(counts),
        centroids=jnp.asarray(centroids, dtype=dtype),
        slotmap=slotmap,
        residual=residual_dev,
        residual_slots=residual_slots,
        residual_valid=residual_valid,
        cmax=cmax,
        k=k,
        epoch=epoch,
    )


@functools.partial(jax.jit, static_argnames=("n_probe", "k"))
def _ivf_topk_program(
    queries: jax.Array,      # (B, D) L2-normalized
    centroids: jax.Array,    # (K, D)
    blocks: jax.Array,       # (K, Cmax, D)
    counts: jax.Array,       # (K,)
    n_probe: int,
    k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (vals (B,k), flat candidate idx (B,k), probes (B,P)).
    Flat idx encodes (probe position p, row c) as p * Cmax + c."""
    cmax = blocks.shape[1]
    cscores = dot_scores(queries, centroids)            # (B, K)
    _, probes = jax.lax.top_k(cscores, n_probe)          # (B, P)
    gathered = blocks[probes]                            # (B, P, Cmax, D)
    scores = jnp.einsum(
        "bd,bpcd->bpc",
        queries.astype(jnp.bfloat16),
        gathered.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    live = jnp.arange(cmax)[None, None, :] < counts[probes][:, :, None]
    scores = jnp.where(live, scores, -jnp.inf)
    flat = scores.reshape(scores.shape[0], -1)           # (B, P*Cmax)
    kk = min(k, flat.shape[1])
    vals, idx = jax.lax.top_k(flat, kk)
    return vals, idx, probes


@functools.partial(jax.jit, static_argnames=("k",))
def _residual_topk(
    queries: jax.Array, residual: jax.Array, valid: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    scores = dot_scores(queries, residual)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    kk = min(k, scores.shape[1])
    return jax.lax.top_k(scores, kk)


def ivf_search(
    layout: IVFLayout,
    queries: np.ndarray,
    k: int,
    n_probe: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Fused IVF top-k. queries (B, D) need not be normalized.
    Returns (scores (B, k), corpus slots (B, k)); slot -1 = no candidate
    (short clusters). Scores of returned rows are exact bf16-GEMM scores,
    identical in kind to the full-scan path."""
    q2 = np.atleast_2d(np.asarray(queries, np.float32))
    b = q2.shape[0]
    # bucket B and k to powers of two so the jit caches a handful of
    # shape classes instead of recompiling per client-supplied batch/limit
    # (same rationale as the fallback path's candidate buckets)
    b_pad = _next_pow2(b)
    if b_pad != b:
        q2 = np.concatenate([q2, np.zeros((b_pad - b, q2.shape[1]),
                                          np.float32)])
    k_prog = _next_pow2(max(k, 8))
    qn = l2_normalize(jnp.asarray(q2))
    n_probe = max(1, min(n_probe, layout.k))
    vals, idx, probes = _ivf_topk_program(
        qn, layout.centroids, layout.blocks, layout.counts, n_probe, k_prog
    )
    vals = np.asarray(vals, np.float32)[:b, :k]
    idx = np.asarray(idx)[:b, :k]
    probes_np = np.asarray(probes)[:b]
    # resolve flat (p, c) -> corpus slot through the host slotmap
    p_pos = idx // layout.cmax
    c_pos = idx % layout.cmax
    cluster_ids = np.take_along_axis(probes_np, p_pos, axis=1)
    slots = layout.slotmap[cluster_ids, c_pos]
    slots = np.where(np.isfinite(vals), slots, -1)
    if layout.residual is not None:
        rvals, ridx = _residual_topk(
            qn, layout.residual, layout.residual_valid, k_prog
        )
        rvals = np.asarray(rvals, np.float32)[:b]
        rslots = layout.residual_slots[np.asarray(ridx)[:b]]
        rslots = np.where(np.isfinite(rvals), rslots, -1)
        # merge the two k-lists per query (host merge of 2k items)
        merged_scores = np.concatenate([vals, rvals], axis=1)
        merged_slots = np.concatenate([slots, rslots], axis=1)
        order = np.argsort(-merged_scores, axis=1)[:, :k]
        vals = np.take_along_axis(merged_scores, order, axis=1)
        slots = np.take_along_axis(merged_slots, order, axis=1)
    if vals.shape[1] < k:
        pad = k - vals.shape[1]
        vals = np.pad(vals, ((0, 0), (0, pad)), constant_values=-np.inf)
        slots = np.pad(slots, ((0, 0), (0, pad)), constant_values=-1)
    return vals, slots


# ------------------------------------------------------------ sharded IVF
#
# IVF composed with mesh sharding (ROADMAP item 2): centroids are
# REPLICATED (every shard probes identically — the centroid GEMM is tiny),
# inverted lists are PER-SHARD (each shard owns the cluster members that
# live in its slot range), and n_probe pruning happens INSIDE the shard
# program, so the fused sharded search gets the same ~P/K FLOP/HBM cut per
# shard that the single-device layout gets. All shards share one static
# (K, Cmax, D) block shape (shard_map needs uniform shapes); skew between
# shards pads with dead rows that the count masks exclude, and per-shard
# overflow spills into a shared-width residual segment scanned brute-force.
#
# The slotmap rides the device this time (the single-device layout resolves
# slots host-side): each shard must translate its local (probe, col) hits
# into GLOBAL corpus slots BEFORE the all-gather merge, so the merge
# exchanges only (vals, global_slot) pairs — the same wire format as the
# dense sharded path.
#
# Invalidation contract == PR 2's layout epoch: the layout serves while its
# build-time epoch matches HostCorpus._layout_epoch (bumped by covered-row
# overwrites and slot remaps; plain adds/removes keep it serving — new rows
# are invisible to pruned search until recluster, removals filter at
# format time through the captured id map).


@dataclass
class ShardedIVFLayout:
    """Per-shard cluster-contiguous layout for the fused sharded IVF path.

    Built by build_sharded_ivf_layout; consumed by the shard_map program in
    parallel.sharded_index (kept there — this module stays mesh-agnostic;
    the device arrays arrive pre-placed via the shardings the caller
    passes in).

    ``quantized=True`` stores the blocks (and residual) as int8 codes with
    per-row dequant MULTIPLIERS (1/scale, 0 for pad rows) so the block
    array costs 1 byte/element instead of 4 — the compressed-residency
    twin of ShardedCorpus's int8 serving mode. Device scores then carry
    int8 rounding noise; the corpus rescores the merged candidate set
    exactly from its host f32 mirror.
    """

    blocks: jax.Array        # (S, K, Cmax, D) zero-padded, P(axis,...)
    counts: jax.Array        # (S, K) int32 live rows per shard-cluster
    slotmap: jax.Array       # (S, K, Cmax) int32 GLOBAL slot, -1 = pad
    centroids: jax.Array     # (K, D) replicated
    residual: Optional[jax.Array]      # (S, Rmax, D) per-shard spill
    residual_slots: Optional[jax.Array]  # (S, Rmax) int32 global slot, -1
    cmax: int
    rmax: int
    k: int                   # cluster count
    n_shards: int
    epoch: int               # corpus layout epoch at build time
    quantized: bool = False
    # int8 mode only: per-row dequant multipliers (0 = dead/pad row)
    block_scales: Optional[jax.Array] = None     # (S, K, Cmax) f32
    residual_scales: Optional[jax.Array] = None  # (S, Rmax) f32

    @property
    def n_rows(self) -> int:
        n = int(np.asarray(jnp.sum(self.slotmap >= 0)))
        if self.residual_slots is not None:
            n += int(np.asarray(jnp.sum(self.residual_slots >= 0)))
        return n


def build_sharded_ivf_layout(
    rows: np.ndarray,
    slots: np.ndarray,
    assignments: np.ndarray,
    centroids: np.ndarray,
    n_shards: int,
    local_n: int,
    shard_sharding,
    replicated_sharding,
    dtype=jnp.float32,
    epoch: int = 0,
    max_block_factor: float = 2.0,
    quantize: bool = False,
) -> ShardedIVFLayout:
    """Build the per-shard inverted lists.

    rows:        (N, D) float32, L2-normalized live rows
    slots:       (N,) GLOBAL corpus slot per row; shard = slot // local_n
    assignments: (N,) cluster id per row
    n_shards/local_n: the corpus's mesh layout (capacity = S * local_n)
    shard_sharding: NamedSharding partitioning the leading shard axis
        (trailing dims replicated) — placed on every (S, ...) array;
    replicated_sharding: NamedSharding for the replicated centroids.
    quantize: store blocks/residual as int8 codes + per-row dequant
        multipliers (compressed residency — see ShardedIVFLayout).
    """
    n, d = rows.shape
    k = centroids.shape[0]
    shard_of = slots // local_n
    in_range = (
        (assignments >= 0) & (assignments < k)
        & (shard_of >= 0) & (shard_of < n_shards)
    )
    rows_v = rows[in_range]
    slots_v = slots[in_range]
    assign_v = assignments[in_range]
    shard_v = shard_of[in_range]
    # shared Cmax across shards: ~factor x the mean shard-cluster size, so
    # one skewed shard pads instead of inflating every shard's block array
    mean = max(1, rows_v.shape[0] // max(1, n_shards * k))
    cmax = _next_pow2(min(max(int(mean * max_block_factor), 8),
                          max(local_n, 1)))
    # vectorized scatter, same trick as the single-device build but keyed
    # by (shard, cluster): sort, rank within the pair, rank < Cmax lands
    # in the block, the rest spills per shard
    pair = shard_v.astype(np.int64) * k + assign_v
    order = np.argsort(pair, kind="stable")
    sorted_pair = pair[order]
    counts_all = np.bincount(sorted_pair, minlength=n_shards * k)
    starts = np.concatenate(([0], np.cumsum(counts_all)[:-1]))
    rank = np.arange(sorted_pair.size) - starts[sorted_pair]
    in_block = rank < cmax
    if quantize:
        from nornicdb_tpu.ops.host_search import quantize_rows_np

        # one pass over the live rows; the scatter then moves 1-byte codes
        # plus a (row,) multiplier column instead of f32 row copies
        codes_v, scale_v = quantize_rows_np(rows_v)
        mult_v = (1.0 / np.maximum(scale_v, 1e-30)).astype(np.float32)
        store_v = codes_v
        blocks = np.zeros((n_shards, k, cmax, d), np.int8)
        block_scales = np.zeros((n_shards, k, cmax), np.float32)
    else:
        store_v = rows_v
        blocks = np.zeros((n_shards, k, cmax, d), np.float32)
        block_scales = None
    slotmap = np.full((n_shards, k, cmax), -1, np.int32)
    s_idx = (sorted_pair // k)[in_block]
    c_idx = (sorted_pair % k)[in_block]
    p_idx = rank[in_block]
    blocks[s_idx, c_idx, p_idx] = store_v[order][in_block]
    slotmap[s_idx, c_idx, p_idx] = slots_v[order][in_block]
    if quantize:
        block_scales[s_idx, c_idx, p_idx] = mult_v[order][in_block]
    counts = np.minimum(
        counts_all.reshape(n_shards, k), cmax
    ).astype(np.int32)
    # per-shard residual spill, padded to a shared LANE-multiple width
    spill_rows = store_v[order][~in_block]
    spill_slots = slots_v[order][~in_block]
    spill_shard = (sorted_pair // k)[~in_block]
    residual_dev = residual_slots_dev = residual_scales_dev = None
    rmax = 0
    if spill_rows.shape[0]:
        per_shard = np.bincount(spill_shard, minlength=n_shards)
        rmax = ((int(per_shard.max()) + LANE - 1) // LANE) * LANE
        residual = np.zeros((n_shards, rmax, d), spill_rows.dtype)
        residual_slots = np.full((n_shards, rmax), -1, np.int32)
        residual_scales = (np.zeros((n_shards, rmax), np.float32)
                           if quantize else None)
        spill_mult = mult_v[order][~in_block] if quantize else None
        # spill rows are already grouped by shard (sorted by pair)
        for s in range(n_shards):
            m = spill_shard == s
            cnt = int(m.sum())
            if cnt:
                residual[s, :cnt] = spill_rows[m]
                residual_slots[s, :cnt] = spill_slots[m]
                if quantize:
                    residual_scales[s, :cnt] = spill_mult[m]
        residual_dev = jax.device_put(
            jnp.asarray(residual) if quantize
            else jnp.asarray(residual, dtype=dtype),
            shard_sharding,
        )
        residual_slots_dev = jax.device_put(
            jnp.asarray(residual_slots), shard_sharding
        )
        if quantize:
            residual_scales_dev = jax.device_put(
                jnp.asarray(residual_scales), shard_sharding
            )
    return ShardedIVFLayout(
        blocks=jax.device_put(
            jnp.asarray(blocks) if quantize
            else jnp.asarray(blocks, dtype=dtype),
            shard_sharding,
        ),
        counts=jax.device_put(jnp.asarray(counts), shard_sharding),
        slotmap=jax.device_put(jnp.asarray(slotmap), shard_sharding),
        centroids=jax.device_put(jnp.asarray(centroids, dtype=dtype),
                                 replicated_sharding),
        residual=residual_dev,
        residual_slots=residual_slots_dev,
        cmax=cmax,
        rmax=rmax,
        k=k,
        n_shards=n_shards,
        epoch=epoch,
        quantized=quantize,
        block_scales=(jax.device_put(jnp.asarray(block_scales),
                                     shard_sharding)
                      if quantize else None),
        residual_scales=residual_scales_dev,
    )
