"""Pallas TPU kernels for the vector-search hot path.

Replaces the reference's fused CUDA kernels
(/root/reference/pkg/gpu/cuda/cuda_kernels.cu:
kernel_cosine_similarity_normalized :263 — one thread block per corpus chunk;
here one grid step per corpus tile feeding the MXU).

The fused kernel streams corpus tiles HBM->VMEM, normalizes in-register, and
contracts against the (small, VMEM-resident) query block — the (Q, N) score
matrix is produced tile-by-tile and never forces an extra HBM round-trip of
the corpus. Top-k stays in XLA (lax.top_k fuses fine as an epilogue).

On non-TPU backends the kernels run in Pallas interpret mode so tests work on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _cosine_tile_kernel(q_ref, c_ref, out_ref):
    """One corpus tile: normalize rows of the tile, contract with queries.

    q_ref:   (Q, D)      — pre-normalized queries, VMEM-resident
    c_ref:   (TILE_N, D) — raw corpus tile (normalization fused here)
    out_ref: (Q, TILE_N)
    """
    c = c_ref[:].astype(jnp.float32)
    inv_norm = jax.lax.rsqrt(jnp.maximum(jnp.sum(c * c, axis=1, keepdims=True), 1e-24))
    c_n = c * inv_norm
    out_ref[:] = jax.lax.dot_general(
        q_ref[:].astype(jnp.float32),
        c_n,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def fused_cosine_scores(
    queries: jax.Array,
    corpus: jax.Array,
    tile_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """(Q, D) x (N, D) -> (Q, N) cosine scores with normalization fused into
    the corpus tile load. N must be a multiple of tile_n (pad + mask upstream).
    Queries must already be L2-normalized.
    """
    q, d = queries.shape
    n = corpus.shape[0]
    tile_n = min(tile_n, n)
    if n % tile_n != 0:
        raise ValueError(
            f"corpus rows ({n}) must be a multiple of tile_n ({tile_n}); "
            "pad with ops.similarity.pad_to_multiple and mask upstream"
        )
    grid = (n // tile_n,)
    return pl.pallas_call(
        _cosine_tile_kernel,
        out_shape=jax.ShapeDtypeStruct((q, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((q, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d + 3 * n * d,
            bytes_accessed=n * d * corpus.dtype.itemsize + q * d * 4 + q * n * 4,
            transcendentals=n,  # rsqrt per corpus row
        ),
        interpret=interpret,
    )(queries, corpus)


def fused_cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 512,
) -> tuple[jax.Array, jax.Array]:
    """Pallas-scored cosine top-k; auto-selects interpret mode off-TPU."""
    scores = fused_cosine_scores(
        queries, corpus, tile_n=tile_n, interpret=not _on_tpu()
    )
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


# ------------------------------------------------------- streaming top-k
#
# The serving kernel (ref: cuda_kernels.cu kernel_cosine_similarity_normalized
# :263 fused with kernel_topk_simple :384 — the reference's CUDA path also
# never materializes the full score matrix). One grid step per corpus tile:
# the tile is DMA'd HBM->VMEM once, scored on the MXU against the
# VMEM-resident queries, and folded into a running per-bin max that lives in
# VMEM across all grid steps. HBM traffic is one corpus read + O(Q*B) state,
# vs. the XLA approx_max_k path which round-trips the (Q, N) score matrix
# (1 GB at Q=256, N=1M) through HBM.
#
# Selection scheme: bins. Tile t, column j maps to bin (t % rows, j) — i.e.
# B = rows * tile_n bins, each keeping the max score (and its global index)
# of the ~N/B columns hashed to it. The exact top-k over the (Q, B) bins runs
# as a tiny XLA epilogue. Two true top-k members collide (one lost) only if
# they share a bin: expected recall ~= 1 - (k-1)/(2B); rows is sized so
# B >= 20*k, giving >= ~0.975 for k=100 — the same contract as the
# lax.approx_max_k path it replaces (and as the reference's HNSW ANN).
# When n_tiles <= rows every column gets its own bin and the result is exact.


def _streaming_topk_kernel(q_ref, c_ref, m_ref, vals_ref, idx_ref,
                           *, tile_n: int, rows: int):
    i = pl.program_id(0)
    scores = jax.lax.dot_general(
        q_ref[:].astype(jnp.bfloat16),
        c_ref[:].astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Q, TILE_N)
    scores = jnp.where(m_ref[:] > 0.5, scores, -jnp.inf)  # mask broadcasts over Q
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + i * tile_n
    r = i % rows

    @pl.when(i < rows)
    def _init():
        vals_ref[r] = scores
        idx_ref[r] = col

    @pl.when(i >= rows)
    def _merge():
        cur = vals_ref[r]
        take = scores > cur
        vals_ref[r] = jnp.where(take, scores, cur)
        idx_ref[r] = jnp.where(take, col, idx_ref[r])


@functools.partial(
    jax.jit, static_argnames=("k", "tile_n", "rows", "interpret")
)
def streaming_cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 1024,
    rows: int = 2,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Single-pass cosine top-k that never materializes (Q, N).

    queries: (Q, D) L2-normalized; corpus: (N, D) L2-normalized rows
    (padding/tombstone rows are excluded by `valid`, so their content is
    irrelevant); valid: (N,) bool. N must be a multiple of tile_n.
    Returns (values (Q, k), indices (Q, k)); values of masked-out rows never
    appear (they score -inf).
    """
    q, d = queries.shape
    n = corpus.shape[0]
    if n % tile_n != 0:
        raise ValueError(f"N ({n}) must be a multiple of tile_n ({tile_n})")
    n_tiles = n // tile_n
    rows = min(rows, n_tiles)
    mask = valid.astype(jnp.float32).reshape(1, n)
    kern = functools.partial(_streaming_topk_kernel, tile_n=tile_n, rows=rows)
    vals, idx = pl.pallas_call(
        kern,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, q, tile_n), jnp.float32),
            jax.ShapeDtypeStruct((rows, q, tile_n), jnp.int32),
        ],
        # every grid step maps to the same block: the running bins stay
        # VMEM-resident for the whole sweep and are written back once
        out_specs=[
            pl.BlockSpec((rows, q, tile_n), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, q, tile_n), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d,
            bytes_accessed=n * d * corpus.dtype.itemsize
            + q * d * queries.dtype.itemsize + 2 * rows * q * tile_n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(queries, corpus, mask)
    # tiny exact top-k over the B = rows*tile_n bins — same merge as the
    # sharded ICI epilogue (lazy import: similarity imports this module)
    from nornicdb_tpu.ops.similarity import merge_topk

    return merge_topk(vals, idx, k)


def pick_tile_n(n: int, preferred: int = 1024) -> int:
    """Largest power-of-two tile (>=128) that divides n, capped at
    `preferred`. Corpus capacities are LANE (128) multiples, so 128 always
    divides; bigger tiles amortize grid overhead."""
    t = preferred
    while t > LANE and n % t != 0:
        t //= 2
    return t


def streaming_rows_for(k: int, tile_n: int, target_bins_per_k: int = 20) -> int:
    """Bin rows so B = rows*tile_n >= target_bins_per_k * k (recall knob)."""
    need = max(2 * tile_n, target_bins_per_k * k)
    return -(-need // tile_n)  # ceil div
