"""Pallas TPU kernels for the vector-search hot path.

Replaces the reference's fused CUDA kernels
(/root/reference/pkg/gpu/cuda/cuda_kernels.cu:
kernel_cosine_similarity_normalized :263 — one thread block per corpus chunk;
here one grid step per corpus tile feeding the MXU).

The fused kernel streams corpus tiles HBM->VMEM, normalizes in-register, and
contracts against the (small, VMEM-resident) query block — the (Q, N) score
matrix is produced tile-by-tile and never forces an extra HBM round-trip of
the corpus. Top-k stays in XLA (lax.top_k fuses fine as an epilogue).

On non-TPU backends the kernels run in Pallas interpret mode so tests work on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _cosine_tile_kernel(q_ref, c_ref, out_ref):
    """One corpus tile: normalize rows of the tile, contract with queries.

    q_ref:   (Q, D)      — pre-normalized queries, VMEM-resident
    c_ref:   (TILE_N, D) — raw corpus tile (normalization fused here)
    out_ref: (Q, TILE_N)
    """
    c = c_ref[:].astype(jnp.float32)
    inv_norm = jax.lax.rsqrt(jnp.maximum(jnp.sum(c * c, axis=1, keepdims=True), 1e-24))
    c_n = c * inv_norm
    out_ref[:] = jax.lax.dot_general(
        q_ref[:].astype(jnp.float32),
        c_n,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def fused_cosine_scores(
    queries: jax.Array,
    corpus: jax.Array,
    tile_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """(Q, D) x (N, D) -> (Q, N) cosine scores with normalization fused into
    the corpus tile load. N must be a multiple of tile_n (pad + mask upstream).
    Queries must already be L2-normalized.
    """
    q, d = queries.shape
    n = corpus.shape[0]
    tile_n = min(tile_n, n)
    if n % tile_n != 0:
        raise ValueError(
            f"corpus rows ({n}) must be a multiple of tile_n ({tile_n}); "
            "pad with ops.similarity.pad_to_multiple and mask upstream"
        )
    grid = (n // tile_n,)
    return pl.pallas_call(
        _cosine_tile_kernel,
        out_shape=jax.ShapeDtypeStruct((q, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((q, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d + 3 * n * d,
            bytes_accessed=n * d * corpus.dtype.itemsize + q * d * 4 + q * n * 4,
            transcendentals=n,  # rsqrt per corpus row
        ),
        interpret=interpret,
    )(queries, corpus)


def fused_cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 512,
) -> tuple[jax.Array, jax.Array]:
    """Pallas-scored cosine top-k; auto-selects interpret mode off-TPU."""
    scores = fused_cosine_scores(
        queries, corpus, tile_n=tile_n, interpret=not _on_tpu()
    )
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)
