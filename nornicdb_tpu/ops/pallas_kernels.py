"""Pallas TPU kernels for the vector-search hot path.

Replaces the reference's fused CUDA kernels
(/root/reference/pkg/gpu/cuda/cuda_kernels.cu:
kernel_cosine_similarity_normalized :263 — one thread block per corpus chunk;
here one grid step per corpus tile feeding the MXU).

The fused kernel streams corpus tiles HBM->VMEM, normalizes in-register, and
contracts against the (small, VMEM-resident) query block — the (Q, N) score
matrix is produced tile-by-tile and never forces an extra HBM round-trip of
the corpus. Top-k stays in XLA (lax.top_k fuses fine as an epilogue).

On non-TPU backends the kernels run in Pallas interpret mode so tests work on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except (RuntimeError, IndexError):  # backend init failed / no devices
        return False


def _cosine_tile_kernel(q_ref, c_ref, out_ref):
    """One corpus tile: normalize rows of the tile, contract with queries.

    q_ref:   (Q, D)      — pre-normalized queries, VMEM-resident
    c_ref:   (TILE_N, D) — raw corpus tile (normalization fused here)
    out_ref: (Q, TILE_N)
    """
    c = c_ref[:].astype(jnp.float32)
    inv_norm = jax.lax.rsqrt(jnp.maximum(jnp.sum(c * c, axis=1, keepdims=True), 1e-24))
    c_n = c * inv_norm
    out_ref[:] = jax.lax.dot_general(
        q_ref[:].astype(jnp.float32),
        c_n,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def fused_cosine_scores(
    queries: jax.Array,
    corpus: jax.Array,
    tile_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """(Q, D) x (N, D) -> (Q, N) cosine scores with normalization fused into
    the corpus tile load. N must be a multiple of tile_n (pad + mask upstream).
    Queries must already be L2-normalized.
    """
    q, d = queries.shape
    n = corpus.shape[0]
    tile_n = min(tile_n, n)
    if n % tile_n != 0:
        raise ValueError(
            f"corpus rows ({n}) must be a multiple of tile_n ({tile_n}); "
            "pad with ops.similarity.pad_to_multiple and mask upstream"
        )
    grid = (n // tile_n,)
    return pl.pallas_call(
        _cosine_tile_kernel,
        out_shape=jax.ShapeDtypeStruct((q, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((q, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d + 3 * n * d,
            bytes_accessed=n * d * corpus.dtype.itemsize + q * d * 4 + q * n * 4,
            transcendentals=n,  # rsqrt per corpus row
        ),
        interpret=interpret,
    )(queries, corpus)


def fused_cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 512,
) -> tuple[jax.Array, jax.Array]:
    """Pallas-scored cosine top-k; auto-selects interpret mode off-TPU."""
    scores = fused_cosine_scores(
        queries, corpus, tile_n=tile_n, interpret=not _on_tpu()
    )
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


# ------------------------------------------------------- streaming top-k
#
# The serving kernel (ref: cuda_kernels.cu kernel_cosine_similarity_normalized
# :263 fused with kernel_topk_simple :384 — the reference's CUDA path also
# never materializes the full score matrix). One grid step per corpus tile:
# the tile is DMA'd HBM->VMEM once, scored on the MXU against the
# VMEM-resident queries, and folded into a running per-bin max that lives in
# VMEM across all grid steps. HBM traffic is one corpus read + O(Q*B) state,
# vs. the XLA approx_max_k path which round-trips the (Q, N) score matrix
# (4 GB at Q=1024, N=1M) through HBM.
#
# Selection scheme: bins. Tile t, column j maps to bin (t % rows, j) — i.e.
# B = rows * tile_n bins, each keeping the best (score, tile) of the ~N/B
# columns hashed to it. Two true top-k members collide (one lost) only if
# they share a bin: expected recall ~= 1 - (k-1)/(2B); rows is sized so
# B >= 20*k, giving >= ~0.975 for k=100 — the same contract as the
# lax.approx_max_k path it replaces (and as the reference's HNSW ANN).
# When n_tiles <= rows every column gets its own bin and the result is exact.
#
# Packed-bin encoding (the VPU-cost trick): scores are biased into [2, 4)
# (+3 for valid columns, -3 for masked ones), where the f32 bit pattern is
# monotonic as a signed int32. The low `tile_bits` mantissa bits are replaced
# by the tile index, so one int32 carries (score, provenance) and the whole
# per-tile merge is a single integer max — measured free on the VPU (kernel
# body == pure-GEMM cost) vs ~2x body cost for the separate (vals, idx)
# two-array merge, at half the VMEM. Masked columns stay negative and lose
# every signed compare. The dropped mantissa bits cost ~2^-11 of score
# resolution — an order of magnitude below the bf16 GEMM noise (~2^-8
# relative) that both this path and the XLA approx_max_k path already carry,
# so scores are decoded straight from the packed bits (a gather+rescore
# epilogue was measured at +9ms/batch: TPU row gathers don't vectorize).


def _streaming_topk_kernel(q_ref, c_ref, b_ref, bins_ref,
                           *, rows: int, tile_bits: int):
    i = pl.program_id(0)
    scores = jax.lax.dot_general(
        q_ref[:].astype(jnp.bfloat16),
        c_ref[:].astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Q, TILE_N)
    # bias +3 valid / -3 masked, then bitcast: valid scores land in [2, 4)
    # where the int32 view is positive and monotonic; masked go negative
    biased = scores + b_ref[:]
    packed = (
        jax.lax.bitcast_convert_type(biased, jnp.int32)
        & jnp.int32(-(1 << tile_bits))
    ) | i
    r = i % rows

    @pl.when(i < rows)
    def _init():
        bins_ref[r] = packed

    @pl.when(i >= rows)
    def _merge():
        bins_ref[r] = jnp.maximum(bins_ref[r], packed)


@functools.partial(
    jax.jit, static_argnames=("k", "tile_n", "rows", "interpret", "epilogue")
)
def streaming_cosine_topk(
    queries: jax.Array,
    corpus: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 512,
    rows: int = 4,
    interpret: bool = False,
    epilogue: str = "sort",
) -> tuple[jax.Array, jax.Array]:
    """Single-pass cosine top-k that never materializes (Q, N).

    queries: (Q, D) L2-normalized; corpus: (N, D) L2-normalized rows
    (padding/tombstone rows are excluded by `valid`, so their content is
    irrelevant); valid: (N,) bool. N must be a multiple of tile_n.
    Returns (values (Q, k), indices (Q, k)); values carry bf16-GEMM-level
    accuracy (see packed-bin note above); masked-out rows never appear
    (they score -inf).
    """
    q, d = queries.shape
    n = corpus.shape[0]
    if n % tile_n != 0:
        raise ValueError(f"N ({n}) must be a multiple of tile_n ({tile_n})")
    n_tiles = n // tile_n
    rows = min(rows, n_tiles)
    tile_bits = max(1, (n_tiles - 1).bit_length())
    bias = jnp.where(valid, 3.0, -3.0).astype(jnp.float32).reshape(1, n)
    kern = functools.partial(
        _streaming_topk_kernel, rows=rows, tile_bits=tile_bits
    )
    bins = pl.pallas_call(
        kern,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=jax.ShapeDtypeStruct((rows, q, tile_n), jnp.int32),
        # every grid step maps to the same block: the running bins stay
        # VMEM-resident for the whole sweep and are written back once
        out_specs=pl.BlockSpec((rows, q, tile_n), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d,
            bytes_accessed=n * d * corpus.dtype.itemsize
            + q * d * queries.dtype.itemsize + rows * q * tile_n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(queries, corpus, bias)

    # epilogue: top-k over the B = rows*tile_n packed bins (int order =
    # score order), then decode score + provenance from the packed bits
    return _decode_packed(
        bins, k=k, n=n, rows=rows, tile_n=tile_n, tile_bits=tile_bits,
        epilogue=epilogue, interpret=interpret,
    )


# ------------------------------------------------- int8 streaming top-k
#
# Same packed-bin scheme, but the MXU runs at the int8 rate (2x bf16 on
# v5e) over an int8-quantized corpus mirror (half the HBM read). Rows are
# symmetric-quantized per-row (scale = 127/max|x|); the per-row dequant
# multiplier rides the same (1, tile) VPU FMA that applies the mask bias, and
# the per-query scale divides out at decode (scaling a query doesn't change
# its ranking). Measured ~1.3x end-to-end over the bf16 kernel at 1M x 1024
# with recall within 0.005 of it (int8 rounding noise ~1e-3 on cosine scores,
# same order as the bf16 GEMM noise both paths already carry).


def _streaming_topk_int8_kernel(q_ref, c_ref, s_ref, b_ref, bins_ref,
                                *, rows: int, tile_bits: int):
    i = pl.program_id(0)
    acc = jax.lax.dot_general(
        q_ref[:], c_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (Q, TILE_N) int32
    biased = acc.astype(jnp.float32) * s_ref[:] + b_ref[:]
    packed = (jax.lax.bitcast_convert_type(biased, jnp.int32)
              & jnp.int32(-(1 << tile_bits))) | i
    r = i % rows

    @pl.when(i < rows)
    def _init():
        bins_ref[r] = packed

    @pl.when(i >= rows)
    def _merge():
        bins_ref[r] = jnp.maximum(bins_ref[r], packed)


@jax.jit
def quantize_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 quantization: returns (int8 rows, scales) with
    x ~= int8 / scale."""
    xf = x.astype(jnp.float32)
    s = 127.0 / jnp.maximum(jnp.max(jnp.abs(xf), axis=1), 1e-9)
    return jnp.round(xf * s[:, None]).astype(jnp.int8), s


def _extract_topk_kernel(flat_ref, out_v_ref, out_i_ref, *, k: int):
    """Exact iterative top-k extraction over packed bins, fully in VMEM.

    k sequential (argmax -> record -> mask-first-occurrence) steps on the
    (Q, B) int32 bins. ~4*Q*B VPU ops per step — for Q=1024, B=2048, k=100
    that is ~0.8G VPU ops, far below what a bitonic sort of B per row costs
    through XLA's top_k, and the bins never leave VMEM.
    """
    flat = flat_ref[:]  # (Q, B) int32
    b = flat.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, flat.shape, 1)
    kpad = out_v_ref.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, (flat.shape[0], kpad), 1)
    neg = jnp.int32(-(2**31))

    def body(j, carry):
        scores, out_v, out_i = carry
        m = jnp.max(scores, axis=1)
        first = jnp.min(
            jnp.where(scores == m[:, None], iota, b), axis=1
        )  # first occurrence: duplicates stay available for later steps
        out_v = jnp.where(col == j, m[:, None], out_v)
        out_i = jnp.where(col == j, first[:, None], out_i)
        scores = jnp.where(iota == first[:, None], neg, scores)
        return scores, out_v, out_i

    init_v = jnp.full(out_v_ref.shape, neg, jnp.int32)
    init_i = jnp.zeros(out_i_ref.shape, jnp.int32)
    _, out_v, out_i = jax.lax.fori_loop(0, k, body, (flat, init_v, init_i))
    out_v_ref[:] = out_v
    out_i_ref[:] = out_i


def _topk_bins(flat, k: int, *, epilogue: str, interpret: bool):
    """Top-k over the (Q, B) packed-bin matrix. Three strategies:

    sort    — XLA lax.top_k (bitonic sort of B per row; the round-2 default)
    approx  — lax.approx_max_k over the monotone f32 bitcast view of the
              packed ints (positive for valid bins, so the f32 ordering
              equals the int ordering); the returned values bitcast straight
              back to the packed ints. TPU PartialReduce beats a full sort.
    pallas  — exact in-VMEM iterative extraction (_extract_topk_kernel)
    """
    q, b = flat.shape
    k = min(k, b)
    if epilogue == "sort":
        return jax.lax.top_k(flat, k)
    if epilogue == "approx":
        f32 = jax.lax.bitcast_convert_type(flat, jnp.float32)
        vals, idx = jax.lax.approx_max_k(f32, k, recall_target=0.99)
        return jax.lax.bitcast_convert_type(vals, jnp.int32), idx
    if epilogue == "pallas":
        kpad = -(-k // LANE) * LANE  # pad the lane dim; slice after
        out_v, out_i = pl.pallas_call(
            functools.partial(_extract_topk_kernel, k=k),
            out_shape=(
                jax.ShapeDtypeStruct((q, kpad), jnp.int32),
                jax.ShapeDtypeStruct((q, kpad), jnp.int32),
            ),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            interpret=interpret,
        )(flat)
        return out_v[:, :k], out_i[:, :k]
    raise ValueError(f"unknown epilogue {epilogue!r}")


def _decode_packed(bins, *, k, n, rows, tile_n, tile_bits,
                   epilogue: str = "sort", interpret: bool = False):
    """Top-k over packed bins + decode (score, global row)."""
    q = bins.shape[1]
    b_total = rows * tile_n
    flat = jnp.swapaxes(bins, 0, 1).reshape(q, b_total)
    top_packed, top_bin = _topk_bins(
        flat, k, epilogue=epilogue, interpret=interpret
    )
    low_mask = (1 << tile_bits) - 1
    tile_idx = top_packed & low_mask
    idx = tile_idx * tile_n + top_bin % tile_n
    # midpoint-reconstruct the truncated mantissa bits, then un-bias
    score_bits = (top_packed & ~low_mask) | (1 << (tile_bits - 1))
    vals = jax.lax.bitcast_convert_type(score_bits, jnp.float32) - 3.0
    vals = jnp.where(top_packed > 0, vals, -jnp.inf)
    return vals, jnp.clip(idx, 0, n - 1)


@functools.partial(
    jax.jit, static_argnames=("k", "tile_n", "rows", "interpret", "epilogue")
)
def streaming_cosine_topk_int8(
    q_i8: jax.Array,
    q_scale: jax.Array,
    c_i8: jax.Array,
    c_scale: jax.Array,
    valid: jax.Array,
    k: int,
    tile_n: int = 512,
    rows: int = 4,
    interpret: bool = False,
    epilogue: str = "sort",
) -> tuple[jax.Array, jax.Array]:
    """int8 single-pass cosine top-k (see module comment). Inputs are
    quantize_rows() outputs of L2-normalized queries/corpus; valid: (N,)
    bool. Returns (values (Q, k) ~cosine scores, indices (Q, k))."""
    q, d = q_i8.shape
    n = c_i8.shape[0]
    if n % tile_n != 0:
        raise ValueError(f"N ({n}) must be a multiple of tile_n ({tile_n})")
    n_tiles = n // tile_n
    rows = min(rows, n_tiles)
    tile_bits = max(1, (n_tiles - 1).bit_length())
    scale = jnp.where(valid, 1.0 / c_scale, 0.0).astype(jnp.float32)
    bias = jnp.where(valid, 3.0, -3.0).astype(jnp.float32)
    kern = functools.partial(
        _streaming_topk_int8_kernel, rows=rows, tile_bits=tile_bits
    )
    bins = pl.pallas_call(
        kern,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((q, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=jax.ShapeDtypeStruct((rows, q, tile_n), jnp.int32),
        out_specs=pl.BlockSpec((rows, q, tile_n), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * q * n * d,
            bytes_accessed=n * d + q * d + rows * q * tile_n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q_i8, c_i8, scale.reshape(1, n), bias.reshape(1, n))
    vals, idx = _decode_packed(
        bins, k=k, n=n, rows=rows, tile_n=tile_n, tile_bits=tile_bits,
        epilogue=epilogue, interpret=interpret,
    )
    return vals / q_scale[:, None], idx


# ------------------------------------------------- ragged paged attention
#
# The genserve kernel (Ragged Paged Attention, PAPERS.md arXiv:2604.15464):
# ONE device program serves a mixed batch of prefill and decode lanes over
# the paged KV pool. Each grid row is one lane; its (P,) page-table row is a
# scalar-prefetch operand, so the BlockSpec index_map DMAs exactly that
# lane's physical pages HBM->VMEM — the XLA path's (L, P, ps, Hkv, Dh)
# block-gather materialization never exists. Pages accumulate into a VMEM
# scratch K/V strip across the page-grid dimension; the last page step runs
# the full (unsoftmax-split) attention for the lane, so the arithmetic is
# EXACTLY layers.attention's — same einsum contractions, same f32 softmax —
# and the outputs stay bit-identical to the XLA reference fallback
# (qwen2._paged_attention), which the dense-equivalence suite holds the
# engine to. A flash-style running softmax would break that contract for no
# VMEM win at serving sizes (P*ps <= max_seq_tokens).
#
# Ragged metadata: positions (L, Tq) carries each query row's cache slot,
# -1 marking padding rows. Padding rows mask EVERY key slot (-1e30): the
# softmax degenerates to a finite uniform over garbage the scheduler never
# gathers back, and no NaN can propagate. Decode lanes are (Tq=1 valid row),
# the prefill chunk is one lane with up to Tq valid rows — same program.


def _ragged_attn_kernel(tbl_ref, q_ref, k_ref, v_ref, pos_ref, out_ref,
                        k_scr, v_scr, *, n_rep: int):
    del tbl_ref  # consumed by the index_maps (scalar prefetch)
    j = pl.program_id(1)
    p = pl.num_programs(1)
    ps = k_ref.shape[1]
    k_scr[pl.ds(j * ps, ps)] = k_ref[0]
    v_scr[pl.ds(j * ps, ps)] = v_ref[0]

    @pl.when(j == p - 1)
    def _attend():
        q = q_ref[0]                      # (Tq, H, Dh)
        k = k_scr[:]                      # (S = P*ps, Hkv, Dh)
        v = v_scr[:]
        s_len, hkv, dh = k.shape
        # GQA expansion, layers.repeat_kv's broadcast+reshape per lane
        k = jnp.broadcast_to(
            k[:, :, None, :], (s_len, hkv, n_rep, dh)
        ).reshape(s_len, hkv * n_rep, dh)
        v = jnp.broadcast_to(
            v[:, :, None, :], (s_len, hkv, n_rep, dh)
        ).reshape(s_len, hkv * n_rep, dh)
        s = jnp.einsum("qhd,khd->hqk", q, k,
                       preferred_element_type=jnp.float32)
        s = s * (q.shape[-1] ** -0.5)
        slot = jax.lax.broadcasted_iota(jnp.int32, (q.shape[0], s_len), 1)
        mask = jnp.where(slot <= pos_ref[0][:, None], 0.0, -1e30)
        s = s + mask[None]
        prob = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hqk,khd->qhd", prob.astype(v.dtype), v,
                       preferred_element_type=jnp.float32)
        out_ref[0] = o.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def ragged_paged_attention(
    q: jax.Array,          # (L, Tq, H, Dh) rope'd queries, lane-padded
    k_pages: jax.Array,    # (num_pages, ps, Hkv, Dh) — one layer's K pool
    v_pages: jax.Array,    # (num_pages, ps, Hkv, Dh)
    tables: jax.Array,     # (L, P) int32 physical page ids (NULL pads)
    positions: jax.Array,  # (L, Tq) int32 cache slot per query row; -1 = pad
    interpret: bool = False,
) -> jax.Array:
    """Mixed prefill+decode attention over the paged KV pool: one grid row
    per lane, per-lane page tables scalar-prefetched so only that lane's
    pages stream HBM->VMEM. Returns (L, Tq, H, Dh) in q.dtype, bit-identical
    to gathering the lane's pages and calling layers.attention."""
    l, tq, h, dh = q.shape
    ps, hkv = k_pages.shape[1], k_pages.shape[2]
    p = tables.shape[1]
    n_rep = h // hkv
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(l, p),
        in_specs=[
            pl.BlockSpec((1, tq, h, dh), lambda i, j, tbl: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, hkv, dh),
                         lambda i, j, tbl: (tbl[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, hkv, dh),
                         lambda i, j, tbl: (tbl[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq), lambda i, j, tbl: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tq, h, dh),
                               lambda i, j, tbl: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((p * ps, hkv, dh), k_pages.dtype),
            pltpu.VMEM((p * ps, hkv, dh), v_pages.dtype),
        ],
    )
    s_len = p * ps
    return pl.pallas_call(
        functools.partial(_ragged_attn_kernel, n_rep=n_rep),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((l, tq, h, dh), q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * l * tq * s_len * h * dh,
            bytes_accessed=(
                2 * l * s_len * hkv * dh * k_pages.dtype.itemsize
                + 2 * l * tq * h * dh * q.dtype.itemsize
            ),
            transcendentals=l * h * tq * s_len,  # softmax exp
        ),
        interpret=interpret,
    )(tables, q, k_pages, v_pages, positions)


def pick_tile_n(n: int, preferred: int = 1024) -> int:
    """Largest power-of-two tile (>=128) that divides n, capped at
    `preferred`. Corpus capacities are LANE (128) multiples, so 128 always
    divides; bigger tiles amortize grid overhead."""
    t = preferred
    while t > LANE and n % t != 0:
        t //= 2
    return t


def streaming_rows_for(k: int, tile_n: int, target_bins_per_k: int = 20) -> int:
    """Bin rows so B = rows*tile_n >= target_bins_per_k * k (recall knob)."""
    need = max(2 * tile_n, target_bins_per_k * k)
    return -(-need // tile_n)  # ceil div
