"""TPU compute ops: similarity, top-k, k-means, Pallas kernels.

Replaces the reference's device stack (pkg/gpu CUDA/Metal/Vulkan/OpenCL,
pkg/simd) with JAX/XLA/Pallas — see SURVEY.md §2.2.
"""

from nornicdb_tpu.ops.kmeans import (
    KMeansResult,
    assign_clusters,
    kmeans_fit,
    kmeans_pp_init,
    lloyd,
    nearest_clusters,
    optimal_k,
    pairwise_sq_dists,
)
from nornicdb_tpu.ops.ivf import (
    IVFLayout,
    ShardedIVFLayout,
    build_ivf_layout,
    build_sharded_ivf_layout,
    ivf_search,
)
from nornicdb_tpu.ops.pallas_kernels import fused_cosine_scores, fused_cosine_topk
from nornicdb_tpu.ops.similarity import (
    LANE,
    DeviceCorpus,
    HostCorpus,
    cosine_scores,
    cosine_topk,
    dot_scores,
    euclidean_scores,
    l2_normalize,
    merge_topk,
    pad_to_multiple,
    score_subset,
    topk_backend,
)

__all__ = [
    "LANE",
    "DeviceCorpus",
    "HostCorpus",
    "cosine_scores",
    "cosine_topk",
    "dot_scores",
    "euclidean_scores",
    "l2_normalize",
    "merge_topk",
    "pad_to_multiple",
    "score_subset",
    "topk_backend",
    "IVFLayout",
    "ShardedIVFLayout",
    "build_ivf_layout",
    "build_sharded_ivf_layout",
    "ivf_search",
    "KMeansResult",
    "assign_clusters",
    "kmeans_fit",
    "kmeans_pp_init",
    "lloyd",
    "nearest_clusters",
    "optimal_k",
    "pairwise_sq_dists",
    "fused_cosine_scores",
    "fused_cosine_topk",
]
