"""Pure-NumPy search fallbacks for DEGRADED_CPU serving.

When the BackendManager (nornicdb_tpu.backend) reports the accelerator
lost, the corpora in ops/similarity.py answer from their host arrays
through these routines instead of blocking on a device that may never
come back — the reference's device-failure CPU retry
(pkg/embed/local_gguf.go:202-294) and WindVE's host-side takeover
(PAPERS.md) as one module.

Contract parity with the device path: inputs are L2-normalized rows, so
cosine == dot; scores are EXACT and candidate membership is exact too
(a full argpartition — CPU fallback trades throughput, never recall).
Results are (values, indices) in the same shape/ordering contract as
``ops.similarity.topk_backend`` so ``HostCorpus._format_results``
resolves them identically.
"""

from __future__ import annotations

import numpy as np


def host_topk(
    queries: np.ndarray,
    corpus: np.ndarray,
    valid: np.ndarray,
    k: int,
) -> tuple[np.ndarray, np.ndarray]:
    """(Q, D) x (N, D) -> exact top-k (values (Q, k), indices (Q, k)).

    ``valid`` masks padding/tombstone rows to -inf, mirroring the device
    kernels; k is clamped to the corpus size."""
    q = np.atleast_2d(np.asarray(queries, np.float32))
    n = corpus.shape[0]
    k = max(1, min(k, n))
    scores = q @ corpus.T  # (Q, N); rows are normalized -> cosine
    scores = np.where(valid[None, :], scores, -np.inf)
    # NaN scores (NaN query components survive normalization's
    # divide-by-norm) break the boundary-widening selection below: every
    # `s >= kth` comparison is False, so fewer than k candidates survive
    # and the fixed-shape write raises.  Map them to -inf — callers
    # already drop non-finite values (_format_results), so a NaN query
    # degrades to "matches nothing" instead of a 500.
    np.copyto(scores, -np.inf, where=np.isnan(scores))
    # ties must keep ascending row order, matching lax.top_k's tie rule
    # on the device path (so degraded serving returns the SAME ids as the
    # device would, not an argpartition-arbitrary tied subset).  A full
    # stable argsort over N rows per query is O(N log N) — too slow for
    # the 10M-row degraded scenario, and it runs under _sync_lock.
    # Instead: O(N) argpartition to the kth score, widen to ALL rows tied
    # at that boundary, and stable-sort only that subset.
    out_v = np.empty((q.shape[0], k), np.float32)
    out_i = np.empty((q.shape[0], k), np.int64)
    for qi in range(q.shape[0]):
        s = scores[qi]
        if k < n:
            kth = s[np.argpartition(-s, k - 1)[k - 1]]
            if kth == -np.inf:
                # fewer than k finite scores: `s >= -inf` holds for EVERY
                # row (-inf >= -inf is True), and the boundary widening
                # would stable-sort the whole corpus — O(N log N) under
                # _sync_lock at a 10M-row capacity with a handful of live
                # rows. Only the finite rows can surface (callers drop
                # non-finite scores); sort those and pad below.
                cand = np.nonzero(np.isfinite(s))[0]
            else:
                cand = np.nonzero(s >= kth)[0]  # ascending row order
        else:
            cand = np.arange(n)
        order = np.argsort(-s[cand], kind="stable")[:k]
        sel = cand[order]
        if sel.size < k:
            # fixed-shape pad with the lowest-index unselected rows; their
            # scores are -inf, which _format_results filters out
            mask = np.ones(n, bool)
            mask[sel] = False
            pad = np.nonzero(mask)[0][: k - sel.size]
            sel = np.concatenate([sel, pad])
        out_i[qi] = sel
        out_v[qi] = s[sel]
    return out_v, out_i


def format_topk_results(
    vals: np.ndarray,
    idx: np.ndarray,
    n_queries: int,
    k: int,
    min_similarity: float,
    ids: list,
) -> list[list[tuple[str, float]]]:
    """Resolve top-k slot indices to (id, score) rows — the one shared
    epilogue for the device path, the DEGRADED_CPU host path, and the
    cross-process shared-memory read plane (server/readplane.py), so every
    serving surface resolves results identically by construction.

    ``ids`` must be the slot map captured with the buffer the indices came
    from — resolving against a live map would misattribute results if a
    background compaction remapped the slot space mid-search."""
    out: list[list[tuple[str, float]]] = []
    for qi in range(n_queries):
        row: list[tuple[str, float]] = []
        for v, i in zip(vals[qi], idx[qi]):
            # i < 0 is the merge_topk/IVF sentinel for "no candidate"
            # (padding rows of a near-empty shard / short cluster);
            # a negative index must never reach ids[i] — Python's
            # negative indexing would attribute the LAST id to it
            if i < 0 or not np.isfinite(v) or v < min_similarity:
                continue
            id_ = ids[i] if i < len(ids) else None
            if id_ is not None:
                row.append((id_, float(v)))
        out.append(row[:k])
    return out


def rescore_rows(rows: np.ndarray, qn: np.ndarray) -> np.ndarray:
    """Deterministic exact f32 dot of each row with a NORMALIZED query.

    This — not a BLAS call — is the canonical f32 rescore: BLAS GEMM/GEMV
    kernels change their summation order with the call's shape (measured:
    the same (row, query) dot differs in the last ulp between M=5 and
    M=512 gemv at D>=64), so two differently-shaped calls cannot
    bit-agree. NumPy's pairwise ``sum`` over a fixed D is shape-
    independent, so every consumer of this function — the int8-residency
    rescore epilogue, score_subset's host twin, the bench's rescore
    invariant — produces bit-identical scores for the same (row, query)
    regardless of candidate-set size."""
    return (np.asarray(rows, np.float32) * qn).sum(
        axis=1, dtype=np.float32
    ).astype(np.float32)


def host_score_rows(
    query: np.ndarray, corpus: np.ndarray, rows: np.ndarray
) -> np.ndarray:
    """Exact re-score of candidate rows (host twin of
    ops.similarity.score_subset); query is normalized first. Scores come
    from the deterministic ``rescore_rows`` kernel, so they bit-match the
    int8-residency rescore path for the same rows."""
    q = np.asarray(query, np.float32).reshape(-1)
    n = float(np.linalg.norm(q))
    if n > 1e-12:
        q = q / n
    return rescore_rows(corpus[rows], q)


def quantize_rows_np(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization on the host: the one definition
    of the int8 mirror contract, shared by the compressed-residency upload
    path (parallel.ShardedCorpus), the shared-memory read plane's
    ``rows_i8``/``scales_i8`` export, and anything else that must agree
    bit-for-bit with the device kernels' quantization.

    Matches ops.pallas_kernels.quantize_rows exactly in the codes
    (np.round and jnp.round are both round-half-to-even) and to within a
    float ulp in the scales: x ~= int8 / scale."""
    r = np.asarray(rows, np.float32)
    scale = (127.0 / np.maximum(np.max(np.abs(r), axis=1), 1e-9)).astype(
        np.float32
    )
    codes = np.round(r * scale[:, None]).astype(np.int8)
    return codes, scale
