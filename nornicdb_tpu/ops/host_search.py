"""Pure-NumPy search fallbacks for DEGRADED_CPU serving.

When the BackendManager (nornicdb_tpu.backend) reports the accelerator
lost, the corpora in ops/similarity.py answer from their host arrays
through these routines instead of blocking on a device that may never
come back — the reference's device-failure CPU retry
(pkg/embed/local_gguf.go:202-294) and WindVE's host-side takeover
(PAPERS.md) as one module.

Contract parity with the device path: inputs are L2-normalized rows, so
cosine == dot; scores are EXACT and candidate membership is exact too
(a full argpartition — CPU fallback trades throughput, never recall).
Results are (values, indices) in the same shape/ordering contract as
``ops.similarity.topk_backend`` so ``HostCorpus._format_results``
resolves them identically.
"""

from __future__ import annotations

import numpy as np


def host_topk(
    queries: np.ndarray,
    corpus: np.ndarray,
    valid: np.ndarray,
    k: int,
) -> tuple[np.ndarray, np.ndarray]:
    """(Q, D) x (N, D) -> exact top-k (values (Q, k), indices (Q, k)).

    ``valid`` masks padding/tombstone rows to -inf, mirroring the device
    kernels; k is clamped to the corpus size."""
    q = np.atleast_2d(np.asarray(queries, np.float32))
    n = corpus.shape[0]
    k = max(1, min(k, n))
    scores = q @ corpus.T  # (Q, N); rows are normalized -> cosine
    scores = np.where(valid[None, :], scores, -np.inf)
    if k >= n:
        idx = np.argsort(-scores, axis=1)
        return np.take_along_axis(scores, idx, axis=1), idx
    part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
    part_scores = np.take_along_axis(scores, part, axis=1)
    order = np.argsort(-part_scores, axis=1)
    return (
        np.take_along_axis(part_scores, order, axis=1),
        np.take_along_axis(part, order, axis=1),
    )


def host_score_rows(
    query: np.ndarray, corpus: np.ndarray, rows: np.ndarray
) -> np.ndarray:
    """Exact re-score of candidate rows (host twin of
    ops.similarity.score_subset); query is normalized first."""
    q = np.asarray(query, np.float32).reshape(-1)
    n = float(np.linalg.norm(q))
    if n > 1e-12:
        q = q / n
    return corpus[rows] @ q
