"""K-means clustering on TPU via jit'd JAX.

Replaces the reference's Metal/CUDA k-means kernels
(/root/reference/pkg/gpu/metal/kmeans_kernels_darwin.metal:
kmeans_compute_distances :71, assign_clusters :124, accumulate/finalize
centroids :192-226, compute_drift :259, pp_distances (k-means++) :330)
and the host loop in pkg/gpu/kmeans.go (ClusterIndex :144, Cluster :232,
optimalK :323, SearchWithClusters :816).

TPU-first: the assign step is one (N, D) x (D, K) GEMM on the MXU; the update
step is a segment-sum; Lloyd iterations run under lax.scan so the whole fit is
a single XLA program (no host round-trips per iteration).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def optimal_k(n: int) -> int:
    """Rule-of-thumb cluster count ≈ sqrt(n/2) (ref: optimalK kmeans.go:323)."""
    if n <= 1:
        return 1
    return max(1, int(math.sqrt(n / 2)))


@jax.jit
def pairwise_sq_dists(x: jax.Array, c: jax.Array) -> jax.Array:
    """(N, D), (K, D) -> (N, K) squared distances; cross term on the MXU
    (ref: kmeans_compute_distances kmeans_kernels_darwin.metal:71)."""
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    cn = jnp.sum(c * c, axis=1)[None, :]
    cross = jax.lax.dot_general(
        x, c, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return jnp.maximum(xn - 2.0 * cross + cn, 0.0)


@jax.jit
def assign_clusters(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """(ref: assign_clusters kmeans_kernels_darwin.metal:124)"""
    return jnp.argmin(pairwise_sq_dists(x, centroids), axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def _update_centroids(
    x: jax.Array, assign: jax.Array, old: jax.Array, k: int
) -> jax.Array:
    """Segment-sum centroid update; empty clusters keep their old centroid
    (ref: accumulate_centroids/finalize_centroids metal kernels :192-226)."""
    sums = jax.ops.segment_sum(x, assign, num_segments=k)
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), assign, num_segments=k)
    fresh = sums / jnp.maximum(counts[:, None], 1.0)
    return jnp.where(counts[:, None] > 0, fresh, old)


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def lloyd(
    x: jax.Array, init_centroids: jax.Array, k: int, iters: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fixed-iteration Lloyd refinement as one lax.scan program.

    Returns (centroids (K, D), assignments (N,), drift (iters,)) where drift
    is the mean centroid movement per iteration (ref: compute_drift :259).
    """

    def step(c, _):
        a = assign_clusters(x, c)
        c2 = _update_centroids(x, a, c, k)
        drift = jnp.mean(jnp.linalg.norm(c2 - c, axis=1))
        return c2, drift

    centroids, drifts = jax.lax.scan(step, init_centroids, None, length=iters)
    return centroids, assign_clusters(x, centroids), drifts


@functools.partial(jax.jit, static_argnames=("k",))
def kmeans_pp_init(key: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """k-means++ seeding (ref: pp_distances kmeans_kernels_darwin.metal:330,
    kmeans.go k-means++ init). D^2-weighted sampling, one candidate at a time,
    expressed as a lax.scan over k-1 picks."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centroids = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])

    def pick(carry, i):
        cents, best_d2, key = carry
        # distance to the most recently added centroid
        d2_new = jnp.sum((x - cents[i - 1][None, :]) ** 2, axis=1)
        best_d2 = jnp.minimum(best_d2, d2_new)
        key, sub = jax.random.split(key)
        probs = best_d2 / jnp.maximum(jnp.sum(best_d2), 1e-12)
        idx = jax.random.choice(sub, n, p=probs)
        cents = cents.at[i].set(x[idx])
        return (cents, best_d2, key), None

    init_d2 = jnp.full((n,), jnp.inf, x.dtype)
    (centroids, _, _), _ = jax.lax.scan(
        pick, (centroids, init_d2, key), jnp.arange(1, k)
    )
    return centroids


@dataclass
class KMeansResult:
    centroids: np.ndarray  # (K, D)
    assignments: np.ndarray  # (N,)
    drift: np.ndarray  # (iters,)
    k: int


_ASSIGN_CHUNK = 1 << 18  # rows per chunked full-set assignment program


def kmeans_fit(
    data: np.ndarray,
    k: int = 0,
    iters: int = 10,
    seed: int = 0,
    sample: int = 0,
) -> KMeansResult:
    """Full fit: k-means++ init + Lloyd (ref: ClusterIndex.Cluster kmeans.go:232).

    ``sample > 0`` caps the Lloyd fit at that many uniformly-sampled rows,
    then assigns the FULL set against the fitted centroids in fixed-shape
    chunks (one compiled program reused across chunks). At 10M×1024 a full
    Lloyd pass is iters × N × K × D FLOPs — O(10^13) — while the sampled
    fit plus one chunked assignment sweep is ~50x cheaper with centroid
    quality statistically indistinguishable for recall purposes (the IVF
    tuner measures the layout that comes out either way)."""
    x_np = np.ascontiguousarray(np.asarray(data, np.float32))
    n = x_np.shape[0]
    if k <= 0:
        k = optimal_k(n)
    k = min(k, n)
    if sample and n > sample and sample >= k:
        rng = np.random.default_rng(seed)
        pick = rng.choice(n, size=sample, replace=False)
        sub = kmeans_fit(x_np[pick], k=k, iters=iters, seed=seed)
        cent = jnp.asarray(sub.centroids)
        assignments = np.empty(n, np.int32)
        d = x_np.shape[1]
        for s in range(0, n, _ASSIGN_CHUNK):
            e = min(s + _ASSIGN_CHUNK, n)
            blk = x_np[s:e]
            if e - s < _ASSIGN_CHUNK:
                # pad the tail to a power-of-two bucket, not the full
                # chunk: a few-thousand-row tail (or a barely-over-sample
                # corpus) must not materialize a mostly-zero 256k×D block;
                # the jit caches O(log chunk) shapes either way
                bucket = 1 << max(0, (e - s - 1).bit_length())
                blk = np.concatenate(
                    [blk, np.zeros((bucket - (e - s), d), np.float32)]
                )
            assignments[s:e] = np.asarray(
                assign_clusters(jnp.asarray(blk), cent)
            )[: e - s]
        return KMeansResult(
            centroids=sub.centroids,
            assignments=assignments,
            drift=sub.drift,
            k=sub.k,
        )
    x = jnp.asarray(x_np)
    key = jax.random.PRNGKey(seed)
    init = kmeans_pp_init(key, x, k)
    centroids, assign, drift = lloyd(x, init, k, iters)
    return KMeansResult(
        centroids=np.asarray(centroids),
        assignments=np.asarray(assign),
        drift=np.asarray(drift),
        k=k,
    )


@functools.partial(jax.jit, static_argnames=("n_probe",))
def nearest_clusters(query: jax.Array, centroids: jax.Array, n_probe: int) -> jax.Array:
    """Pick the n_probe closest centroids for cluster-pruned search
    (ref: SearchWithClusters kmeans.go:816)."""
    d = pairwise_sq_dists(query.reshape(1, -1), centroids)[0]
    _, idx = jax.lax.top_k(-d, n_probe)
    return idx
