"""Graph algorithms: TPU-vectorized where the math is dense, host where
it's combinatorial.

Behavioral reference: /root/reference/apoc/algo/ (PageRank, Betweenness/
Closeness/DegreeCentrality, Dijkstra, AStar) and /root/reference/apoc/
community/ (Louvain, LabelPropagation, Modularity, TriangleCount,
ClusteringCoefficient, ConnectedComponents, SCC/WCC, KCore, Conductance,
Density). The reference runs these as Go loops over adjacency maps; here
the iteration-heavy numeric ones (PageRank, WCC min-label propagation,
label propagation) are edge-array programs under `jax.jit` — contributions
flow along edges via `segment_sum`/`segment_min`, which XLA lowers to
TPU-friendly scatter-adds over static shapes — and the inherently
sequential ones (Brandes betweenness, Tarjan SCC, k-core peeling, Louvain,
Dijkstra/A*) run on host over numpy edge arrays.

Edge-array convention: graphs arrive as (src, dst) int32 arrays of node
indices [0, n). Directed edges; undirected algorithms symmetrize
internally.
"""

from __future__ import annotations

import functools
import heapq
from collections import defaultdict
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# TPU path: PageRank (ref: apoc/algo PageRank — damped power iteration)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)  # jit cache is keyed on fn identity; memoize
def _pagerank_jit(n: int, damping: float, iters: int):
    @jax.jit
    def run(src: jnp.ndarray, dst: jnp.ndarray) -> jnp.ndarray:
        out_deg = jax.ops.segment_sum(
            jnp.ones_like(src, dtype=jnp.float32), src, num_segments=n)
        safe_deg = jnp.maximum(out_deg, 1.0)

        def body(_, rank):
            contrib = rank[src] / safe_deg[src]
            incoming = jax.ops.segment_sum(contrib, dst, num_segments=n)
            # dangling nodes redistribute uniformly (standard PageRank fix)
            dangling = jnp.sum(jnp.where(out_deg == 0, rank, 0.0))
            return (1.0 - damping) / n + damping * (incoming + dangling / n)

        rank0 = jnp.full((n,), 1.0 / n, dtype=jnp.float32)
        return jax.lax.fori_loop(0, iters, body, rank0)

    return run


def pagerank(src: np.ndarray, dst: np.ndarray, n: int,
             damping: float = 0.85, iters: int = 20) -> np.ndarray:
    if n == 0:
        return np.zeros((0,), dtype=np.float32)
    if len(src) == 0:
        return np.full((n,), 1.0 / n, dtype=np.float32)
    run = _pagerank_jit(n, float(damping), int(iters))
    return np.asarray(run(jnp.asarray(src, jnp.int32),
                          jnp.asarray(dst, jnp.int32)))


# ---------------------------------------------------------------------------
# TPU path: connected components via min-label propagation
# (ref: community ConnectedComponents/WeaklyConnectedComponents)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _wcc_jit(n: int):
    @jax.jit
    def run(src: jnp.ndarray, dst: jnp.ndarray) -> jnp.ndarray:
        labels0 = jnp.arange(n, dtype=jnp.int32)

        def cond(state):
            labels, changed = state
            return changed

        def body(state):
            labels, _ = state
            # push the smaller label across every (symmetrized) edge
            upd = jax.ops.segment_min(labels[src], dst, num_segments=n)
            new = jnp.minimum(labels, upd)
            return new, jnp.any(new != labels)

        labels, _ = jax.lax.while_loop(cond, body, (labels0, jnp.bool_(True)))
        return labels

    return run


def connected_components(src: np.ndarray, dst: np.ndarray,
                         n: int) -> np.ndarray:
    """Weakly connected components; returns a component label per node
    (the smallest member index)."""
    if n == 0:
        return np.zeros((0,), dtype=np.int32)
    if len(src) == 0:
        return np.arange(n, dtype=np.int32)
    s = np.concatenate([src, dst]).astype(np.int32)
    d = np.concatenate([dst, src]).astype(np.int32)
    return np.asarray(_wcc_jit(n)(jnp.asarray(s), jnp.asarray(d)))


# ---------------------------------------------------------------------------
# TPU path: label propagation (ref: community LabelPropagation) — each
# round a node adopts the label with the highest incident weight; one-hot
# scatter keeps it a fixed-shape segment_sum program.
# ---------------------------------------------------------------------------


def label_propagation(src: np.ndarray, dst: np.ndarray, n: int,
                      iters: int = 10) -> np.ndarray:
    if n == 0:
        return np.zeros((0,), dtype=np.int32)
    if len(src) == 0:
        return np.arange(n, dtype=np.int32)
    s = np.concatenate([src, dst]).astype(np.int32)
    d = np.concatenate([dst, src]).astype(np.int32)
    labels = np.arange(n, dtype=np.int32)
    # host loop with numpy bincount per round: label domains shrink every
    # round, so dense one-hot (n×n) on device would waste HBM; this stays
    # O(E) per round
    for _ in range(int(iters)):
        counts: dict[tuple[int, int], int] = defaultdict(int)
        for a, b in zip(d, labels[s]):
            counts[(int(a), int(b))] += 1
        new = labels.copy()
        best: dict[int, tuple[int, int]] = {}
        for (node, lab), c in counts.items():
            cur = best.get(node)
            # deterministic: higher count wins, ties -> smaller label
            if cur is None or c > cur[0] or (c == cur[0] and lab < cur[1]):
                best[node] = (c, lab)
        for node, (_, lab) in best.items():
            new[node] = lab
        if np.array_equal(new, labels):
            break
        labels = new
    return labels


# ---------------------------------------------------------------------------
# Host path: degree / closeness / betweenness centrality (ref: apoc/algo)
# ---------------------------------------------------------------------------


def _adj(src, dst, n, undirected=True) -> list[list[int]]:
    adj: list[list[int]] = [[] for _ in range(n)]
    for a, b in zip(src, dst):
        adj[int(a)].append(int(b))
        if undirected:
            adj[int(b)].append(int(a))
    return adj


def build_csr(src: np.ndarray, dst: np.ndarray, n: int,
              undirected: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """(offsets, neighbors) CSR arrays — the frontier-batched BFS input
    shape the adjacency snapshot (storage/adjacency.py) also serves."""
    s = np.asarray(src, np.int64)
    d = np.asarray(dst, np.int64)
    if undirected:
        s, d = np.concatenate([s, d]), np.concatenate([d, s])
    counts = np.bincount(s, minlength=n) if s.size else np.zeros(n, np.int64)
    offsets = np.zeros(n + 1, np.int64)
    offsets[1:] = np.cumsum(counts)
    order = np.argsort(s, kind="stable")
    return offsets, d[order].astype(np.int32)


def _frontier_neighbors(offsets: np.ndarray, neighbors: np.ndarray,
                        frontier: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
    """One batched gather: (heads, nbrs) for every CSR entry of `frontier`
    — replaces a per-node Python adjacency loop per BFS level."""
    starts = offsets[frontier]
    cnts = offsets[frontier + 1] - starts
    total = int(cnts.sum())
    if total == 0:
        empty = np.zeros(0, np.int64)
        return empty, empty
    shift = np.repeat(np.cumsum(cnts) - cnts, cnts)
    gather = np.repeat(starts, cnts) + np.arange(total) - shift
    return np.repeat(frontier, cnts), neighbors[gather].astype(np.int64)


def bfs_distances_csr(offsets: np.ndarray, neighbors: np.ndarray,
                      source: int, n: int) -> np.ndarray:
    """Unweighted hop distances from `source` (-1 unreached), one numpy
    gather + dedup per level."""
    dist = np.full(n, -1, np.int32)
    dist[source] = 0
    frontier = np.asarray([source], np.int64)
    level = 0
    while frontier.size:
        _, nbrs = _frontier_neighbors(offsets, neighbors, frontier)
        if not nbrs.size:
            break
        nbrs = nbrs[dist[nbrs] < 0]
        if not nbrs.size:
            break
        frontier = np.unique(nbrs)
        level += 1
        dist[frontier] = level
    return dist


def degree_centrality(src: np.ndarray, dst: np.ndarray, n: int,
                      direction: str = "both") -> np.ndarray:
    out = np.zeros((n,), dtype=np.float32)
    if direction in ("both", "out"):
        np.add.at(out, src.astype(int), 1.0)
    if direction in ("both", "in"):
        np.add.at(out, dst.astype(int), 1.0)
    return out


def closeness_centrality(src, dst, n) -> np.ndarray:
    """closeness(v) = (reachable-1) / sum(dist) scaled by reachable/n
    (the Wasserman-Faust variant the reference uses). Per-source BFS runs
    over CSR arrays with batched frontier gathers instead of a Python
    adjacency loop per node."""
    out = np.zeros((n,), dtype=np.float32)
    if n == 0:
        return out
    offsets, neighbors = build_csr(src, dst, n)
    for v in range(n):
        dist = bfs_distances_csr(offsets, neighbors, v, n)
        reached = dist > 0
        total = int(dist[reached].sum())
        reach = int(reached.sum())
        if total > 0 and reach > 0:
            out[v] = (reach / total) * (reach / max(n - 1, 1))
    return out


def betweenness_centrality(src, dst, n) -> np.ndarray:
    """Brandes' algorithm (exact, unweighted) over CSR arrays: the forward
    pass is a frontier-batched BFS per source (sigma accumulated with
    scatter-adds over each level's edge batch), the backward pass replays
    the recorded level batches in reverse — no per-edge Python loops."""
    bc = np.zeros((n,), dtype=np.float64)
    if n == 0:
        return bc.astype(np.float32)
    offsets, neighbors = build_csr(src, dst, n)
    for s in range(n):
        sigma = np.zeros(n)
        sigma[s] = 1.0
        dist = np.full(n, -1, np.int64)
        dist[s] = 0
        frontier = np.asarray([s], np.int64)
        level_edges: list[tuple[np.ndarray, np.ndarray]] = []
        level = 0
        while frontier.size:
            heads, nbrs = _frontier_neighbors(offsets, neighbors, frontier)
            if not nbrs.size:
                break
            newly = nbrs[dist[nbrs] < 0]
            if newly.size:
                newly = np.unique(newly)
                dist[newly] = level + 1
            keep = dist[nbrs] == level + 1
            h, w = heads[keep], nbrs[keep]
            if w.size:
                np.add.at(sigma, w, sigma[h])
                level_edges.append((h, w))
            frontier = newly
            level += 1
        delta = np.zeros(n)
        for h, w in reversed(level_edges):
            np.add.at(delta, h, sigma[h] / sigma[w] * (1.0 + delta[w]))
        visited = dist >= 0
        visited[s] = False
        bc[visited] += delta[visited]
    return (bc / 2.0).astype(np.float32)  # undirected double-count


# ---------------------------------------------------------------------------
# Host path: triangles / clustering (ref: community TriangleCount)
# ---------------------------------------------------------------------------


def triangle_counts(src, dst, n) -> np.ndarray:
    nbrs: list[set[int]] = [set() for _ in range(n)]
    for a, b in zip(src, dst):
        a, b = int(a), int(b)
        if a != b:
            nbrs[a].add(b)
            nbrs[b].add(a)
    out = np.zeros((n,), dtype=np.int64)
    for v in range(n):
        for u in nbrs[v]:
            if u > v:
                common = nbrs[v] & nbrs[u]
                for w in common:
                    if w > u:
                        out[v] += 1
                        out[u] += 1
                        out[w] += 1
    return out


def clustering_coefficient(src, dst, n) -> np.ndarray:
    tri = triangle_counts(src, dst, n)
    nbrs: list[set[int]] = [set() for _ in range(n)]
    for a, b in zip(src, dst):
        a, b = int(a), int(b)
        if a != b:
            nbrs[a].add(b)
            nbrs[b].add(a)
    out = np.zeros((n,), dtype=np.float32)
    for v in range(n):
        k = len(nbrs[v])
        if k >= 2:
            out[v] = 2.0 * tri[v] / (k * (k - 1))
    return out


# ---------------------------------------------------------------------------
# Host path: SCC (Tarjan, iterative), k-core peeling
# ---------------------------------------------------------------------------


def strongly_connected_components(src, dst, n) -> np.ndarray:
    adj = _adj(src, dst, n, undirected=False)
    index = np.full((n,), -1)
    low = np.zeros((n,), dtype=np.int64)
    on_stack = np.zeros((n,), dtype=bool)
    comp = np.full((n,), -1, dtype=np.int32)
    stack: list[int] = []
    counter = 0
    n_comp = 0
    for root in range(n):
        if index[root] >= 0:
            continue
        work: list[tuple[int, int]] = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i]
                if index[w] < 0:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp[w] = n_comp
                    if w == v:
                        break
                n_comp += 1
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return comp


def k_core(src, dst, n) -> np.ndarray:
    """Core number per node (peeling)."""
    nbrs: list[set[int]] = [set() for _ in range(n)]
    for a, b in zip(src, dst):
        a, b = int(a), int(b)
        if a != b:
            nbrs[a].add(b)
            nbrs[b].add(a)
    deg = np.array([len(s) for s in nbrs])
    core = np.zeros((n,), dtype=np.int32)
    alive = set(range(n))
    k = 0
    while alive:
        peel = [v for v in alive if deg[v] <= k]
        if not peel:
            k += 1
            continue
        for v in peel:
            core[v] = k
            alive.discard(v)
            for u in nbrs[v]:
                if u in alive:
                    deg[u] -= 1
    return core


# ---------------------------------------------------------------------------
# Host path: Louvain (one-pass greedy + aggregation) and modularity
# (ref: community Louvain/Modularity)
# ---------------------------------------------------------------------------


def modularity(src, dst, n, labels) -> float:
    m = len(src)
    if m == 0:
        return 0.0
    deg = np.zeros((n,))
    np.add.at(deg, src.astype(int), 1.0)
    np.add.at(deg, dst.astype(int), 1.0)
    labels = np.asarray(labels)
    q = 0.0
    for a, b in zip(src, dst):
        if labels[int(a)] == labels[int(b)]:
            q += 1.0
    q /= m
    comm_deg: dict[Any, float] = defaultdict(float)
    for v in range(n):
        comm_deg[labels[v]] += deg[v]
    q -= sum((d / (2.0 * m)) ** 2 for d in comm_deg.values())
    return float(q)


def louvain(src, dst, n, max_passes: int = 5) -> np.ndarray:
    """Greedy modularity optimization, local-move phase repeated until no
    gain (single level — the reference's DefaultLouvainConfig similarly
    bounds passes)."""
    if n == 0:
        return np.zeros((0,), dtype=np.int32)
    nbrs: list[dict[int, float]] = [defaultdict(float) for _ in range(n)]
    for a, b in zip(src, dst):
        a, b = int(a), int(b)
        if a != b:
            nbrs[a][b] += 1.0
            nbrs[b][a] += 1.0
    m = max(len(src), 1)
    deg = np.array([sum(d.values()) for d in nbrs])
    labels = np.arange(n, dtype=np.int32)
    comm_deg = deg.astype(np.float64).copy()
    for _ in range(max_passes):
        moved = False
        for v in range(n):
            cur = labels[v]
            comm_deg[cur] -= deg[v]
            weights: dict[int, float] = defaultdict(float)
            for u, w in nbrs[v].items():
                weights[labels[u]] += w
            best_c, best_gain = cur, 0.0
            for c, w_in in weights.items():
                gain = w_in / m - comm_deg[c] * deg[v] / (2.0 * m * m)
                if gain > best_gain + 1e-12:
                    best_c, best_gain = c, gain
            labels[v] = best_c
            comm_deg[best_c] += deg[v]
            if best_c != cur:
                moved = True
        if not moved:
            break
    # compact labels
    _, compact = np.unique(labels, return_inverse=True)
    return compact.astype(np.int32)


def density(src, dst, n) -> float:
    if n < 2:
        return 0.0
    return float(len(src)) / (n * (n - 1))


def conductance(src, dst, n, labels, community) -> float:
    """cut(S, V\\S) / min(vol(S), vol(V\\S))."""
    labels = np.asarray(labels)
    cut = vol_in = vol_out = 0
    for a, b in zip(src, dst):
        a_in = labels[int(a)] == community
        b_in = labels[int(b)] == community
        if a_in != b_in:
            cut += 1
        if a_in:
            vol_in += 1
        else:
            vol_out += 1
        if b_in:
            vol_in += 1
        else:
            vol_out += 1
    denom = min(vol_in, vol_out)
    return float(cut) / denom if denom else 0.0


# ---------------------------------------------------------------------------
# Host path: weighted shortest paths (ref: apoc/algo Dijkstra/AStar)
# ---------------------------------------------------------------------------


def dijkstra(adj: dict[int, list[tuple[int, float]]], start: int,
             goal: Optional[int] = None,
             heuristic: Optional[Callable[[int], float]] = None,
             ) -> tuple[dict[int, float], dict[int, int]]:
    """Returns (dist, prev). With `heuristic` this is A* toward `goal`."""
    dist = {start: 0.0}
    prev: dict[int, int] = {}
    h0 = heuristic(start) if heuristic else 0.0
    pq: list[tuple[float, int]] = [(h0, start)]
    done: set[int] = set()
    while pq:
        _, v = heapq.heappop(pq)
        if v in done:
            continue
        done.add(v)
        if goal is not None and v == goal:
            break
        for w, cost in adj.get(v, []):
            nd = dist[v] + cost
            if nd < dist.get(w, float("inf")):
                dist[w] = nd
                prev[w] = v
                f = nd + (heuristic(w) if heuristic else 0.0)
                heapq.heappush(pq, (f, w))
    return dist, prev


def reconstruct_path(prev: dict[int, int], start: int, goal: int) -> list[int]:
    if goal != start and goal not in prev:
        return []
    path = [goal]
    while path[-1] != start:
        path.append(prev[path[-1]])
    return list(reversed(path))
