"""Configuration: YAML file + environment + runtime feature flags.

Behavioral reference: /root/reference/pkg/config/config.go:82-420
(Config, LoadFromFile/LoadFromEnv, FindConfigFile discovery),
feature_flags.go:210-506 (mutex-guarded flag registry with helpers like
IsKalmanEnabled/IsAutoTLPEnabled and test helpers WithXEnabled).
Precedence: explicit args > YAML > env > defaults
(ref: cmd/nornicdb/main.go:246-309).
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field, fields
from typing import Any, Optional

CONFIG_FILENAMES = ("nornicdb.yaml", "nornicdb.yml", ".nornicdb.yaml")
ENV_PREFIX = "NORNICDB_"


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    http_port: int = 7474
    bolt_port: int = 7687
    auth_enabled: bool = False
    base_path: str = ""
    jwt_secret: str = ""
    token_ttl: float = 24 * 3600.0
    max_failed_logins: int = 5
    lockout_duration: float = 300.0


@dataclass
class DatabaseConfig:
    data_dir: str = ""
    encryption_enabled: bool = False
    encryption_key: str = ""
    async_writes: bool = True
    wal_sync: bool = False
    auto_compact_interval: float = 300.0


@dataclass
class EmbeddingConfig:
    enabled: bool = True
    provider: str = "tpu"  # tpu | hash
    dimensions: int = 1024
    chunk_tokens: int = 512
    chunk_overlap: int = 50
    workers: int = 1
    cache_size: int = 10000


@dataclass
class MemoryConfig:
    decay_enabled: bool = False
    decay_interval: float = 3600.0
    archive_threshold: float = 0.05
    query_cache_size: int = 1000
    query_cache_ttl: float = 60.0


@dataclass
class ComplianceConfig:
    audit_enabled: bool = False
    audit_path: str = ""
    retention_enabled: bool = False


@dataclass
class TelemetryConfig:
    """Knobs for the process-global telemetry layer (nornicdb_tpu.telemetry):
    applied via ``telemetry.configure(**vars(cfg.telemetry))`` at server
    startup; the same knobs are env-readable at import time
    (NORNICDB_TRACING / NORNICDB_TRACE_SAMPLE / NORNICDB_SLOW_QUERY_MS)."""

    tracing_enabled: bool = True
    trace_sample: float = 1.0  # fraction of ingress requests traced
    trace_buffer: int = 256  # completed traces kept for /admin/traces
    slow_query_ms: float = 1000.0  # 0 disables slow-query capture
    slow_buffer: int = 128  # entries kept for /admin/slow-queries
    # fleet federation: a worker exposition older than this at scrape
    # time is dropped from the merged /metrics (dead worker / wedged
    # publisher segments must age out, not flatline forever)
    fleet_staleness_s: float = 10.0
    # upper bound for POST /admin/profile?seconds=N jax.profiler captures
    profile_max_seconds: float = 60.0
    # predictive admission (telemetry/costmodel.py): predictions are
    # multiplied by cost_conservatism before the deadline comparison, and
    # admission fails OPEN while model confidence sits below
    # cost_min_confidence (a cold model must never turn traffic away)
    cost_conservatism: float = 1.5
    cost_min_confidence: float = 0.25
    predictive_admission: bool = True
    # per-route latency SLO targets, "route=ms,route=ms" — feeds the
    # nornicdb_slo_burn_rate gauges (docs/capacity.md)
    slo_targets: str = "embed=250,search=250,generate=5000"
    # SLO objective: burn rate = miss fraction / (1 - objective)
    slo_objective: float = 0.99


@dataclass
class BackendConfig:
    """Device lifecycle knobs (nornicdb_tpu.backend.BackendManager):
    applied by ``cli serve`` via ``backend.configure(cfg.backend)`` before
    servers take traffic.  See docs/backend.md for the state machine and
    the failure playbook these knobs tune."""

    # seconds a caller waits for PJRT init + first-touch before serving
    # from CPU host arrays (the init keeps running on the manager's
    # worker thread; recovery is automatic when it completes)
    acquire_timeout: float = 15.0
    # health-probe cadence and per-probe budget
    probe_interval: float = 5.0
    probe_timeout: float = 5.0
    # a green probe slower than this counts as a failure (sick-but-alive
    # accelerators must degrade too, not just dead ones)
    probe_latency_threshold: float = 1.0
    # hysteresis: consecutive failures before READY -> DEGRADED_CPU, and
    # consecutive green probes before DEGRADED_CPU -> RECOVERING
    degrade_after: int = 3
    recover_after: int = 2
    # "cpu" serves degraded requests from host arrays; "fail" raises
    # DeviceUnavailable to the caller instead (strict deployments)
    fallback: str = "cpu"
    # recovery re-upload: "full" re-ships the whole corpus (device memory
    # assumed lost), "dirty" trusts a surviving resident buffer and only
    # patches blocks written while degraded
    recovery_reupload: str = "full"


@dataclass
class ServingConfig:
    """Continuous batching engine knobs (nornicdb_tpu.serving): applied by
    ``cli serve`` — the engine wraps the production embedder, so every
    embed path (HTTP /nornicdb/embed, query embedding, the background
    EmbedWorker) batches continuously with admission control.  Env form:
    ``NORNICDB_SERVING_<FIELD>``.  See docs/operations.md "Embed serving
    tuning"."""

    # master switch for the continuous batching engine
    enabled: bool = True
    # production embedder selection: "full" = the configured encoder as
    # is; "student" = the distilled checkpoint at student_model_dir,
    # admitted ONLY when its eval MRR clears student_min_mrr (the config
    # is rejected at startup otherwise — serving/student_gate.py)
    embedder: str = "full"
    student_model_dir: str = ""
    student_min_mrr: float = 0.6
    student_eval_suite: str = ""  # JSON suite path; "" = builtin suite
    # admission control: queued texts/tokens beyond these shed new
    # requests with 429/RESOURCE_EXHAUSTED (an empty queue always admits)
    max_queue: int = 4096
    max_queue_tokens: int = 262144
    # per-request deadline; expired work is shed pre-dispatch and waiting
    # callers give up at deadline + grace. 0 disables (not recommended
    # for serving — the deadline is the no-indefinite-block guarantee)
    deadline_ms: float = 2000.0
    # batch window under low queue depth (a deep queue dispatches
    # immediately at max_batch_tokens)
    batch_wait_ms: float = 2.0
    # ragged scheduler: token budget per packed dispatch + row-grid bound
    max_batch_tokens: int = 8192
    max_rows: int = 16
    # host staging pipeline depth (double buffering; >=1)
    staging_depth: int = 2


@dataclass
class GenServeConfig:
    """Continuous-batching generation engine knobs (nornicdb_tpu.genserve):
    applied by ``cli serve`` via ``genserve.configure(cfg.genserve)``.  The
    engine serves Heimdall chat/QC and the GraphRAG answer endpoint from a
    paged KV cache with prefill/decode interleaving — see
    docs/generation.md.  Env form: ``NORNICDB_GENSERVE_<FIELD>`` (e.g.
    ``NORNICDB_GENSERVE_PAGE_SIZE``, ``NORNICDB_GENSERVE_POOL_PAGES``,
    ``NORNICDB_GENSERVE_MAX_SEQS``, ``NORNICDB_GENSERVE_DEADLINE_MS``,
    ``NORNICDB_GENSERVE_FALLBACK``)."""

    # master switch: off = Heimdall keeps the synchronous per-request path
    enabled: bool = True
    # "paged" = paged-KV continuous batching; "dense" = the per-sequence
    # dense-cache fallback path (numerically equivalent, no cross-request
    # decode batching — the equivalence reference and escape hatch)
    mode: str = "paged"
    # KV page geometry: slots per page and physical pages in the pool
    # (one page is reserved as the null/scratch page)
    page_size: int = 16
    pool_pages: int = 129
    # concurrency + per-sequence bound (prompt + generated tokens; the
    # page-table width is max_seq_tokens / page_size)
    max_seqs: int = 8
    max_seq_tokens: int = 256
    # max tokens per interleaved prefill chunk (bucketed to powers of two
    # so jits stay bounded)
    prefill_chunk: int = 64
    # admission control: queued requests beyond this shed with
    # 429/RESOURCE_EXHAUSTED (an empty queue always admits)
    max_queue: int = 64
    # per-request deadline; expired requests are shed (0 disables — not
    # recommended: the deadline is the no-indefinite-block guarantee)
    deadline_ms: float = 10000.0
    # degraded backend policy: "cpu" re-prefills and decodes on host,
    # "fail" raises DeviceUnavailable instead (strict deployments)
    fallback: str = "cpu"
    # GraphRAG answer endpoint: retrieved context nodes + decode budget
    rag_context_nodes: int = 5
    rag_max_new_tokens: int = 64


@dataclass
class WorkersConfig:
    """Prefork protocol workers (server/workers.py): multi-core scale-out
    for the protocol surface, applied by ``cli serve``.  Workers are
    subprocesses binding a shared public port with SO_REUSEPORT; vector
    search is served through the primary's device broker (fused
    cross-worker device dispatch) with a shared-memory host-search
    fallback.  Env form: ``NORNICDB_WORKERS_<FIELD>``.  See
    docs/operations.md "Multi-process serving"."""

    # worker processes fronting the HTTP surface (0 disables the pool)
    http: int = 0
    # worker processes fronting the native gRPC search surface (needs
    # NORNICDB_GRPC_ENABLED; they share the HTTP pool's device broker)
    grpc: int = 0
    # public port the HTTP worker pool binds (0 = ephemeral, printed at
    # startup); gRPC workers use grpc_port the same way
    port: int = 0
    grpc_port: int = 0
    # device broker (one PJRT owner, fused cross-worker search/embed
    # batches over a Unix socket) — disabling it degrades workers to
    # cache + proxy only
    broker: bool = True
    # shared-memory read plane (corpus + CSR adjacency segments): the
    # workers' host-search fallback when the broker is down or the
    # backend is DEGRADED_CPU
    read_plane: bool = True
    # respawn crashed workers automatically
    respawn: bool = True
    # shared-segment republish cadence in seconds: worker reads are at
    # most this stale; each publish copies the corpus host arrays, so
    # raise it for very large corpora under constant writes
    publish_interval: float = 0.05
    # fleet telemetry: workers publish their metrics registry (and
    # slow-query ring) into per-proc shm segments the primary's /metrics
    # merges under a proc label (docs/observability.md "Metrics
    # federation & staleness")
    metrics: bool = True
    metrics_interval: float = 0.5
    # per-worker token bucket mirrored BEFORE the response cache
    # (effective ceiling is n_workers x rate); 0 disables
    rate_limit: float = 0.0
    rate_burst: float = 0.0


@dataclass
class SearchTuningConfig:
    """Vector-serving knobs (nornicdb_tpu.search.SearchConfig): applied by
    ``cli serve`` via ``search.service.configure_defaults`` before the
    first SearchService is built.  The same knobs are env-readable as
    ``NORNICDB_SEARCH_<FIELD>`` for embedded processes.  See
    docs/operations.md "Sharded serving tuning"."""

    # auto | tpu | sharded | hnsw — "auto" starts single-device and
    # promotes to the mesh-sharded path past sharded_min_rows
    backend: str = "auto"
    sharded_min_rows: int = 100_000
    # recall knobs: exact full-sort, per-shard candidate oversampling,
    # IVF probe count (0 = tuner-governed; explicit values bypass the
    # recall eval gate — debugging only, see docs/operations.md
    # "Recall tuning")
    exact: bool = False
    local_k: int = 0
    n_probe: int = 0
    # recall-governed IVF autotuning: operators set the floor, the tuner
    # measures and picks (n_probe, local_k); floors it can't meet serve
    # full scan (nornicdb_ivf_tunes_total{outcome="floor_unmet"})
    recall_target: float = 0.95
    tune_enabled: bool = True
    tune_sample: int = 64
    tune_k: int = 100
    tune_min_rows: int = 4096
    drift_threshold: float = 0.25
    cluster_fit_sample: int = 262_144
    # int8 compressed residency for the sharded corpus: device holds int8
    # codes + scales (≈4x rows/HBM byte), merged candidates exact-rescored
    # in f32 from the host mirror (oversampled rescore_factor × k)
    int8_residency: bool = False
    rescore_factor: int = 4
    # micro-batching + write-behind sync (PR 2)
    batching_enabled: bool = False
    batch_window: float = 0.002
    batch_max: int = 256
    # batched-search admission: pending queries beyond batch_max_queue
    # shed with 429/RESOURCE_EXHAUSTED (0 = unbounded); queries older
    # than batch_deadline_ms at dispatch are shed too (0 disables)
    batch_max_queue: int = 1024
    batch_deadline_ms: float = 0.0
    write_behind: bool = False


@dataclass
class AppConfig:
    server: ServerConfig = field(default_factory=ServerConfig)
    database: DatabaseConfig = field(default_factory=DatabaseConfig)
    embedding: EmbeddingConfig = field(default_factory=EmbeddingConfig)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    compliance: ComplianceConfig = field(default_factory=ComplianceConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    backend: BackendConfig = field(default_factory=BackendConfig)
    search: SearchTuningConfig = field(default_factory=SearchTuningConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    genserve: GenServeConfig = field(default_factory=GenServeConfig)
    workers: WorkersConfig = field(default_factory=WorkersConfig)


def find_config_file(start_dir: str = ".") -> Optional[str]:
    """(ref: FindConfigFile config.go)"""
    d = os.path.abspath(start_dir)
    while True:
        for name in CONFIG_FILENAMES:
            p = os.path.join(d, name)
            if os.path.exists(p):
                return p
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _apply_dict(cfg: Any, data: dict) -> None:
    for f in fields(cfg):
        if f.name in data:
            v = data[f.name]
            current = getattr(cfg, f.name)
            if hasattr(current, "__dataclass_fields__") and isinstance(v, dict):
                _apply_dict(current, v)
            else:
                setattr(cfg, f.name, type(current)(v) if current is not None else v)


def load_from_file(path: str, cfg: Optional[AppConfig] = None) -> AppConfig:
    import yaml

    cfg = cfg or AppConfig()
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    _apply_dict(cfg, data)
    return cfg


# the reference's flat env names -> (section, field) here, so a user
# migrating from the reference keeps their environment working
# (ref: pkg/config/config.go LoadFromEnv + cmd/nornicdb/main.go:108-141)
ENV_ALIASES: dict[str, tuple[str, str]] = {
    "NORNICDB_DATA_DIR": ("database", "data_dir"),
    "NORNICDB_HTTP_PORT": ("server", "http_port"),
    "NORNICDB_BOLT_PORT": ("server", "bolt_port"),
    "NORNICDB_ADDRESS": ("server", "host"),
    "NORNICDB_HOST": ("server", "host"),
    "NORNICDB_AUTH": ("server", "auth_enabled"),
    "NORNICDB_AUTH_ENABLED": ("server", "auth_enabled"),
    "NORNICDB_BASE_PATH": ("server", "base_path"),
    "NORNICDB_AUTH_JWT_SECRET": ("server", "jwt_secret"),
    "NORNICDB_AUTH_TOKEN_EXPIRY": ("server", "token_ttl"),
    "NORNICDB_MAX_FAILED_LOGINS": ("server", "max_failed_logins"),
    "NORNICDB_LOCKOUT_DURATION": ("server", "lockout_duration"),
    "NORNICDB_ENCRYPTION_AT_REST": ("database", "encryption_enabled"),
    "NORNICDB_ENCRYPTION_KEY": ("database", "encryption_key"),
    "NORNICDB_ASYNC_WRITES_ENABLED": ("database", "async_writes"),
    "NORNICDB_STRICT_DURABILITY": ("database", "wal_sync"),
    "NORNICDB_EMBEDDING_ENABLED": ("embedding", "enabled"),
    "NORNICDB_EMBEDDING_PROVIDER": ("embedding", "provider"),
    "NORNICDB_EMBEDDING_DIMENSIONS": ("embedding", "dimensions"),
    "NORNICDB_EMBEDDING_CACHE_SIZE": ("embedding", "cache_size"),
    "NORNICDB_EMBEDDING_WORKERS": ("embedding", "workers"),
    "NORNICDB_MEMORY_DECAY_ENABLED": ("memory", "decay_enabled"),
    "NORNICDB_MEMORY_DECAY_INTERVAL": ("memory", "decay_interval"),
    "NORNICDB_QUERY_CACHE_SIZE": ("memory", "query_cache_size"),
    "NORNICDB_QUERY_CACHE_TTL": ("memory", "query_cache_ttl"),
    "NORNICDB_AUDIT_ENABLED": ("compliance", "audit_enabled"),
    "NORNICDB_AUDIT_LOG_PATH": ("compliance", "audit_path"),
    "NORNICDB_RETENTION_ENABLED": ("compliance", "retention_enabled"),
    # device lifecycle (the generic NORNICDB_BACKEND_<FIELD> forms work
    # too; these shorter aliases match the reference's GPU knob style)
    "NORNICDB_DEVICE_ACQUIRE_TIMEOUT": ("backend", "acquire_timeout"),
    "NORNICDB_DEVICE_PROBE_INTERVAL": ("backend", "probe_interval"),
    "NORNICDB_DEVICE_PROBE_TIMEOUT": ("backend", "probe_timeout"),
    "NORNICDB_DEVICE_FALLBACK": ("backend", "fallback"),
    "NORNICDB_DEVICE_RECOVERY_REUPLOAD": ("backend", "recovery_reupload"),
    # continuous batching engine (generic NORNICDB_SERVING_<FIELD> forms
    # work too; these short aliases cover the common operational knobs)
    "NORNICDB_EMBED_DEADLINE_MS": ("serving", "deadline_ms"),
    "NORNICDB_EMBED_MAX_QUEUE": ("serving", "max_queue"),
    "NORNICDB_STUDENT_MODEL": ("serving", "student_model_dir"),
    "NORNICDB_STUDENT_MIN_MRR": ("serving", "student_min_mrr"),
    # prefork worker pool (the generic NORNICDB_WORKERS_<FIELD> forms
    # work too; these aliases match the reference's worker knob style)
    "NORNICDB_HTTP_WORKERS": ("workers", "http"),
    "NORNICDB_GRPC_WORKERS": ("workers", "grpc"),
    "NORNICDB_WORKER_PORT": ("workers", "port"),
    "NORNICDB_TRACING": ("telemetry", "tracing_enabled"),
    "NORNICDB_TRACE_SAMPLE": ("telemetry", "trace_sample"),
    "NORNICDB_TRACE_BUFFER": ("telemetry", "trace_buffer"),
    "NORNICDB_SLOW_QUERY_MS": ("telemetry", "slow_query_ms"),
    "NORNICDB_SLOW_QUERY_BUFFER": ("telemetry", "slow_buffer"),
}


def _coerce_env(current: Any, raw: str) -> Any:
    if isinstance(current, bool):
        # the reference's WAL sync mode takes words, not just booleans
        return raw.lower() in ("1", "true", "yes", "always", "sync")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    return raw


def load_from_env(cfg: Optional[AppConfig] = None) -> AppConfig:
    """NORNICDB_<SECTION>_<FIELD>, plus the reference's flat names via
    ENV_ALIASES; the section form wins when both are set
    (ref: LoadFromEnv)."""
    cfg = cfg or AppConfig()
    for env, (section_name, field_name) in ENV_ALIASES.items():
        if env in os.environ:
            section = getattr(cfg, section_name)
            current = getattr(section, field_name)
            setattr(section, field_name, _coerce_env(current, os.environ[env]))
    for section_field in fields(cfg):
        section = getattr(cfg, section_field.name)
        for f in fields(section):
            env = f"{ENV_PREFIX}{section_field.name.upper()}_{f.name.upper()}"
            if env in os.environ:
                current = getattr(section, f.name)
                setattr(section, f.name,
                        _coerce_env(current, os.environ[env]))
    return cfg


def load(start_dir: str = ".") -> AppConfig:
    cfg = AppConfig()
    path = find_config_file(start_dir)
    if path:
        load_from_file(path, cfg)
    load_from_env(cfg)
    return cfg


# ---------------------------------------------------------------- flags
class FeatureFlags:
    """Runtime feature-flag registry (ref: feature_flags.go:210-506)."""

    DEFAULTS = {
        "kalman": True,
        "auto_tlp": True,
        "llm_qc": False,
        "gpu_clustering": True,  # kept name for parity; means TPU k-means
        "cooldowns": True,
        "mmr": False,
        "cross_encoder_rerank": False,
        "query_cache": True,
    }

    # the reference's flag env names (feature_flags.go) -> flag keys here
    ENV_FLAG_ALIASES = {
        "NORNICDB_KALMAN_ENABLED": "kalman",
        "NORNICDB_AUTO_TLP_ENABLED": "auto_tlp",
        "NORNICDB_AUTO_TLP_LLM_QC_ENABLED": "llm_qc",
        "NORNICDB_KMEANS_CLUSTERING_ENABLED": "gpu_clustering",
        "NORNICDB_COOLDOWNS_ENABLED": "cooldowns",
        "NORNICDB_MMR_ENABLED": "mmr",
        "NORNICDB_RERANK_ENABLED": "cross_encoder_rerank",
        "NORNICDB_QUERY_CACHE_ENABLED": "query_cache",
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._flags = dict(self.DEFAULTS)
        # reference-style names first, NORNICDB_FLAG_<NAME> wins over them
        for env, name in self.ENV_FLAG_ALIASES.items():
            raw = os.environ.get(env)
            if raw is not None:
                self._flags[name] = raw.lower() in ("1", "true", "yes")
        for name in list(self._flags):
            env = os.environ.get(f"{ENV_PREFIX}FLAG_{name.upper()}")
            if env is not None:
                self._flags[name] = env.lower() in ("1", "true", "yes")

    def is_enabled(self, name: str) -> bool:
        with self._lock:
            return bool(self._flags.get(name, False))

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            self._flags[name] = value

    def all(self) -> dict[str, bool]:
        with self._lock:
            return dict(self._flags)

    @contextlib.contextmanager
    def with_enabled(self, name: str, value: bool = True):
        """Test helper (ref: WithXEnabled test helpers)."""
        with self._lock:
            old = self._flags.get(name)
            self._flags[name] = value
        try:
            yield
        finally:
            with self._lock:
                self._flags[name] = old

    # parity helpers (ref: IsKalmanEnabled :350, IsAutoTLPEnabled :430)
    def is_kalman_enabled(self) -> bool:
        return self.is_enabled("kalman")

    def is_auto_tlp_enabled(self) -> bool:
        return self.is_enabled("auto_tlp")


flags = FeatureFlags()


def resolve_import_url(url: str) -> str:
    """Gate + resolve a file-import URL for LOAD CSV / apoc.load.*.

    The reference refuses LOAD CSV outright in embedded mode
    (pkg/cypher/clauses.go:1800) and gates apoc file access behind its
    import setting; this framework supports local file import as an
    explicit operator opt-in:

    - NORNICDB_APOC_IMPORT_ENABLED=true must be set, else any file import
      raises (arbitrary local file reads are never a default capability).
    - Non-file URL schemes are refused (zero-egress).
    - If NORNICDB_IMPORT_DIR is set, the resolved real path must live
      under it (the reference's server.directories.import confinement);
      symlinks cannot escape because the check runs on os.path.realpath.
    """
    if os.environ.get("NORNICDB_APOC_IMPORT_ENABLED", "").lower() not in (
        "1", "true", "yes",
    ):
        raise PermissionError(
            "file import is disabled; set NORNICDB_APOC_IMPORT_ENABLED=true"
        )
    path = str(url)
    if path.startswith("file://"):
        path = path[7:]
    elif "://" in path:
        raise PermissionError(
            "only file:// URLs are supported for import (zero-egress)"
        )
    real = os.path.realpath(path)
    import_dir = os.environ.get("NORNICDB_IMPORT_DIR")
    if import_dir:
        root = os.path.realpath(import_dir)
        if not (real == root or real.startswith(root + os.sep)):
            raise PermissionError(
                f"import path escapes NORNICDB_IMPORT_DIR: {url}"
            )
    return real
