"""Configuration: YAML file + environment + runtime feature flags.

Behavioral reference: /root/reference/pkg/config/config.go:82-420
(Config, LoadFromFile/LoadFromEnv, FindConfigFile discovery),
feature_flags.go:210-506 (mutex-guarded flag registry with helpers like
IsKalmanEnabled/IsAutoTLPEnabled and test helpers WithXEnabled).
Precedence: explicit args > YAML > env > defaults
(ref: cmd/nornicdb/main.go:246-309).
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field, fields
from typing import Any, Optional

CONFIG_FILENAMES = ("nornicdb.yaml", "nornicdb.yml", ".nornicdb.yaml")
ENV_PREFIX = "NORNICDB_"


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    http_port: int = 7474
    bolt_port: int = 7687
    auth_enabled: bool = False
    base_path: str = ""


@dataclass
class DatabaseConfig:
    data_dir: str = ""
    encryption_enabled: bool = False
    encryption_key: str = ""
    async_writes: bool = True
    wal_sync: bool = False
    auto_compact_interval: float = 300.0


@dataclass
class EmbeddingConfig:
    enabled: bool = True
    provider: str = "tpu"  # tpu | hash
    dimensions: int = 1024
    chunk_tokens: int = 512
    chunk_overlap: int = 50
    workers: int = 1
    cache_size: int = 10000


@dataclass
class MemoryConfig:
    decay_enabled: bool = False
    decay_interval: float = 3600.0
    archive_threshold: float = 0.05
    query_cache_size: int = 1000
    query_cache_ttl: float = 60.0


@dataclass
class ComplianceConfig:
    audit_enabled: bool = False
    audit_path: str = ""
    retention_enabled: bool = False


@dataclass
class AppConfig:
    server: ServerConfig = field(default_factory=ServerConfig)
    database: DatabaseConfig = field(default_factory=DatabaseConfig)
    embedding: EmbeddingConfig = field(default_factory=EmbeddingConfig)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    compliance: ComplianceConfig = field(default_factory=ComplianceConfig)


def find_config_file(start_dir: str = ".") -> Optional[str]:
    """(ref: FindConfigFile config.go)"""
    d = os.path.abspath(start_dir)
    while True:
        for name in CONFIG_FILENAMES:
            p = os.path.join(d, name)
            if os.path.exists(p):
                return p
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _apply_dict(cfg: Any, data: dict) -> None:
    for f in fields(cfg):
        if f.name in data:
            v = data[f.name]
            current = getattr(cfg, f.name)
            if hasattr(current, "__dataclass_fields__") and isinstance(v, dict):
                _apply_dict(current, v)
            else:
                setattr(cfg, f.name, type(current)(v) if current is not None else v)


def load_from_file(path: str, cfg: Optional[AppConfig] = None) -> AppConfig:
    import yaml

    cfg = cfg or AppConfig()
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    _apply_dict(cfg, data)
    return cfg


def load_from_env(cfg: Optional[AppConfig] = None) -> AppConfig:
    """NORNICDB_<SECTION>_<FIELD> (ref: LoadFromEnv)."""
    cfg = cfg or AppConfig()
    for section_field in fields(cfg):
        section = getattr(cfg, section_field.name)
        for f in fields(section):
            env = f"{ENV_PREFIX}{section_field.name.upper()}_{f.name.upper()}"
            if env in os.environ:
                raw = os.environ[env]
                current = getattr(section, f.name)
                if isinstance(current, bool):
                    setattr(section, f.name, raw.lower() in ("1", "true", "yes"))
                elif isinstance(current, int):
                    setattr(section, f.name, int(raw))
                elif isinstance(current, float):
                    setattr(section, f.name, float(raw))
                else:
                    setattr(section, f.name, raw)
    return cfg


def load(start_dir: str = ".") -> AppConfig:
    cfg = AppConfig()
    path = find_config_file(start_dir)
    if path:
        load_from_file(path, cfg)
    load_from_env(cfg)
    return cfg


# ---------------------------------------------------------------- flags
class FeatureFlags:
    """Runtime feature-flag registry (ref: feature_flags.go:210-506)."""

    DEFAULTS = {
        "kalman": True,
        "auto_tlp": True,
        "llm_qc": False,
        "gpu_clustering": True,  # kept name for parity; means TPU k-means
        "cooldowns": True,
        "mmr": False,
        "cross_encoder_rerank": False,
        "query_cache": True,
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._flags = dict(self.DEFAULTS)
        # env overrides: NORNICDB_FLAG_<NAME>=true/false
        for name in list(self._flags):
            env = os.environ.get(f"{ENV_PREFIX}FLAG_{name.upper()}")
            if env is not None:
                self._flags[name] = env.lower() in ("1", "true", "yes")

    def is_enabled(self, name: str) -> bool:
        with self._lock:
            return bool(self._flags.get(name, False))

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            self._flags[name] = value

    def all(self) -> dict[str, bool]:
        with self._lock:
            return dict(self._flags)

    @contextlib.contextmanager
    def with_enabled(self, name: str, value: bool = True):
        """Test helper (ref: WithXEnabled test helpers)."""
        with self._lock:
            old = self._flags.get(name)
            self._flags[name] = value
        try:
            yield
        finally:
            with self._lock:
                self._flags[name] = old

    # parity helpers (ref: IsKalmanEnabled :350, IsAutoTLPEnabled :430)
    def is_kalman_enabled(self) -> bool:
        return self.is_enabled("kalman")

    def is_auto_tlp_enabled(self) -> bool:
        return self.is_enabled("auto_tlp")


flags = FeatureFlags()
