"""Raft consensus for replicated graph storage.

Behavioral reference: /root/reference/pkg/replication/raft.go:97-1368 —
hand-written Raft: randomized election timers, RequestVote RPCs (:248-360),
log replication via AppendEntries, commit index advancement, apply loop,
AddVoter (:1368). Consensus runs on the host plane (CPU) over DCN; the
device plane is untouched (SURVEY.md §2.3).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.telemetry.tracing import tracer as _tracer
from nornicdb_tpu.replication.ha_standby import apply_op
from nornicdb_tpu.replication.transport import (
    MSG_APPEND_ENTRIES,
    MSG_VOTE_REQUEST,
    Message,
    Transport,
)
from nornicdb_tpu.storage.types import Engine

log = logging.getLogger(__name__)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


@dataclass
class LogEntry:
    term: int
    index: int
    op: str = ""
    data: dict[str, Any] = field(default_factory=dict)


@dataclass
class RaftConfig:
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.3
    heartbeat_interval: float = 0.05


class RaftNode:
    """(ref: RaftReplicator raft.go:97)"""

    def __init__(
        self,
        node_id: str,
        transport: Transport,
        peers: list[str],
        storage: Optional[Engine] = None,
        config: Optional[RaftConfig] = None,
        seed: Optional[int] = None,
        state_dir: Optional[str] = None,
    ):
        self.node_id = node_id
        self.transport = transport
        self.peer_ids = [p for p in peers if p != node_id]
        self.storage = storage
        self.config = config or RaftConfig()
        self.rng = random.Random(seed if seed is not None else hash(node_id))
        # persistent state (term/vote/log are durable when state_dir is set;
        # fsynced BEFORE replying to RPCs, so a restarted node cannot vote
        # twice in one term — Raft's election-safety invariant, ref:
        # raft.go persistent state handling)
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: list[LogEntry] = []
        self._state_path: Optional[str] = None
        self._log_path: Optional[str] = None
        self._log_f = None
        self._state_dir = state_dir
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            self._state_path = os.path.join(state_dir, f"raft-{node_id}.state")
            self._log_path = os.path.join(state_dir, f"raft-{node_id}.log")
            good_bytes = self._load_persistent()
            # chop a torn tail (crash mid-append) BEFORE reopening in append
            # mode — otherwise the next entry lands on the partial line and
            # every later fsync'd entry is unreadable on the following restart
            if good_bytes is not None:
                try:
                    if os.path.getsize(self._log_path) > good_bytes:
                        os.truncate(self._log_path, good_bytes)
                except OSError:
                    pass
            self._log_f = open(self._log_path, "ab")
        # volatile
        self.state = FOLLOWER
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: Optional[str] = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._last_heard = time.time()
        self._election_deadline = self._new_deadline()
        self._threads: list[threading.Thread] = []
        self.on_apply: Optional[Callable[[LogEntry], None]] = None
        transport.set_handler(self._on_message)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        # a stop()/start() cycle must reopen the durable log: with _log_f
        # still None the persist helpers would silently no-op while
        # _handle_append keeps acking — a durability promise never written
        with self._lock:
            if self._log_path is not None and self._log_f is None:
                self._log_f = open(self._log_path, "ab")
        self._stop.clear()
        t = threading.Thread(target=self._tick_loop, daemon=True,
                             name=f"raft-{self.node_id}")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        # close under the RPC lock: a late AppendEntries dispatched by the
        # transport must see _log_f is None, not a closed file object
        with self._lock:
            if self._log_f is not None:
                f, self._log_f = self._log_f, None
                f.close()

    # -- durable state (term/vote/log) ------------------------------------
    def _load_persistent(self) -> Optional[int]:
        """Returns the byte offset of the last intact log line (for torn-tail
        truncation), or None when there is no log file."""
        try:
            with open(self._state_path) as f:
                st = json.load(f)
            self.current_term = int(st.get("current_term", 0))
            self.voted_for = st.get("voted_for")
        except (OSError, ValueError):
            pass
        good = None
        try:
            with open(self._log_path, "rb") as f:
                good = 0
                for line in f:
                    if not line.endswith(b"\n"):
                        break  # torn tail
                    stripped = line.strip()
                    if stripped:
                        try:
                            e = json.loads(stripped)
                            self.log.append(
                                LogEntry(e["term"], e["index"],
                                         e.get("op", ""), e.get("data", {}))
                            )
                        except (ValueError, KeyError, TypeError):
                            # TypeError: valid JSON that is not an object
                            # ('null', '5', '[..]') must also truncate, not
                            # crash the node on every restart
                            break  # corrupt line: keep only the prefix
                    good += len(line)
        except OSError:
            pass
        return good

    def _fsync_dir(self) -> None:
        """Durably record renames: fsync the state directory itself, or an
        os.replace'd file can vanish on power loss after the RPC reply."""
        if not self._state_dir:
            return
        try:
            fd = os.open(self._state_dir, os.O_RDONLY)
            try:
                # deliberate fsync under the RPC lock: the rename must be
                # durable before the reply leaves (Raft election safety)
                os.fsync(fd)  # nornlint: disable=NL-LK02
            finally:
                os.close(fd)
        except OSError:
            pass

    def _persist_state(self) -> None:
        if self._state_path is None:
            return
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"current_term": self.current_term, "voted_for": self.voted_for},
                f,
            )
            f.flush()
            # deliberate fsync under the RPC lock: a vote/term must be
            # durable BEFORE the reply leaves, or a restarted node can vote
            # twice in one term (Raft election safety)
            os.fsync(f.fileno())  # nornlint: disable=NL-LK02
        os.replace(tmp, self._state_path)
        self._fsync_dir()

    def _persist_log_append(self, entries: list[LogEntry]) -> None:
        if self._log_f is None:
            return
        for e in entries:
            self._log_f.write(
                json.dumps(
                    {"term": e.term, "index": e.index, "op": e.op, "data": e.data}
                ).encode() + b"\n"
            )
        self._log_f.flush()
        # deliberate fsync under the RPC lock: the AppendEntries ack is a
        # durability promise, and appends must hit the file in log order
        os.fsync(self._log_f.fileno())  # nornlint: disable=NL-LK02

    def _persist_log_rewrite(self) -> None:
        """Full rewrite after a conflict truncation (rare path)."""
        if self._log_path is None or self._log_f is None:
            return
        self._log_f.close()
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            for e in self.log:
                f.write(
                    json.dumps(
                        {"term": e.term, "index": e.index, "op": e.op,
                         "data": e.data}
                    ).encode() + b"\n"
                )
            f.flush()
            # deliberate fsync under the RPC lock: conflict truncation must
            # be durable before the reject reply triggers a leader resend
            os.fsync(f.fileno())  # nornlint: disable=NL-LK02
        os.replace(tmp, self._log_path)
        self._fsync_dir()
        self._log_f = open(self._log_path, "ab")

    def _new_deadline(self) -> float:
        return time.time() + self.rng.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _tick_loop(self) -> None:
        while not self._stop.wait(0.01):
            with self._lock:
                state = self.state
                deadline = self._election_deadline
            if state == LEADER:
                self._broadcast_append_entries()
                self._stop.wait(self.config.heartbeat_interval)
            elif time.time() >= deadline:
                self._start_election()

    # -- elections (ref: raft.go:248-360) ------------------------------------
    def _start_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.node_id
            self._persist_state()  # durable before any vote request leaves
            self.leader_id = None
            self._election_deadline = self._new_deadline()
            last_idx = len(self.log)
            last_term = self.log[-1].term if self.log else 0
        votes = 1
        vote_lock = threading.Lock()
        majority = (len(self.peer_ids) + 1) // 2 + 1
        done = threading.Event()

        def ask(peer: str):
            nonlocal votes
            try:
                resp = self.transport.request(
                    peer,
                    Message(
                        MSG_VOTE_REQUEST,
                        {
                            "term": term,
                            "candidate": self.node_id,
                            "last_log_index": last_idx,
                            "last_log_term": last_term,
                        },
                    ),
                    timeout=self.config.election_timeout_min,
                )
            except ReplicationError:
                return
            payload = resp.payload
            if not isinstance(payload, dict):
                return
            rterm = payload.get("term", 0)
            if isinstance(rterm, int) and rterm > term:
                with self._lock:
                    self._step_down(rterm)
                done.set()
                return
            if payload.get("vote_granted") is True:
                with vote_lock:
                    votes += 1
                    if votes >= majority:
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True)
                   for p in self.peer_ids]
        for t in threads:
            t.start()
        done.wait(self.config.election_timeout_max)
        with self._lock:
            if self.state == CANDIDATE and self.current_term == term and votes >= majority:
                self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.node_id
        for p in self.peer_ids:
            self.next_index[p] = len(self.log) + 1
            self.match_index[p] = 0
        # immediate heartbeat to assert leadership
        threading.Thread(target=self._broadcast_append_entries, daemon=True).start()

    def _step_down(self, term: int) -> None:
        # voted_for only resets when the term actually increases: clearing it
        # on a same-term transition (e.g. candidate seeing the elected
        # leader's AppendEntries) would let this node vote twice in one term
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_state()
        self.state = FOLLOWER
        self._election_deadline = self._new_deadline()

    # -- log replication --------------------------------------------------------
    def propose(self, op: str, data: dict[str, Any]) -> int:
        """Leader-only: append an op, replicate, return its index."""
        applied: list[LogEntry] = []
        with _tracer.span("replication.propose", {"op": op}):
            with self._lock:
                if self.state != LEADER:
                    raise ReplicationError(
                        f"not the leader (leader={self.leader_id})"
                    )
                entry = LogEntry(self.current_term, len(self.log) + 1, op, data)
                self.log.append(entry)
                self._persist_log_append([entry])
                index = entry.index
                if not self.peer_ids:
                    # single-node cluster: a majority of one holds it already
                    applied = self._advance_commit()
            self._notify_applied(applied)
            self._broadcast_append_entries()
        return index

    def _broadcast_append_entries(self) -> None:
        for peer in self.peer_ids:
            # copy_context: the sender threads inherit the proposer's trace
            # context, so transport.request stamps the AppendEntries frames
            # with the originating request's traceparent
            ctx = contextvars.copy_context()
            threading.Thread(
                target=ctx.run, args=(self._send_append, peer), daemon=True
            ).start()

    def _send_append(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.current_term
            next_idx = self.next_index.get(peer, len(self.log) + 1)
            prev_idx = next_idx - 1
            prev_term = self.log[prev_idx - 1].term if prev_idx >= 1 and prev_idx <= len(self.log) else 0
            entries = [
                {"term": e.term, "index": e.index, "op": e.op, "data": e.data}
                for e in self.log[next_idx - 1 :]
            ]
            commit = self.commit_index
        try:
            resp = self.transport.request(
                peer,
                Message(
                    MSG_APPEND_ENTRIES,
                    {
                        "term": term,
                        "leader": self.node_id,
                        "prev_log_index": prev_idx,
                        "prev_log_term": prev_term,
                        "entries": entries,
                        "leader_commit": commit,
                    },
                ),
                timeout=0.5,
            )
        except ReplicationError:
            return
        payload = resp.payload if isinstance(resp.payload, dict) else {}
        rterm = payload.get("term", 0)
        applied: list[LogEntry] = []
        with self._lock:
            if isinstance(rterm, int) and rterm > self.current_term:
                self._step_down(rterm)
                return
            if self.state != LEADER:
                return
            if payload.get("success") is True:
                match = prev_idx + len(entries)
                self.match_index[peer] = max(self.match_index.get(peer, 0), match)
                self.next_index[peer] = self.match_index[peer] + 1
                applied = self._advance_commit()
            else:
                self.next_index[peer] = max(1, self.next_index.get(peer, 1) - 1)
        self._notify_applied(applied)

    def _advance_commit(self) -> list[LogEntry]:
        """Commit entries replicated to a majority (current-term only).
        Returns the newly applied entries for post-lock notification."""
        for idx in range(len(self.log), self.commit_index, -1):
            if self.log[idx - 1].term != self.current_term:
                continue
            count = 1 + sum(
                1 for p in self.peer_ids if self.match_index.get(p, 0) >= idx
            )
            if count >= (len(self.peer_ids) + 1) // 2 + 1:
                self.commit_index = idx
                return self._apply_committed()
        return []

    def _apply_committed(self) -> list[LogEntry]:
        """Apply committed entries to storage (still under ``_lock``: the
        state machine must advance in log order).  ``on_apply`` observers are
        NOT invoked here — the callback is externally supplied code that may
        take its own locks (e.g. Region._on_local_apply takes the outbox
        lock) or block, and running it under the RPC lock stalls every
        vote/append in flight (nornlint NL-LK03).  Callers collect the
        returned entries and hand them to :meth:`_notify_applied` after
        releasing ``_lock``."""
        applied: list[LogEntry] = []
        with _tracer.span("replication.commit"):
            while self.last_applied < self.commit_index:
                self.last_applied += 1
                entry = self.log[self.last_applied - 1]
                if self.storage is not None and entry.op:
                    apply_op(self.storage, entry.op, entry.data)
                applied.append(entry)
        return applied

    def _notify_applied(self, entries: list[LogEntry]) -> None:
        """Fire on_apply outside ``_lock``.  Entries within one batch are
        delivered in log order; batches acked on different transport threads
        may overlap (observers needing total order must key by entry.index,
        as Region's outbox does)."""
        if self.on_apply is None:
            return
        for entry in entries:
            try:
                self.on_apply(entry)
            except Exception:
                # the log entry IS applied; an observer callback crash
                # must not stall commit advancement, but it is a bug
                log.exception(
                    "on_apply callback failed at index %d", entry.index)

    # -- RPC handlers ----------------------------------------------------------------
    def _on_message(self, msg: Message) -> Optional[Message]:
        if msg.type == MSG_VOTE_REQUEST:
            return self._handle_vote(msg)
        if msg.type == MSG_APPEND_ENTRIES:
            return self._handle_append(msg)
        return None

    def _handle_vote(self, msg: Message) -> Message:
        p = msg.payload if isinstance(msg.payload, dict) else {}
        term = p.get("term")
        candidate = p.get("candidate")
        if not isinstance(term, int) or not isinstance(candidate, str):
            return Message(0, {"term": self.current_term, "vote_granted": False})
        with self._lock:
            if term > self.current_term:
                self._step_down(term)
            granted = False
            if term == self.current_term and self.voted_for in (None, candidate):
                # candidate log must be at least as up-to-date (ref: §5.4.1)
                last_term = self.log[-1].term if self.log else 0
                cand_last_term = p.get("last_log_term", 0)
                cand_last_idx = p.get("last_log_index", 0)
                if not isinstance(cand_last_term, int) or not isinstance(cand_last_idx, int):
                    cand_last_term, cand_last_idx = -1, -1
                up_to_date = (cand_last_term, cand_last_idx) >= (last_term, len(self.log))
                if up_to_date:
                    granted = True
                    self.voted_for = candidate
                    self._persist_state()  # fsync the vote before replying
                    self._election_deadline = self._new_deadline()
            return Message(0, {"term": self.current_term, "vote_granted": granted})

    def _handle_append(self, msg: Message) -> Message:
        p = msg.payload if isinstance(msg.payload, dict) else {}
        term = p.get("term")
        if not isinstance(term, int):
            return Message(0, {"term": self.current_term, "success": False})
        # child of the transport-continued trace when the leader's
        # AppendEntries carried a traceparent; no-op otherwise
        with _tracer.span("replication.append",
                          {"entries": len(p.get("entries") or [])}):
            return self._handle_append_locked(p)

    def _handle_append_locked(self, p: dict) -> Message:
        term = p["term"]  # validated by _handle_append
        with self._lock:
            if term < self.current_term:
                return Message(0, {"term": self.current_term, "success": False})
            if term > self.current_term or self.state != FOLLOWER:
                self._step_down(term)
            leader = p.get("leader")
            if isinstance(leader, str):
                self.leader_id = leader
            self._election_deadline = self._new_deadline()
            prev_idx = p.get("prev_log_index", 0)
            prev_term = p.get("prev_log_term", 0)
            if not isinstance(prev_idx, int) or not isinstance(prev_term, int):
                return Message(0, {"term": self.current_term, "success": False})
            if prev_idx > len(self.log):
                return Message(0, {"term": self.current_term, "success": False})
            if prev_idx >= 1 and self.log[prev_idx - 1].term != prev_term:
                self.log = self.log[: prev_idx - 1]  # conflict: truncate
                self._persist_log_rewrite()
                return Message(0, {"term": self.current_term, "success": False})
            entries = p.get("entries", [])
            if not isinstance(entries, list):
                # malformed batch: success would falsely advance the leader's
                # match_index and let it commit entries we never appended
                return Message(0, {"term": self.current_term, "success": False})
            truncated = False
            appended: list[LogEntry] = []

            def _reject():
                if truncated:
                    self._persist_log_rewrite()
                elif appended:
                    self._persist_log_append(appended)
                return Message(0, {"term": self.current_term, "success": False})

            for e in entries:
                if not isinstance(e, dict):
                    return _reject()
                idx = e.get("index")
                eterm = e.get("term")
                if not isinstance(idx, int) or not isinstance(eterm, int):
                    return _reject()
                if idx <= len(self.log):
                    if self.log[idx - 1].term != eterm:
                        self.log = self.log[: idx - 1]
                        truncated = True
                    else:
                        continue
                if idx == len(self.log) + 1:
                    entry = LogEntry(
                        eterm, idx, e.get("op", ""),
                        e.get("data", {}) if isinstance(e.get("data"), dict) else {},
                    )
                    self.log.append(entry)
                    appended.append(entry)
                else:
                    return _reject()
            # fsync the durable log before acking (success advances the
            # leader's match_index — the ack is a durability promise)
            if truncated:
                self._persist_log_rewrite()
            elif appended:
                self._persist_log_append(appended)
            leader_commit = p.get("leader_commit", 0)
            applied: list[LogEntry] = []
            if isinstance(leader_commit, int) and leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, len(self.log))
                applied = self._apply_committed()
            reply = Message(0, {"term": self.current_term, "success": True})
        # observers run after the RPC lock is released (see _apply_committed)
        self._notify_applied(applied)
        return reply

    # -- membership (ref: AddVoter raft.go:1368) -----------------------------------
    def add_voter(self, node_id: str) -> None:
        with self._lock:
            if node_id not in self.peer_ids and node_id != self.node_id:
                self.peer_ids.append(node_id)
                if self.state == LEADER:
                    self.next_index[node_id] = len(self.log) + 1
                    self.match_index[node_id] = 0


class RaftCluster:
    """Test/embedding helper: spin up N in-process Raft nodes."""

    def __init__(self, n: int, network, storages: Optional[list[Engine]] = None,
                 config: Optional[RaftConfig] = None, transports=None):
        from nornicdb_tpu.replication.transport import InProcTransport

        ids = [f"node-{i}" for i in range(n)]
        self.nodes: list[RaftNode] = []
        for i, nid in enumerate(ids):
            t = transports[i] if transports else InProcTransport(nid, network)
            storage = storages[i] if storages else None
            self.nodes.append(
                RaftNode(nid, t, ids, storage=storage, config=config, seed=i)
            )

    def start(self):
        for n in self.nodes:
            n.start()

    def stop(self):
        for n in self.nodes:
            n.stop()

    def leader(self, timeout: float = 5.0) -> Optional[RaftNode]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            leaders = [n for n in self.nodes if n.state == LEADER]
            if len(leaders) == 1:
                # stable when every live node agrees
                return leaders[0]
            time.sleep(0.02)
        return None
