"""Replication transport: typed, length-prefixed messages with
request/response correlation.

Behavioral reference: /root/reference/pkg/replication/transport.go:46-520 —
1-byte message type + 4-byte length + JSON payload framing, pending-map
request correlation (:359-435), TLS-optional TCP. Two implementations:

  - InProcTransport: in-memory pipes for tests (the reference's MockTransport
    pattern — replication_test.go mocks)
  - TcpTransport: real sockets over DCN between TPU-VM hosts

The device plane (search/top-k merge) never touches this layer — it rides
ICI inside jit'd programs (SURVEY.md §5).
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

# message types (ref: transport.go message type byte)
MSG_REQUEST = 1
MSG_RESPONSE = 2
MSG_HEARTBEAT = 3
MSG_WAL_BATCH = 4
MSG_VOTE_REQUEST = 5
MSG_VOTE_RESPONSE = 6
MSG_APPEND_ENTRIES = 7
MSG_APPEND_RESPONSE = 8
MSG_FENCE = 9
MSG_PROMOTE = 10
MSG_SNAPSHOT = 11


@dataclass
class Message:
    type: int
    payload: dict[str, Any] = field(default_factory=dict)
    request_id: str = ""
    sender: str = ""
    # W3C traceparent carried across the wire so a replication RPC keeps
    # its originating request's trace id (telemetry tentpole); empty on
    # untraced messages and omitted from the frame
    traceparent: str = ""

    def encode(self) -> bytes:
        obj = {"payload": self.payload, "request_id": self.request_id,
               "sender": self.sender}
        if self.traceparent:
            obj["tp"] = self.traceparent
        body = json.dumps(obj, separators=(",", ":")).encode()
        return bytes([self.type]) + struct.pack(">I", len(body)) + body

    @staticmethod
    def decode(data: bytes) -> "Message":
        if len(data) < 5:
            raise ReplicationError("short message")
        mtype = data[0]
        (length,) = struct.unpack(">I", data[1:5])
        body = data[5 : 5 + length]
        obj = json.loads(body)
        return Message(
            mtype, obj.get("payload", {}), obj.get("request_id", ""),
            obj.get("sender", ""), obj.get("tp", ""),
        )


Handler = Callable[[Message], Optional[Message]]


class Transport:
    """Abstract peer-to-peer transport."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self.handler: Optional[Handler] = None
        self._pending: dict[str, threading.Event] = {}
        self._responses: dict[str, Message] = {}
        self._plock = threading.Lock()

    def set_handler(self, handler: Handler) -> None:
        self.handler = handler

    # -- to be implemented --------------------------------------------------
    def send(self, peer: str, msg: Message) -> None:
        raise NotImplementedError

    def peers(self) -> list[str]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- request/response correlation (ref: transport.go:359-435) -----------
    def request(self, peer: str, msg: Message, timeout: float = 5.0) -> Message:
        msg.request_id = str(uuid.uuid4())
        msg.sender = self.node_id
        if not msg.traceparent:
            # attach the caller's trace id so the peer's handler spans join
            # this request's trace (None -> stays empty, zero overhead)
            msg.traceparent = _tracer.current_traceparent() or ""
        ev = threading.Event()
        with self._plock:
            self._pending[msg.request_id] = ev
        try:
            self.send(peer, msg)
            if not ev.wait(timeout):
                raise ReplicationError(f"request to {peer} timed out")
            with self._plock:
                return self._responses.pop(msg.request_id)
        finally:
            with self._plock:
                self._pending.pop(msg.request_id, None)
                # a response landing between the timeout and this cleanup
                # would otherwise be orphaned forever
                self._responses.pop(msg.request_id, None)

    def _deliver(self, msg: Message) -> None:
        """Called by implementations when a message arrives."""
        if msg.type == MSG_RESPONSE and msg.request_id:
            with self._plock:
                ev = self._pending.get(msg.request_id)
                if ev is not None:
                    self._responses[msg.request_id] = msg
                    ev.set()
                    return
        if self.handler is not None:
            try:
                if msg.traceparent:
                    # continue the sender's trace on this node: the
                    # handler's spans (raft append/commit, storage ops)
                    # record under the originating request's trace id
                    with _tracer.start_trace(
                        f"replication.handle.{msg.type}",
                        traceparent=msg.traceparent,
                        attrs={"sender": msg.sender},
                    ):
                        reply = self.handler(msg)
                else:
                    reply = self.handler(msg)
            except Exception:
                # a handler blown up by a garbage payload (chaos-corrupted
                # frame, malformed peer) must not kill the delivery thread;
                # the message is lost, which the sender already tolerates
                from nornicdb_tpu.telemetry.metrics import count_error

                count_error("replication.handler")
                log.warning("message handler failed for type %s from %s",
                            msg.type, msg.sender, exc_info=True)
                return
            if reply is not None and msg.request_id:
                reply.type = MSG_RESPONSE
                reply.request_id = msg.request_id
                reply.sender = self.node_id
                try:
                    self.send(msg.sender, reply)
                except (ReplicationError, OSError) as e:
                    # reply path down (InProc raises ReplicationError, TCP
                    # raw socket errors): caller retries; don't kill delivery
                    log.warning("reply to %s dropped: %s", msg.sender, e)


class InProcNetwork:
    """Shared registry connecting InProcTransports (test cluster in one
    process — ref: replication mocks)."""

    def __init__(self) -> None:
        self.nodes: dict[str, "InProcTransport"] = {}
        self._lock = threading.Lock()

    def register(self, t: "InProcTransport") -> None:
        with self._lock:
            self.nodes[t.node_id] = t

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self.nodes.pop(node_id, None)

    def route(self, target: str, msg: Message) -> None:
        with self._lock:
            node = self.nodes.get(target)
        if node is None or not node.alive:
            raise ReplicationError(f"peer {target} unreachable")
        node._incoming(msg)


class InProcTransport(Transport):
    def __init__(self, node_id: str, network: InProcNetwork):
        super().__init__(node_id)
        self.network = network
        self.alive = True
        network.register(self)

    def send(self, peer: str, msg: Message) -> None:
        if not self.alive:
            raise ReplicationError("transport closed")
        if not msg.sender:
            msg.sender = self.node_id
        # deliver on a worker thread: network IO is asynchronous
        encoded = msg.encode()  # exercise the wire codec

        def _deliver():
            try:
                self.network.route(peer, Message.decode(encoded))
            except ReplicationError:
                pass

        threading.Thread(target=_deliver, daemon=True).start()

    def _incoming(self, msg: Message) -> None:
        self._deliver(msg)

    def peers(self) -> list[str]:
        return [n for n in self.network.nodes if n != self.node_id]

    def close(self) -> None:
        self.alive = False
        self.network.unregister(self.node_id)


class TcpTransport(Transport):
    """Real TCP transport (ref: transport.go TCP+TLS). Peer addresses are
    provided as {node_id: (host, port)}."""

    def __init__(self, node_id: str, bind: tuple[str, int],
                 peer_addrs: dict[str, tuple[str, int]]):
        super().__init__(node_id)
        self.peer_addrs = dict(peer_addrs)
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    header = _read_exact(self.request, 5)
                    (length,) = struct.unpack(">I", header[1:5])
                    body = _read_exact(self.request, length)
                    outer._deliver(Message.decode(header + body))
                except Exception:
                    # one bad frame must not kill the listener thread, but
                    # a corrupt/truncated peer stream is worth a trace
                    log.warning(
                        "dropped undecodable frame from %s",
                        self.client_address, exc_info=True)

        self._server = socketserver.ThreadingTCPServer(bind, _Handler)
        self._server.daemon_threads = True
        self.bind = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def send(self, peer: str, msg: Message) -> None:
        addr = self.peer_addrs.get(peer)
        if addr is None:
            raise ReplicationError(f"unknown peer {peer}")
        if not msg.sender:
            msg.sender = self.node_id
        with socket.create_connection(addr, timeout=5) as s:
            s.sendall(msg.encode())

    def peers(self) -> list[str]:
        return list(self.peer_addrs)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ReplicationError("connection closed")
        buf += part
    return buf
