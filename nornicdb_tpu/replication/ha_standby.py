"""HA hot-standby replication: primary ships WAL batches to a standby.

Behavioral reference: /root/reference/pkg/replication/ha_standby.go:169-336 —
primary streams WAL entry batches, heartbeats, fencing (FenceRequest :148),
standby promote (:159). Storage bridging mirrors storage_adapter.go.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.replication.transport import (
    MSG_FENCE,
    MSG_HEARTBEAT,
    MSG_PROMOTE,
    MSG_WAL_BATCH,
    Message,
    Transport,
)
from nornicdb_tpu.storage.types import Edge, Engine, Node
from nornicdb_tpu.storage.wal import (
    OP_CREATE_EDGE,
    OP_CREATE_NODE,
    OP_DELETE_EDGE,
    OP_DELETE_NODE,
    OP_UPDATE_EDGE,
    OP_UPDATE_NODE,
    apply_storage_op,
)

log = logging.getLogger(__name__)


def apply_op(engine: Engine, op: str, data: dict[str, Any]) -> None:
    """Apply one replicated op — shared dispatch with WAL recovery
    (ref: storage_adapter.go; nornicdb_tpu.storage.wal.apply_storage_op)."""
    apply_storage_op(engine, op, data)


class ReplicatedEngine(Engine):
    """Engine decorator that records ops into an in-memory log for shipping
    (the primary side of WAL shipping)."""

    def __init__(self, base: Engine):
        super().__init__()
        self.base = base
        self._log: list[tuple[int, str, dict]] = []
        self._seq = 0
        self._lock = threading.Lock()
        self.fenced = False
        base.on_event(self._emit)

    def _record(self, op: str, data: dict) -> None:
        with self._lock:
            self._seq += 1
            self._log.append((self._seq, op, data))

    def prune_through(self, seq: int) -> None:
        """Drop acked entries so log memory and scan cost stay bounded by the
        unshipped backlog."""
        with self._lock:
            self._log = [e for e in self._log if e[0] > seq]

    def _check_fence(self) -> None:
        if self.fenced:
            raise ReplicationError("primary is fenced (ref: FenceRequest)")

    def entries_since(self, seq: int) -> list[tuple[int, str, dict]]:
        with self._lock:
            return [e for e in self._log if e[0] > seq]

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    # mutations: fence-checked + logged
    def create_node(self, node: Node) -> Node:
        self._check_fence()
        out = self.base.create_node(node)
        self._record(OP_CREATE_NODE, out.to_dict())
        return out

    def update_node(self, node: Node) -> Node:
        self._check_fence()
        out = self.base.update_node(node)
        self._record(OP_UPDATE_NODE, out.to_dict())
        return out

    def delete_node(self, node_id: str) -> None:
        self._check_fence()
        self.base.delete_node(node_id)
        self._record(OP_DELETE_NODE, {"id": node_id})

    def create_edge(self, edge: Edge) -> Edge:
        self._check_fence()
        out = self.base.create_edge(edge)
        self._record(OP_CREATE_EDGE, out.to_dict())
        return out

    def update_edge(self, edge: Edge) -> Edge:
        self._check_fence()
        out = self.base.update_edge(edge)
        self._record(OP_UPDATE_EDGE, out.to_dict())
        return out

    def delete_edge(self, edge_id: str) -> None:
        self._check_fence()
        self.base.delete_edge(edge_id)
        self._record(OP_DELETE_EDGE, {"id": edge_id})

    # reads delegate
    def get_node(self, node_id):
        return self.base.get_node(node_id)

    def get_nodes_by_label(self, label):
        return self.base.get_nodes_by_label(label)

    def all_nodes(self):
        return self.base.all_nodes()

    def get_edge(self, edge_id):
        return self.base.get_edge(edge_id)

    def get_edges_by_type(self, t):
        return self.base.get_edges_by_type(t)

    def get_outgoing_edges(self, node_id):
        return self.base.get_outgoing_edges(node_id)

    def get_incoming_edges(self, node_id):
        return self.base.get_incoming_edges(node_id)

    def all_edges(self):
        return self.base.all_edges()

    def node_count(self):
        return self.base.node_count()

    def edge_count(self):
        return self.base.edge_count()

    def mark_pending_embed(self, node_id):
        self.base.mark_pending_embed(node_id)

    def unmark_pending_embed(self, node_id):
        self.base.unmark_pending_embed(node_id)

    def pending_embed_ids(self, limit=0):
        return self.base.pending_embed_ids(limit)


@dataclass
class HAConfig:
    batch_interval: float = 0.05
    heartbeat_interval: float = 0.2
    heartbeat_timeout: float = 1.0


class HAPrimary:
    """(ref: HAStandbyReplicator primary role ha_standby.go:169)"""

    def __init__(
        self,
        engine: ReplicatedEngine,
        transport: Transport,
        standby_id: str,
        config: Optional[HAConfig] = None,
    ):
        self.engine = engine
        self.transport = transport
        self.standby_id = standby_id
        self.config = config or HAConfig()
        self._shipped_seq = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        transport.set_handler(self._on_message)

    def start(self) -> None:
        for fn in (self._ship_loop, self._heartbeat_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    def _ship_loop(self) -> None:
        while not self._stop.wait(self.config.batch_interval):
            self.ship_now()

    def ship_now(self) -> int:
        """Ship outstanding entries; returns how many were sent."""
        entries = self.engine.entries_since(self._shipped_seq)
        if not entries:
            return 0
        payload = {
            "entries": [
                {"seq": s, "op": op, "data": data} for s, op, data in entries
            ]
        }
        try:
            resp = self.transport.request(
                self.standby_id, Message(MSG_WAL_BATCH, payload), timeout=2.0
            )
            payload_in = resp.payload if isinstance(resp.payload, dict) else {}
            acked = payload_in.get("acked_seq", self._shipped_seq)
            if not isinstance(acked, (int, float)):
                return 0  # malformed ack (e.g. chaos corruption): retry later
            self._shipped_seq = max(self._shipped_seq, int(acked))
            self.engine.prune_through(self._shipped_seq)
            return len(entries)
        except ReplicationError:
            return 0
        except Exception:
            # never let a bad response kill the ship loop thread
            log.warning("WAL ship attempt failed; will retry", exc_info=True)
            return 0

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.config.heartbeat_interval):
            try:
                self.transport.send(
                    self.standby_id,
                    Message(MSG_HEARTBEAT, {"seq": self.engine.last_seq,
                                            "ts": time.time()}),
                )
            except ReplicationError:
                pass

    def fence(self) -> None:
        """Stop accepting writes (split-brain prevention, ref: :148)."""
        self.engine.fenced = True

    def _on_message(self, msg: Message) -> Optional[Message]:
        if msg.type == MSG_FENCE:
            self.fence()
            return Message(0, {"fenced": True})
        return None


class HAStandby:
    """(ref: standby role + promote ha_standby.go:159-336)"""

    def __init__(
        self,
        engine: Engine,
        transport: Transport,
        primary_id: str,
        config: Optional[HAConfig] = None,
    ):
        self.engine = engine
        self.transport = transport
        self.primary_id = primary_id
        self.config = config or HAConfig()
        self.applied_seq = 0
        self.last_heartbeat = time.monotonic()
        self.promoted = False
        self._lock = threading.Lock()
        transport.set_handler(self._on_message)

    def _on_message(self, msg: Message) -> Optional[Message]:
        if msg.type == MSG_WAL_BATCH:
            return self._apply_batch(msg)
        if msg.type == MSG_HEARTBEAT:
            self.last_heartbeat = time.monotonic()
            return None
        if msg.type == MSG_PROMOTE:
            self.promote()
            return Message(0, {"promoted": True})
        return None

    def _apply_batch(self, msg: Message) -> Message:
        with self._lock:
            if self.promoted:
                # refuse the old primary's stream after promotion so a failed
                # fence cannot split-brain our engine
                return Message(0, {"acked_seq": self.applied_seq,
                                   "error": "promoted"})
            entries = msg.payload.get("entries")
            if not isinstance(entries, list):
                return Message(0, {"acked_seq": self.applied_seq, "error": "bad batch"})
            for e in entries:
                seq = e.get("seq") if isinstance(e, dict) else None
                if not isinstance(seq, int):
                    break  # corrupted entry: ack up to the gap; retransmit heals
                if seq <= self.applied_seq:
                    continue  # duplicate / replay
                if seq != self.applied_seq + 1:
                    break  # out-of-order hole: wait for retransmit
                op = e.get("op")
                data = e.get("data")
                if not isinstance(op, str) or not isinstance(data, dict):
                    break  # corrupted payload: don't skip past it
                apply_op(self.engine, op, data)
                self.applied_seq = seq
            return Message(0, {"acked_seq": self.applied_seq})

    def heartbeat_healthy(self) -> bool:
        return (time.monotonic() - self.last_heartbeat) < self.config.heartbeat_timeout

    def promote(self) -> ReplicatedEngine:
        """Become the writable primary (ref: promote :159): fence the old
        primary (best effort), then wrap our engine for future shipping."""
        try:
            self.transport.request(
                self.primary_id, Message(MSG_FENCE, {}), timeout=1.0
            )
        except ReplicationError:
            pass  # primary is gone — that's why we're promoting
        self.promoted = True
        return ReplicatedEngine(self.engine)
