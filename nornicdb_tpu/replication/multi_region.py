"""Multi-region replication: region-local Raft + async cross-region push.

Behavioral reference: /root/reference/pkg/replication/multi_region.go —
each region runs its own consensus group for low-latency local commits;
committed entries ship asynchronously to peer regions (eventual consistency
across regions, strong consistency within one). Conflict policy:
last-writer-wins by (origin_seq, region) — matching the reference's async
push semantics.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.replication.ha_standby import apply_op
from nornicdb_tpu.replication.raft import RaftCluster, RaftConfig, RaftNode
from nornicdb_tpu.replication.transport import (
    MSG_WAL_BATCH,
    Message,
    Transport,
)
from nornicdb_tpu.storage.types import Engine


@dataclass
class RegionConfig:
    name: str
    nodes: int = 3
    push_interval: float = 0.1


class Region:
    """One region: a local Raft group + an outbound async shipper."""

    def __init__(
        self,
        config: RegionConfig,
        network,
        storages: Optional[list[Engine]] = None,
        raft_config: Optional[RaftConfig] = None,
        inter_region_transport: Optional[Transport] = None,
    ):
        self.config = config
        self.storages = storages or []
        self.cluster = RaftCluster(
            config.nodes, network, storages=storages, config=raft_config
        )
        # rename node ids to be region-scoped so regions share one network
        for node in self.cluster.nodes:
            old_id = node.transport.node_id
            node.node_id = f"{config.name}/{node.node_id}"
            node.transport.node_id = node.node_id
            node.peer_ids = [f"{config.name}/{p}" if "/" not in p else p
                             for p in node.peer_ids]
            network.unregister(old_id)  # drop the pre-rename registration
            network.register(node.transport)
        self.transport = inter_region_transport
        self._outbox: dict[int, dict[str, Any]] = {}  # index -> entry (deduped)
        self._outbox_lock = threading.Lock()
        self._pushed: dict[str, int] = {}  # peer region -> last shipped idx
        self._prune_floor = 0  # outbox entries <= floor have been discarded
        self._applied_remote: dict[str, int] = {}  # origin region -> last seq
        self._peers: dict[str, str] = {}  # region name -> transport peer id
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # capture local commits for cross-region shipping
        for node in self.cluster.nodes:
            node.on_apply = self._on_local_apply

    # -- local commits -> outbox --------------------------------------------
    def _on_local_apply(self, entry) -> None:
        if not entry.op:
            return
        if entry.data.get("__origin__"):  # replicated from another region
            return
        with self._outbox_lock:
            # every node in the region applies the same committed entry;
            # keying by index dedups to one outbox copy
            self._outbox[entry.index] = {
                "seq": entry.index,
                "op": entry.op,
                "data": entry.data,
                "origin": self.config.name,
            }

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.cluster.start()
        if self.transport is not None:
            self.transport.set_handler(self._on_message)
            self._thread = threading.Thread(target=self._push_loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.cluster.stop()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def connect(self, region_name: str, peer_id: str) -> None:
        """Register a peer region. A peer joining AFTER outbox pruning starts
        from the prune floor — entries below it need a snapshot bootstrap
        (import/export), same as adding a fresh Raft voter mid-life."""
        self._peers[region_name] = peer_id
        self._pushed.setdefault(region_name, self._prune_floor)

    def leader(self, timeout: float = 5.0) -> Optional[RaftNode]:
        return self.cluster.leader(timeout)

    def propose(self, op: str, data: dict[str, Any]) -> int:
        leader = self.leader()
        if leader is None:
            raise ReplicationError(f"region {self.config.name}: no leader")
        return leader.propose(op, data)

    # -- async cross-region push (ref: multi_region.go push loop) -----------
    def _push_loop(self) -> None:
        while not self._stop.wait(self.config.push_interval):
            self.push_now()

    def push_now(self) -> int:
        if self.transport is None:
            return 0
        with self._outbox_lock:
            outbox = sorted(self._outbox.values(), key=lambda e: e["seq"])
        total = 0
        for region, peer in self._peers.items():
            last = self._pushed.get(region, 0)
            entries = [e for e in outbox if e["seq"] > last]
            if not entries:
                continue
            try:
                resp = self.transport.request(
                    peer,
                    Message(MSG_WAL_BATCH, {"entries": entries,
                                            "origin": self.config.name}),
                    timeout=2.0,
                )
                payload = resp.payload if isinstance(resp.payload, dict) else {}
                acked = payload.get("acked_seq")
                if isinstance(acked, int):
                    self._pushed[region] = max(last, acked)
                    total += len(entries)
            except ReplicationError:
                continue  # retried next tick — async, at-least-once
        # prune entries every peer has acked (bounded memory; same idea as
        # ReplicatedEngine.prune_through)
        if self._peers:
            floor = min(
                self._pushed.get(r, 0) for r in self._peers
            )
            if floor > self._prune_floor:
                self._prune_floor = floor
                with self._outbox_lock:
                    self._outbox = {
                        i: e for i, e in self._outbox.items() if i > floor
                    }
        return total

    # -- inbound remote batches ----------------------------------------------
    def _on_message(self, msg: Message) -> Optional[Message]:
        if msg.type != MSG_WAL_BATCH:
            return None
        payload = msg.payload if isinstance(msg.payload, dict) else {}
        origin = payload.get("origin", "")
        entries = payload.get("entries", [])
        if not isinstance(entries, list) or not isinstance(origin, str):
            return Message(0, {"acked_seq": self._applied_remote.get(origin, 0)})
        last = self._applied_remote.get(origin, 0)
        for e in sorted(
            (x for x in entries if isinstance(x, dict)),
            key=lambda x: x.get("seq", 0),
        ):
            seq = e.get("seq")
            op = e.get("op")
            data = e.get("data")
            if not isinstance(seq, int) or seq <= last:
                continue
            if not isinstance(op, str) or not isinstance(data, dict):
                break
            # replicate through the LOCAL Raft group so every node in this
            # region applies it; tag origin to stop ping-pong re-shipping
            tagged = dict(data)
            tagged["__origin__"] = origin
            leader = self.leader(timeout=1.0)
            if leader is None:
                break
            try:
                index = leader.propose(op, tagged)
            except ReplicationError:
                break
            # ack only after the entry COMMITS locally — an ack on a bare
            # leader append could be lost to a leader crash and never resent
            deadline = time.time() + 2.0
            while leader.commit_index < index:
                if time.time() > deadline or leader.state != "leader":
                    break
                time.sleep(0.005)
            if leader.commit_index < index:
                break  # not committed: don't ack; origin retries
            last = seq
        self._applied_remote[origin] = last
        return Message(0, {"acked_seq": last})


class MultiRegion:
    """Convenience wrapper running N regions in-process (ref: multi_region.go)."""

    def __init__(self, names: list[str], network, nodes_per_region: int = 3,
                 storages: Optional[dict[str, list[Engine]]] = None,
                 raft_config: Optional[RaftConfig] = None):
        from nornicdb_tpu.replication.transport import InProcTransport

        self.regions: dict[str, Region] = {}
        for name in names:
            transport = InProcTransport(f"region-{name}", network)
            self.regions[name] = Region(
                RegionConfig(name, nodes_per_region),
                network,
                storages=(storages or {}).get(name),
                raft_config=raft_config,
                inter_region_transport=transport,
            )
        for name, region in self.regions.items():
            for other in names:
                if other != name:
                    region.connect(other, f"region-{other}")

    def start(self) -> None:
        for r in self.regions.values():
            r.start()

    def stop(self) -> None:
        for r in self.regions.values():
            r.stop()
