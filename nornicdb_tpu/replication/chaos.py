"""Chaos transport: fault injection for replication tests and the soak
harness.

Behavioral reference: /root/reference/pkg/replication/chaos_test.go:446
(ChaosTransport) — packet loss, latency (incl. cross-region spikes), data
corruption, connection drops, duplication, reordering, mixed failures.

Beyond the reference shape this transport also injects **receive-path**
faults (drop/delay on delivery, independent of the send path) and
**asymmetric partitions** (A→B blocked while B→A flows — the classic
one-way network split that splits Raft quorums without either side
noticing).  Fault counters live in the process metrics registry as
``nornicdb_chaos_events_total{event=...}`` so a soak run reads them from
``/metrics`` next to every other family; the per-instance ``stats`` dict
remains for direct test introspection.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.replication.transport import Message, Transport
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

_EVENTS = (
    "sent", "dropped", "duplicated", "corrupted", "reordered",
    "rx_dropped", "rx_delayed", "partitioned",
)
_CHAOS_EVENTS = _REGISTRY.counter(
    "nornicdb_chaos_events_total",
    "Faults injected by ChaosTransport instances (send + receive path)",
    labels=("event",),
)
_EVENT_CELLS = {e: _CHAOS_EVENTS.labels(e) for e in _EVENTS}


@dataclass
class ChaosConfig:
    loss_rate: float = 0.0  # drop outgoing messages
    duplicate_rate: float = 0.0
    corrupt_rate: float = 0.0  # flip payload bytes
    reorder_rate: float = 0.0  # delay to shuffle ordering
    latency: float = 0.0  # fixed added latency (s)
    latency_jitter: float = 0.0
    drop_connections: bool = False  # every send raises
    # receive-path faults: applied to DELIVERY on this node, after the
    # sender's transport already did its work — models asymmetric links
    # and NIC-side loss the sender cannot observe
    rx_loss_rate: float = 0.0
    rx_delay: float = 0.0
    rx_delay_jitter: float = 0.0
    seed: int = 0


class ChaosTransport(Transport):
    """Wraps any Transport, injecting faults on the send AND receive path."""

    def __init__(self, inner: Transport, config: ChaosConfig):
        super().__init__(inner.node_id)
        self.inner = inner
        self.config = config
        self.rng = random.Random(config.seed)
        # separate stream for delivery-side decisions: send and receive run
        # on different threads, and sharing one RNG would make either path's
        # sequence depend on the other's interleaving
        self.rng_rx = random.Random(config.seed + 0x5EED)
        self.stats = {e: 0 for e in _EVENTS}
        # asymmetric partition: directed (src, dst) pairs that are blocked.
        # Checked on the send path for (me -> peer) and on the receive path
        # for (sender -> me), so one ChaosTransport can cut either direction
        # of a link independently.
        self._partitions: set[tuple[str, str]] = set()
        self._plock = threading.Lock()
        # our handler chain must observe inner deliveries
        inner.set_handler(self._on_inner)

    def _count(self, event: str) -> None:
        self.stats[event] += 1
        _EVENT_CELLS[event].inc()

    # -- partitions ---------------------------------------------------------
    def partition(self, src: str, dst: str) -> None:
        """Block messages flowing src → dst (asymmetric: the reverse
        direction keeps working unless partitioned separately)."""
        with self._plock:
            self._partitions.add((src, dst))

    def partition_both(self, a: str, b: str) -> None:
        with self._plock:
            self._partitions.add((a, b))
            self._partitions.add((b, a))

    def heal(self, src: str | None = None, dst: str | None = None) -> None:
        """Remove one directed block, or every block when called bare."""
        with self._plock:
            if src is None and dst is None:
                self._partitions.clear()
            else:
                self._partitions.discard((src, dst))

    def _blocked(self, src: str, dst: str) -> bool:
        with self._plock:
            return (src, dst) in self._partitions

    # -- receive path -------------------------------------------------------
    def _on_inner(self, msg: Message):
        cfg = self.config
        if msg.sender and self._blocked(msg.sender, self.node_id):
            self._count("partitioned")
            return None
        if cfg.rx_loss_rate and self.rng_rx.random() < cfg.rx_loss_rate:
            self._count("rx_dropped")
            return None
        delay = cfg.rx_delay
        if cfg.rx_delay_jitter:
            delay += self.rng_rx.random() * cfg.rx_delay_jitter
        if delay > 0:
            self._count("rx_delayed")
            threading.Timer(delay, self._deliver, args=(msg,)).start()
        else:
            self._deliver(msg)
        return None

    def set_handler(self, handler):
        self.handler = handler

    def peers(self):
        return self.inner.peers()

    def close(self):
        self.inner.close()

    # -- send path ----------------------------------------------------------
    def send(self, peer: str, msg: Message) -> None:
        cfg = self.config
        self._count("sent")
        if cfg.drop_connections:
            raise ReplicationError("connection dropped (chaos)")
        if self._blocked(self.node_id, peer):
            self._count("partitioned")
            return  # silently eaten by the split
        if self.rng.random() < cfg.loss_rate:
            self._count("dropped")
            return  # silently lost
        if self.rng.random() < cfg.corrupt_rate:
            self._count("corrupted")
            msg = self._corrupt(msg)
        sends = 1
        if self.rng.random() < cfg.duplicate_rate:
            self._count("duplicated")
            sends = 2
        delay = cfg.latency + self.rng.random() * cfg.latency_jitter
        if self.rng.random() < cfg.reorder_rate:
            self._count("reordered")
            delay += self.rng.random() * 0.05
        for _ in range(sends):
            if delay > 0:
                threading.Timer(
                    delay, self._safe_send, args=(peer, msg)
                ).start()
            else:
                self._safe_send(peer, msg)

    def _safe_send(self, peer: str, msg: Message) -> None:
        try:
            self.inner.send(peer, msg)
        except ReplicationError:
            pass

    def _corrupt(self, msg: Message) -> Message:
        """Corrupt a payload value; receivers must survive garbage."""
        bad = Message(msg.type, dict(msg.payload), msg.request_id, msg.sender)
        if bad.payload:
            k = self.rng.choice(list(bad.payload))
            bad.payload[k] = "\x00CORRUPT\xff"
        else:
            bad.payload = {"__garbage__": self.rng.random()}
        return bad
