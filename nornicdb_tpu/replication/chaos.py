"""Chaos transport: fault injection for replication tests.

Behavioral reference: /root/reference/pkg/replication/chaos_test.go:446
(ChaosTransport) — packet loss, latency (incl. cross-region spikes), data
corruption, connection drops, duplication, reordering, mixed failures.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from nornicdb_tpu.errors import ReplicationError
from nornicdb_tpu.replication.transport import Message, Transport


@dataclass
class ChaosConfig:
    loss_rate: float = 0.0  # drop outgoing messages
    duplicate_rate: float = 0.0
    corrupt_rate: float = 0.0  # flip payload bytes
    reorder_rate: float = 0.0  # delay to shuffle ordering
    latency: float = 0.0  # fixed added latency (s)
    latency_jitter: float = 0.0
    drop_connections: bool = False  # every send raises
    seed: int = 0


class ChaosTransport(Transport):
    """Wraps any Transport, injecting faults on the send path."""

    def __init__(self, inner: Transport, config: ChaosConfig):
        super().__init__(inner.node_id)
        self.inner = inner
        self.config = config
        self.rng = random.Random(config.seed)
        self.stats = {"sent": 0, "dropped": 0, "duplicated": 0, "corrupted": 0,
                      "reordered": 0}
        # our handler chain must observe inner deliveries
        inner.set_handler(self._on_inner)

    def _on_inner(self, msg: Message):
        self._deliver(msg)
        return None

    def set_handler(self, handler):
        self.handler = handler

    def peers(self):
        return self.inner.peers()

    def close(self):
        self.inner.close()

    def send(self, peer: str, msg: Message) -> None:
        cfg = self.config
        self.stats["sent"] += 1
        if cfg.drop_connections:
            raise ReplicationError("connection dropped (chaos)")
        if self.rng.random() < cfg.loss_rate:
            self.stats["dropped"] += 1
            return  # silently lost
        if self.rng.random() < cfg.corrupt_rate:
            self.stats["corrupted"] += 1
            msg = self._corrupt(msg)
        sends = 1
        if self.rng.random() < cfg.duplicate_rate:
            self.stats["duplicated"] += 1
            sends = 2
        delay = cfg.latency + self.rng.random() * cfg.latency_jitter
        if self.rng.random() < cfg.reorder_rate:
            self.stats["reordered"] += 1
            delay += self.rng.random() * 0.05
        for _ in range(sends):
            if delay > 0:
                threading.Timer(
                    delay, self._safe_send, args=(peer, msg)
                ).start()
            else:
                self._safe_send(peer, msg)

    def _safe_send(self, peer: str, msg: Message) -> None:
        try:
            self.inner.send(peer, msg)
        except ReplicationError:
            pass

    def _corrupt(self, msg: Message) -> Message:
        """Corrupt a payload value; receivers must survive garbage."""
        bad = Message(msg.type, dict(msg.payload), msg.request_id, msg.sender)
        if bad.payload:
            k = self.rng.choice(list(bad.payload))
            bad.payload[k] = "\x00CORRUPT\xff"
        else:
            bad.payload = {"__garbage__": self.rng.random()}
        return bad
