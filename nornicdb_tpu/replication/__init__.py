"""Replication library (ref: /root/reference/pkg/replication/ — standalone,
exercised by its own tests; HA WAL shipping, Raft consensus, chaos-tested
transport over DCN)."""

from nornicdb_tpu.replication.chaos import ChaosConfig, ChaosTransport
from nornicdb_tpu.replication.ha_standby import (
    HAConfig,
    HAPrimary,
    HAStandby,
    ReplicatedEngine,
    apply_op,
)
from nornicdb_tpu.replication.raft import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    LogEntry,
    RaftCluster,
    RaftConfig,
    RaftNode,
)
from nornicdb_tpu.replication.transport import (
    InProcNetwork,
    InProcTransport,
    Message,
    TcpTransport,
    Transport,
)

__all__ = [
    "ChaosConfig", "ChaosTransport", "HAConfig", "HAPrimary", "HAStandby",
    "ReplicatedEngine", "apply_op", "CANDIDATE", "FOLLOWER", "LEADER",
    "LogEntry", "RaftCluster", "RaftConfig", "RaftNode", "InProcNetwork",
    "InProcTransport", "Message", "TcpTransport", "Transport",
]
