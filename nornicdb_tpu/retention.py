"""GDPR data retention: per-category policies, legal holds, erasure requests.

Behavioral reference: /root/reference/pkg/retention/retention.go —
Policy :144, LegalHold :205, ErasureRequest :273 (status workflow),
Manager :350 with delete/archive callbacks; GDPR endpoints
(pkg/server /gdpr/export|delete, SURVEY.md layer 11).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import NornicError
from nornicdb_tpu.storage.types import Engine, Node

# erasure workflow states (ref: ErasureRequest :273)
ERASURE_PENDING = "pending"
ERASURE_APPROVED = "approved"
ERASURE_COMPLETED = "completed"
ERASURE_REJECTED = "rejected"


@dataclass
class Policy:
    """(ref: Policy retention.go:144)"""

    category: str  # matches node property "category" or a label
    max_age: float  # seconds
    action: str = "delete"  # delete | archive


@dataclass
class LegalHold:
    """(ref: LegalHold retention.go:205)"""

    id: str
    reason: str
    node_ids: set[str] = field(default_factory=set)
    categories: set[str] = field(default_factory=set)
    created_at: float = field(default_factory=time.time)
    released: bool = False


@dataclass
class ErasureRequest:
    id: str
    subject: str  # node id or property match value
    status: str = ERASURE_PENDING
    requested_at: float = field(default_factory=time.time)
    completed_at: Optional[float] = None
    erased_count: int = 0


class RetentionManager:
    """(ref: retention.Manager retention.go:350)"""

    def __init__(
        self,
        storage: Engine,
        on_delete: Optional[Callable[[Node], None]] = None,
        on_archive: Optional[Callable[[Node], None]] = None,
        now_fn: Callable[[], float] = time.time,
    ):
        self.storage = storage
        self.on_delete = on_delete
        self.on_archive = on_archive
        self.now = now_fn
        self._lock = threading.RLock()
        self.policies: dict[str, Policy] = {}
        self.holds: dict[str, LegalHold] = {}
        self.erasures: dict[str, ErasureRequest] = {}

    # -- policies -------------------------------------------------------------
    def set_policy(self, policy: Policy) -> None:
        with self._lock:
            self.policies[policy.category] = policy

    def remove_policy(self, category: str) -> None:
        with self._lock:
            self.policies.pop(category, None)

    def _node_category(self, node: Node) -> Optional[str]:
        cat = node.properties.get("category")
        if isinstance(cat, str):
            return cat
        for label in node.labels:
            if label in self.policies:
                return label
        return None

    def _held(self, node: Node) -> bool:
        cat = self._node_category(node)
        with self._lock:
            for hold in self.holds.values():
                if hold.released:
                    continue
                if node.id in hold.node_ids:
                    return True
                if cat and cat in hold.categories:
                    return True
        return False

    def enforce(self) -> dict[str, int]:
        """Apply policies to expired nodes; legal holds win
        (ref: enforcement loop)."""
        deleted = archived = held = 0
        now = self.now()
        for node in list(self.storage.all_nodes()):
            cat = self._node_category(node)
            if cat is None:
                continue
            policy = self.policies.get(cat)
            if policy is None:
                continue
            if now - node.created_at < policy.max_age:
                continue
            if self._held(node):
                held += 1
                continue
            if policy.action == "archive":
                if "Archived" not in node.labels:
                    node.labels.append("Archived")
                    self.storage.update_node(node)
                    if self.on_archive:
                        self.on_archive(node)
                    archived += 1
            else:
                self.storage.delete_node(node.id)
                if self.on_delete:
                    self.on_delete(node)
                deleted += 1
        return {"deleted": deleted, "archived": archived, "held": held}

    # -- legal holds -----------------------------------------------------------
    def create_hold(self, reason: str, node_ids: Optional[set[str]] = None,
                    categories: Optional[set[str]] = None) -> LegalHold:
        hold = LegalHold(
            id=str(uuid.uuid4()), reason=reason,
            node_ids=set(node_ids or ()), categories=set(categories or ()),
        )
        with self._lock:
            self.holds[hold.id] = hold
        return hold

    def release_hold(self, hold_id: str) -> None:
        with self._lock:
            hold = self.holds.get(hold_id)
            if hold is None:
                raise NornicError(f"hold {hold_id} not found")
            hold.released = True

    # -- erasure workflow (GDPR right to be forgotten) ----------------------------
    def request_erasure(self, subject: str) -> ErasureRequest:
        req = ErasureRequest(id=str(uuid.uuid4()), subject=subject)
        with self._lock:
            self.erasures[req.id] = req
        return req

    def approve_erasure(self, request_id: str) -> ErasureRequest:
        with self._lock:
            req = self.erasures.get(request_id)
            if req is None:
                raise NornicError(f"erasure request {request_id} not found")
            if req.status != ERASURE_PENDING:
                raise NornicError(f"erasure request is {req.status}")
            req.status = ERASURE_APPROVED
            return req

    def reject_erasure(self, request_id: str) -> None:
        with self._lock:
            req = self.erasures.get(request_id)
            if req is not None:
                req.status = ERASURE_REJECTED

    def execute_erasure(self, request_id: str) -> ErasureRequest:
        """Delete all nodes belonging to the subject (by id or by a
        `subject`/`owner` property match), unless legally held."""
        with self._lock:
            req = self.erasures.get(request_id)
            if req is None:
                raise NornicError(f"erasure request {request_id} not found")
            if req.status != ERASURE_APPROVED:
                raise NornicError("erasure must be approved first")
        erased = 0
        for node in list(self.storage.all_nodes()):
            matches = (
                node.id == req.subject
                or node.properties.get("subject") == req.subject
                or node.properties.get("owner") == req.subject
            )
            if not matches or self._held(node):
                continue
            self.storage.delete_node(node.id)
            if self.on_delete:
                self.on_delete(node)
            erased += 1
        with self._lock:
            req.status = ERASURE_COMPLETED
            req.completed_at = self.now()
            req.erased_count = erased
        return req

    def export_subject(self, subject: str) -> list[dict[str, Any]]:
        """GDPR data export (ref: /gdpr/export)."""
        out = []
        for node in self.storage.all_nodes():
            if (
                node.id == subject
                or node.properties.get("subject") == subject
                or node.properties.get("owner") == subject
            ):
                out.append(node.to_dict())
        return out
