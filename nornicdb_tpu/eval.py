"""Search-quality evaluation harness.

Behavioral reference: /root/reference/pkg/eval/harness.go:175 (Harness),
computeMetrics :309, precision/recall :424-442; JSON test suites with
thresholds + reporter (cmd/eval, docs/advanced/search-evaluation.md).
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class EvalCase:
    query: str
    relevant: list[str]  # relevant doc/node ids (ordered by ideal relevance)


@dataclass
class EvalMetrics:
    precision_at_k: float
    recall_at_k: float
    mrr: float
    ndcg: float
    k: int

    def as_dict(self) -> dict[str, float]:
        return {
            f"precision@{self.k}": round(self.precision_at_k, 4),
            f"recall@{self.k}": round(self.recall_at_k, 4),
            "mrr": round(self.mrr, 4),
            "ndcg": round(self.ndcg, 4),
        }


def precision_at_k(results: list[str], relevant: set[str], k: int) -> float:
    """(ref: precision harness.go:424)"""
    top = results[:k]
    if not top:
        return 0.0
    return sum(1 for r in top if r in relevant) / len(top)


def recall_at_k(results: list[str], relevant: set[str], k: int) -> float:
    """(ref: recall harness.go:442)"""
    if not relevant:
        return 0.0
    return sum(1 for r in results[:k] if r in relevant) / len(relevant)


def mrr(results: list[str], relevant: set[str]) -> float:
    for i, r in enumerate(results, 1):
        if r in relevant:
            return 1.0 / i
    return 0.0


def ndcg_at_k(results: list[str], relevant: list[str], k: int) -> float:
    rel_rank = {r: len(relevant) - i for i, r in enumerate(relevant)}
    dcg = sum(
        rel_rank.get(r, 0) / math.log2(i + 1)
        for i, r in enumerate(results[:k], 1)
    )
    ideal = sorted(rel_rank.values(), reverse=True)[:k]
    idcg = sum(v / math.log2(i + 1) for i, v in enumerate(ideal, 1))
    return dcg / idcg if idcg > 0 else 0.0


@dataclass
class EvalReport:
    metrics: EvalMetrics
    per_case: list[dict[str, Any]]
    passed: bool
    thresholds: dict[str, float] = field(default_factory=dict)


class Harness:
    """(ref: eval.Harness harness.go:175)"""

    def __init__(
        self,
        search_fn: Callable[[str, int], list[str]],
        k: int = 10,
        thresholds: Optional[dict[str, float]] = None,
    ):
        self.search_fn = search_fn  # query, k -> ranked ids
        self.k = k
        self.thresholds = thresholds or {}

    def run(self, cases: list[EvalCase]) -> EvalReport:
        """(ref: computeMetrics harness.go:309)"""
        per_case = []
        p_sum = r_sum = mrr_sum = ndcg_sum = 0.0
        for case in cases:
            results = self.search_fn(case.query, self.k)
            rel = set(case.relevant)
            p = precision_at_k(results, rel, self.k)
            r = recall_at_k(results, rel, self.k)
            m = mrr(results, rel)
            n = ndcg_at_k(results, case.relevant, self.k)
            p_sum += p
            r_sum += r
            mrr_sum += m
            ndcg_sum += n
            per_case.append(
                {"query": case.query, "precision": p, "recall": r,
                 "mrr": m, "ndcg": n, "results": results[: self.k]}
            )
        n_cases = max(len(cases), 1)
        metrics = EvalMetrics(
            p_sum / n_cases, r_sum / n_cases, mrr_sum / n_cases,
            ndcg_sum / n_cases, self.k,
        )
        passed = all(
            metrics.as_dict().get(name, 0.0) >= threshold
            for name, threshold in self.thresholds.items()
        )
        return EvalReport(metrics, per_case, passed, dict(self.thresholds))

    @staticmethod
    def load_suite(path: str) -> list[EvalCase]:
        """JSON suite: [{"query": ..., "relevant": [...]}, ...]"""
        with open(path) as f:
            data = json.load(f)
        return [EvalCase(c["query"], list(c["relevant"])) for c in data]
