"""Graph pattern matching: binds MATCH/MERGE patterns against storage.

Behavioral reference: /root/reference/pkg/cypher/match.go:124 (executeMatch),
traversal.go:886-1330 (BFS findPaths :1127, shortestPath :1332). Uses the
schema property index for equality lookups when available (the reference's
pattern fastpaths, optimized_executors.go).
"""

from __future__ import annotations

import logging
from typing import Any, Iterator, Optional

from nornicdb_tpu.cypher import ast
from nornicdb_tpu.cypher.expr import EvalContext, evaluate
from nornicdb_tpu.errors import CypherTypeError, NotFoundError
from nornicdb_tpu.storage.schema import SchemaManager
from nornicdb_tpu.storage.types import Edge, Engine, Node

log = logging.getLogger(__name__)

MAX_VAR_LENGTH = 15  # traversal depth cap (ref: traversal.go bounds)

# Live partial paths the batched var-length walk may hold before handing
# the query back to the lazy generic DFS: the batched walk materializes a
# whole frontier level at once, so a dense deep pattern (branching^hops)
# must not trade the generic path's O(depth) walk state for unbounded
# memory. Tests lower this to force the fallback.
MAX_BATCHED_PATHS = 100_000


def make_path(nodes: list[Node], rels: list[Edge]) -> dict[str, Any]:
    return {"__path__": True, "nodes": nodes, "relationships": rels}


def _rel_id(e) -> str:
    """path_rels holds full Edge objects where materialization is needed
    (rel variable bound, named path) and bare edge-id strings elsewhere —
    isomorphism checks work uniformly through this."""
    return e if isinstance(e, str) else e.id


class PatternMatcher:
    def __init__(self, storage: Engine, schema: Optional[SchemaManager] = None,
                 executor=None):
        self.storage = storage
        self.schema = schema
        self.executor = executor
        # no-copy adjacency where the engine offers it (probe once:
        # NamespacedEngine surfaces AttributeError when its base lacks it)
        self._iter_adj = getattr(storage, "iter_adjacency", None)
        if self._iter_adj is not None:
            try:
                self._iter_adj("\x00probe\x00", "out")
            except AttributeError:
                self._iter_adj = None
            except Exception:
                log.debug("iter_adjacency probe failed; keeping fast path",
                          exc_info=True)
        # shared CSR topology snapshot (storage/adjacency.py): resolved on
        # first traversal; False = engine cannot host one
        self._snapshot: Any = None

    def _snap(self):
        """The engine's adjacency snapshot, attaching on first use."""
        if self._snapshot is None:
            try:
                from nornicdb_tpu.storage.adjacency import attach_snapshot

                self._snapshot = attach_snapshot(self.storage)
            except Exception:
                log.debug("adjacency snapshot unavailable; traversals use "
                          "the engine-scan path", exc_info=True)
                self._snapshot = False
        return self._snapshot or None

    def _snap_ready(self):
        """Snapshot only if already built — plain one-hop expansion must
        not pay the first full build. Falls through to a snapshot another
        consumer (GDS, link prediction) already attached to the engine."""
        if self._snapshot is False:
            return None
        snap = self._snapshot or \
            getattr(self.storage, "_adjacency_snapshot", None)
        return snap if (snap is not None and snap.ready()) else None

    # -- public --------------------------------------------------------------
    def match_path(
        self,
        path: ast.PatternPath,
        row: dict[str, Any],
        params: dict[str, Any],
    ) -> Iterator[dict[str, Any]]:
        """Yield binding rows extending `row` with this path's variables."""
        if path.shortest:
            yield from self._match_shortest(path, row, params)
            return
        yield from self._match_elements(path, row, params, 0, row, [], [])

    # -- node candidates -------------------------------------------------------
    def _node_props(
        self, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> Optional[dict[str, Any]]:
        if node_pat.properties is None:
            return None
        ctx = EvalContext(row, params, self.executor)
        v = evaluate(node_pat.properties, ctx)
        if not isinstance(v, dict):
            raise CypherTypeError("node pattern properties must be a map")
        return v

    def _node_matches(
        self, node: Node, node_pat: ast.NodePattern, props: Optional[dict]
    ) -> bool:
        labels = node_pat.labels
        if labels:
            # single-label is the overwhelmingly common shape; skip the
            # genexpr machinery (profiled top cost of unanchored scans)
            if len(labels) == 1:
                if labels[0] not in node.labels:
                    return False
            elif not any(l in node.labels for l in labels):
                return False
        if props:
            for k, v in props.items():
                if not _value_eq(node.properties.get(k), v):
                    return False
        return True

    def _passes_inline_where(
        self, node: Node, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> bool:
        """Inline predicate (n:L WHERE n.x > 1) — evaluated with the node
        bound under its pattern variable."""
        if node_pat.where is None:
            return True
        bindings = dict(row)
        if node_pat.variable:
            bindings[node_pat.variable] = node
        ctx = EvalContext(bindings, params, self.executor)
        return evaluate(node_pat.where, ctx) is True

    def _candidates(
        self, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> list[Node]:
        # bound variable -> single candidate
        if node_pat.variable and node_pat.variable in row:
            v = row[node_pat.variable]
            if v is None:
                return []
            if not isinstance(v, Node):
                raise CypherTypeError(
                    f"variable `{node_pat.variable}` is not a node"
                )
            props = self._node_props(node_pat, row, params)
            return [v] if self._node_matches(v, node_pat, props) else []
        props = self._node_props(node_pat, row, params)
        # index-backed equality lookup (ref: optimized_executors.go fastpath)
        if self.schema is not None and node_pat.labels and props:
            for label in node_pat.labels:
                keys = sorted(props.keys())
                ids = self.schema.lookup(label, keys, [props[k] for k in keys])
                if ids is None and len(keys) > 1:
                    for k in keys:
                        ids = self.schema.lookup(label, [k], [props[k]])
                        if ids is not None:
                            break
                if ids is not None:
                    nodes = self.storage.batch_get_nodes(sorted(ids))
                    return [n for n in nodes if self._node_matches(n, node_pat, props)]
        if node_pat.labels:
            seen: dict[str, Node] = {}
            for label in node_pat.labels:
                for n in self.storage.get_nodes_by_label(label):
                    seen[n.id] = n
            nodes = sorted(seen.values(), key=lambda n: n.id)
            return [n for n in nodes if self._node_matches(n, node_pat, props)]
        return [
            n
            for n in sorted(self.storage.all_nodes(), key=lambda n: n.id)
            if self._node_matches(n, node_pat, props)
        ]

    # -- relationship matching ---------------------------------------------------
    def _rel_props(
        self, rel_pat: ast.RelPattern, row: dict, params: dict
    ) -> Optional[dict[str, Any]]:
        if rel_pat.properties is None:
            return None
        ctx = EvalContext(row, params, self.executor)
        return evaluate(rel_pat.properties, ctx)

    def _rel_matches(self, edge: Edge, rel_pat: ast.RelPattern, props) -> bool:
        if rel_pat.types and edge.type not in rel_pat.types:
            return False
        if props:
            for k, v in props.items():
                if not _value_eq(edge.properties.get(k), v):
                    return False
        return True

    def _expand(
        self, node_id: str, rel_pat: ast.RelPattern, props,
        materialize: bool = True,
    ) -> list[tuple]:
        """Edges leaving `node_id` per the pattern direction.

        materialize=True -> (Edge, other_id) pairs (needed when the rel
        binds a variable, the path is named, or the pattern filters on
        edge properties). materialize=False with fast adjacency ->
        (edge_id, other_id) pairs, skipping per-edge defensive copies —
        the dominant cost of unanchored traversal scans."""
        if not materialize and props is None:
            snap = self._snap_ready()
            if snap is not None:
                pairs = snap.expand_pairs(
                    node_id, rel_pat.direction, rel_pat.types)
                if pairs is not None:
                    return pairs
        if not materialize and props is None and self._iter_adj is not None:
            out = []
            types = rel_pat.types
            if rel_pat.direction in ("out", "both"):
                for eid, t, oid in self._iter_adj(node_id, "out"):
                    if not types or t in types:
                        out.append((eid, oid))
            if rel_pat.direction in ("in", "both"):
                for eid, t, oid in self._iter_adj(node_id, "in"):
                    if not types or t in types:
                        out.append((eid, oid))
            out.sort()
            return out
        out = []
        if rel_pat.direction in ("out", "both"):
            for e in self.storage.get_outgoing_edges(node_id):
                if self._rel_matches(e, rel_pat, props):
                    out.append((e, e.end_node))
        if rel_pat.direction in ("in", "both"):
            for e in self.storage.get_incoming_edges(node_id):
                if self._rel_matches(e, rel_pat, props):
                    out.append((e, e.start_node))
        out.sort(key=lambda t: t[0].id)
        return out

    # -- recursive path walk ------------------------------------------------------
    def _match_elements(
        self,
        path: ast.PatternPath,
        base_row: dict,
        params: dict,
        idx: int,
        row: dict,
        path_nodes: list[Node],
        path_rels: list[Edge],
    ) -> Iterator[dict[str, Any]]:
        elements = path.elements
        if idx >= len(elements):
            out = dict(row)
            if path.name:
                out[path.name] = make_path(path_nodes, path_rels)
            yield out
            return
        el = elements[idx]
        if isinstance(el, ast.NodePattern):
            if idx == 0:
                for node in self._candidates(el, row, params):
                    if not self._passes_inline_where(node, el, row, params):
                        continue
                    new_row = dict(row)
                    if el.variable:
                        new_row[el.variable] = node
                    yield from self._match_elements(
                        path, base_row, params, idx + 1, new_row,
                        path_nodes + [node], path_rels,
                    )
            else:
                raise CypherTypeError("internal: node pattern out of sequence")
            return
        # relationship element: el, followed by target node element
        rel_pat = el
        target_pat = elements[idx + 1]
        src = path_nodes[-1]
        props = self._rel_props(rel_pat, row, params)
        tprops = self._node_props(target_pat, row, params)
        if rel_pat.var_length:
            yield from self._match_var_length(
                path, params, idx, row, path_nodes, path_rels, rel_pat,
                target_pat, props, tprops, src,
            )
            return
        need_edges = bool(rel_pat.variable or path.name)
        for edge, other_id in self._expand(
            src.id, rel_pat, props, materialize=need_edges
        ):
            eid = _rel_id(edge)
            if any(_rel_id(e) == eid for e in path_rels):
                continue  # relationship isomorphism
            try:
                other = self.storage.get_node(other_id)
            except NotFoundError:
                continue
            if not self._node_matches(other, target_pat, tprops):
                continue
            if not self._passes_inline_where(other, target_pat, row, params):
                continue
            if target_pat.variable and target_pat.variable in row:
                bound = row[target_pat.variable]
                if not isinstance(bound, Node) or bound.id != other.id:
                    continue
            new_row = dict(row)
            if rel_pat.variable:
                new_row[rel_pat.variable] = edge
            if target_pat.variable:
                new_row[target_pat.variable] = other
            yield from self._match_elements(
                path, row, params, idx + 2, new_row,
                path_nodes + [other], path_rels + [edge],
            )

    def _match_var_length(
        self, path, params, idx, row, path_nodes, path_rels,
        rel_pat, target_pat, props, tprops, src,
    ) -> Iterator[dict[str, Any]]:
        """Variable-length expansion (ref: findPaths traversal.go:1127).

        With an edge-property filter or no usable snapshot this is the
        original DFS over per-node engine expansion; otherwise the
        frontier-batched CSR walk (_var_length_batched) produces the same
        paths — sorted back into the DFS's lexicographic edge-id order —
        with node/edge materialization only for surviving bindings."""
        max_h = min(rel_pat.max_hops, MAX_VAR_LENGTH)
        min_h = rel_pat.min_hops
        need_edges = bool(rel_pat.variable or path.name)

        if props is None:
            snap = self._snap()
            if snap is not None and snap.ensure():
                found = self._var_length_batched(
                    snap, params, row, path_rels, rel_pat, target_pat,
                    tprops, src, min_h, max_h, need_edges)
                if found is not None:
                    # no zero-edge filter needed: the batched walk only
                    # yields at hops >= min_h, one edge per hop
                    start_nodes = list(path_nodes)
                    for new_row, nodes, rels in found:
                        yield from self._match_elements(
                            path, row, params, idx + 2, new_row,
                            start_nodes + nodes, path_rels + rels,
                        )
                    return

        def walk(curr: Node, hops: int, rels: list[Edge], nodes: list[Node]):
            if hops >= min_h:
                if self._node_matches(curr, target_pat, tprops) and \
                        self._passes_inline_where(curr, target_pat, row, params):
                    if target_pat.variable and target_pat.variable in row:
                        bound = row[target_pat.variable]
                        ok = isinstance(bound, Node) and bound.id == curr.id
                    else:
                        ok = True
                    if ok:
                        new_row = dict(row)
                        if rel_pat.variable:
                            new_row[rel_pat.variable] = list(rels)
                        if target_pat.variable:
                            new_row[target_pat.variable] = curr
                        yield new_row, list(nodes), list(rels)
            if hops >= max_h:
                return
            for edge, other_id in self._expand(
                curr.id, rel_pat, props, materialize=need_edges
            ):
                eid = _rel_id(edge)
                if any(_rel_id(e) == eid for e in rels) or any(
                    _rel_id(e) == eid for e in path_rels
                ):
                    continue
                try:
                    other = self.storage.get_node(other_id)
                except NotFoundError:
                    continue
                yield from walk(other, hops + 1, rels + [edge], nodes + [other])

        start_nodes = list(path_nodes)
        if min_h == 0:
            # zero-length: current node is also the target
            for new_row, nodes, rels in walk(src, 0, [], []):
                yield from self._match_elements(
                    path, row, params, idx + 2, new_row,
                    start_nodes + nodes, path_rels + rels,
                )
            return
        for new_row, nodes, rels in walk(src, 0, [], []):
            if not rels:
                continue
            yield from self._match_elements(
                path, row, params, idx + 2, new_row,
                start_nodes + nodes, path_rels + rels,
            )

    def _var_length_batched(
        self, snap, params, row, path_rels, rel_pat, target_pat,
        tprops, src, min_h: int, max_h: int, need_edges: bool,
    ) -> Optional[list[tuple[dict, list[Node], list]]]:
        """Frontier-batched var-length walk over CSR slices: each hop is
        one batched gather over the unique frontier endpoints (rel-type
        filtering via the code column), partial paths stay as index/edge-id
        tuples, and Nodes/Edges are fetched only for paths that survive the
        target checks. Results are sorted by their edge-id sequence, which
        reproduces the generic DFS's yield order exactly. None -> caller
        falls back to the generic walk."""
        src_idx = snap.index_of(src.id)
        if src_idx is None:
            return None
        codes = snap.type_codes(rel_pat.types)
        excluded = {_rel_id(e) for e in path_rels}
        bound_idx = -1  # -1 = unbound; None = bound to a node not in vocab
        if target_pat.variable and target_pat.variable in row:
            bound = row[target_pat.variable]
            if not isinstance(bound, Node):
                return []
            bound_idx = snap.index_of(bound.id)
        node_cache: dict[int, Node] = {src_idx: src}
        edge_cache: dict[str, Edge] = {}

        def fetch_nodes(idxs) -> None:
            missing = [i for i in idxs if i not in node_cache]
            if not missing:
                return
            ids = snap.ids_of(missing)
            got = {n.id: n for n in self.storage.batch_get_nodes(ids)}
            for i, nid in zip(missing, ids):
                n = got.get(nid)
                if n is not None:
                    node_cache[i] = n

        # partial path: (endpoint idx, edge-id tuple, node-idx tuple)
        matched: list[tuple[tuple, tuple, Node]] = []
        level: list[tuple[int, tuple, tuple]] = [(src_idx, (), ())]
        hops = 0
        while True:
            if hops >= min_h and level:
                # bound target: only paths ending AT the bound node can
                # yield — filter on indices before materializing anything
                check = level if bound_idx == -1 else \
                    [p for p in level if p[0] == bound_idx]
                fetch_nodes({p[0] for p in check})
                for last, eids, nidxs in check:
                    curr = node_cache.get(last)
                    if curr is None:
                        continue  # vanished mid-walk: generic skips it too
                    if not self._node_matches(curr, target_pat, tprops):
                        continue
                    if not self._passes_inline_where(curr, target_pat,
                                                     row, params):
                        continue
                    matched.append((eids, nidxs, curr))
            if hops >= max_h or not level:
                break
            endpoints = list(dict.fromkeys(p[0] for p in level))
            adj = snap.expand_frontier(endpoints, rel_pat.direction, codes)
            nxt = []
            for last, eids, nidxs in level:
                for eid, oidx in adj.get(last, ()):
                    if eid in excluded or eid in eids:
                        continue  # relationship isomorphism
                    nxt.append((oidx, eids + (eid,), nidxs + (oidx,)))
            if len(nxt) + len(matched) > MAX_BATCHED_PATHS:
                return None  # combinatorial blowup: lazy generic DFS instead
            level = nxt
            hops += 1
        matched.sort(key=lambda t: t[0])
        out = []
        for eids, nidxs, curr in matched:
            fetch_nodes(set(nidxs))
            nodes: list[Node] = []
            ok = True
            for i in nidxs:
                n = node_cache.get(i)
                if n is None:
                    ok = False
                    break
                nodes.append(n)
            if not ok:
                continue
            rels: list = []
            if need_edges:
                for eid in eids:
                    e = edge_cache.get(eid)
                    if e is None:
                        try:
                            e = self.storage.get_edge(eid)
                        except NotFoundError:
                            break
                        edge_cache[eid] = e
                    rels.append(e)
                if len(rels) != len(eids):
                    continue
            else:
                rels = list(eids)
            new_row = dict(row)
            if rel_pat.variable:
                new_row[rel_pat.variable] = list(rels)
            if target_pat.variable:
                new_row[target_pat.variable] = curr
            out.append((new_row, nodes, rels))
        return out

    # -- shortest path -------------------------------------------------------------
    def _match_shortest(
        self, path: ast.PatternPath, row: dict, params: dict
    ) -> Iterator[dict[str, Any]]:
        """(ref: shortestPath traversal.go:1332) — BFS between two bound/matched
        endpoints over the middle relationship pattern."""
        if len(path.elements) != 3:
            raise CypherTypeError("shortestPath expects (a)-[rel]-(b)")
        start_pat, rel_pat, end_pat = path.elements
        props = self._rel_props(rel_pat, row, params)
        max_h = min(rel_pat.max_hops if rel_pat.var_length else MAX_VAR_LENGTH,
                    MAX_VAR_LENGTH)
        for start in self._candidates(start_pat, row, params):
            for end in self._candidates(end_pat, row, params):
                found = self._bfs_shortest(
                    start, end, rel_pat, props, max_h,
                    all_paths=(path.shortest == "allshortest"),
                )
                for nodes, rels in found:
                    out = dict(row)
                    if start_pat.variable:
                        out[start_pat.variable] = start
                    if end_pat.variable:
                        out[end_pat.variable] = end
                    if rel_pat.variable:
                        out[rel_pat.variable] = rels
                    if path.name:
                        out[path.name] = make_path(nodes, rels)
                    yield out

    def _bfs_shortest(
        self, start: Node, end: Node, rel_pat, props, max_h: int,
        all_paths: bool = False,
    ) -> list[tuple[list[Node], list[Edge]]]:
        if start.id == end.id:
            return [([start], [])]
        if props is None:
            snap = self._snap()
            if snap is not None and snap.ensure():
                res = self._bfs_shortest_batched(
                    snap, start, end, rel_pat, max_h, all_paths)
                if res is not None:
                    return res
        return self._bfs_shortest_generic(
            start, end, rel_pat, props, max_h, all_paths)

    def _bfs_shortest_batched(
        self, snap, start: Node, end: Node, rel_pat, max_h: int,
        all_paths: bool,
    ) -> Optional[list[tuple[list[Node], list[Edge]]]]:
        """BFS over CSR slices: one batched expansion per level over the
        unique frontier endpoints; partial paths are index/edge-id tuples
        and only result paths materialize Nodes/Edges. Frontier order and
        per-node edge-id order match the generic BFS, so the first path
        found (and the all-shortest set) is identical."""
        si = snap.index_of(start.id)
        ei = snap.index_of(end.id)
        if si is None or ei is None:
            return None  # snapshot lagging the engine: generic path decides
        codes = snap.type_codes(rel_pat.types)
        frontier: list[tuple[int, tuple, tuple]] = [(si, (), ())]
        visited = {si}
        found: list[tuple[tuple, tuple]] = []
        for _ in range(max_h):
            endpoints = list(dict.fromkeys(p[0] for p in frontier))
            adj = snap.expand_frontier(endpoints, rel_pat.direction, codes)
            nxt: list[tuple[int, tuple, tuple]] = []
            level_visited: set[int] = set()
            for nid, eids, nidxs in frontier:
                for eid, oidx in adj.get(nid, ()):
                    if oidx in visited:
                        continue
                    p = (eids + (eid,), nidxs + (oidx,))
                    if oidx == ei:
                        found.append(p)
                        if not all_paths:
                            return self._materialize_index_paths(
                                snap, start, found)
                        continue
                    level_visited.add(oidx)
                    nxt.append((oidx, p[0], p[1]))
            if found:
                break
            visited |= level_visited
            frontier = nxt
            if not frontier:
                break
        return self._materialize_index_paths(snap, start, found)

    def _materialize_index_paths(
        self, snap, start: Node, items: list[tuple[tuple, tuple]],
    ) -> list[tuple[list[Node], list[Edge]]]:
        node_cache: dict[int, Node] = {}
        out: list[tuple[list[Node], list[Edge]]] = []
        for eids, nidxs in items:
            nodes = [start]
            ok = True
            for i in nidxs:
                n = node_cache.get(i)
                if n is None:
                    try:
                        n = self.storage.get_node(snap.id_of(i))
                    except NotFoundError:
                        ok = False
                        break
                    node_cache[i] = n
                nodes.append(n)
            if not ok:
                continue
            rels: list[Edge] = []
            for eid in eids:
                try:
                    rels.append(self.storage.get_edge(eid))
                except NotFoundError:
                    ok = False
                    break
            if ok:
                out.append((nodes, rels))
        return out

    def _bfs_shortest_generic(
        self, start: Node, end: Node, rel_pat, props, max_h: int,
        all_paths: bool = False,
    ) -> list[tuple[list[Node], list[Edge]]]:
        if start.id == end.id:
            return [([start], [])]
        frontier: list[tuple[str, list[Node], list[Edge]]] = [(start.id, [start], [])]
        visited = {start.id}
        results: list[tuple[list[Node], list[Edge]]] = []
        for _ in range(max_h):
            nxt: list[tuple[str, list[Node], list[Edge]]] = []
            level_visited: set[str] = set()
            for nid, nodes, rels in frontier:
                for edge, other_id in self._expand(nid, rel_pat, props):
                    if other_id in visited:
                        continue
                    try:
                        other = self.storage.get_node(other_id)
                    except NotFoundError:
                        continue
                    p = (nodes + [other], rels + [edge])
                    if other_id == end.id:
                        results.append(p)
                        if not all_paths:
                            return results
                        continue
                    level_visited.add(other_id)
                    nxt.append((other_id, p[0], p[1]))
            if results:
                return results
            visited |= level_visited
            frontier = nxt
            if not frontier:
                break
        return results


def _value_eq(a: Any, b: Any) -> bool:
    if a is None and b is None:
        return True
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b
