"""Graph pattern matching: binds MATCH/MERGE patterns against storage.

Behavioral reference: /root/reference/pkg/cypher/match.go:124 (executeMatch),
traversal.go:886-1330 (BFS findPaths :1127, shortestPath :1332). Uses the
schema property index for equality lookups when available (the reference's
pattern fastpaths, optimized_executors.go).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from nornicdb_tpu.cypher import ast
from nornicdb_tpu.cypher.expr import EvalContext, evaluate
from nornicdb_tpu.errors import CypherTypeError, NotFoundError
from nornicdb_tpu.storage.schema import SchemaManager
from nornicdb_tpu.storage.types import Edge, Engine, Node

MAX_VAR_LENGTH = 15  # traversal depth cap (ref: traversal.go bounds)


def make_path(nodes: list[Node], rels: list[Edge]) -> dict[str, Any]:
    return {"__path__": True, "nodes": nodes, "relationships": rels}


def _rel_id(e) -> str:
    """path_rels holds full Edge objects where materialization is needed
    (rel variable bound, named path) and bare edge-id strings elsewhere —
    isomorphism checks work uniformly through this."""
    return e if isinstance(e, str) else e.id


class PatternMatcher:
    def __init__(self, storage: Engine, schema: Optional[SchemaManager] = None,
                 executor=None):
        self.storage = storage
        self.schema = schema
        self.executor = executor
        # no-copy adjacency where the engine offers it (probe once:
        # NamespacedEngine surfaces AttributeError when its base lacks it)
        self._iter_adj = getattr(storage, "iter_adjacency", None)
        if self._iter_adj is not None:
            try:
                self._iter_adj("\x00probe\x00", "out")
            except AttributeError:
                self._iter_adj = None
            except Exception:
                pass

    # -- public --------------------------------------------------------------
    def match_path(
        self,
        path: ast.PatternPath,
        row: dict[str, Any],
        params: dict[str, Any],
    ) -> Iterator[dict[str, Any]]:
        """Yield binding rows extending `row` with this path's variables."""
        if path.shortest:
            yield from self._match_shortest(path, row, params)
            return
        yield from self._match_elements(path, row, params, 0, row, [], [])

    # -- node candidates -------------------------------------------------------
    def _node_props(
        self, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> Optional[dict[str, Any]]:
        if node_pat.properties is None:
            return None
        ctx = EvalContext(row, params, self.executor)
        v = evaluate(node_pat.properties, ctx)
        if not isinstance(v, dict):
            raise CypherTypeError("node pattern properties must be a map")
        return v

    def _node_matches(
        self, node: Node, node_pat: ast.NodePattern, props: Optional[dict]
    ) -> bool:
        labels = node_pat.labels
        if labels:
            # single-label is the overwhelmingly common shape; skip the
            # genexpr machinery (profiled top cost of unanchored scans)
            if len(labels) == 1:
                if labels[0] not in node.labels:
                    return False
            elif not any(l in node.labels for l in labels):
                return False
        if props:
            for k, v in props.items():
                if not _value_eq(node.properties.get(k), v):
                    return False
        return True

    def _passes_inline_where(
        self, node: Node, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> bool:
        """Inline predicate (n:L WHERE n.x > 1) — evaluated with the node
        bound under its pattern variable."""
        if node_pat.where is None:
            return True
        bindings = dict(row)
        if node_pat.variable:
            bindings[node_pat.variable] = node
        ctx = EvalContext(bindings, params, self.executor)
        return evaluate(node_pat.where, ctx) is True

    def _candidates(
        self, node_pat: ast.NodePattern, row: dict, params: dict
    ) -> list[Node]:
        # bound variable -> single candidate
        if node_pat.variable and node_pat.variable in row:
            v = row[node_pat.variable]
            if v is None:
                return []
            if not isinstance(v, Node):
                raise CypherTypeError(
                    f"variable `{node_pat.variable}` is not a node"
                )
            props = self._node_props(node_pat, row, params)
            return [v] if self._node_matches(v, node_pat, props) else []
        props = self._node_props(node_pat, row, params)
        # index-backed equality lookup (ref: optimized_executors.go fastpath)
        if self.schema is not None and node_pat.labels and props:
            for label in node_pat.labels:
                keys = sorted(props.keys())
                ids = self.schema.lookup(label, keys, [props[k] for k in keys])
                if ids is None and len(keys) > 1:
                    for k in keys:
                        ids = self.schema.lookup(label, [k], [props[k]])
                        if ids is not None:
                            break
                if ids is not None:
                    nodes = self.storage.batch_get_nodes(sorted(ids))
                    return [n for n in nodes if self._node_matches(n, node_pat, props)]
        if node_pat.labels:
            seen: dict[str, Node] = {}
            for label in node_pat.labels:
                for n in self.storage.get_nodes_by_label(label):
                    seen[n.id] = n
            nodes = sorted(seen.values(), key=lambda n: n.id)
            return [n for n in nodes if self._node_matches(n, node_pat, props)]
        return [
            n
            for n in sorted(self.storage.all_nodes(), key=lambda n: n.id)
            if self._node_matches(n, node_pat, props)
        ]

    # -- relationship matching ---------------------------------------------------
    def _rel_props(
        self, rel_pat: ast.RelPattern, row: dict, params: dict
    ) -> Optional[dict[str, Any]]:
        if rel_pat.properties is None:
            return None
        ctx = EvalContext(row, params, self.executor)
        return evaluate(rel_pat.properties, ctx)

    def _rel_matches(self, edge: Edge, rel_pat: ast.RelPattern, props) -> bool:
        if rel_pat.types and edge.type not in rel_pat.types:
            return False
        if props:
            for k, v in props.items():
                if not _value_eq(edge.properties.get(k), v):
                    return False
        return True

    def _expand(
        self, node_id: str, rel_pat: ast.RelPattern, props,
        materialize: bool = True,
    ) -> list[tuple]:
        """Edges leaving `node_id` per the pattern direction.

        materialize=True -> (Edge, other_id) pairs (needed when the rel
        binds a variable, the path is named, or the pattern filters on
        edge properties). materialize=False with fast adjacency ->
        (edge_id, other_id) pairs, skipping per-edge defensive copies —
        the dominant cost of unanchored traversal scans."""
        if not materialize and props is None and self._iter_adj is not None:
            out = []
            types = rel_pat.types
            if rel_pat.direction in ("out", "both"):
                for eid, t, oid in self._iter_adj(node_id, "out"):
                    if not types or t in types:
                        out.append((eid, oid))
            if rel_pat.direction in ("in", "both"):
                for eid, t, oid in self._iter_adj(node_id, "in"):
                    if not types or t in types:
                        out.append((eid, oid))
            out.sort()
            return out
        out = []
        if rel_pat.direction in ("out", "both"):
            for e in self.storage.get_outgoing_edges(node_id):
                if self._rel_matches(e, rel_pat, props):
                    out.append((e, e.end_node))
        if rel_pat.direction in ("in", "both"):
            for e in self.storage.get_incoming_edges(node_id):
                if self._rel_matches(e, rel_pat, props):
                    out.append((e, e.start_node))
        out.sort(key=lambda t: t[0].id)
        return out

    # -- recursive path walk ------------------------------------------------------
    def _match_elements(
        self,
        path: ast.PatternPath,
        base_row: dict,
        params: dict,
        idx: int,
        row: dict,
        path_nodes: list[Node],
        path_rels: list[Edge],
    ) -> Iterator[dict[str, Any]]:
        elements = path.elements
        if idx >= len(elements):
            out = dict(row)
            if path.name:
                out[path.name] = make_path(path_nodes, path_rels)
            yield out
            return
        el = elements[idx]
        if isinstance(el, ast.NodePattern):
            if idx == 0:
                for node in self._candidates(el, row, params):
                    if not self._passes_inline_where(node, el, row, params):
                        continue
                    new_row = dict(row)
                    if el.variable:
                        new_row[el.variable] = node
                    yield from self._match_elements(
                        path, base_row, params, idx + 1, new_row,
                        path_nodes + [node], path_rels,
                    )
            else:
                raise CypherTypeError("internal: node pattern out of sequence")
            return
        # relationship element: el, followed by target node element
        rel_pat = el
        target_pat = elements[idx + 1]
        src = path_nodes[-1]
        props = self._rel_props(rel_pat, row, params)
        tprops = self._node_props(target_pat, row, params)
        if rel_pat.var_length:
            yield from self._match_var_length(
                path, params, idx, row, path_nodes, path_rels, rel_pat,
                target_pat, props, tprops, src,
            )
            return
        need_edges = bool(rel_pat.variable or path.name)
        for edge, other_id in self._expand(
            src.id, rel_pat, props, materialize=need_edges
        ):
            eid = _rel_id(edge)
            if any(_rel_id(e) == eid for e in path_rels):
                continue  # relationship isomorphism
            try:
                other = self.storage.get_node(other_id)
            except NotFoundError:
                continue
            if not self._node_matches(other, target_pat, tprops):
                continue
            if not self._passes_inline_where(other, target_pat, row, params):
                continue
            if target_pat.variable and target_pat.variable in row:
                bound = row[target_pat.variable]
                if not isinstance(bound, Node) or bound.id != other.id:
                    continue
            new_row = dict(row)
            if rel_pat.variable:
                new_row[rel_pat.variable] = edge
            if target_pat.variable:
                new_row[target_pat.variable] = other
            yield from self._match_elements(
                path, row, params, idx + 2, new_row,
                path_nodes + [other], path_rels + [edge],
            )

    def _match_var_length(
        self, path, params, idx, row, path_nodes, path_rels,
        rel_pat, target_pat, props, tprops, src,
    ) -> Iterator[dict[str, Any]]:
        """Variable-length expansion via DFS with edge-set de-dup
        (ref: findPaths traversal.go:1127)."""
        max_h = min(rel_pat.max_hops, MAX_VAR_LENGTH)
        min_h = rel_pat.min_hops
        need_edges = bool(rel_pat.variable or path.name)

        def walk(curr: Node, hops: int, rels: list[Edge], nodes: list[Node]):
            if hops >= min_h:
                if self._node_matches(curr, target_pat, tprops) and \
                        self._passes_inline_where(curr, target_pat, row, params):
                    if target_pat.variable and target_pat.variable in row:
                        bound = row[target_pat.variable]
                        ok = isinstance(bound, Node) and bound.id == curr.id
                    else:
                        ok = True
                    if ok:
                        new_row = dict(row)
                        if rel_pat.variable:
                            new_row[rel_pat.variable] = list(rels)
                        if target_pat.variable:
                            new_row[target_pat.variable] = curr
                        yield new_row, list(nodes), list(rels)
            if hops >= max_h:
                return
            for edge, other_id in self._expand(
                curr.id, rel_pat, props, materialize=need_edges
            ):
                eid = _rel_id(edge)
                if any(_rel_id(e) == eid for e in rels) or any(
                    _rel_id(e) == eid for e in path_rels
                ):
                    continue
                try:
                    other = self.storage.get_node(other_id)
                except NotFoundError:
                    continue
                yield from walk(other, hops + 1, rels + [edge], nodes + [other])

        start_nodes = list(path_nodes)
        if min_h == 0:
            # zero-length: current node is also the target
            for new_row, nodes, rels in walk(src, 0, [], []):
                yield from self._match_elements(
                    path, row, params, idx + 2, new_row,
                    start_nodes + nodes, path_rels + rels,
                )
            return
        for new_row, nodes, rels in walk(src, 0, [], []):
            if not rels:
                continue
            yield from self._match_elements(
                path, row, params, idx + 2, new_row,
                start_nodes + nodes, path_rels + rels,
            )

    # -- shortest path -------------------------------------------------------------
    def _match_shortest(
        self, path: ast.PatternPath, row: dict, params: dict
    ) -> Iterator[dict[str, Any]]:
        """(ref: shortestPath traversal.go:1332) — BFS between two bound/matched
        endpoints over the middle relationship pattern."""
        if len(path.elements) != 3:
            raise CypherTypeError("shortestPath expects (a)-[rel]-(b)")
        start_pat, rel_pat, end_pat = path.elements
        props = self._rel_props(rel_pat, row, params)
        max_h = min(rel_pat.max_hops if rel_pat.var_length else MAX_VAR_LENGTH,
                    MAX_VAR_LENGTH)
        for start in self._candidates(start_pat, row, params):
            for end in self._candidates(end_pat, row, params):
                found = self._bfs_shortest(
                    start, end, rel_pat, props, max_h,
                    all_paths=(path.shortest == "allshortest"),
                )
                for nodes, rels in found:
                    out = dict(row)
                    if start_pat.variable:
                        out[start_pat.variable] = start
                    if end_pat.variable:
                        out[end_pat.variable] = end
                    if rel_pat.variable:
                        out[rel_pat.variable] = rels
                    if path.name:
                        out[path.name] = make_path(nodes, rels)
                    yield out

    def _bfs_shortest(
        self, start: Node, end: Node, rel_pat, props, max_h: int,
        all_paths: bool = False,
    ) -> list[tuple[list[Node], list[Edge]]]:
        if start.id == end.id:
            return [([start], [])]
        frontier: list[tuple[str, list[Node], list[Edge]]] = [(start.id, [start], [])]
        visited = {start.id}
        results: list[tuple[list[Node], list[Edge]]] = []
        for _ in range(max_h):
            nxt: list[tuple[str, list[Node], list[Edge]]] = []
            level_visited: set[str] = set()
            for nid, nodes, rels in frontier:
                for edge, other_id in self._expand(nid, rel_pat, props):
                    if other_id in visited:
                        continue
                    try:
                        other = self.storage.get_node(other_id)
                    except NotFoundError:
                        continue
                    p = (nodes + [other], rels + [edge])
                    if other_id == end.id:
                        results.append(p)
                        if not all_paths:
                            return results
                        continue
                    level_visited.add(other_id)
                    nxt.append((other_id, p[0], p[1]))
            if results:
                return results
            visited |= level_visited
            frontier = nxt
            if not frontier:
                break
        return results


def _value_eq(a: Any, b: Any) -> bool:
    if a is None and b is None:
        return True
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b
