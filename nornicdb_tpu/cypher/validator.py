"""Opt-in strict Cypher semantic validation.

Behavioral reference: the reference ships a full generated ANTLR grammar
used for validation only, switched by NORNICDB_PARSER=nornic|antlr
(/root/reference/pkg/cypher/antlr/, executor.go:1572-1655,
docs/architecture/cypher-parser-modes.md — "Syntax Validation: Lenient"
vs "Strict OpenCypher"). This build's recursive-descent parser already
rejects malformed token streams; what the lenient path misses is the
*semantic* layer of OpenCypher validation. This module is that layer:
a pure AST pass (no execution), enabled by NORNICDB_PARSER=strict (the
reference's `antlr` value is accepted as an alias) or per-executor via
`executor.strict_validation = True`.

Checks and the Neo4j errors they mirror:
- queries cannot conclude with MATCH/WITH/UNWIND/LOAD CSV
- undefined variable references ("Variable `x` not defined"), with scope
  threaded through WITH projections, UNWIND, CALL YIELD, subqueries
- expressions in WITH must be aliased
- invalid use of aggregating functions (WHERE, UNWIND, pattern
  properties) and nested aggregation
- RETURN * with no variables in scope
- duplicate result column names
- conflicting variable redeclaration (node var reused as rel var; same
  rel variable bound twice in one pattern; CREATE of a bound variable
  with labels/properties)
- variable-length relationships in CREATE/MERGE
- non-integer or negative SKIP/LIMIT literals
- UNION branches must have identical column names
"""

from __future__ import annotations

import os
from typing import Optional

from nornicdb_tpu.cypher import ast
from nornicdb_tpu.errors import CypherSyntaxError

# aggregating functions per OpenCypher (ref: the ANTLR grammar's
# aggregate rules; executor fast-agg family traversal_fast_agg.go)
AGGREGATES = {
    "count", "sum", "avg", "min", "max", "collect", "stdev", "stdevp",
    "percentilecont", "percentiledisc",
}


def strict_mode_enabled() -> bool:
    """NORNICDB_PARSER=strict|antlr (ref: config.SetParserType)."""
    return os.environ.get("NORNICDB_PARSER", "").lower() in ("strict", "antlr")


def _err(msg: str) -> CypherSyntaxError:
    return CypherSyntaxError(f"strict validation: {msg}")


class _Scope:
    """Variable scope with an `open` escape hatch: once we pass a
    construct whose bindings we cannot enumerate (CALL ... YIELD *),
    undefined-variable errors are suppressed, but every other check
    still runs."""

    def __init__(self, names: Optional[set[str]] = None, open_: bool = False):
        self.names: set[str] = set(names or ())
        self.open = open_

    def has(self, name: str) -> bool:
        return self.open or name in self.names

    def copy(self) -> "_Scope":
        return _Scope(self.names, self.open)


class Validator:
    def validate(self, stmt: ast.Statement) -> None:
        if isinstance(stmt, ast.Query):
            self._query(stmt)
        elif isinstance(stmt, ast.UseCommand) and stmt.query is not None:
            self._query(stmt.query)
        # DDL/admin statements are fully checked by the parser

    # -- query level -------------------------------------------------------
    def _query(self, q: ast.Query, outer: Optional[_Scope] = None) -> None:
        cols = self._single_query(q, outer)
        for union_q, _all in q.unions:
            ucols = self._single_query(union_q, outer)
            if cols is not None and ucols is not None and cols != ucols:
                raise _err(
                    "All sub queries in an UNION must have the same "
                    f"column names (got {cols} vs {ucols})"
                )

    def _single_query(
        self, q: ast.Query, outer: Optional[_Scope] = None
    ) -> Optional[list[str]]:
        """Validates one UNION branch; returns its column names (None if
        unknowable, e.g. RETURN *)."""
        scope = outer.copy() if outer is not None else _Scope()
        columns: Optional[list[str]] = None
        for i, clause in enumerate(q.clauses):
            last = i == len(q.clauses) - 1
            if last and isinstance(
                clause,
                (ast.MatchClause, ast.WithClause, ast.UnwindClause,
                 ast.LoadCsvClause),
            ):
                kind = {
                    ast.MatchClause: "MATCH",
                    ast.WithClause: "WITH",
                    ast.UnwindClause: "UNWIND",
                    ast.LoadCsvClause: "LOAD CSV",
                }[type(clause)]
                raise _err(
                    f"Query cannot conclude with {kind} (must be a RETURN "
                    "clause, an update clause, a unit subquery call, or a "
                    "procedure call with no YIELD)"
                )
            columns = self._clause(clause, scope)
        return columns

    # -- clauses -----------------------------------------------------------
    def _clause(self, clause, scope: _Scope) -> Optional[list[str]]:
        if isinstance(clause, ast.MatchClause):
            self._match(clause, scope)
        elif isinstance(clause, ast.CreateClause):
            self._create(clause, scope)
        elif isinstance(clause, ast.MergeClause):
            self._merge(clause, scope)
        elif isinstance(clause, ast.SetClause):
            for item in clause.items:
                self._set_item(item, scope)
        elif isinstance(clause, ast.RemoveClause):
            for item in clause.items:
                self._set_item(item, scope)
        elif isinstance(clause, ast.DeleteClause):
            for e in clause.exprs:
                if isinstance(e, (ast.Literal, ast.MapLiteral, ast.ListLiteral)):
                    raise _err("DELETE expected a node or relationship "
                               "variable, got a literal")
                self._expr(e, scope)
        elif isinstance(clause, (ast.WithClause, ast.ReturnClause)):
            return self._projection(clause, scope)
        elif isinstance(clause, ast.UnwindClause):
            self._no_aggregates(clause.expr, "UNWIND")
            self._expr(clause.expr, scope)
            scope.names.add(clause.variable)
        elif isinstance(clause, ast.CallClause):
            for a in clause.args:
                self._expr(a, scope)
            if clause.yield_star:
                scope.open = True
            for name, alias in clause.yield_items:
                scope.names.add(alias or name)
            if clause.where is not None:
                self._expr(clause.where, scope)
        elif isinstance(clause, ast.CallSubquery):
            for v in clause.imported:
                if not scope.has(v):
                    raise _err(f"Variable `{v}` not defined (imported into "
                               "CALL subquery)")
            inner = _Scope(set(clause.imported), scope.open)
            self._query(clause.query, inner)
            # the subquery's RETURN aliases join the outer scope
            for sub_clause in clause.query.clauses:
                if isinstance(sub_clause, ast.ReturnClause):
                    if sub_clause.star:
                        scope.open = True
                    for item in sub_clause.items:
                        scope.names.add(item.key)
        elif isinstance(clause, ast.ForeachClause):
            self._expr(clause.expr, scope)
            body_scope = scope.copy()
            body_scope.names.add(clause.variable)
            for upd in clause.updates:
                if isinstance(
                    upd, (ast.MatchClause, ast.WithClause, ast.ReturnClause,
                          ast.UnwindClause, ast.CallClause)
                ):
                    raise _err(
                        "Invalid use of "
                        f"{type(upd).__name__.replace('Clause', '').upper()} "
                        "inside FOREACH (only updating clauses are allowed)"
                    )
                self._clause(upd, body_scope)
        elif isinstance(clause, ast.LoadCsvClause):
            self._expr(clause.url, scope)
            scope.names.add(clause.variable)
        return None

    def _match(self, clause: ast.MatchClause, scope: _Scope) -> None:
        new = scope.copy()
        for path in clause.patterns:
            self._pattern(path, new, binding=True, updating=False)
        if clause.where is not None:
            self._no_aggregates(clause.where, "WHERE")
            self._expr(clause.where, new)
        scope.names |= new.names

    def _create(self, clause: ast.CreateClause, scope: _Scope) -> None:
        for path in clause.patterns:
            self._pattern(path, scope, binding=True, updating=True)

    def _merge(self, clause: ast.MergeClause, scope: _Scope) -> None:
        self._pattern(clause.pattern, scope, binding=True, updating=True)
        for item in clause.on_create + clause.on_match:
            self._set_item(item, scope)

    def _set_item(self, item: ast.SetItem, scope: _Scope) -> None:
        self._expr(item.target, scope)
        if item.value is not None:
            self._no_aggregates(item.value, "SET")
            self._expr(item.value, scope)

    def _projection(self, clause, scope: _Scope) -> Optional[list[str]]:
        is_with = isinstance(clause, ast.WithClause)
        if clause.star and not scope.open and not scope.names:
            raise _err(
                f"{'WITH' if is_with else 'RETURN'} * is not allowed when "
                "there are no variables in scope"
            )
        names: list[str] = []
        for item in clause.items:
            if is_with and item.alias is None and not isinstance(
                item.expr, ast.Variable
            ):
                raise _err(
                    "Expression in WITH must be aliased (use AS)"
                )
            self._check_nested_aggregates(item.expr)
            self._expr(item.expr, scope)
            names.append(item.key)
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise _err(
                "Multiple result columns with the same name are not "
                f"supported ({sorted(dupes)})"
            )
        # ORDER BY/WHERE see both input scope and the new aliases
        extended = scope.copy()
        extended.names |= set(names)
        for item in clause.order_by:
            self._expr(item.expr, extended)
        if is_with and clause.where is not None:
            self._expr(clause.where, extended)
        for bound, label in ((clause.skip, "SKIP"), (clause.limit, "LIMIT")):
            # fold unary minus so LIMIT -1 (UnaryOp('-', Literal(1)))
            # is seen as the negative literal it is
            if (
                isinstance(bound, ast.UnaryOp)
                and bound.op == "-"
                and isinstance(bound.operand, ast.Literal)
                and isinstance(bound.operand.value, (int, float))
                and not isinstance(bound.operand.value, bool)
            ):
                bound = ast.Literal(-bound.operand.value)
            if isinstance(bound, ast.Literal):
                v = bound.value
                if not isinstance(v, int) or isinstance(v, bool):
                    raise _err(f"{label} must be a non-negative integer "
                               f"(got {v!r})")
                if v < 0:
                    raise _err(f"{label} must be a non-negative integer "
                               f"(got {v})")
            elif bound is not None and not isinstance(bound, ast.Parameter):
                # expressions referencing variables are not allowed here
                for name in self._free_variables(bound):
                    raise _err(
                        f"It is not allowed to refer to variables "
                        f"(`{name}`) in {label}"
                    )
        if is_with:
            new = _Scope(set(names))
            if clause.star:
                new.names |= scope.names
                new.open = scope.open
            scope.names = new.names
            scope.open = new.open
            return None
        return None if clause.star else names

    # -- patterns ----------------------------------------------------------
    def _pattern(
        self, path: ast.PatternPath, scope: _Scope, binding: bool,
        updating: bool,
    ) -> None:
        """Validates one pattern path, binding its variables into scope.

        `updating` marks CREATE/MERGE patterns, which have stricter rules
        (no var-length rels, no re-binding with labels/properties).
        """
        rel_vars_here: set[str] = set()
        node_vars: set[str] = set()
        rel_vars: set[str] = set()
        for el in path.elements:
            if isinstance(el, ast.NodePattern):
                if el.variable:
                    node_vars.add(el.variable)
            else:
                if el.variable:
                    rel_vars.add(el.variable)
        for el in path.elements:
            if isinstance(el, ast.NodePattern):
                if el.variable:
                    if el.variable in rel_vars:
                        raise _err(
                            f"Type mismatch: `{el.variable}` is used as "
                            "both a node and a relationship variable"
                        )
                    already = el.variable in scope.names
                    if updating and already and (el.labels or el.properties):
                        raise _err(
                            f"Can't create/merge node `{el.variable}` with "
                            "labels or properties here — the variable is "
                            "already declared in this context"
                        )
                    if binding:
                        scope.names.add(el.variable)
                if el.properties is not None:
                    self._no_aggregates(el.properties, "pattern properties")
                    self._expr(el.properties, self._pattern_scope(scope, path))
                if el.where is not None:
                    self._expr(el.where, self._pattern_scope(scope, path))
            else:  # RelPattern
                if el.var_length and updating:
                    raise _err(
                        "Variable length relationships cannot be used in "
                        "CREATE or MERGE"
                    )
                if el.variable:
                    if el.variable in rel_vars_here:
                        raise _err(
                            "Cannot use the same relationship variable "
                            f"`{el.variable}` for multiple relationships"
                        )
                    rel_vars_here.add(el.variable)
                    if el.variable in node_vars:
                        raise _err(
                            f"Type mismatch: `{el.variable}` is used as "
                            "both a node and a relationship variable"
                        )
                    if binding:
                        scope.names.add(el.variable)
                if el.properties is not None:
                    self._no_aggregates(el.properties, "pattern properties")
                    self._expr(el.properties, self._pattern_scope(scope, path))
        if path.name:
            if binding:
                scope.names.add(path.name)

    @staticmethod
    def _pattern_scope(scope: _Scope, path: ast.PatternPath) -> _Scope:
        """Expressions inside a pattern may reference variables bound
        anywhere in the same pattern (plus the enclosing scope)."""
        s = scope.copy()
        for el in path.elements:
            if el.variable:
                s.names.add(el.variable)
        if path.name:
            s.names.add(path.name)
        return s

    # -- expressions -------------------------------------------------------
    def _expr(self, e, scope: _Scope) -> None:
        if e is None or isinstance(e, (ast.Literal, ast.Parameter)):
            return
        if isinstance(e, ast.Variable):
            if not scope.has(e.name):
                raise _err(f"Variable `{e.name}` not defined")
            return
        if isinstance(e, ast.Property):
            self._expr(e.subject, scope)
            return
        if isinstance(e, ast.ListLiteral):
            for x in e.items:
                self._expr(x, scope)
            return
        if isinstance(e, ast.MapLiteral):
            for x in e.items.values():
                self._expr(x, scope)
            return
        if isinstance(e, ast.FunctionCall):
            for a in e.args:
                self._expr(a, scope)
            return
        if isinstance(e, ast.UnaryOp):
            self._expr(e.operand, scope)
            return
        if isinstance(e, ast.BinaryOp):
            self._expr(e.left, scope)
            self._expr(e.right, scope)
            return
        if isinstance(e, ast.IsNull):
            self._expr(e.operand, scope)
            return
        if isinstance(e, ast.Subscript):
            self._expr(e.subject, scope)
            self._expr(e.index, scope)
            return
        if isinstance(e, ast.Slice):
            self._expr(e.subject, scope)
            self._expr(e.start, scope)
            self._expr(e.end, scope)
            return
        if isinstance(e, ast.CaseExpr):
            self._expr(e.subject, scope)
            for w, t in e.whens:
                self._expr(w, scope)
                self._expr(t, scope)
            self._expr(e.default, scope)
            return
        if isinstance(e, ast.ListComprehension):
            self._expr(e.source, scope)
            inner = scope.copy()
            inner.names.add(e.variable)
            self._expr(e.where, inner)
            self._expr(e.projection, inner)
            return
        if isinstance(e, ast.MapProjection):
            self._expr(e.subject, scope)
            for kind, payload in e.items:
                if kind == "alias":
                    self._expr(payload[1], scope)
                elif kind == "var":
                    self._expr(ast.Variable(payload), scope)
            return
        if isinstance(e, ast.PatternComprehension):
            inner = scope.copy()
            self._pattern(e.pattern, inner, binding=True, updating=False)
            self._expr(e.where, inner)
            self._expr(e.projection, inner)
            return
        if isinstance(e, ast.PatternPredicate):
            # bare pattern predicate: may introduce no new bindings; all
            # its variables must exist OR be anonymous
            inner = scope.copy()
            self._pattern(e.pattern, inner, binding=True, updating=False)
            return
        if isinstance(e, (ast.ExistsSubquery, ast.CountSubquery)):
            inner = scope.copy()
            self._pattern(e.pattern, inner, binding=True, updating=False)
            self._expr(e.where, inner)
            return
        if isinstance(e, ast.ReduceExpr):
            self._expr(e.init, scope)
            self._expr(e.source, scope)
            inner = scope.copy()
            inner.names.add(e.accumulator)
            inner.names.add(e.variable)
            self._expr(e.body, inner)
            return
        if isinstance(e, ast.Quantifier):
            self._expr(e.source, scope)
            inner = scope.copy()
            inner.names.add(e.variable)
            self._expr(e.predicate, inner)
            return
        # unknown expression node: nothing to check

    # -- aggregate rules ---------------------------------------------------
    def _iter_function_calls(self, e):
        if isinstance(e, ast.FunctionCall):
            yield e
        for child in self._children(e):
            yield from self._iter_function_calls(child)

    @staticmethod
    def _children(e):
        if isinstance(e, ast.FunctionCall):
            return list(e.args)
        if isinstance(e, ast.UnaryOp):
            return [e.operand]
        if isinstance(e, ast.BinaryOp):
            return [e.left, e.right]
        if isinstance(e, ast.IsNull):
            return [e.operand]
        if isinstance(e, ast.Property):
            return [e.subject]
        if isinstance(e, ast.ListLiteral):
            return list(e.items)
        if isinstance(e, ast.MapLiteral):
            return list(e.items.values())
        if isinstance(e, ast.Subscript):
            return [e.subject, e.index]
        if isinstance(e, ast.Slice):
            return [x for x in (e.subject, e.start, e.end) if x is not None]
        if isinstance(e, ast.CaseExpr):
            out = [x for x in (e.subject, e.default) if x is not None]
            for w, t in e.whens:
                out += [w, t]
            return out
        if isinstance(e, ast.ListComprehension):
            return [x for x in (e.source, e.where, e.projection)
                    if x is not None]
        if isinstance(e, ast.ReduceExpr):
            return [e.init, e.source, e.body]
        if isinstance(e, ast.Quantifier):
            return [e.source, e.predicate]
        if isinstance(e, ast.MapProjection):
            out = [e.subject]
            for kind, payload in e.items:
                if kind == "alias":
                    out.append(payload[1])
            return out
        if isinstance(e, ast.PatternComprehension):
            return Validator._pattern_exprs(e.pattern) + [
                x for x in (e.where, e.projection) if x is not None
            ]
        if isinstance(e, ast.PatternPredicate):
            return Validator._pattern_exprs(e.pattern)
        if isinstance(e, (ast.ExistsSubquery, ast.CountSubquery)):
            return Validator._pattern_exprs(e.pattern) + (
                [e.where] if e.where is not None else []
            )
        return []

    @staticmethod
    def _pattern_exprs(path: ast.PatternPath) -> list:
        """Expressions embedded in a pattern: property maps and inline
        WHEREs."""
        out: list = []
        for el in path.elements:
            if el.properties is not None:
                out.append(el.properties)
            if isinstance(el, ast.NodePattern) and el.where is not None:
                out.append(el.where)
        return out

    def _no_aggregates(self, e, context: str) -> None:
        for fc in self._iter_function_calls(e):
            if fc.name.lower() in AGGREGATES:
                raise _err(
                    f"Invalid use of aggregating function "
                    f"{fc.name}(...) in {context}"
                )

    def _check_nested_aggregates(self, e) -> None:
        for fc in self._iter_function_calls(e):
            if fc.name.lower() in AGGREGATES:
                for inner in fc.args:
                    for nested in self._iter_function_calls(inner):
                        if nested.name.lower() in AGGREGATES:
                            raise _err(
                                "Can't use aggregate functions inside of "
                                f"aggregate functions ({nested.name} inside "
                                f"{fc.name})"
                            )

    # -- helpers -----------------------------------------------------------
    def _free_variables(self, e) -> set[str]:
        out: set[str] = set()
        if isinstance(e, ast.Variable):
            out.add(e.name)
        for child in self._children(e):
            out |= self._free_variables(child)
        return out


def validate(stmt: ast.Statement) -> None:
    """Run the strict semantic pass; raises CypherSyntaxError."""
    Validator().validate(stmt)
