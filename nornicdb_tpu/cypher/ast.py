"""Cypher AST node definitions."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Union


# ---------------------------------------------------------------- expressions
@dataclass
class Literal:
    value: Any


@dataclass
class Parameter:
    name: str


@dataclass
class Variable:
    name: str


@dataclass
class Property:
    subject: "Expr"
    key: str


@dataclass
class ListLiteral:
    items: list["Expr"]


@dataclass
class MapLiteral:
    items: dict[str, "Expr"]


@dataclass
class FunctionCall:
    name: str  # lowercased, may be dotted (apoc.text.join)
    args: list["Expr"]
    distinct: bool = False


@dataclass
class UnaryOp:
    op: str  # NOT, -, +
    operand: "Expr"


@dataclass
class BinaryOp:
    op: str  # + - * / % ^ = <> < > <= >= AND OR XOR IN =~ STARTS ENDS CONTAINS
    left: "Expr"
    right: "Expr"


@dataclass
class IsNull:
    operand: "Expr"
    negated: bool = False


@dataclass
class Subscript:
    subject: "Expr"
    index: "Expr"


@dataclass
class Slice:
    subject: "Expr"
    start: Optional["Expr"]
    end: Optional["Expr"]


@dataclass
class CaseExpr:
    subject: Optional["Expr"]  # simple CASE has a subject; searched has None
    whens: list[tuple["Expr", "Expr"]]
    default: Optional["Expr"]


@dataclass
class ListComprehension:
    variable: str
    source: "Expr"
    where: Optional["Expr"]
    projection: Optional["Expr"]


@dataclass
class MapProjection:
    """n {.a, .b, .*, key: expr, var} — Neo4j map projection."""

    subject: "Expr"
    items: list[tuple[str, Any]]  # (kind, payload): prop/all/alias/var


@dataclass
class PatternComprehension:
    """[(a)-[:R]->(b) WHERE p | expr]"""

    pattern: "PatternPath"
    where: Optional["Expr"]
    projection: "Expr"


@dataclass
class PatternPredicate:
    """A bare pattern used as a boolean predicate, e.g. WHERE (a)-[:KNOWS]->(b)."""

    pattern: "PatternPath"


@dataclass
class ExistsSubquery:
    pattern: "PatternPath"
    where: Optional["Expr"] = None


@dataclass
class CountSubquery:
    pattern: "PatternPath"
    where: Optional["Expr"] = None


@dataclass
class CollectSubquery:
    """COLLECT { MATCH ... RETURN expr } — Neo4j 5 collect subquery;
    evaluates the inner single-column query per row, returns the list."""

    query: "Query"


@dataclass
class LabelPredicate:
    """n:Label[:Label...] used as a boolean expression (WHERE n:Person)."""

    subject: "Expr"
    labels: list[str]


@dataclass
class ReduceExpr:
    """reduce(acc = init, x IN list | expr)"""

    accumulator: str
    init: "Expr"
    variable: str
    source: "Expr"
    body: "Expr"


@dataclass
class Quantifier:
    """ALL/ANY/NONE/SINGLE(x IN list WHERE pred)"""

    kind: str
    variable: str
    source: "Expr"
    predicate: "Expr"


Expr = Union[
    Literal, Parameter, Variable, Property, ListLiteral, MapLiteral,
    FunctionCall, UnaryOp, BinaryOp, IsNull, Subscript, Slice, CaseExpr,
    ListComprehension, PatternPredicate, ExistsSubquery, CountSubquery,
    CollectSubquery, LabelPredicate,
    Quantifier, ReduceExpr, MapProjection, PatternComprehension,
]


# ---------------------------------------------------------------- patterns
@dataclass
class NodePattern:
    variable: Optional[str]
    labels: list[str]
    properties: Optional[MapLiteral]
    where: Optional["Expr"] = None  # inline (n:L WHERE n.x > 1)


@dataclass
class RelPattern:
    variable: Optional[str]
    types: list[str]
    properties: Optional[MapLiteral]
    direction: str  # "out" (->), "in" (<-), "both" (-)
    min_hops: int = 1
    max_hops: int = 1
    var_length: bool = False


@dataclass
class PatternPath:
    """node (rel node)* — optionally named: p = (a)-[r]->(b)."""

    elements: list[Union[NodePattern, RelPattern]]
    name: Optional[str] = None
    shortest: Optional[str] = None  # None | "shortest" | "allshortest"


# ---------------------------------------------------------------- clauses
@dataclass
class ReturnItem:
    expr: Expr
    alias: Optional[str]

    @property
    def key(self) -> str:
        return self.alias or expr_text(self.expr)


@dataclass
class OrderItem:
    expr: Expr
    descending: bool = False


@dataclass
class MatchClause:
    patterns: list[PatternPath]
    optional: bool = False
    where: Optional[Expr] = None


@dataclass
class CreateClause:
    patterns: list[PatternPath]


@dataclass
class MergeClause:
    pattern: PatternPath
    on_create: list["SetItem"] = field(default_factory=list)
    on_match: list["SetItem"] = field(default_factory=list)


@dataclass
class SetItem:
    # kinds: property (a.x = v), variable (a = {..} / a += {..}), label (a:Foo)
    kind: str
    target: Expr
    value: Optional[Expr] = None
    labels: list[str] = field(default_factory=list)
    merge: bool = False  # += semantics


@dataclass
class SetClause:
    items: list[SetItem]


@dataclass
class RemoveClause:
    items: list[SetItem]  # property / label kinds


@dataclass
class DeleteClause:
    exprs: list[Expr]
    detach: bool = False


@dataclass
class WithClause:
    items: list[ReturnItem]
    distinct: bool = False
    order_by: list[OrderItem] = field(default_factory=list)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None
    where: Optional[Expr] = None
    star: bool = False


@dataclass
class ReturnClause:
    items: list[ReturnItem]
    distinct: bool = False
    order_by: list[OrderItem] = field(default_factory=list)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None
    star: bool = False


@dataclass
class UnwindClause:
    expr: Expr
    variable: str
    # reference-dialect extension: UNWIND ... AS x WHERE pred row filter
    where: Optional[Expr] = None


@dataclass
class CallClause:
    procedure: str
    args: list[Expr]
    yield_items: list[tuple[str, Optional[str]]]  # (name, alias)
    where: Optional[Expr] = None
    yield_star: bool = False
    # standalone-call tail without RETURN (CALL ... YIELD ... LIMIT n)
    order_by: list["OrderItem"] = field(default_factory=list)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None


@dataclass
class CallSubquery:
    query: "Query"
    imported: list[str] = field(default_factory=list)
    # CALL { ... } IN TRANSACTIONS [OF n ROWS]
    in_transactions: bool = False
    batch_rows: int = 1000
    # reference-dialect tail without RETURN (CALL { ... } ORDER BY ...)
    order_by: list["OrderItem"] = field(default_factory=list)
    skip: Optional["Expr"] = None
    limit: Optional["Expr"] = None


@dataclass
class ForeachClause:
    variable: str
    expr: Expr
    updates: list[Any] = field(default_factory=list)


@dataclass
class LoadCsvClause:
    url: Expr
    variable: str
    with_headers: bool = False
    field_terminator: str = ","


Clause = Union[
    MatchClause, CreateClause, MergeClause, SetClause, RemoveClause,
    DeleteClause, WithClause, ReturnClause, UnwindClause, CallClause,
    CallSubquery, ForeachClause, LoadCsvClause,
]


@dataclass
class Query:
    clauses: list[Clause]
    # UNION chains: list of (query, all) appended to this one
    unions: list[tuple["Query", bool]] = field(default_factory=list)
    explain: bool = False
    profile: bool = False


_UPDATING_CLAUSES = (
    CreateClause, MergeClause, SetClause, RemoveClause, DeleteClause,
    ForeachClause, LoadCsvClause,
)

# procedures known to be pure reads; every other CALL is treated as updating.
# Shared with the executor's read/write classification so the parse-time
# COLLECT gate and RBAC/cacheability never disagree on what counts as a write.
READONLY_PROCEDURES = (
    "db.labels", "db.relationshiptypes", "db.propertykeys",
    "dbms.components", "db.index.vector.querynodes",
    "db.index.vector.queryrelationships",
    "db.index.fulltext.querynodes",
    "db.index.fulltext.queryrelationships", "apoc.help",
    # every gds.* STREAM procedure is read-only; the graph catalog is not
    # (see MUTATING_PROCEDURE_EXCEPTIONS)
    "gds.",
    # read-only graph scans/traversals; NOT apoc.lock./apoc.export. etc. —
    # side-effectful-but-non-mutating procedures must stay write-classified
    # or the cache would skip their side effects on repeat calls
    "apoc.search.", "apoc.path.", "apoc.meta.",
    "apoc.schema.nodes", "apoc.schema.relationships",
)

# procedures under a read-only prefix that DO mutate state — classified as
# writes so the result cache never serves a stale catalog and RBAC treats
# them as writes (gds.graph.project registers, drop removes)
MUTATING_PROCEDURE_EXCEPTIONS = ("gds.graph.project", "gds.graph.drop")


def procedure_is_readonly(name: str) -> bool:
    name = name.lower()
    if name.startswith(MUTATING_PROCEDURE_EXCEPTIONS):
        return False
    return name.startswith(READONLY_PROCEDURES)


def has_updating_clause(q: "Query") -> bool:
    """True if the query (or a nested CALL { } subquery / UNION branch)
    contains an updating clause, including CALLs of procedures not known to
    be read-only. Used to reject writes where Neo4j forbids them
    (COLLECT { } subqueries) and to keep read/write classification honest
    for expression-level subqueries."""
    for c in q.clauses:
        if isinstance(c, _UPDATING_CLAUSES):
            return True
        if isinstance(c, CallClause) and not procedure_is_readonly(
            c.procedure
        ):
            return True
        if isinstance(c, CallSubquery) and has_updating_clause(c.query):
            return True
    return any(has_updating_clause(sub) for sub, _ in q.unions)


# ---------------------------------------------------------------- DDL / admin
@dataclass
class CreateIndex:
    name: Optional[str]
    kind: str  # property/composite/vector/fulltext/range/text
    label: str
    properties: list[str]
    options: dict[str, Any] = field(default_factory=dict)
    if_not_exists: bool = False


@dataclass
class DropIndex:
    name: str
    if_exists: bool = False


@dataclass
class CreateConstraint:
    name: Optional[str]
    label: str
    properties: list[str]
    kind: str = "unique"
    if_not_exists: bool = False


@dataclass
class DropConstraint:
    name: str
    if_exists: bool = False


@dataclass
class ShowCommand:
    what: str  # indexes/constraints/databases/procedures/functions
    yield_items: list[str] = field(default_factory=list)
    target: Optional[str] = None  # SHOW ALIASES FOR DATABASE <target>


@dataclass
class DatabaseCommand:
    op: str  # create/drop/start/stop/alias...
    name: str
    if_not_exists: bool = False
    if_exists: bool = False
    options: dict[str, Any] = field(default_factory=dict)


@dataclass
class UseCommand:
    database: str
    query: Optional[Query] = None


@dataclass
class TxCommand:
    op: str  # begin/commit/rollback


Statement = Union[
    Query, CreateIndex, DropIndex, CreateConstraint, DropConstraint,
    ShowCommand, DatabaseCommand, UseCommand, TxCommand,
]


def expr_text(e: Expr) -> str:
    """Render an expression back to a column-name-ish string."""
    if isinstance(e, Variable):
        return e.name
    if isinstance(e, Property):
        return f"{expr_text(e.subject)}.{e.key}"
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Parameter):
        return f"${e.name}"
    if isinstance(e, FunctionCall):
        inner = ", ".join(expr_text(a) for a in e.args)
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{inner})"
    if isinstance(e, BinaryOp):
        return f"{expr_text(e.left)} {e.op} {expr_text(e.right)}"
    if isinstance(e, UnaryOp):
        return f"{e.op} {expr_text(e.operand)}"
    if isinstance(e, CountSubquery):
        return "COUNT { ... }"
    if isinstance(e, ExistsSubquery):
        return "EXISTS { ... }"
    return type(e).__name__.lower()
