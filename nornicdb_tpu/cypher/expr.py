"""Cypher expression evaluation.

Three-valued logic (null propagation) follows Neo4j semantics, which the
reference mirrors (pkg/cypher executor expression handling; compat spec in
neo4j_compat_test.go).
"""

from __future__ import annotations

import functools
import re
from typing import Any, Optional

from nornicdb_tpu.cypher import ast
from nornicdb_tpu.cypher.functions import FUNCTIONS
from nornicdb_tpu.errors import CypherSyntaxError, CypherTypeError
from nornicdb_tpu.storage.types import Edge, Node

try:  # the `regex` engine supports a hard match timeout
    import regex as _regex_mod
except ImportError:  # pragma: no cover - regex is in the standard image
    _regex_mod = None

# Bound on a single =~ evaluation. The reference runs on Go's RE2, which is
# linear-time by construction (chaos_injection_test.go TestInjection_RegexReDoS
# relies on that); CPython's `re` backtracks exponentially, so a catastrophic
# pattern like (a+)+$ would hang the executor for hours. The `regex` module's
# timeout gives the same guarantee operationally: evil patterns error out
# instead of wedging the query thread.
REGEX_TIMEOUT_S = 2.0


class BoundedPattern:
    """A compiled regex whose matches are time-bounded. Compile once, match
    per row (the columnar WHERE path scans whole property columns)."""

    def __init__(self, pattern):
        try:
            if _regex_mod is not None:
                self._pat = _regex_mod.compile(pattern)
            else:  # pragma: no cover - regex is in the standard image
                self._pat = re.compile(pattern)
        except Exception:
            raise CypherSyntaxError(f"invalid regex: {pattern!r}")
        self._pattern = pattern

    def fullmatch(self, value) -> bool:
        try:
            if _regex_mod is not None:
                return self._pat.fullmatch(
                    value, timeout=REGEX_TIMEOUT_S) is not None
            return self._pat.fullmatch(value) is not None
        except TimeoutError:
            raise CypherSyntaxError(
                f"regex timed out after {REGEX_TIMEOUT_S}s "
                f"(catastrophic backtracking?): {self._pattern!r}"
            )


@functools.lru_cache(maxsize=256)
def _compiled(pattern) -> BoundedPattern:
    return BoundedPattern(pattern)


def regex_fullmatch(pattern, value) -> bool:
    """Cypher `=~`: full match, bounded runtime, CypherSyntaxError on a bad
    pattern. Raises TypeError for non-string subjects (caller semantics)."""
    try:
        pat = _compiled(pattern)
    except TypeError:  # unhashable pattern (caller passed a non-string)
        pat = BoundedPattern(pattern)
    return pat.fullmatch(value)


class EvalContext:
    """Evaluation context: row bindings + params + hooks into the executor."""

    def __init__(
        self,
        bindings: dict[str, Any],
        params: dict[str, Any],
        executor=None,
    ):
        self.bindings = bindings
        self.params = params
        self.executor = executor  # for subqueries / startNode / endNode

    def child(self, extra: dict[str, Any]) -> "EvalContext":
        merged = dict(self.bindings)
        merged.update(extra)
        return EvalContext(merged, self.params, self.executor)


def evaluate(e: ast.Expr, ctx: EvalContext) -> Any:
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.Parameter):
        if e.name not in ctx.params:
            raise CypherSyntaxError(f"missing parameter ${e.name}")
        return ctx.params[e.name]
    if isinstance(e, ast.Variable):
        if e.name in ctx.bindings:
            return ctx.bindings[e.name]
        raise CypherSyntaxError(f"variable `{e.name}` not defined")
    if isinstance(e, ast.Property):
        subject = evaluate(e.subject, ctx)
        if subject is None:
            return None
        if isinstance(subject, (Node, Edge)):
            v = subject.properties.get(e.key)
            if v is None and e.key == "id":
                # `n.id` falls back to the entity id when no id property
                # exists (ref contract: neo4j_compat_test.go:299 returns the
                # storage ID for nodes created without an id property)
                return subject.id
            return v
        if isinstance(subject, dict):
            return subject.get(e.key)
        raise CypherTypeError(f"cannot access property .{e.key} on {type(subject).__name__}")
    if isinstance(e, ast.ListLiteral):
        return [evaluate(i, ctx) for i in e.items]
    if isinstance(e, ast.MapLiteral):
        if "__param__" in e.items:  # (n $props) pattern form
            return evaluate(e.items["__param__"], ctx)
        return {k: evaluate(v, ctx) for k, v in e.items.items()}
    if isinstance(e, ast.UnaryOp):
        return _unary(e, ctx)
    if isinstance(e, ast.BinaryOp):
        return _binary(e, ctx)
    if isinstance(e, ast.IsNull):
        v = evaluate(e.operand, ctx)
        return (v is not None) if e.negated else (v is None)
    if isinstance(e, ast.Subscript):
        subject = evaluate(e.subject, ctx)
        idx = evaluate(e.index, ctx)
        if subject is None or idx is None:
            return None
        if isinstance(subject, dict):
            return subject.get(idx)
        if isinstance(subject, (Node, Edge)):
            return subject.properties.get(idx)
        if isinstance(subject, list):
            i = int(idx)
            if -len(subject) <= i < len(subject):
                return subject[i]
            return None
        raise CypherTypeError("subscript on non-list/map")
    if isinstance(e, ast.Slice):
        subject = evaluate(e.subject, ctx)
        if subject is None:
            return None
        start = evaluate(e.start, ctx) if e.start is not None else None
        end = evaluate(e.end, ctx) if e.end is not None else None
        return subject[
            int(start) if start is not None else None : int(end) if end is not None else None
        ]
    if isinstance(e, ast.CaseExpr):
        if e.subject is not None:
            subj = evaluate(e.subject, ctx)
            for cond, result in e.whens:
                if _eq(subj, evaluate(cond, ctx)) is True:
                    return evaluate(result, ctx)
        else:
            for cond, result in e.whens:
                if evaluate(cond, ctx) is True:
                    return evaluate(result, ctx)
        return evaluate(e.default, ctx) if e.default is not None else None
    if isinstance(e, ast.ListComprehension):
        src = evaluate(e.source, ctx)
        if src is None:
            return None
        out = []
        for item in src:
            child = ctx.child({e.variable: item})
            if e.where is not None and evaluate(e.where, child) is not True:
                continue
            out.append(evaluate(e.projection, child) if e.projection is not None else item)
        return out
    if isinstance(e, ast.Quantifier):
        src = evaluate(e.source, ctx)
        if src is None:
            return None
        results = [evaluate(e.predicate, ctx.child({e.variable: item})) for item in src]
        truths = [r is True for r in results]
        if e.kind == "all":
            return all(truths)
        if e.kind == "any":
            return any(truths)
        if e.kind == "none":
            return not any(truths)
        if e.kind == "single":
            return sum(truths) == 1
    if isinstance(e, ast.MapProjection):
        subject = evaluate(e.subject, ctx)
        if subject is None:
            return None
        if isinstance(subject, (Node, Edge)):
            props = subject.properties
        elif isinstance(subject, dict):
            props = subject
        else:
            raise CypherTypeError("map projection needs a node/relationship/map")
        out: dict[str, Any] = {}
        for kind, payload in e.items:
            if kind == "all":
                out.update(props)
            elif kind == "prop":
                out[payload] = props.get(payload)
            elif kind == "alias":
                name, expr2 = payload
                out[name] = evaluate(expr2, ctx)
            elif kind == "var":
                out[payload] = evaluate(ast.Variable(payload), ctx)
        return out
    if isinstance(e, ast.PatternComprehension):
        if ctx.executor is None:
            raise CypherTypeError("pattern comprehension requires executor context")
        return ctx.executor.eval_pattern_comprehension(e, ctx)
    if isinstance(e, ast.ReduceExpr):
        src = evaluate(e.source, ctx)
        if src is None:
            return None
        acc = evaluate(e.init, ctx)
        for item in src:
            acc = evaluate(e.body, ctx.child({e.accumulator: acc, e.variable: item}))
        return acc
    if isinstance(e, ast.FunctionCall):
        return _function(e, ctx)
    if isinstance(e, (ast.PatternPredicate, ast.ExistsSubquery, ast.CountSubquery)):
        if ctx.executor is None:
            raise CypherTypeError("pattern predicate requires executor context")
        return ctx.executor.eval_pattern_expr(e, ctx)
    if isinstance(e, ast.LabelPredicate):
        # n:Label[:Label...] — true iff the subject node has EVERY label;
        # on a relationship, r:TYPE checks the relationship type (Neo4j 5
        # relationship type expressions)
        subject = evaluate(e.subject, ctx)
        if subject is None:
            return None
        if isinstance(subject, Edge):
            return subject.type in e.labels
        if not isinstance(subject, Node):
            raise CypherTypeError(
                "label predicate expects a node or relationship"
            )
        return all(label in subject.labels for label in e.labels)
    if isinstance(e, ast.CollectSubquery):
        if ctx.executor is None:
            raise CypherTypeError("COLLECT subquery requires executor context")
        return ctx.executor.eval_collect_subquery(e, ctx)
    raise CypherTypeError(f"cannot evaluate {type(e).__name__}")


def _unary(e: ast.UnaryOp, ctx: EvalContext) -> Any:
    v = evaluate(e.operand, ctx)
    if e.op == "NOT":
        if v is None:
            return None
        if not isinstance(v, bool):
            raise CypherTypeError("NOT expects a boolean")
        return not v
    if v is None:
        return None
    if e.op == "-":
        return -v
    return v


def _eq(a: Any, b: Any) -> Optional[bool]:
    if a is None or b is None:
        return None
    if isinstance(a, (Node, Edge)) and isinstance(b, (Node, Edge)):
        return a.id == b.id
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if type(a) is not type(b) and not (
        isinstance(a, (list, dict)) and isinstance(b, (list, dict))
    ):
        return False
    if isinstance(a, list) and isinstance(b, list):
        if len(a) != len(b):
            return False
        return all(_eq(x, y) is True for x, y in zip(a, b))
    return a == b


def _compare(op: str, a: Any, b: Any) -> Optional[bool]:
    if a is None or b is None:
        return None
    try:
        if op == "<":
            return a < b
        if op == ">":
            return a > b
        if op == "<=":
            return a <= b
        if op == ">=":
            return a >= b
    except TypeError:
        return None
    return None


def _binary(e: ast.BinaryOp, ctx: EvalContext) -> Any:
    op = e.op
    if op in ("AND", "OR", "XOR"):
        left = evaluate(e.left, ctx)
        # three-valued logic with short-circuit
        if op == "AND":
            if left is False:
                return False
            right = evaluate(e.right, ctx)
            if right is False:
                return False
            if left is None or right is None:
                return None
            return True
        if op == "OR":
            if left is True:
                return True
            right = evaluate(e.right, ctx)
            if right is True:
                return True
            if left is None or right is None:
                return None
            return False
        right = evaluate(e.right, ctx)
        if left is None or right is None:
            return None
        return bool(left) != bool(right)

    a = evaluate(e.left, ctx)
    b = evaluate(e.right, ctx)
    if op == "=":
        return _eq(a, b)
    if op in ("<>", "!="):  # != is the reference-dialect alias for <>
        r = _eq(a, b)
        return None if r is None else not r
    if op in ("<", ">", "<=", ">="):
        return _compare(op, a, b)
    if op == "IN":
        if b is None:
            return None
        if not isinstance(b, list):
            raise CypherTypeError("IN expects a list")
        if a is None:
            return None
        found_null = False
        for item in b:
            r = _eq(a, item)
            if r is True:
                return True
            if r is None:
                found_null = True
        return None if found_null else False
    if op == "STARTS WITH":
        if a is None or b is None:
            return None
        return str(a).startswith(str(b))
    if op == "ENDS WITH":
        if a is None or b is None:
            return None
        return str(a).endswith(str(b))
    if op == "CONTAINS":
        if a is None or b is None:
            return None
        return str(b) in str(a)
    if op == "=~":
        if a is None or b is None:
            return None
        return regex_fullmatch(b, a)
    if a is None or b is None:
        return None
    # temporal arithmetic: datetime/date ± duration, duration ± duration
    if op in ("+", "-") and (_temporal_kind(a) or _temporal_kind(b)):
        out = _temporal_arith(op, a, b)
        if out is not None:
            return out
    if op == "+":
        if isinstance(a, list) and isinstance(b, list):
            return a + b
        if isinstance(a, list):
            return a + [b]
        if isinstance(b, list):
            return [a] + b
        if isinstance(a, str) or isinstance(b, str):
            if isinstance(a, str) and isinstance(b, str):
                return a + b
            # string + number coerces (Neo4j allows string concatenation)
            return _to_str(a) + _to_str(b)
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        if isinstance(a, int) and isinstance(b, int):
            if b == 0:
                raise CypherTypeError("/ by zero")
            q = a // b
            if (a % b != 0) and ((a < 0) != (b < 0)):
                q += 1
            return q
        if b == 0:
            raise CypherTypeError("/ by zero")
        return a / b
    if op == "%":
        if b == 0:
            raise CypherTypeError("% by zero")
        return a - b * int(a / b) if isinstance(a, float) or isinstance(b, float) else _cmod(a, b)
    if op == "^":
        return float(a) ** float(b)
    raise CypherTypeError(f"unknown operator {op}")


def _temporal_kind(v: Any) -> Optional[str]:
    if isinstance(v, dict):
        return v.get("__temporal__")
    return None


def _temporal_arith(op: str, a: Any, b: Any) -> Any:
    """datetime/date ± duration → datetime/date; duration ± duration →
    duration; datetime - datetime → duration. None = not a temporal combo
    (caller falls through to numeric/list semantics)."""
    from nornicdb_tpu.cypher import temporal_fns as t

    ka, kb = _temporal_kind(a), _temporal_kind(b)
    if ka in ("datetime", "date") and kb == "duration":
        ms = a["epochMillis"] + (b["milliseconds"] if op == "+" else -b["milliseconds"])
        out = t.fn_from_epoch_millis(ms)
        return t.fn_date(out) if ka == "date" else out
    if ka == "duration" and kb in ("datetime", "date") and op == "+":
        return _temporal_arith("+", b, a)
    if ka == "duration" and kb == "duration":
        ms = a["milliseconds"] + (b["milliseconds"] if op == "+" else -b["milliseconds"])
        return t.fn_duration({"seconds": ms / 1000.0})
    if ka in ("datetime", "date") and kb in ("datetime", "date") and op == "-":
        return t.fn_duration(
            {"seconds": (a["epochMillis"] - b["epochMillis"]) / 1000.0}
        )
    return None


def _cmod(a: int, b: int) -> int:
    return a - b * int(a / b)


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _function(e: ast.FunctionCall, ctx: EvalContext) -> Any:
    name = e.name
    if name in ("startnode", "endnode"):
        rel = evaluate(e.args[0], ctx) if e.args else None
        if rel is None:
            return None
        if not isinstance(rel, Edge):
            raise CypherTypeError(f"{name}() expects a relationship")
        if ctx.executor is None:
            raise CypherTypeError(f"{name}() requires executor context")
        nid = rel.start_node if name == "startnode" else rel.end_node
        return ctx.executor.get_node_or_none(nid)
    fn = FUNCTIONS.get(name)
    if fn is None and ctx.executor is not None:
        fn = ctx.executor.lookup_function(name)
    if fn is None:
        raise CypherSyntaxError(f"unknown function {name}()")
    args = [evaluate(a, ctx) for a in e.args]
    if getattr(fn, "needs_executor", False):
        return fn(ctx.executor, *args)
    return fn(*args)
