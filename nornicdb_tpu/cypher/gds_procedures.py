"""GDS-compatible procedures + Kalman Cypher functions.

Behavioral reference: /root/reference/pkg/cypher/linkprediction.go
(gds.linkPrediction.* procedures over pkg/linkpredict),
kalman_functions.go:115-195 (kalman.* scalar functions),
fastrp.go:361-652 (gds.fastRP.* node embeddings).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.cypher.functions import register
from nornicdb_tpu.errors import CypherSyntaxError, CypherTypeError
from nornicdb_tpu.filter.kalman import Kalman, KalmanConfig
from nornicdb_tpu.linkpredict.topology import (
    SCORERS,
    build_graph,
    score_pair,
    top_candidates,
)
from nornicdb_tpu.storage.types import Node


def _method_from_name(proc_name: str) -> str:
    # gds.linkprediction.adamicadar -> adamicAdar
    tail = proc_name.rsplit(".", 1)[-1]
    for m in SCORERS:
        if m.lower() == tail:
            return m
    raise CypherSyntaxError(f"unknown link prediction method {tail}")


def _cached_graph(ex: CypherExecutor):
    """Per-executor graph projection cache, invalidated by count changes —
    avoids a full O(N+E) rebuild per input row (the reference builds one
    projection per procedure call too, graph_builder.go)."""
    key = (ex.storage.node_count(), ex.storage.edge_count())
    cached = getattr(ex, "_lp_graph_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    g = build_graph(ex.storage)
    ex._lp_graph_cache = (key, g)
    return g


def _lp_pair(ex: CypherExecutor, args: list[Any], method: str):
    if len(args) < 2:
        raise CypherSyntaxError("expected (node1, node2)")
    a, b = args[0], args[1]
    a_id = a.id if isinstance(a, Node) else str(a)
    b_id = b.id if isinstance(b, Node) else str(b)
    g = _cached_graph(ex)
    return ["score"], [[score_pair(g, a_id, b_id, method)]]


for _m in list(SCORERS):
    def _make(meth):
        def fn(ex, args, row):
            return _lp_pair(ex, args, meth)

        return fn

    procedure(f"gds.linkprediction.{_m.lower()}")(_make(_m))


@procedure("gds.linkprediction.suggest")
def proc_lp_suggest(ex: CypherExecutor, args, row):
    """Top non-adjacent candidate pairs (ref: linkprediction.go suggest)."""
    method = str(args[0]) if args else "adamicAdar"
    limit = int(args[1]) if len(args) > 1 else 20
    g = build_graph(ex.storage)
    rows = []
    for a_id, b_id, score in top_candidates(g, method, limit):
        na, nb = ex.get_node_or_none(a_id), ex.get_node_or_none(b_id)
        if na is not None and nb is not None:
            rows.append([na, nb, score])
    return ["node1", "node2", "score"], rows


@procedure("gds.fastrp.stream")
def proc_fastrp(ex: CypherExecutor, args, row):
    """FastRP node embeddings (ref: fastrp.go:361-652): iterative neighbor
    averaging over random projections, here computed as adjacency matmuls."""
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    dims = int(cfg.get("embeddingDimension", 128))
    iterations = int(cfg.get("iterationWeights") and len(cfg["iterationWeights"]) or 3)
    weights = cfg.get("iterationWeights") or [0.0, 1.0, 1.0][:iterations]
    g = build_graph(ex.storage)
    if g.n == 0:
        return ["nodeId", "embedding"], []
    rng = np.random.default_rng(int(cfg.get("randomSeed", 42)))
    # sparse random projection init (+-1/sqrt(dims))
    emb = rng.choice(
        [-1.0, 0.0, 1.0], size=(g.n, dims), p=[1 / 6, 2 / 3, 1 / 6]
    ).astype(np.float32) * np.sqrt(3.0 / dims)
    a = np.zeros((g.n, g.n), np.float32)
    for i, nbrs in enumerate(g.neighbors):
        for j in nbrs:
            a[i, j] = 1.0
    deg = np.maximum(a.sum(axis=1, keepdims=True), 1.0)
    a = a / deg  # row-normalized
    out = np.zeros_like(emb)
    curr = emb
    for w in weights:
        curr = a @ curr
        norms = np.maximum(np.linalg.norm(curr, axis=1, keepdims=True), 1e-12)
        curr = curr / norms
        out += float(w) * curr
    norms = np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-12)
    out = out / norms
    return (
        ["nodeId", "embedding"],
        [[g.ids[i], out[i].tolist()] for i in range(g.n)],
    )


# ---------------------------------------------------------------- kalman fns
def _kalman_states(ex: CypherExecutor) -> dict[str, Kalman]:
    """Per-executor state (not module-global) so independent DB instances /
    databases never share or leak filter state."""
    states = getattr(ex, "_kalman_states", None)
    if states is None:
        states = {}
        ex._kalman_states = states
    return states


@register("kalman.filter")
def fn_kalman_filter(ex, key, measurement, process_noise=1e-3, measurement_noise=1e-1):
    """Stateful named scalar filter (ref: kalman_functions.go:115-195)."""
    if key is None or measurement is None:
        return None
    states = _kalman_states(ex)
    k = states.get(str(key))
    if k is None:
        k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
        states[str(key)] = k
    return k.process(float(measurement))


fn_kalman_filter.needs_executor = True


@register("kalman.predict")
def fn_kalman_predict(ex, key):
    k = _kalman_states(ex).get(str(key))
    return None if k is None else k.predict()


fn_kalman_predict.needs_executor = True


@register("kalman.reset")
def fn_kalman_reset(ex, key):
    _kalman_states(ex).pop(str(key), None)
    return True


fn_kalman_reset.needs_executor = True


@register("kalman.smooth")
def fn_kalman_smooth(values, process_noise=1e-3, measurement_noise=1e-1):
    """Smooth a list of measurements in one call."""
    if values is None:
        return None
    if not isinstance(values, list):
        raise CypherTypeError("kalman.smooth expects a list")
    k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
    return [k.process(float(v)) for v in values]
