"""GDS-compatible procedures + Kalman Cypher functions.

Behavioral reference: /root/reference/pkg/cypher/linkprediction.go
(gds.linkPrediction.* procedures over pkg/linkpredict),
kalman_functions.go:115-195 (kalman.* scalar functions),
fastrp.go:361-652 (gds.fastRP.* node embeddings).
"""

from __future__ import annotations

import logging
from typing import Any

import numpy as np

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.cypher.functions import register
from nornicdb_tpu.errors import (
    AlreadyExistsError,
    CypherSyntaxError,
    CypherTypeError,
    NotFoundError,
)
from nornicdb_tpu.filter.kalman import Kalman, KalmanConfig
from nornicdb_tpu.linkpredict.topology import (
    SCORERS,
    build_graph,
    score_pair,
    top_candidates,
)
from nornicdb_tpu.storage.types import Node

log = logging.getLogger(__name__)


def _adj_snapshot(ex: CypherExecutor):
    """The engine's shared CSR adjacency snapshot (storage/adjacency.py),
    attached on first GDS/link-prediction call. After its first build the
    topology stays event-maintained — repeated procedures never rescan
    `all_edges()`."""
    snap = getattr(ex, "_adj_snapshot_cache", None)
    if snap is None:
        try:
            from nornicdb_tpu.storage.adjacency import attach_snapshot

            snap = attach_snapshot(ex.storage)
        except Exception:
            log.debug("adjacency snapshot unavailable; GDS uses the "
                      "engine-scan path", exc_info=True)
            snap = False
        ex._adj_snapshot_cache = snap
    return snap or None


def _method_from_name(proc_name: str) -> str:
    # gds.linkprediction.adamicadar -> adamicAdar
    tail = proc_name.rsplit(".", 1)[-1]
    for m in SCORERS:
        if m.lower() == tail:
            return m
    raise CypherSyntaxError(f"unknown link prediction method {tail}")


def _cached_graph(ex: CypherExecutor):
    """Graph projection served from the CSR snapshot when available —
    generation-tagged, so repeated calls on an unchanged graph reuse the
    same projection and any mutation (even one that leaves the counts
    unchanged, e.g. paired CREATE+DELETE) is visible. The count-keyed
    per-executor cache remains as the fallback for engines without a
    snapshot."""
    snap = _adj_snapshot(ex)
    if snap is not None and snap.ensure():
        return snap.graph_view()
    key = (ex.storage.node_count(), ex.storage.edge_count())
    cached = getattr(ex, "_lp_graph_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    g = build_graph(ex.storage)
    ex._lp_graph_cache = (key, g)
    return g


def _lp_pair(ex: CypherExecutor, args: list[Any], method: str):
    if len(args) < 2:
        raise CypherSyntaxError("expected (node1, node2)")
    a, b = args[0], args[1]
    a_id = a.id if isinstance(a, Node) else str(a)
    b_id = b.id if isinstance(b, Node) else str(b)
    g = _cached_graph(ex)
    return ["score"], [[score_pair(g, a_id, b_id, method)]]


for _m in list(SCORERS):
    def _make(meth):
        def fn(ex, args, row):
            return _lp_pair(ex, args, meth)

        return fn

    procedure(f"gds.linkprediction.{_m.lower()}")(_make(_m))


def _source_candidates(
    ex: CypherExecutor, method: str, source: str, top_k: int
) -> list[list[Any]]:
    """Per-source candidate streaming: score `source` against every
    non-adjacent node (ref: the map-config .stream form,
    gds.linkPrediction.X.stream({sourceNode, topK}) linkprediction.go)."""
    g = _cached_graph(ex)
    if source not in g.index:
        return []
    si = g.index[source]
    scored = []
    for j in range(g.n):
        if j == si or j in g.neighbors[si]:
            continue
        v = score_pair(g, source, g.ids[j], method)
        if v > 0:
            scored.append((g.ids[j], v))
    scored.sort(key=lambda t: -t[1])
    rows = []
    for b_id, v in scored[:top_k]:
        na, nb = ex.get_node_or_none(source), ex.get_node_or_none(b_id)
        if na is not None and nb is not None:
            rows.append([na, nb, v])
    return rows


def _stream_config(args: list[Any]) -> tuple[str, int]:
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    source = cfg.get("sourceNode", "")
    # accept a Node object or an id string (same normalization as _lp_pair)
    source = source.id if isinstance(source, Node) else str(source)
    top_k = int(cfg.get("topK", 10))
    return source, top_k


for _m in list(SCORERS):
    def _make_stream(meth):
        def fn(ex, args, row):
            source, top_k = _stream_config(args)
            if not source:
                raise CypherSyntaxError("sourceNode required")
            return (["node1", "node2", "score"],
                    _source_candidates(ex, meth, source, top_k))

        return fn

    procedure(f"gds.linkprediction.{_m.lower()}.stream")(_make_stream(_m))


@procedure("gds.linkprediction.predict.stream")
def proc_lp_predict_stream(ex: CypherExecutor, args, row):
    """Hybrid topology+semantic prediction stream (ref: hybrid.go:61-222,
    gds.linkPrediction.predict.stream)."""
    from nornicdb_tpu.linkpredict.topology import HybridConfig, hybrid_score

    cfg = args[0] if args and isinstance(args[0], dict) else {}
    source = cfg.get("sourceNode", "")
    source = source.id if isinstance(source, Node) else str(source)
    if not source:
        raise CypherSyntaxError("sourceNode required")
    top_k = int(cfg.get("topK", 10))
    method = str(cfg.get("algorithm", "adamic_adar"))
    method = {"adamic_adar": "adamicAdar", "common_neighbors":
              "commonNeighbors", "preferential_attachment":
              "preferentialAttachment", "resource_allocation":
              "resourceAllocation"}.get(method, method)
    hcfg = HybridConfig(
        topology_weight=float(cfg.get("topologyWeight", 0.5)),
        semantic_weight=float(cfg.get("semanticWeight", 0.5)),
    )
    if method in SCORERS:
        hcfg.methods = [method]
    g = _cached_graph(ex)
    if source not in g.index:
        return ["node1", "node2", "score"], []
    src_node = ex.get_node_or_none(source)
    emb_a = src_node.embedding if src_node is not None else None
    si = g.index[source]
    scored = []
    for j in range(g.n):
        if j == si or j in g.neighbors[si]:
            continue
        b_id = g.ids[j]
        nb = ex.get_node_or_none(b_id)
        emb_b = nb.embedding if nb is not None else None
        v = hybrid_score(g, source, b_id, emb_a, emb_b, hcfg)
        if v > 0:
            scored.append((b_id, v))
    scored.sort(key=lambda t: -t[1])
    rows = []
    for b_id, v in scored[:top_k]:
        nb = ex.get_node_or_none(b_id)
        if src_node is not None and nb is not None:
            rows.append([src_node, nb, v])
    return ["node1", "node2", "score"], rows


@procedure("gds.linkprediction.suggest")
def proc_lp_suggest(ex: CypherExecutor, args, row):
    """Top non-adjacent candidate pairs (ref: linkprediction.go suggest)."""
    method = str(args[0]) if args else "adamicAdar"
    limit = int(args[1]) if len(args) > 1 else 20
    g = _cached_graph(ex)  # generation-tagged: always current topology
    rows = []
    for a_id, b_id, score in top_candidates(g, method, limit):
        na, nb = ex.get_node_or_none(a_id), ex.get_node_or_none(b_id)
        if na is not None and nb is not None:
            rows.append([na, nb, score])
    return ["node1", "node2", "score"], rows


@procedure("gds.fastrp.stats")
def proc_fastrp_stats(ex: CypherExecutor, args, row):
    """gds.fastRP.stats(name, config) — summary counts without streaming
    embeddings (ref: fastrp.go stats mode)."""
    cfg = next((a for a in args if isinstance(a, dict)), {})
    g = _cached_graph(ex)
    return (
        ["nodeCount", "embeddingDimension"],
        [[g.n, int(cfg.get("embeddingDimension", 128))]],
    )


@procedure("gds.fastrp.stream")
def proc_fastrp(ex: CypherExecutor, args, row):
    """FastRP node embeddings (ref: fastrp.go:361-652): iterative neighbor
    averaging over random projections, here computed as adjacency matmuls."""
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    dims = int(cfg.get("embeddingDimension", 128))
    iterations = int(cfg.get("iterationWeights") and len(cfg["iterationWeights"]) or 3)
    weights = cfg.get("iterationWeights") or [0.0, 1.0, 1.0][:iterations]
    g = _cached_graph(ex)
    if g.n == 0:
        return ["nodeId", "embedding"], []
    rng = np.random.default_rng(int(cfg.get("randomSeed", 42)))
    # sparse random projection init (+-1/sqrt(dims))
    emb = rng.choice(
        [-1.0, 0.0, 1.0], size=(g.n, dims), p=[1 / 6, 2 / 3, 1 / 6]
    ).astype(np.float32) * np.sqrt(3.0 / dims)
    a = np.zeros((g.n, g.n), np.float32)
    for i, nbrs in enumerate(g.neighbors):
        for j in nbrs:
            a[i, j] = 1.0
    deg = np.maximum(a.sum(axis=1, keepdims=True), 1.0)
    a = a / deg  # row-normalized
    out = np.zeros_like(emb)
    curr = emb
    for w in weights:
        curr = a @ curr
        norms = np.maximum(np.linalg.norm(curr, axis=1, keepdims=True), 1e-12)
        curr = curr / norms
        out += float(w) * curr
    norms = np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-12)
    out = out / norms
    return (
        ["nodeId", "embedding"],
        [[g.ids[i], out[i].tolist()] for i in range(g.n)],
    )


# ---------------------------------------------------------------- kalman fns
def _kalman_states(ex: CypherExecutor) -> dict[str, Kalman]:
    """Per-executor state (not module-global) so independent DB instances /
    databases never share or leak filter state."""
    states = getattr(ex, "_kalman_states", None)
    if states is None:
        states = {}
        ex._kalman_states = states
    return states


@register("kalman.init")
def fn_kalman_init(config=None):
    """kalman.init([config]) -> state JSON string stored on a node
    property (ref: kalman_functions.go:254 kalmanInit — Q scales
    processNoise by 0.001, defaults R=88, P=30, varianceScale=10)."""
    import json as _json

    state = {
        "x": 0.0, "p": 30.0, "q": 0.1 * 0.001, "r": 88.0,
        "varianceScale": 10.0, "initialized": False,
    }
    if isinstance(config, dict):
        if config.get("processNoise") is not None:
            state["q"] = float(config["processNoise"]) * 0.001
        if config.get("measurementNoise") is not None:
            state["r"] = float(config["measurementNoise"])
        if config.get("initialCovariance") is not None:
            state["p"] = float(config["initialCovariance"])
        if config.get("varianceScale") is not None:
            state["varianceScale"] = float(config["varianceScale"])
    return _json.dumps(state)


def _kalman_load(state):
    """Parse a state JSON; malformed input is a clean type error, never a
    raw JSONDecodeError up through the query (kalman_functions_test.go
    interpolates real state strings; user queries may not)."""
    import json as _json

    try:
        s = _json.loads(state)
    except (TypeError, ValueError):
        raise CypherTypeError(f"invalid kalman state: {state!r}")
    if not isinstance(s, dict):
        raise CypherTypeError(f"invalid kalman state: {state!r}")
    return s


@register("kalman.process")
def fn_kalman_process(measurement, state):
    """kalman.process(measurement, stateJson) -> {value, state}
    (ref: kalmanProcess — returns the smoothed value plus the updated
    state JSON to store back on the node)."""
    import json as _json

    if measurement is None or state is None:
        return None
    s = _kalman_load(state)
    z = float(measurement)
    if not s.get("initialized"):
        s["x"] = z
        s["initialized"] = True
    else:
        p = s.get("p", 30.0) + s.get("q", 1e-4)
        k = p / (p + s.get("r", 88.0))
        s["x"] = s.get("x", 0.0) + k * (z - s.get("x", 0.0))
        s["p"] = (1 - k) * p
    return {"value": s["x"], "state": _json.dumps(s)}


@register("kalman.state")
def fn_kalman_state(state):
    """kalman.state(stateJson) -> MAP view of the stored filter state."""
    return None if state is None else _kalman_load(state)


# -- velocity model (2-state: position + velocity; ref: kalman_functions_test
# kalman.velocity.* family) ---------------------------------------------------
@register("kalman.velocity.init")
def fn_kalman_velocity_init(config=None):
    import json as _json

    state = {
        "model": "velocity", "x": 0.0, "v": 0.0,
        "p": 30.0, "q": 1e-4, "r": 88.0, "dt": 1.0, "initialized": False,
    }
    if isinstance(config, dict):
        if config.get("processNoise") is not None:
            state["q"] = float(config["processNoise"]) * 0.001
        if config.get("measurementNoise") is not None:
            state["r"] = float(config["measurementNoise"])
        if config.get("dt") is not None:
            state["dt"] = float(config["dt"])
    return _json.dumps(state)


@register("kalman.velocity.process")
def fn_kalman_velocity_process(measurement, state):
    """-> {value, velocity, state}: position smoothed, velocity estimated
    from the innovation (reduced-order alpha-beta form of the 2-state
    filter — same observable behavior, one scalar gain pair)."""
    import json as _json

    if measurement is None or state is None:
        return None
    s = _kalman_load(state)
    z = float(measurement)
    dt = s.get("dt", 1.0)
    if not s.get("initialized"):
        s["x"], s["v"] = z, 0.0
        s["initialized"] = True
    else:
        pred = s.get("x", 0.0) + s.get("v", 0.0) * dt
        p = s.get("p", 30.0) + s.get("q", 1e-4)
        alpha = p / (p + s.get("r", 88.0))
        beta = alpha * alpha / (2 - alpha)
        resid = z - pred
        s["x"] = pred + alpha * resid
        s["v"] = s.get("v", 0.0) + (beta / dt) * resid
        s["p"] = (1 - alpha) * p
    return {"value": s["x"], "velocity": s["v"], "state": _json.dumps(s)}


@register("kalman.velocity.predict")
def fn_kalman_velocity_predict(state, steps=1):
    """Extrapolate position `steps` intervals ahead: x + v*steps*dt."""
    if state is None:
        return None
    s = _kalman_load(state)
    return (s.get("x", 0.0)
            + s.get("v", 0.0) * float(steps) * s.get("dt", 1.0))


# -- adaptive model (hysteresis gates noise adaptation; ref:
# kalman.adaptive.* family) ---------------------------------------------------
@register("kalman.adaptive.init")
def fn_kalman_adaptive_init(config=None):
    import json as _json

    state = {
        "model": "adaptive", "x": 0.0, "p": 30.0, "q": 1e-4, "r": 88.0,
        "hysteresis": 2, "breach": 0, "initialized": False,
    }
    if isinstance(config, dict):
        if config.get("hysteresis") is not None:
            state["hysteresis"] = int(config["hysteresis"])
        if config.get("processNoise") is not None:
            state["q"] = float(config["processNoise"]) * 0.001
        if config.get("measurementNoise") is not None:
            state["r"] = float(config["measurementNoise"])
    return _json.dumps(state)


@register("kalman.adaptive.process")
def fn_kalman_adaptive_process(measurement, state):
    """Standard update; after `hysteresis` consecutive large innovations,
    the filter re-seeds on the measurement (level-shift tracking)."""
    import json as _json

    if measurement is None or state is None:
        return None
    s = _kalman_load(state)
    z = float(measurement)
    if not s.get("initialized"):
        s["x"], s["initialized"] = z, True
    else:
        p = s.get("p", 30.0) + s.get("q", 1e-4)
        r = s.get("r", 88.0)
        resid = z - s.get("x", 0.0)
        if resid * resid > 9 * (p + r):  # > 3 sigma
            s["breach"] = s.get("breach", 0) + 1
        else:
            s["breach"] = 0
        if s["breach"] >= s.get("hysteresis", 2):
            s["x"], s["p"], s["breach"] = z, 30.0, 0  # re-seed on shift
        else:
            k = p / (p + r)
            s["x"] = s.get("x", 0.0) + k * resid
            s["p"] = (1 - k) * p
    return {"value": s["x"], "state": _json.dumps(s)}


@register("kalman.filter")
def fn_kalman_filter(ex, key, measurement, process_noise=1e-3, measurement_noise=1e-1):
    """Stateful named scalar filter (ref: kalman_functions.go:115-195)."""
    if key is None or measurement is None:
        return None
    states = _kalman_states(ex)
    k = states.get(str(key))
    if k is None:
        k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
        states[str(key)] = k
    return k.process(float(measurement))


fn_kalman_filter.needs_executor = True


@register("kalman.predict")
def fn_kalman_predict(ex, key, steps=1):
    """Two forms: kalman.predict(stateJson, steps) extrapolates from a
    serialized state (ref: kalman_functions_test.go:405); kalman.predict(key)
    reads the named in-memory filter from kalman.filter."""
    if isinstance(key, str) and key.lstrip()[:1] == "{":
        s = _kalman_load(key)
        return (s.get("x", 0.0)
                + s.get("v", 0.0) * float(steps) * s.get("dt", 1.0))
    k = _kalman_states(ex).get(str(key))
    return None if k is None else k.predict()


fn_kalman_predict.needs_executor = True


@register("kalman.reset")
def fn_kalman_reset(ex, key):
    _kalman_states(ex).pop(str(key), None)
    return True


fn_kalman_reset.needs_executor = True


@register("kalman.smooth")
def fn_kalman_smooth(values, process_noise=1e-3, measurement_noise=1e-1):
    """Smooth a list of measurements in one call."""
    if values is None:
        return None
    if not isinstance(values, list):
        raise CypherTypeError("kalman.smooth expects a list")
    k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
    return [k.process(float(v)) for v in values]


# ---------------------------------------------------------------- algorithms
# (ref: /root/reference/apoc/algo/ + /root/reference/apoc/community/ —
# exposed both under gds.* stream procedures and apoc.algo.* aliases)

from nornicdb_tpu.ops import graph_algos as _ga  # noqa: E402


def _edge_arrays(ex: CypherExecutor):
    """Directed (src, dst) index arrays + sorted id list, served from the
    CSR snapshot (generation-tagged: repeated calls on an unchanged graph
    reuse the same arrays, mutations — including count-neutral ones — are
    always visible, and no `all_edges()` rescan ever runs after the first
    snapshot build). Count-keyed executor cache kept as the fallback."""
    snap = _adj_snapshot(ex)
    if snap is not None and snap.ensure():
        view = snap.edge_arrays()
        return view.ids, view.index, view.src, view.dst
    key = (ex.storage.node_count(), ex.storage.edge_count())
    cached = getattr(ex, "_algo_graph_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    ids = sorted(n.id for n in ex.storage.all_nodes())
    index = {id_: i for i, id_ in enumerate(ids)}
    src, dst = [], []
    for e in ex.storage.all_edges():
        a, b = index.get(e.start_node), index.get(e.end_node)
        if a is not None and b is not None:
            src.append(a)
            dst.append(b)
    out = (ids, index,
           np.asarray(src, dtype=np.int32), np.asarray(dst, dtype=np.int32))
    ex._algo_graph_cache = (key, out)
    return out


def _node_rows(ex, ids, values, col):
    rows = []
    for i, v in enumerate(values):
        n = ex.get_node_or_none(ids[i])
        if n is not None:
            rows.append([n, v])
    return ["node", col], rows


@procedure("gds.pagerank.stream")
def proc_pagerank(ex: CypherExecutor, args, row):
    """(ref: apoc/algo PageRank — damped power iteration, on-TPU
    segment_sum program)"""
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    ids, _, src, dst = _edge_arrays(ex)
    scores = _ga.pagerank(src, dst, len(ids),
                          damping=float(cfg.get("dampingFactor", 0.85)),
                          iters=int(cfg.get("maxIterations", 20)))
    return _node_rows(ex, ids, [float(s) for s in scores], "score")


@procedure("gds.wcc.stream")
def proc_wcc(ex: CypherExecutor, args, row):
    """(ref: community WeaklyConnectedComponents — min-label propagation)"""
    ids, _, src, dst = _edge_arrays(ex)
    comp = _ga.connected_components(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in comp], "componentId")


@procedure("gds.scc.stream")
def proc_scc(ex: CypherExecutor, args, row):
    """(ref: community StronglyConnectedComponents — Tarjan)"""
    ids, _, src, dst = _edge_arrays(ex)
    comp = _ga.strongly_connected_components(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in comp], "componentId")


@procedure("gds.labelpropagation.stream")
def proc_label_prop(ex: CypherExecutor, args, row):
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    ids, _, src, dst = _edge_arrays(ex)
    labels = _ga.label_propagation(src, dst, len(ids),
                                   iters=int(cfg.get("maxIterations", 10)))
    return _node_rows(ex, ids, [int(c) for c in labels], "communityId")


@procedure("gds.louvain.stream")
def proc_louvain(ex: CypherExecutor, args, row):
    """(ref: community Louvain — greedy modularity local moves)"""
    ids, _, src, dst = _edge_arrays(ex)
    labels = _ga.louvain(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in labels], "communityId")


@procedure("gds.trianglecount.stream")
def proc_triangles(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    tri = _ga.triangle_counts(src, dst, len(ids))
    return _node_rows(ex, ids, [int(t) for t in tri], "triangleCount")


@procedure("gds.localclusteringcoefficient.stream")
def proc_clustering(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    cc = _ga.clustering_coefficient(src, dst, len(ids))
    return _node_rows(ex, ids, [float(c) for c in cc], "localClusteringCoefficient")


_ORIENTATIONS = {
    # GDS-standard names plus the plain aliases
    "natural": "out", "reverse": "in", "undirected": "both",
    "out": "out", "in": "in", "both": "both",
}


@procedure("gds.degree.stream")
def proc_degree(ex: CypherExecutor, args, row):
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    raw = str(cfg.get("orientation", "UNDIRECTED")).lower()
    direction = _ORIENTATIONS.get(raw)
    if direction is None:
        raise CypherSyntaxError(
            f"gds.degree.stream: unknown orientation {raw!r} "
            "(NATURAL, REVERSE, UNDIRECTED)")
    ids, _, src, dst = _edge_arrays(ex)
    deg = _ga.degree_centrality(src, dst, len(ids), direction=direction)
    return _node_rows(ex, ids, [float(d) for d in deg], "score")


@procedure("gds.closeness.stream")
def proc_closeness(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    c = _ga.closeness_centrality(src, dst, len(ids))
    return _node_rows(ex, ids, [float(x) for x in c], "score")


@procedure("gds.betweenness.stream")
def proc_betweenness(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    b = _ga.betweenness_centrality(src, dst, len(ids))
    return _node_rows(ex, ids, [float(x) for x in b], "score")


@procedure("gds.kcore.stream")
def proc_kcore(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    core = _ga.k_core(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in core], "coreValue")


@procedure("gds.graph.density")
def proc_density(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    return ["density"], [[_ga.density(src, dst, len(ids))]]


@procedure("gds.modularity")
def proc_modularity(ex: CypherExecutor, args, row):
    """gds.modularity(communityMap) — {nodeId/elementId: communityId}."""
    if not args or not isinstance(args[0], dict):
        raise CypherSyntaxError("gds.modularity({nodeId: communityId})")
    ids, index, src, dst = _edge_arrays(ex)
    labels = np.arange(len(ids))
    for nid, c in args[0].items():
        i = index.get(str(nid))
        if i is not None:
            labels[i] = int(c)
    return ["modularity"], [[_ga.modularity(src, dst, len(ids), labels)]]


def _weighted_adj(ex, index, weight_prop, orientation: str = "natural"):
    """Directed by default (GDS NATURAL); UNDIRECTED symmetrizes. Self-loops
    contribute one entry either way."""
    undirected = str(orientation).lower() == "undirected"
    adj: dict[int, list[tuple[int, float]]] = {}
    for e in ex.storage.all_edges():
        a, b = index.get(e.start_node), index.get(e.end_node)
        if a is None or b is None:
            continue
        w = 1.0
        if weight_prop:
            try:
                w = float(e.properties.get(weight_prop, 1.0))
            except (TypeError, ValueError):
                w = 1.0
        adj.setdefault(a, []).append((b, w))
        if undirected and b != a:
            adj.setdefault(b, []).append((a, w))
    return adj


def _path_edges(ex, ids, path_idx, weight_prop):
    """Cheapest connecting edge per consecutive node pair, so the returned
    __path__ carries real relationships (length()/apoc.path.* depend on
    them)."""
    rels = []
    for i, j in zip(path_idx, path_idx[1:]):
        best = None
        best_w = None
        # an UNDIRECTED search may traverse an edge against its direction,
        # so check both orientations for the connecting relationship
        candidates = [e for e in ex.storage.get_outgoing_edges(ids[i])
                      if e.end_node == ids[j]]
        candidates += [e for e in ex.storage.get_incoming_edges(ids[i])
                       if e.start_node == ids[j]]
        for e in candidates:
            w = 1.0
            if weight_prop:
                try:
                    w = float(e.properties.get(weight_prop, 1.0))
                except (TypeError, ValueError):
                    w = 1.0
            if best is None or w < best_w:
                best, best_w = e, w
        if best is not None:
            rels.append(best)
    return rels


@procedure("gds.shortestpath.dijkstra.stream")
def proc_dijkstra(ex: CypherExecutor, args, row):
    """gds.shortestPath.dijkstra.stream(source, target, config) —
    config.relationshipWeightProperty selects the cost property."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "gds.shortestPath.dijkstra.stream(source, target, config)")
    src_n = _resolve_node(ex, args[0])
    dst_n = _resolve_node(ex, args[1])
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src_n.id), index.get(dst_n.id)
    if s is None or t is None:
        return ["totalCost", "nodeIds", "path"], []
    weight_prop = cfg.get("relationshipWeightProperty")
    adj = _weighted_adj(ex, index, weight_prop,
                        orientation=cfg.get("orientation", "natural"))
    dist, prev = _ga.dijkstra(adj, s, goal=t)
    if t not in dist:
        return ["totalCost", "nodeIds", "path"], []
    path_idx = _ga.reconstruct_path(prev, s, t)
    nodes = [ex.get_node_or_none(ids[i]) for i in path_idx]
    rels = _path_edges(ex, ids, path_idx, weight_prop)
    return (["totalCost", "nodeIds", "path"],
            [[dist[t], [ids[i] for i in path_idx],
              {"__path__": True, "nodes": nodes, "relationships": rels}]])


@procedure("gds.shortestpath.astar.stream")
def proc_astar(ex: CypherExecutor, args, row):
    """A* with haversine heuristic over config.latitudeProperty/
    longitudeProperty (ref: apoc/algo AStar)."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "gds.shortestPath.astar.stream(source, target, config)")
    src_n = _resolve_node(ex, args[0])
    dst_n = _resolve_node(ex, args[1])
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    lat_p = cfg.get("latitudeProperty", "latitude")
    lon_p = cfg.get("longitudeProperty", "longitude")
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src_n.id), index.get(dst_n.id)
    if s is None or t is None:
        return ["totalCost", "nodeIds"], []
    coords = {}
    for nid, i in index.items():
        n = ex.get_node_or_none(nid)
        if n is not None and lat_p in n.properties and lon_p in n.properties:
            coords[i] = (float(n.properties[lat_p]), float(n.properties[lon_p]))
    goal_xy = coords.get(t)

    def heuristic(v):
        xy = coords.get(v)
        if xy is None or goal_xy is None:
            return 0.0
        from nornicdb_tpu.apoc.functions_ext import spatial_distance
        return spatial_distance(
            {"latitude": xy[0], "longitude": xy[1]},
            {"latitude": goal_xy[0], "longitude": goal_xy[1]})

    adj = _weighted_adj(ex, index, cfg.get("relationshipWeightProperty"),
                        orientation=cfg.get("orientation", "natural"))
    dist, prev = _ga.dijkstra(adj, s, goal=t, heuristic=heuristic)
    if t not in dist:
        return ["totalCost", "nodeIds"], []
    path_idx = _ga.reconstruct_path(prev, s, t)
    return ["totalCost", "nodeIds"], [[dist[t], [ids[i] for i in path_idx]]]


# apoc.algo.* aliases (the reference exposes the same algorithms there)
procedure("apoc.algo.pagerank")(proc_pagerank)
procedure("apoc.algo.betweenness")(proc_betweenness)
procedure("apoc.algo.closeness")(proc_closeness)
procedure("apoc.algo.community")(proc_louvain)


def _apoc_community_shape(ex, args, rows_fn):
    """apoc.algo.{louvain,labelPropagation}([labels]) YIELD node, community
    — the apoc flavor filters by label list and names the column
    `community` (apoc_community_test.go), unlike gds.* (communityId)."""
    labels = None
    if args and isinstance(args[0], list):
        labels = {str(l) for l in args[0]}
    cols, rows = rows_fn()
    out = []
    for node, community in rows:
        if labels and not (set(node.labels) & labels):
            continue
        out.append([node, community])
    return ["node", "community"], out


@procedure("apoc.algo.louvain")
def proc_apoc_louvain(ex: CypherExecutor, args, row):
    return _apoc_community_shape(
        ex, args, lambda: proc_louvain(ex, [], row))


@procedure("apoc.algo.labelpropagation")
def proc_apoc_label_prop(ex: CypherExecutor, args, row):
    return _apoc_community_shape(
        ex, args, lambda: proc_label_prop(ex, [], row))


@procedure("apoc.neighbors.byhop")
def proc_neighbors_byhop(ex: CypherExecutor, args, row):
    """apoc.neighbors.byhop(start, relType, hops) YIELD nodes, depth —
    one row per hop level with the nodes first reached at that depth."""
    if not args:
        raise CypherSyntaxError("expected (node, relType, hops)")
    src = _resolve_node(ex, args[0])
    rel_type = str(args[1]) if len(args) > 1 and args[1] is not None else None
    hops = int(args[2]) if len(args) > 2 and args[2] is not None else 1
    ids, index, _, _ = _edge_arrays(ex)
    s = index.get(src.id)
    if s is None:
        return ["nodes", "depth"], []
    adj = _filtered_weighted_adj(ex, index, rel_type, None)
    frontier, seen = {s}, {s}
    out = []
    for depth in range(1, hops + 1):
        frontier = {
            nxt for cur in frontier for nxt, _w in adj.get(cur, [])
        } - seen
        if not frontier:
            break
        seen |= frontier
        level = [n for i in sorted(frontier)
                 if (n := ex.get_node_or_none(ids[i])) is not None]
        out.append([level, depth])
    return ["nodes", "depth"], out
procedure("apoc.algo.wcc")(proc_wcc)


def _resolve_node(ex: CypherExecutor, v):
    """Procedures accept Node objects OR id strings (the reference's
    apoc.algo tests call with ids: apoc_algorithms_test.go:75). A string
    that is not a storage id falls back to the `id` PROPERTY — the
    reference's engine-level fixtures set Node.ID directly, while Cypher
    CREATE here assigns storage ids and keeps {id: ...} as a property."""
    if isinstance(v, Node):
        return v
    n = ex.get_node_or_none(str(v))
    if n is None:
        n = next(
            (c for c in ex.storage.all_nodes()
             if c.properties.get("id") == v),
            None,
        )
    if n is None:
        raise CypherTypeError(f"start node not found: {v!r}")
    return n


def _apoc_algo_args(ex, args):
    """(start, end, relTypesAndDirs, weightProperty) — the apoc.algo
    calling convention (apoc_algorithms_test.go)."""
    if len(args) < 2:
        raise CypherSyntaxError("expected (startNode, endNode, relType, weightProp)")
    src = _resolve_node(ex, args[0])
    dst = _resolve_node(ex, args[1])
    rel_type = str(args[2]) if len(args) > 2 and args[2] is not None else None
    weight = str(args[3]) if len(args) > 3 and args[3] is not None else None
    return src, dst, rel_type, weight


def _filtered_weighted_adj(ex, index, rel_type, weight_prop):
    """Adjacency restricted to a relationship-type spec, undirected (the
    apoc path algorithms traverse both directions like the reference's).
    The spec uses apoc syntax: 'KNOWS', 'KNOWS>', '<KNOWS', 'A|B'."""
    types = None
    if rel_type:
        types = {t.strip("<>") for t in str(rel_type).split("|")
                 if t.strip("<>")}
    adj: dict[int, list[tuple[int, float]]] = {}
    for e in ex.storage.all_edges():
        if types and e.type not in types:
            continue
        s, t = index.get(e.start_node), index.get(e.end_node)
        if s is None or t is None:
            continue
        w = 1.0
        if weight_prop:
            try:
                w = float(e.properties.get(weight_prop, 1.0))
            except (TypeError, ValueError):
                w = 1.0
        adj.setdefault(s, []).append((t, w))
        adj.setdefault(t, []).append((s, w))
    return adj


def _ids_to_path(ex, ids, path_idx, rel_type, weight_prop):
    nodes = [ex.get_node_or_none(ids[i]) for i in path_idx]
    rels = _path_edges(ex, ids, path_idx, weight_prop)
    return {"__path__": True, "nodes": nodes, "relationships": rels}


@procedure("apoc.algo.dijkstra")
def proc_apoc_dijkstra(ex: CypherExecutor, args, row):
    """apoc.algo.dijkstra(start, end, relType, weightProp) YIELD path,
    weight (ref: apoc_algorithms_test.go:75)."""
    src, dst, rel_type, weight_prop = _apoc_algo_args(ex, args)
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src.id), index.get(dst.id)
    if s is None or t is None:
        return ["path", "weight"], []
    adj = _filtered_weighted_adj(ex, index, rel_type, weight_prop)
    dist, prev = _ga.dijkstra(adj, s, goal=t)
    if t not in dist:
        return ["path", "weight"], []
    path_idx = _ga.reconstruct_path(prev, s, t)
    return (["path", "weight"],
            [[_ids_to_path(ex, ids, path_idx, rel_type, weight_prop),
              dist[t]]])


@procedure("apoc.algo.astar")
def proc_apoc_astar(ex: CypherExecutor, args, row):
    """apoc.algo.aStar — same yield shape as dijkstra (the zero heuristic
    is admissible without coordinates)."""
    return proc_apoc_dijkstra(ex, args, row)


@procedure("apoc.algo.allsimplepaths")
def proc_all_simple_paths(ex: CypherExecutor, args, row):
    """apoc.algo.allSimplePaths(start, end, relType, maxHops) YIELD path."""
    src, dst, rel_type, _ = _apoc_algo_args(ex, args)
    max_hops = int(args[3]) if len(args) > 3 and args[3] is not None else 10
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src.id), index.get(dst.id)
    if s is None or t is None:
        return ["path"], []
    adj = _filtered_weighted_adj(ex, index, rel_type, None)
    out = []

    def dfs(cur, path):
        if len(path) > max_hops + 1:
            return
        if cur == t:
            out.append([_ids_to_path(ex, ids, path, rel_type, None)])
            return
        for nxt, _w in adj.get(cur, []):
            if nxt not in path:
                dfs(nxt, path + [nxt])

    dfs(s, [s])
    return ["path"], out


# -- gds.graph.* catalog (ref: fastrp_test.go:186-244) ------------------------
def _graph_catalog(ex: CypherExecutor) -> dict:
    cat = getattr(ex, "_gds_graph_catalog", None)
    if cat is None:
        cat = ex._gds_graph_catalog = {}
    return cat


@procedure("gds.graph.project")
def proc_graph_project(ex: CypherExecutor, args, row):
    """gds.graph.project(name, nodeLabel, relType) YIELD graphName,
    nodeCount, relationshipCount. '*' projects everything."""
    if len(args) < 1:
        raise CypherSyntaxError("gds.graph.project(name, nodeLabel, relType)")
    name = str(args[0])
    label = str(args[1]) if len(args) > 1 and args[1] is not None else "*"
    rel_type = str(args[2]) if len(args) > 2 and args[2] is not None else "*"
    cat = _graph_catalog(ex)
    if name in cat:
        raise AlreadyExistsError(f"graph {name} already exists")
    if label == "*":
        n_count = ex.storage.node_count()
    else:
        n_count = sum(1 for _ in ex.storage.get_nodes_by_label(label))
    if rel_type == "*":
        r_count = ex.storage.edge_count()
    else:
        r_count = sum(1 for e in ex.storage.all_edges() if e.type == rel_type)
    cat[name] = {"label": label, "relType": rel_type,
                 "nodeCount": n_count, "relationshipCount": r_count}
    return (["graphName", "nodeCount", "relationshipCount"],
            [[name, n_count, r_count]])


@procedure("gds.graph.drop")
def proc_graph_drop(ex: CypherExecutor, args, row):
    name = str(args[0]) if args else ""
    cat = _graph_catalog(ex)
    if name not in cat:
        raise NotFoundError(f"graph {name} not found")
    del cat[name]
    return ["graphName"], [[name]]


@procedure("gds.graph.list")
def proc_graph_list(ex: CypherExecutor, args, row):
    cat = _graph_catalog(ex)
    if args:  # gds.graph.list(name)
        name = str(args[0])
        items = [(name, cat[name])] if name in cat else []
    else:
        items = sorted(cat.items())
    return (["graphName", "nodeCount", "relationshipCount"],
            [[n, g["nodeCount"], g["relationshipCount"]] for n, g in items])


@procedure("gds.graph.exists")
def proc_graph_exists(ex: CypherExecutor, args, row):
    name = str(args[0]) if args else ""
    return (["graphName", "exists"],
            [[name, name in _graph_catalog(ex)]])
