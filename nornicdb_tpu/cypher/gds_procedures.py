"""GDS-compatible procedures + Kalman Cypher functions.

Behavioral reference: /root/reference/pkg/cypher/linkprediction.go
(gds.linkPrediction.* procedures over pkg/linkpredict),
kalman_functions.go:115-195 (kalman.* scalar functions),
fastrp.go:361-652 (gds.fastRP.* node embeddings).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from nornicdb_tpu.cypher.executor import CypherExecutor, procedure
from nornicdb_tpu.cypher.functions import register
from nornicdb_tpu.errors import CypherSyntaxError, CypherTypeError
from nornicdb_tpu.filter.kalman import Kalman, KalmanConfig
from nornicdb_tpu.linkpredict.topology import (
    SCORERS,
    build_graph,
    score_pair,
    top_candidates,
)
from nornicdb_tpu.storage.types import Node


def _method_from_name(proc_name: str) -> str:
    # gds.linkprediction.adamicadar -> adamicAdar
    tail = proc_name.rsplit(".", 1)[-1]
    for m in SCORERS:
        if m.lower() == tail:
            return m
    raise CypherSyntaxError(f"unknown link prediction method {tail}")


def _cached_graph(ex: CypherExecutor):
    """Per-executor graph projection cache, invalidated by count changes —
    avoids a full O(N+E) rebuild per input row (the reference builds one
    projection per procedure call too, graph_builder.go)."""
    key = (ex.storage.node_count(), ex.storage.edge_count())
    cached = getattr(ex, "_lp_graph_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    g = build_graph(ex.storage)
    ex._lp_graph_cache = (key, g)
    return g


def _lp_pair(ex: CypherExecutor, args: list[Any], method: str):
    if len(args) < 2:
        raise CypherSyntaxError("expected (node1, node2)")
    a, b = args[0], args[1]
    a_id = a.id if isinstance(a, Node) else str(a)
    b_id = b.id if isinstance(b, Node) else str(b)
    g = _cached_graph(ex)
    return ["score"], [[score_pair(g, a_id, b_id, method)]]


for _m in list(SCORERS):
    def _make(meth):
        def fn(ex, args, row):
            return _lp_pair(ex, args, meth)

        return fn

    procedure(f"gds.linkprediction.{_m.lower()}")(_make(_m))


def _source_candidates(
    ex: CypherExecutor, method: str, source: str, top_k: int
) -> list[list[Any]]:
    """Per-source candidate streaming: score `source` against every
    non-adjacent node (ref: the map-config .stream form,
    gds.linkPrediction.X.stream({sourceNode, topK}) linkprediction.go)."""
    g = _cached_graph(ex)
    if source not in g.index:
        return []
    si = g.index[source]
    scored = []
    for j in range(g.n):
        if j == si or j in g.neighbors[si]:
            continue
        v = score_pair(g, source, g.ids[j], method)
        if v > 0:
            scored.append((g.ids[j], v))
    scored.sort(key=lambda t: -t[1])
    rows = []
    for b_id, v in scored[:top_k]:
        na, nb = ex.get_node_or_none(source), ex.get_node_or_none(b_id)
        if na is not None and nb is not None:
            rows.append([na, nb, v])
    return rows


def _stream_config(args: list[Any]) -> tuple[str, int]:
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    source = cfg.get("sourceNode", "")
    # accept a Node object or an id string (same normalization as _lp_pair)
    source = source.id if isinstance(source, Node) else str(source)
    top_k = int(cfg.get("topK", 10))
    return source, top_k


for _m in list(SCORERS):
    def _make_stream(meth):
        def fn(ex, args, row):
            source, top_k = _stream_config(args)
            if not source:
                raise CypherSyntaxError("sourceNode required")
            return (["node1", "node2", "score"],
                    _source_candidates(ex, meth, source, top_k))

        return fn

    procedure(f"gds.linkprediction.{_m.lower()}.stream")(_make_stream(_m))


@procedure("gds.linkprediction.predict.stream")
def proc_lp_predict_stream(ex: CypherExecutor, args, row):
    """Hybrid topology+semantic prediction stream (ref: hybrid.go:61-222,
    gds.linkPrediction.predict.stream)."""
    from nornicdb_tpu.linkpredict.topology import HybridConfig, hybrid_score

    cfg = args[0] if args and isinstance(args[0], dict) else {}
    source = cfg.get("sourceNode", "")
    source = source.id if isinstance(source, Node) else str(source)
    if not source:
        raise CypherSyntaxError("sourceNode required")
    top_k = int(cfg.get("topK", 10))
    method = str(cfg.get("algorithm", "adamic_adar"))
    method = {"adamic_adar": "adamicAdar", "common_neighbors":
              "commonNeighbors", "preferential_attachment":
              "preferentialAttachment", "resource_allocation":
              "resourceAllocation"}.get(method, method)
    hcfg = HybridConfig(
        topology_weight=float(cfg.get("topologyWeight", 0.5)),
        semantic_weight=float(cfg.get("semanticWeight", 0.5)),
    )
    if method in SCORERS:
        hcfg.methods = [method]
    g = _cached_graph(ex)
    if source not in g.index:
        return ["node1", "node2", "score"], []
    src_node = ex.get_node_or_none(source)
    emb_a = src_node.embedding if src_node is not None else None
    si = g.index[source]
    scored = []
    for j in range(g.n):
        if j == si or j in g.neighbors[si]:
            continue
        b_id = g.ids[j]
        nb = ex.get_node_or_none(b_id)
        emb_b = nb.embedding if nb is not None else None
        v = hybrid_score(g, source, b_id, emb_a, emb_b, hcfg)
        if v > 0:
            scored.append((b_id, v))
    scored.sort(key=lambda t: -t[1])
    rows = []
    for b_id, v in scored[:top_k]:
        nb = ex.get_node_or_none(b_id)
        if src_node is not None and nb is not None:
            rows.append([src_node, nb, v])
    return ["node1", "node2", "score"], rows


@procedure("gds.linkprediction.suggest")
def proc_lp_suggest(ex: CypherExecutor, args, row):
    """Top non-adjacent candidate pairs (ref: linkprediction.go suggest)."""
    method = str(args[0]) if args else "adamicAdar"
    limit = int(args[1]) if len(args) > 1 else 20
    g = build_graph(ex.storage)
    rows = []
    for a_id, b_id, score in top_candidates(g, method, limit):
        na, nb = ex.get_node_or_none(a_id), ex.get_node_or_none(b_id)
        if na is not None and nb is not None:
            rows.append([na, nb, score])
    return ["node1", "node2", "score"], rows


@procedure("gds.fastrp.stats")
def proc_fastrp_stats(ex: CypherExecutor, args, row):
    """gds.fastRP.stats(name, config) — summary counts without streaming
    embeddings (ref: fastrp.go stats mode)."""
    cfg = next((a for a in args if isinstance(a, dict)), {})
    g = _cached_graph(ex)
    return (
        ["nodeCount", "embeddingDimension"],
        [[g.n, int(cfg.get("embeddingDimension", 128))]],
    )


@procedure("gds.fastrp.stream")
def proc_fastrp(ex: CypherExecutor, args, row):
    """FastRP node embeddings (ref: fastrp.go:361-652): iterative neighbor
    averaging over random projections, here computed as adjacency matmuls."""
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    dims = int(cfg.get("embeddingDimension", 128))
    iterations = int(cfg.get("iterationWeights") and len(cfg["iterationWeights"]) or 3)
    weights = cfg.get("iterationWeights") or [0.0, 1.0, 1.0][:iterations]
    g = build_graph(ex.storage)
    if g.n == 0:
        return ["nodeId", "embedding"], []
    rng = np.random.default_rng(int(cfg.get("randomSeed", 42)))
    # sparse random projection init (+-1/sqrt(dims))
    emb = rng.choice(
        [-1.0, 0.0, 1.0], size=(g.n, dims), p=[1 / 6, 2 / 3, 1 / 6]
    ).astype(np.float32) * np.sqrt(3.0 / dims)
    a = np.zeros((g.n, g.n), np.float32)
    for i, nbrs in enumerate(g.neighbors):
        for j in nbrs:
            a[i, j] = 1.0
    deg = np.maximum(a.sum(axis=1, keepdims=True), 1.0)
    a = a / deg  # row-normalized
    out = np.zeros_like(emb)
    curr = emb
    for w in weights:
        curr = a @ curr
        norms = np.maximum(np.linalg.norm(curr, axis=1, keepdims=True), 1e-12)
        curr = curr / norms
        out += float(w) * curr
    norms = np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-12)
    out = out / norms
    return (
        ["nodeId", "embedding"],
        [[g.ids[i], out[i].tolist()] for i in range(g.n)],
    )


# ---------------------------------------------------------------- kalman fns
def _kalman_states(ex: CypherExecutor) -> dict[str, Kalman]:
    """Per-executor state (not module-global) so independent DB instances /
    databases never share or leak filter state."""
    states = getattr(ex, "_kalman_states", None)
    if states is None:
        states = {}
        ex._kalman_states = states
    return states


@register("kalman.init")
def fn_kalman_init(config=None):
    """kalman.init([config]) -> state JSON string stored on a node
    property (ref: kalman_functions.go:254 kalmanInit — Q scales
    processNoise by 0.001, defaults R=88, P=30, varianceScale=10)."""
    import json as _json

    state = {
        "x": 0.0, "p": 30.0, "q": 0.1 * 0.001, "r": 88.0,
        "varianceScale": 10.0, "initialized": False,
    }
    if isinstance(config, dict):
        if config.get("processNoise") is not None:
            state["q"] = float(config["processNoise"]) * 0.001
        if config.get("measurementNoise") is not None:
            state["r"] = float(config["measurementNoise"])
        if config.get("initialCovariance") is not None:
            state["p"] = float(config["initialCovariance"])
        if config.get("varianceScale") is not None:
            state["varianceScale"] = float(config["varianceScale"])
    return _json.dumps(state)


@register("kalman.process")
def fn_kalman_process(measurement, state):
    """kalman.process(measurement, stateJson) -> {value, state}
    (ref: kalmanProcess — returns the smoothed value plus the updated
    state JSON to store back on the node)."""
    import json as _json

    if measurement is None or state is None:
        return None
    s = _json.loads(state)
    z = float(measurement)
    if not s.get("initialized"):
        s["x"] = z
        s["initialized"] = True
    else:
        p = s["p"] + s["q"]
        k = p / (p + s["r"])
        s["x"] = s["x"] + k * (z - s["x"])
        s["p"] = (1 - k) * p
    return {"value": s["x"], "state": _json.dumps(s)}


@register("kalman.state")
def fn_kalman_state(state):
    """kalman.state(stateJson) -> MAP view of the stored filter state."""
    import json as _json

    return None if state is None else _json.loads(state)


@register("kalman.filter")
def fn_kalman_filter(ex, key, measurement, process_noise=1e-3, measurement_noise=1e-1):
    """Stateful named scalar filter (ref: kalman_functions.go:115-195)."""
    if key is None or measurement is None:
        return None
    states = _kalman_states(ex)
    k = states.get(str(key))
    if k is None:
        k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
        states[str(key)] = k
    return k.process(float(measurement))


fn_kalman_filter.needs_executor = True


@register("kalman.predict")
def fn_kalman_predict(ex, key):
    k = _kalman_states(ex).get(str(key))
    return None if k is None else k.predict()


fn_kalman_predict.needs_executor = True


@register("kalman.reset")
def fn_kalman_reset(ex, key):
    _kalman_states(ex).pop(str(key), None)
    return True


fn_kalman_reset.needs_executor = True


@register("kalman.smooth")
def fn_kalman_smooth(values, process_noise=1e-3, measurement_noise=1e-1):
    """Smooth a list of measurements in one call."""
    if values is None:
        return None
    if not isinstance(values, list):
        raise CypherTypeError("kalman.smooth expects a list")
    k = Kalman(KalmanConfig(float(process_noise), float(measurement_noise)))
    return [k.process(float(v)) for v in values]


# ---------------------------------------------------------------- algorithms
# (ref: /root/reference/apoc/algo/ + /root/reference/apoc/community/ —
# exposed both under gds.* stream procedures and apoc.algo.* aliases)

from nornicdb_tpu.ops import graph_algos as _ga  # noqa: E402


def _edge_arrays(ex: CypherExecutor):
    """Directed (src, dst) index arrays + sorted id list, cached per
    executor and invalidated on count change (same policy as
    _cached_graph)."""
    key = (ex.storage.node_count(), ex.storage.edge_count())
    cached = getattr(ex, "_algo_graph_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    ids = sorted(n.id for n in ex.storage.all_nodes())
    index = {id_: i for i, id_ in enumerate(ids)}
    src, dst = [], []
    for e in ex.storage.all_edges():
        a, b = index.get(e.start_node), index.get(e.end_node)
        if a is not None and b is not None:
            src.append(a)
            dst.append(b)
    out = (ids, index,
           np.asarray(src, dtype=np.int32), np.asarray(dst, dtype=np.int32))
    ex._algo_graph_cache = (key, out)
    return out


def _node_rows(ex, ids, values, col):
    rows = []
    for i, v in enumerate(values):
        n = ex.get_node_or_none(ids[i])
        if n is not None:
            rows.append([n, v])
    return ["node", col], rows


@procedure("gds.pagerank.stream")
def proc_pagerank(ex: CypherExecutor, args, row):
    """(ref: apoc/algo PageRank — damped power iteration, on-TPU
    segment_sum program)"""
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    ids, _, src, dst = _edge_arrays(ex)
    scores = _ga.pagerank(src, dst, len(ids),
                          damping=float(cfg.get("dampingFactor", 0.85)),
                          iters=int(cfg.get("maxIterations", 20)))
    return _node_rows(ex, ids, [float(s) for s in scores], "score")


@procedure("gds.wcc.stream")
def proc_wcc(ex: CypherExecutor, args, row):
    """(ref: community WeaklyConnectedComponents — min-label propagation)"""
    ids, _, src, dst = _edge_arrays(ex)
    comp = _ga.connected_components(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in comp], "componentId")


@procedure("gds.scc.stream")
def proc_scc(ex: CypherExecutor, args, row):
    """(ref: community StronglyConnectedComponents — Tarjan)"""
    ids, _, src, dst = _edge_arrays(ex)
    comp = _ga.strongly_connected_components(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in comp], "componentId")


@procedure("gds.labelpropagation.stream")
def proc_label_prop(ex: CypherExecutor, args, row):
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    ids, _, src, dst = _edge_arrays(ex)
    labels = _ga.label_propagation(src, dst, len(ids),
                                   iters=int(cfg.get("maxIterations", 10)))
    return _node_rows(ex, ids, [int(c) for c in labels], "communityId")


@procedure("gds.louvain.stream")
def proc_louvain(ex: CypherExecutor, args, row):
    """(ref: community Louvain — greedy modularity local moves)"""
    ids, _, src, dst = _edge_arrays(ex)
    labels = _ga.louvain(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in labels], "communityId")


@procedure("gds.trianglecount.stream")
def proc_triangles(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    tri = _ga.triangle_counts(src, dst, len(ids))
    return _node_rows(ex, ids, [int(t) for t in tri], "triangleCount")


@procedure("gds.localclusteringcoefficient.stream")
def proc_clustering(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    cc = _ga.clustering_coefficient(src, dst, len(ids))
    return _node_rows(ex, ids, [float(c) for c in cc], "localClusteringCoefficient")


_ORIENTATIONS = {
    # GDS-standard names plus the plain aliases
    "natural": "out", "reverse": "in", "undirected": "both",
    "out": "out", "in": "in", "both": "both",
}


@procedure("gds.degree.stream")
def proc_degree(ex: CypherExecutor, args, row):
    cfg = args[0] if args and isinstance(args[0], dict) else {}
    raw = str(cfg.get("orientation", "UNDIRECTED")).lower()
    direction = _ORIENTATIONS.get(raw)
    if direction is None:
        raise CypherSyntaxError(
            f"gds.degree.stream: unknown orientation {raw!r} "
            "(NATURAL, REVERSE, UNDIRECTED)")
    ids, _, src, dst = _edge_arrays(ex)
    deg = _ga.degree_centrality(src, dst, len(ids), direction=direction)
    return _node_rows(ex, ids, [float(d) for d in deg], "score")


@procedure("gds.closeness.stream")
def proc_closeness(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    c = _ga.closeness_centrality(src, dst, len(ids))
    return _node_rows(ex, ids, [float(x) for x in c], "score")


@procedure("gds.betweenness.stream")
def proc_betweenness(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    b = _ga.betweenness_centrality(src, dst, len(ids))
    return _node_rows(ex, ids, [float(x) for x in b], "score")


@procedure("gds.kcore.stream")
def proc_kcore(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    core = _ga.k_core(src, dst, len(ids))
    return _node_rows(ex, ids, [int(c) for c in core], "coreValue")


@procedure("gds.graph.density")
def proc_density(ex: CypherExecutor, args, row):
    ids, _, src, dst = _edge_arrays(ex)
    return ["density"], [[_ga.density(src, dst, len(ids))]]


@procedure("gds.modularity")
def proc_modularity(ex: CypherExecutor, args, row):
    """gds.modularity(communityMap) — {nodeId/elementId: communityId}."""
    if not args or not isinstance(args[0], dict):
        raise CypherSyntaxError("gds.modularity({nodeId: communityId})")
    ids, index, src, dst = _edge_arrays(ex)
    labels = np.arange(len(ids))
    for nid, c in args[0].items():
        i = index.get(str(nid))
        if i is not None:
            labels[i] = int(c)
    return ["modularity"], [[_ga.modularity(src, dst, len(ids), labels)]]


def _weighted_adj(ex, index, weight_prop, orientation: str = "natural"):
    """Directed by default (GDS NATURAL); UNDIRECTED symmetrizes. Self-loops
    contribute one entry either way."""
    undirected = str(orientation).lower() == "undirected"
    adj: dict[int, list[tuple[int, float]]] = {}
    for e in ex.storage.all_edges():
        a, b = index.get(e.start_node), index.get(e.end_node)
        if a is None or b is None:
            continue
        w = 1.0
        if weight_prop:
            try:
                w = float(e.properties.get(weight_prop, 1.0))
            except (TypeError, ValueError):
                w = 1.0
        adj.setdefault(a, []).append((b, w))
        if undirected and b != a:
            adj.setdefault(b, []).append((a, w))
    return adj


def _path_edges(ex, ids, path_idx, weight_prop):
    """Cheapest connecting edge per consecutive node pair, so the returned
    __path__ carries real relationships (length()/apoc.path.* depend on
    them)."""
    rels = []
    for i, j in zip(path_idx, path_idx[1:]):
        best = None
        best_w = None
        # an UNDIRECTED search may traverse an edge against its direction,
        # so check both orientations for the connecting relationship
        candidates = [e for e in ex.storage.get_outgoing_edges(ids[i])
                      if e.end_node == ids[j]]
        candidates += [e for e in ex.storage.get_incoming_edges(ids[i])
                       if e.start_node == ids[j]]
        for e in candidates:
            w = 1.0
            if weight_prop:
                try:
                    w = float(e.properties.get(weight_prop, 1.0))
                except (TypeError, ValueError):
                    w = 1.0
            if best is None or w < best_w:
                best, best_w = e, w
        if best is not None:
            rels.append(best)
    return rels


@procedure("gds.shortestpath.dijkstra.stream")
def proc_dijkstra(ex: CypherExecutor, args, row):
    """gds.shortestPath.dijkstra.stream(source, target, config) —
    config.relationshipWeightProperty selects the cost property."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "gds.shortestPath.dijkstra.stream(source, target, config)")
    src_n, dst_n = args[0], args[1]
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src_n.id), index.get(dst_n.id)
    if s is None or t is None:
        return ["totalCost", "nodeIds", "path"], []
    weight_prop = cfg.get("relationshipWeightProperty")
    adj = _weighted_adj(ex, index, weight_prop,
                        orientation=cfg.get("orientation", "natural"))
    dist, prev = _ga.dijkstra(adj, s, goal=t)
    if t not in dist:
        return ["totalCost", "nodeIds", "path"], []
    path_idx = _ga.reconstruct_path(prev, s, t)
    nodes = [ex.get_node_or_none(ids[i]) for i in path_idx]
    rels = _path_edges(ex, ids, path_idx, weight_prop)
    return (["totalCost", "nodeIds", "path"],
            [[dist[t], [ids[i] for i in path_idx],
              {"__path__": True, "nodes": nodes, "relationships": rels}]])


@procedure("gds.shortestpath.astar.stream")
def proc_astar(ex: CypherExecutor, args, row):
    """A* with haversine heuristic over config.latitudeProperty/
    longitudeProperty (ref: apoc/algo AStar)."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "gds.shortestPath.astar.stream(source, target, config)")
    src_n, dst_n = args[0], args[1]
    cfg = args[2] if len(args) > 2 and isinstance(args[2], dict) else {}
    lat_p = cfg.get("latitudeProperty", "latitude")
    lon_p = cfg.get("longitudeProperty", "longitude")
    ids, index, _, _ = _edge_arrays(ex)
    s, t = index.get(src_n.id), index.get(dst_n.id)
    if s is None or t is None:
        return ["totalCost", "nodeIds"], []
    coords = {}
    for nid, i in index.items():
        n = ex.get_node_or_none(nid)
        if n is not None and lat_p in n.properties and lon_p in n.properties:
            coords[i] = (float(n.properties[lat_p]), float(n.properties[lon_p]))
    goal_xy = coords.get(t)

    def heuristic(v):
        xy = coords.get(v)
        if xy is None or goal_xy is None:
            return 0.0
        from nornicdb_tpu.apoc.functions_ext import spatial_distance
        return spatial_distance(
            {"latitude": xy[0], "longitude": xy[1]},
            {"latitude": goal_xy[0], "longitude": goal_xy[1]})

    adj = _weighted_adj(ex, index, cfg.get("relationshipWeightProperty"),
                        orientation=cfg.get("orientation", "natural"))
    dist, prev = _ga.dijkstra(adj, s, goal=t, heuristic=heuristic)
    if t not in dist:
        return ["totalCost", "nodeIds"], []
    path_idx = _ga.reconstruct_path(prev, s, t)
    return ["totalCost", "nodeIds"], [[dist[t], [ids[i] for i in path_idx]]]


# apoc.algo.* aliases (the reference exposes the same algorithms there)
procedure("apoc.algo.pagerank")(proc_pagerank)
procedure("apoc.algo.betweenness")(proc_betweenness)
procedure("apoc.algo.closeness")(proc_closeness)
procedure("apoc.algo.community")(proc_louvain)
procedure("apoc.algo.wcc")(proc_wcc)
procedure("apoc.algo.dijkstra")(proc_dijkstra)
procedure("apoc.algo.astar")(proc_astar)
