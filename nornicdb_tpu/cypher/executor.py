"""Cypher executor: clause pipeline over binding rows.

Behavioral reference: /root/reference/pkg/cypher/executor.go (Execute
:490-695), match.go, create.go, merge.go, executor_mutations.go, call.go,
call_vector.go, call_fulltext.go. The architecture differs deliberately
(SURVEY.md §7): parsed AST -> row pipeline, not keyword re-dispatch.

Explicit transactions implement ROLLBACK with an executor-level undo log
(inverse operations), mirroring the reference's transaction-aware WAL undo
(pkg/storage/wal.go:1845).
"""

from __future__ import annotations

import logging

import copy
import csv as csv_mod
import functools
import io
import threading
import time
import uuid
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

import numpy as np

from nornicdb_tpu.cypher import ast
from nornicdb_tpu.cypher.expr import EvalContext, evaluate
from nornicdb_tpu.cypher.functions import FUNCTIONS, is_aggregate
from nornicdb_tpu.cypher.matcher import PatternMatcher, make_path
from nornicdb_tpu.cypher.parser import parse
from nornicdb_tpu.cypher.validator import strict_mode_enabled, validate
from nornicdb_tpu.errors import (
    AlreadyExistsError,
    CypherSyntaxError,
    CypherTypeError,
    NornicError,
    NotFoundError,
    TransactionError,
)
from nornicdb_tpu.storage.schema import SchemaManager
from nornicdb_tpu.storage.types import Edge, Engine, Node, new_id
from nornicdb_tpu.telemetry import slowlog as _slowlog
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

# stage cells resolved once at import: the per-query cost is one
# perf_counter pair + one cell observe per stage, plus a single
# contextvar read for the (usually no-op) span
_STAGE_HIST = _REGISTRY.histogram(
    "nornicdb_cypher_stage_seconds",
    "Cypher execute latency by stage",
    labels=("stage",),
)
_STAGE_PARSE = _STAGE_HIST.labels("parse")
_STAGE_PLAN = _STAGE_HIST.labels("plan")
_STAGE_MATCH = _STAGE_HIST.labels("match")
_STAGE_PROJECT = _STAGE_HIST.labels("project")
_STAGE_EXECUTE = _STAGE_HIST.labels("execute")
_slow_log = _slowlog.slow_log


@dataclass
class Stats:
    nodes_created: int = 0
    nodes_deleted: int = 0
    relationships_created: int = 0
    relationships_deleted: int = 0
    properties_set: int = 0
    labels_added: int = 0
    labels_removed: int = 0
    indexes_added: int = 0
    constraints_added: int = 0

    def as_dict(self) -> dict[str, int]:
        return {k: v for k, v in self.__dict__.items() if v}


@dataclass
class Result:
    columns: list[str]
    rows: list[list[Any]]
    stats: Stats = field(default_factory=Stats)
    plan: Optional[str] = None

    def rows_as_dicts(self) -> list[dict[str, Any]]:
        return [dict(zip(self.columns, r)) for r in self.rows]

    def single(self) -> Optional[list[Any]]:
        return self.rows[0] if self.rows else None


ProcedureFn = Callable[["CypherExecutor", list[Any], dict[str, Any]], tuple[list[str], list[list[Any]]]]
PROCEDURES: dict[str, ProcedureFn] = {}
# registration is import-time in practice, but apoc extension modules may
# load lazily from executing sessions — serialize writers
_PROCEDURES_LOCK = threading.Lock()


def procedure(name: str):
    def deco(fn):
        with _PROCEDURES_LOCK:
            PROCEDURES[name.lower()] = fn
        return fn

    return deco


_query_log = logging.getLogger("nornicdb.query")
_log = logging.getLogger(__name__)


class CypherExecutor:
    """(ref: cypher.StorageExecutor executor.go:187)"""

    def __init__(
        self,
        storage: Engine,
        schema: Optional[SchemaManager] = None,
        db=None,
        cache=None,
        log_queries: bool = False,
    ):
        self.storage = storage
        if schema is None:
            # a self-created schema must hear the engine's write events, or
            # an index created before data silently never indexes anything.
            # Lazy: the subscription (and any node scan) only happens at
            # the first index/constraint DDL, so per-request executors over
            # a shared engine cost nothing (the DB facade attaches its own
            # schema eagerly; a passed-in one is the caller's to wire).
            schema = SchemaManager()
            schema.attach_lazy(storage)
        self.schema = schema
        self.db = db  # DB facade: embedder, search service, multidb hooks
        self.cache = cache  # QueryCache (ref: pkg/cache wiring main.go:320)
        # per-executor (NOT process-global: two DBs in one process must not
        # leak each other's query text into logs)
        self.log_queries = log_queries
        self.matcher = PatternMatcher(storage, self.schema, self)
        self._plugin_functions: dict[str, Callable] = {}
        # explicit transaction state (ref: executor.go tx statements :611)
        # Undo frames are THREAD-LOCAL: protocol servers run concurrent
        # statements on one executor, and a shared frame would let thread
        # A's rollback undo thread B's committed writes (the race
        # TestRollback_ConcurrentWritesDuringRollback exercises). Explicit
        # transactions are per-connection-thread too (Bolt session model).
        self._tx_state = threading.local()
        # Write statements serialize while their undo frame is live:
        # rollback restores whole-entity pre-images, so a concurrent write
        # statement committing between another's mutation and its unwind
        # would be silently erased (lost update). Reads never take this.
        self._write_stmt_lock = threading.RLock()
        self._last_call_columns: list[str] = []
        self.query_count = 0
        self._colindex: Any = None  # lazy ColumnarScanIndex; False = unusable
        # columnar operator pipeline + shape-keyed plan cache
        # (cypher/columnar.py; NORNICDB_CYPHER_COLUMNAR=0 disables)
        from nornicdb_tpu.cypher.columnar import ColumnarEngine

        self.columnar = ColumnarEngine(self)
        # opt-in strict OpenCypher semantic validation (ref: the ANTLR
        # validation mode, executor.go:1572-1584, NORNICDB_PARSER=antlr;
        # here NORNICDB_PARSER=strict, with `antlr` accepted as an alias)
        self.strict_validation = strict_mode_enabled()

    def _scan_index(self):
        """Lazily attach the event-maintained columnar scan index
        (cypher/colindex.py) to this executor's storage."""
        if self._colindex is None:
            try:
                from nornicdb_tpu.cypher.colindex import ColumnarScanIndex

                self._colindex = ColumnarScanIndex(self.storage)
            except Exception:
                _log.debug("columnar scan index unavailable; label "
                                "scans use the engine path", exc_info=True)
                self._colindex = False
        return self._colindex or None

    # -- public ----------------------------------------------------------------
    def execute(self, query: str, params: Optional[dict[str, Any]] = None) -> Result:
        """(ref: Execute executor.go:490 — analyze -> cache -> route)

        Telemetry wrapper: every statement lands in the cypher-stage
        latency histogram and opens a ``cypher.execute`` span (a no-op
        handle unless an ingress started a trace on this context);
        statements over the slow-query threshold are captured with plan,
        span breakdown, and adjacency/device-sync counter deltas."""
        t0 = time.perf_counter()
        probe = (
            _slowlog.counters_probe(self.db) if _slow_log.enabled else None
        )
        self.columnar.begin_statement()
        with _tracer.span("cypher.execute") as sp:
            if sp.trace_id is not None:
                sp.set_attr("query", _slowlog.redact_query(query))
            try:
                return self._execute_traced(query, params)
            finally:
                duration = time.perf_counter() - t0
                _STAGE_EXECUTE.observe(duration)
                if self.log_queries:
                    # --log-queries (ref: cmd/nornicdb/main.go:137): every
                    # statement with wall time, via the logging module
                    _query_log.info("%.1fms %s", duration * 1e3,
                                    " ".join(query.split()))
                if _slow_log.enabled and duration >= _slow_log.threshold_s:
                    self._record_slow_query(query, params, duration, probe)

    def _record_slow_query(
        self,
        query: str,
        params: Optional[dict[str, Any]],
        duration: float,
        probe_before: Optional[dict],
    ) -> None:
        """Capture one over-threshold statement into the global slow-query
        ring.  Plan summary is computed here — only slow queries pay for
        EXPLAIN — and must never break the caller's result path."""
        try:
            plan = None
            try:
                stmt = parse(query)  # memoized: cache hit for this query
                if isinstance(stmt, ast.Query):
                    plan = self._explain(stmt)
            except Exception:  # unparseable/plan-less statements: no plan
                _log.debug("no plan for slow query", exc_info=True)
                plan = None
            cur = _tracer.capture()
            col_trace = self.columnar.last_trace()
            columnar = None
            if col_trace is not None:
                # plan-cache key + per-operator timings of the LAST
                # columnar execution on this thread — the slow statement,
                # when it ran columnar at all
                columnar = {
                    "plan_key": col_trace["key"],
                    "outcome": col_trace["outcome"],
                    "cache": col_trace["cache"],
                    "total_ms": col_trace["total_ms"],
                    "operators": [
                        {"op": label, "engine": engine, "rows": rows_n,
                         "ms": ms}
                        for label, engine, rows_n, ms in col_trace["ops"]
                    ],
                }
            _slow_log.maybe_record(
                query,
                params,
                duration,
                plan=plan,
                probe_before=probe_before,
                probe_after=_slowlog.counters_probe(self.db),
                trace_spans=cur.trace.spans if cur is not None else None,
                trace_id=cur.trace_id if cur is not None else None,
                columnar=columnar,
            )
        except Exception:
            _log.warning("slow-query capture failed", exc_info=True)

    def _execute_traced(self, query: str,
                        params: Optional[dict[str, Any]] = None) -> Result:
        self.query_count += 1
        params = params or {}
        stripped = query.lstrip()
        if stripped[:4].lower() == ":use":
            # browser-style :use prefix (ref: executor.go:500-541 — the
            # :USE line selects the database for the rest of the text)
            rest = stripped[4:].lstrip()
            parts = rest.split(None, 1)
            if not parts:
                raise CypherSyntaxError(":use requires a database name")
            query = f"USE {parts[0]}" + (
                f" {parts[1]}" if len(parts) > 1 else ""
            )
        # plan-cache text fast path: repeat read traffic skips parse,
        # validation, classification AND planning. Only full-columnar
        # read-only plans are ever text-bound (maybe_bind_text), so the
        # write-statement machinery below cannot be bypassed. A None from
        # the runner (snapshot momentarily unable to serve) falls through
        # to the normal path.
        if self.columnar.enabled and self._tx_undo is None:
            entry = self.columnar.cache.text_probe(query)
            if entry is not None:
                res = self._execute_text_plan(entry, query, params)
                if res is not None:
                    return res
        _t_parse = time.perf_counter()
        with _tracer.span("cypher.parse"):
            stmt = parse(query)
        _STAGE_PARSE.observe(time.perf_counter() - _t_parse)
        if self.strict_validation:
            validate(stmt)
        if isinstance(stmt, ast.Query):
            # per-database query rate limit (ref: enforcement.go
            # MaxQueriesPerSecond); the bucket lives on the LimitedEngine —
            # except for the DEFAULT database, whose executor runs on the
            # main facade chain, so its state comes from the manager
            limits, bucket = self._query_limits()
            if bucket is not None and not bucket.take():
                raise NornicError(
                    "database query rate limit exceeded "
                    f"({limits.max_queries_per_second}/s)"
                )
        if self.cache is not None and isinstance(stmt, ast.Query):
            write = _is_write_query(stmt)
            if self._tx_undo is not None and not write:
                # reads inside an explicit tx bypass the cache entirely:
                # no stale serve, no spurious invalidation
                return self.execute_statement(stmt, params)
            if not write:
                hit = self.cache.get(query, params)
                if hit is not None:
                    return _copy_result(hit)
                result = self.execute_statement(stmt, params)
                self.columnar.maybe_bind_text(query, stmt)
                if not _is_nondeterministic(stmt):
                    # reads with unlabeled dependencies get EMPTY label sets,
                    # which invalidate_labels always drops — soundness over
                    # retention
                    self.cache.put(
                        query, params, result, _read_cache_labels(stmt)
                    )
                    # the caller gets a COPY on the miss too: the cached
                    # object must never be reachable from callers, or one
                    # mutating a row — or a returned node's properties
                    # dict — would poison every later hit
                    return _copy_result(result)
                return result
            result = self.execute_statement(stmt, params)
            labels = _write_labels(stmt)
            if labels:
                self.cache.invalidate_labels(labels)
            else:
                self.cache.clear()  # unscoped write: drop everything
            return result
        result = self.execute_statement(stmt, params)
        if isinstance(stmt, ast.Query):
            # cache-less executors still get the plan-cache text fast path
            self.columnar.maybe_bind_text(query, stmt)
        return result

    def _execute_text_plan(
        self, entry, query: str, params: dict[str, Any]
    ) -> Optional[Result]:
        """Run a text-bound (full-columnar, read-only) plan, replicating
        the normal read path's rate-limit and result-cache interplay."""
        limits, bucket = self._query_limits()
        if bucket is not None and not bucket.take():
            raise NornicError(
                "database query rate limit exceeded "
                f"({limits.max_queries_per_second}/s)"
            )
        if self.cache is not None:
            hit = self.cache.get(query, params)
            if hit is not None:
                return _copy_result(hit)
        result = self.columnar.run_text_entry(entry, params, Stats())
        if result is None:
            return None  # momentary bail: the generic path re-runs it
        if self.cache is not None and entry.cacheable:
            self.cache.put(query, params, result, set(entry.labels))
            return _copy_result(result)
        return result

    def execute_statement(self, stmt: ast.Statement, params: dict[str, Any]) -> Result:
        if isinstance(stmt, ast.Query):
            if stmt.explain or stmt.profile:
                _t_plan = time.perf_counter()
                with _tracer.span("cypher.plan"):
                    plan = self._explain(stmt)
                _STAGE_PLAN.observe(time.perf_counter() - _t_plan)
                if stmt.explain:
                    return Result(["plan"], [[plan]], plan=plan)
            t0 = time.perf_counter()
            result = self._run_query_atomic(stmt, params)
            if stmt.profile:
                result.plan = (self._explain(stmt)
                               + f"\nruntime: {(time.perf_counter()-t0)*1000:.2f} ms"
                               + f", rows: {len(result.rows)}")
                trace = self.columnar.last_trace(stmt)
                if trace is not None:
                    # measured per-operator timings from THIS execution
                    result.plan += (
                        f"\ncolumnar execution [{trace['outcome']}, cache "
                        f"{trace['cache']}, {trace['total_ms']} ms]:")
                    for label, engine, rows_n, ms in trace["ops"]:
                        result.plan += \
                            f"\n  {label} [{engine}] rows={rows_n} {ms} ms"
            return result
        if isinstance(stmt, ast.CreateIndex):
            r = self._create_index(stmt)
            self._invalidate_cache_for_ddl()
            return r
        if isinstance(stmt, ast.DropIndex):
            self.schema.drop_index(stmt.name, stmt.if_exists)
            self._invalidate_cache_for_ddl()
            return Result([], [])
        if isinstance(stmt, ast.CreateConstraint):
            self.schema.create_constraint(
                stmt.name, stmt.label, stmt.properties, stmt.kind, stmt.if_not_exists
            )
            self._invalidate_cache_for_ddl()
            r = Result([], [])
            r.stats.constraints_added = 1
            return r
        if isinstance(stmt, ast.DropConstraint):
            self.schema.drop_constraint(stmt.name, stmt.if_exists)
            self._invalidate_cache_for_ddl()
            return Result([], [])
        if isinstance(stmt, ast.ShowCommand):
            return self._show(stmt)
        if isinstance(stmt, ast.DatabaseCommand):
            return self._database_command(stmt)
        if isinstance(stmt, ast.UseCommand):
            return self._use_command(stmt, params)
        if isinstance(stmt, ast.TxCommand):
            return self._tx_command(stmt)
        raise CypherSyntaxError(f"unsupported statement {type(stmt).__name__}")

    # -- query pipeline -----------------------------------------------------------
    # The executor-level pattern-fastpath family (ref: DetectQueryPattern
    # query_patterns.go, ExecuteOptimized optimized_executors.go) is fully
    # RETIRED into the columnar operator pipeline (cypher/columnar.py):
    # counts are planner short circuits (NodeCountOp/EdgeCountOp) and
    # edge-property aggregation runs over the CSR-resident edge property
    # columns (storage/adjacency.py edge_prop_column) — deleted here, not
    # shadowed.
    def _run_query(
        self,
        q: ast.Query,
        params: dict[str, Any],
        start_rows: Optional[list[dict]] = None,
        stats: Optional[Stats] = None,
    ) -> Result:
        result = self._run_single(q, params, start_rows, stats)
        for sub, all_ in q.unions:
            other = self._run_single(sub, params, start_rows, stats)
            if other.columns != result.columns:
                raise CypherSyntaxError("UNION queries must return the same columns")
            result.rows.extend(other.rows)
            if not all_:
                seen = set()
                unique = []
                for r in result.rows:
                    key = _hashable(r)
                    if key not in seen:
                        seen.add(key)
                        unique.append(r)
                result.rows = unique
        return result

    def _run_single(
        self,
        q: ast.Query,
        params: dict[str, Any],
        start_rows: Optional[list[dict]] = None,
        stats: Optional[Stats] = None,
    ) -> Result:
        stats = stats if stats is not None else Stats()
        if start_rows is None:
            # columnar operator pipeline (cypher/columnar.py): compiled
            # plans over the CSR snapshot with per-operator fallback; a
            # None return means "serve it generically" (unsupported shape
            # or the snapshot cannot serve this engine/query right now)
            res = self.columnar.try_query(q, params, stats)
            if res is not None:
                return res
        rows: list[dict[str, Any]] = (
            [dict(r) for r in start_rows] if start_rows is not None else [{}]
        )
        return self._finish_clauses(q, params, rows, 0, stats)

    def _finish_clauses(
        self,
        q: ast.Query,
        params: dict[str, Any],
        rows: list[dict],
        start_idx: int,
        stats: Stats,
    ) -> Result:
        """Run clauses from ``start_idx`` over generic binding rows — the
        whole query when called from _run_single, the generic tail when
        the columnar pipeline hands a partial binding table back."""
        columns: list[str] = []
        out_rows: list[list[Any]] = []
        produced = False
        # per-database query budget (ref: enforcement.go MaxQueryTime):
        # checked at clause boundaries — coarse, but enough to stop
        # multi-clause runaways without per-row overhead. Monotonic clock:
        # wall-time steps must not expire (or extend) the budget.
        limits, _ = self._query_limits()
        deadline = (
            time.monotonic() + limits.max_query_time
            if limits is not None and getattr(limits, "max_query_time", 0)
            else None
        )
        for clause in q.clauses[start_idx:]:
            if deadline is not None and time.monotonic() > deadline:
                raise NornicError(
                    f"query exceeded max_query_time "
                    f"({limits.max_query_time}s)"
                )
            if isinstance(clause, ast.ReturnClause):
                columns, out_rows = self._project(clause, rows, params, stats)
                produced = True
                break
            rows = self._apply_clause(clause, rows, params, stats)
        if not produced:
            last = q.clauses[-1] if q.clauses else None
            if isinstance(last, ast.CallClause):
                # standalone CALL: its yielded columns are the result
                if last.yield_items:
                    columns = [a or n for n, a in last.yield_items]
                else:
                    columns = self._last_call_columns
                out_rows = [[r.get(c) for c in columns] for r in rows]
        return Result(columns, out_rows, stats)

    def _apply_clause(
        self, clause: ast.Clause, rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        if isinstance(clause, ast.MatchClause):
            return self._match(clause, rows, params)
        if isinstance(clause, ast.CreateClause):
            return self._create(clause, rows, params, stats)
        if isinstance(clause, ast.MergeClause):
            return self._merge(clause, rows, params, stats)
        if isinstance(clause, ast.SetClause):
            return self._set(clause.items, rows, params, stats)
        if isinstance(clause, ast.RemoveClause):
            return self._remove(clause.items, rows, params, stats)
        if isinstance(clause, ast.DeleteClause):
            return self._delete(clause, rows, params, stats)
        if isinstance(clause, ast.WithClause):
            return self._with(clause, rows, params, stats)
        if isinstance(clause, ast.UnwindClause):
            return self._unwind(clause, rows, params)
        if isinstance(clause, ast.CallClause):
            return self._call(clause, rows, params, stats)
        if isinstance(clause, ast.CallSubquery):
            return self._call_subquery(clause, rows, params, stats)
        if isinstance(clause, ast.ForeachClause):
            return self._foreach(clause, rows, params, stats)
        if isinstance(clause, ast.LoadCsvClause):
            return self._load_csv(clause, rows, params)
        raise CypherSyntaxError(f"unsupported clause {type(clause).__name__}")

    # -- MATCH -----------------------------------------------------------------
    def _match(self, clause: ast.MatchClause, rows: list[dict], params: dict) -> list[dict]:
        _t_match = time.perf_counter()
        with _tracer.span("cypher.match"):
            out = self._match_inner(clause, rows, params)
        _STAGE_MATCH.observe(time.perf_counter() - _t_match)
        return out

    def _match_inner(self, clause: ast.MatchClause, rows: list[dict], params: dict) -> list[dict]:
        fast = self._match_scan_fast(clause, rows, params)
        if fast is not None:
            return fast
        out: list[dict] = []
        for row in rows:
            matched: list[dict] = [row]
            for pattern in clause.patterns:
                nxt: list[dict] = []
                for r in matched:
                    nxt.extend(self.matcher.match_path(pattern, r, params))
                matched = nxt
            if clause.where is not None:
                matched = [
                    r
                    for r in matched
                    if evaluate(clause.where, EvalContext(r, params, self)) is True
                ]
            if clause.optional and not matched:
                null_row = dict(row)
                for pattern in clause.patterns:
                    for var in _pattern_variables(pattern):
                        null_row.setdefault(var, None)
                out.append(null_row)
            else:
                out.extend(matched)
        return out

    def _match_scan_fast(
        self, clause: ast.MatchClause, rows: list[dict], params: dict
    ) -> Optional[list[dict]]:
        """Large single-node-pattern scans with a WHERE: columnar mask over
        the candidate list + thread-pooled residual filter, instead of a
        full expression-tree walk per row (ref: parallelFilterNodes
        parallel.go:99 + the MinBatchSize gate :100; columnar design note in
        cypher/parallel.py). Semantics-identical to the generic path — the
        chaos suite runs both and compares."""
        from nornicdb_tpu.cypher.parallel import (
            compile_where,
            get_parallel_config,
            parallel_filter,
        )

        if len(clause.patterns) != 1 or len(rows) != 1:
            return None
        pattern = clause.patterns[0]
        if pattern.name or pattern.shortest or len(pattern.elements) != 1:
            return None
        node_pat = pattern.elements[0]
        if not isinstance(node_pat, ast.NodePattern) or not node_pat.variable:
            return None
        row = rows[0]
        if node_pat.variable in row:
            return None
        where = _and_exprs(node_pat.where, clause.where)
        if where is None:
            return None  # unfiltered scan is already a single pass
        cfg = get_parallel_config()
        if not cfg.enabled:
            return None
        cw = compile_where(where, node_pat.variable)
        nodes: Optional[list] = None
        # preferred: columns straight from the scan index — only survivors
        # ever materialize as Nodes
        if (
            cw.has_columnar
            and len(node_pat.labels) == 1
            and node_pat.properties is None
        ):
            label = node_pat.labels[0]
            # the columnar mask is one vectorized numpy op — profitable far
            # below cfg.min_batch_size (that gate prices THREAD dispatch;
            # parallel_filter still applies it to any residual predicate)
            if self.storage.count_nodes_by_label(label) < cfg.columnar_min_rows:
                return None
            idx = self._scan_index()
            if idx is not None:
                ids = idx.masked_ids(label, cw, params)
                if ids is not None:
                    nodes = self.storage.batch_get_nodes(sorted(ids))
        if nodes is None:
            candidates = self.matcher._candidates(
                ast.NodePattern(node_pat.variable, node_pat.labels,
                                node_pat.properties),
                row, params,
            )
            if len(candidates) < cfg.min_batch_size:
                return None
            nodes = candidates
            if cw.has_columnar:
                mask = cw.mask(nodes, params)
                nodes = [n for n, m in zip(nodes, mask) if m]
        if cw.residual is not None:
            res = cw.residual
            var = node_pat.variable

            def pred(n):
                return evaluate(res, EvalContext({**row, var: n}, params, self))

            nodes = parallel_filter(nodes, pred)
        out = [{**row, node_pat.variable: n} for n in nodes]
        if clause.optional and not out:
            null_row = dict(row)
            null_row.setdefault(node_pat.variable, None)
            return [null_row]
        return out

    # -- CREATE ------------------------------------------------------------------
    def _create(
        self, clause: ast.CreateClause, rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        out = []
        for row in rows:
            new_row = dict(row)
            for pattern in clause.patterns:
                self._create_path(pattern, new_row, params, stats)
            out.append(new_row)
        return out

    def _create_path(
        self, pattern: ast.PatternPath, row: dict, params: dict, stats: Stats
    ) -> None:
        elements = pattern.elements
        nodes: list[Node] = []
        rels: list[Edge] = []
        prev_node: Optional[Node] = None
        i = 0
        while i < len(elements):
            el = elements[i]
            if isinstance(el, ast.NodePattern):
                node = self._resolve_or_create_node(el, row, params, stats)
                nodes.append(node)
                if i > 0:
                    rel_pat = elements[i - 1]
                    edge = self._create_edge(rel_pat, prev_node, node, row, params, stats)
                    rels.append(edge)
                prev_node = node
                i += 1
            else:
                i += 1
        if pattern.name:
            row[pattern.name] = make_path(nodes, rels)

    def _resolve_or_create_node(
        self, pat: ast.NodePattern, row: dict, params: dict, stats: Stats
    ) -> Node:
        if pat.variable and pat.variable in row:
            v = row[pat.variable]
            if not isinstance(v, Node):
                raise CypherTypeError(f"variable `{pat.variable}` is not a node")
            if pat.labels or pat.properties:
                raise CypherSyntaxError(
                    f"variable `{pat.variable}` already declared"
                )
            return v
        props = {}
        if pat.properties is not None:
            props = evaluate(pat.properties, EvalContext(row, params, self)) or {}
        node = Node(labels=list(pat.labels), properties=dict(props))
        self.schema.check_unique(node)
        created = self.storage.create_node(node)
        self._record_undo(lambda nid=created.id: self.storage.delete_node(nid))
        if self.db is not None and getattr(self.db.config, "embed_enabled", False):
            self.storage.mark_pending_embed(created.id)
        stats.nodes_created += 1
        stats.properties_set += len(props)
        stats.labels_added += len(pat.labels)
        if pat.variable:
            row[pat.variable] = created
        return created

    def _create_edge(
        self, rel_pat: ast.RelPattern, start: Node, end: Node, row, params, stats
    ) -> Edge:
        if rel_pat.direction == "both":
            raise CypherSyntaxError("CREATE requires a directed relationship")
        if rel_pat.var_length:
            raise CypherSyntaxError("cannot CREATE a variable-length relationship")
        props = {}
        if rel_pat.properties is not None:
            props = evaluate(rel_pat.properties, EvalContext(row, params, self)) or {}
        rel_type = rel_pat.types[0] if rel_pat.types else "RELATED_TO"
        s, t = (start, end) if rel_pat.direction == "out" else (end, start)
        edge = Edge(start_node=s.id, end_node=t.id, type=rel_type, properties=dict(props))
        created = self.storage.create_edge(edge)
        self._record_undo(lambda eid=created.id: self.storage.delete_edge(eid))
        stats.relationships_created += 1
        stats.properties_set += len(props)
        if rel_pat.variable:
            row[rel_pat.variable] = created
        return created

    # -- MERGE --------------------------------------------------------------------
    def _merge(
        self, clause: ast.MergeClause, rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        """(ref: merge.go)"""
        out = []
        for row in rows:
            matches = list(self.matcher.match_path(clause.pattern, row, params))
            if matches:
                for m in matches:
                    if clause.on_match:
                        self._set(clause.on_match, [m], params, stats)
                        m = self._refresh_row(m)
                    out.append(m)
            else:
                new_row = dict(row)
                self._create_path(clause.pattern, new_row, params, stats)
                if clause.on_create:
                    self._set(clause.on_create, [new_row], params, stats)
                    new_row = self._refresh_row(new_row)
                out.append(new_row)
        return out

    def _refresh_row(self, row: dict) -> dict:
        """Re-fetch entities after SET so later clauses see fresh copies."""
        out = {}
        for k, v in row.items():
            if isinstance(v, Node):
                try:
                    out[k] = self.storage.get_node(v.id)
                except NotFoundError:
                    out[k] = v
            elif isinstance(v, Edge):
                try:
                    out[k] = self.storage.get_edge(v.id)
                except NotFoundError:
                    out[k] = v
            else:
                out[k] = v
        return out

    # -- SET / REMOVE ----------------------------------------------------------------
    def _set(
        self, items: list[ast.SetItem], rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        for row in rows:
            ctx = EvalContext(row, params, self)
            for item in items:
                if item.kind == "property":
                    assert isinstance(item.target, ast.Property)
                    entity = evaluate(item.target.subject, ctx)
                    if entity is None:
                        continue
                    value = evaluate(item.value, ctx) if item.value is not None else None
                    self._set_property(entity, item.target.key, value, stats)
                elif item.kind == "variable":
                    entity = evaluate(item.target, ctx)
                    if entity is None:
                        continue
                    value = evaluate(item.value, ctx)
                    if not isinstance(value, dict):
                        if isinstance(value, (Node, Edge)):
                            value = dict(value.properties)
                        else:
                            raise CypherTypeError("SET n = expects a map")
                    self._set_all_properties(entity, value, item.merge, stats)
                elif item.kind == "label":
                    entity = evaluate(item.target, ctx)
                    if entity is None:
                        continue
                    if not isinstance(entity, Node):
                        raise CypherTypeError("labels can only be set on nodes")
                    self._add_labels(entity, item.labels, stats)
            # refresh entity bindings so subsequent clauses see updates
            refreshed = self._refresh_row(row)
            row.clear()
            row.update(refreshed)
        return rows

    def _set_property(self, entity, key: str, value, stats: Stats) -> None:
        if isinstance(entity, Node):
            node = self.storage.get_node(entity.id)
            old = node.copy()
            if value is None:
                node.properties.pop(key, None)
            else:
                node.properties[key] = _to_storable(value)
            self.schema.check_unique(node, exclude_id=node.id)
            self.storage.update_node(node)
            self._record_undo(lambda o=old: self.storage.update_node(o))
            stats.properties_set += 1
        elif isinstance(entity, Edge):
            edge = self.storage.get_edge(entity.id)
            old = edge.copy()
            if value is None:
                edge.properties.pop(key, None)
            else:
                edge.properties[key] = _to_storable(value)
            self.storage.update_edge(edge)
            self._record_undo(lambda o=old: self.storage.update_edge(o))
            stats.properties_set += 1
        else:
            raise CypherTypeError("SET target must be a node or relationship")

    def _set_all_properties(self, entity, value: dict, merge: bool, stats: Stats) -> None:
        value = {k: _to_storable(v) for k, v in value.items()}
        if isinstance(entity, Node):
            node = self.storage.get_node(entity.id)
            old = node.copy()
            if merge:
                node.properties.update(value)
            else:
                node.properties = dict(value)
            self.schema.check_unique(node, exclude_id=node.id)
            self.storage.update_node(node)
            self._record_undo(lambda o=old: self.storage.update_node(o))
            stats.properties_set += len(value)
        elif isinstance(entity, Edge):
            edge = self.storage.get_edge(entity.id)
            old = edge.copy()
            if merge:
                edge.properties.update(value)
            else:
                edge.properties = dict(value)
            self.storage.update_edge(edge)
            self._record_undo(lambda o=old: self.storage.update_edge(o))
            stats.properties_set += len(value)
        else:
            raise CypherTypeError("SET target must be a node or relationship")

    def _add_labels(self, entity: Node, labels: list[str], stats: Stats) -> None:
        node = self.storage.get_node(entity.id)
        old = node.copy()
        added = 0
        for lbl in labels:
            if lbl not in node.labels:
                node.labels.append(lbl)
                added += 1
        if added:
            self.storage.update_node(node)
            self._record_undo(lambda o=old: self.storage.update_node(o))
            stats.labels_added += added

    def _remove(
        self, items: list[ast.SetItem], rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        for row in rows:
            ctx = EvalContext(row, params, self)
            for item in items:
                if item.kind == "property":
                    assert isinstance(item.target, ast.Property)
                    entity = evaluate(item.target.subject, ctx)
                    if entity is None:
                        continue
                    self._set_property(entity, item.target.key, None, stats)
                elif item.kind == "label":
                    entity = evaluate(item.target, ctx)
                    if entity is None:
                        continue
                    node = self.storage.get_node(entity.id)
                    old = node.copy()
                    removed = 0
                    for lbl in item.labels:
                        if lbl in node.labels:
                            node.labels.remove(lbl)
                            removed += 1
                    if removed:
                        self.storage.update_node(node)
                        self._record_undo(lambda o=old: self.storage.update_node(o))
                        stats.labels_removed += removed
            refreshed = self._refresh_row(row)
            row.clear()
            row.update(refreshed)
        return rows

    # -- DELETE ------------------------------------------------------------------
    def _delete(
        self, clause: ast.DeleteClause, rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        deleted_nodes: set[str] = set()
        deleted_edges: set[str] = set()
        for row in rows:
            ctx = EvalContext(row, params, self)
            for expr in clause.exprs:
                v = evaluate(expr, ctx)
                items = v if isinstance(v, list) else [v]
                for item in items:
                    if item is None:
                        continue
                    if isinstance(item, Node):
                        self._delete_node(item.id, clause.detach, deleted_nodes,
                                          deleted_edges, stats)
                    elif isinstance(item, Edge):
                        self._delete_edge(item.id, deleted_edges, stats)
                    elif isinstance(item, dict) and item.get("__path__"):
                        # deleting a path deletes its relationships AND nodes
                        for e in item.get("relationships", []):
                            self._delete_edge(e.id, deleted_edges, stats)
                        for pn in item.get("nodes", []):
                            self._delete_node(pn.id, clause.detach, deleted_nodes,
                                              deleted_edges, stats)
                    else:
                        raise CypherTypeError("DELETE expects nodes/relationships")
        return rows

    def _delete_node(
        self,
        node_id: str,
        detach: bool,
        deleted_nodes: set[str],
        deleted_edges: set[str],
        stats: Stats,
    ) -> None:
        if node_id in deleted_nodes:
            return
        try:
            old = self.storage.get_node(node_id)
        except NotFoundError:
            deleted_nodes.add(node_id)  # already gone (e.g. earlier cascade)
            return
        old_edges = {
            e.id: e
            for e in self.storage.get_outgoing_edges(node_id)
            + self.storage.get_incoming_edges(node_id)
        }
        if old_edges and not detach:
            raise CypherTypeError(
                "cannot delete node with relationships; use DETACH DELETE"
            )
        self.storage.delete_node(node_id)
        deleted_nodes.add(node_id)
        stats.nodes_deleted += 1
        cascaded = set(old_edges) - deleted_edges
        stats.relationships_deleted += len(cascaded)
        deleted_edges.update(old_edges)

        def undo_node(o=old, es=[old_edges[i] for i in cascaded]):
            self.storage.create_node(o)
            for e in es:
                try:
                    self.storage.create_edge(e)
                except Exception:
                    _log.debug("undo: cascaded-edge restore failed",
                               exc_info=True)

        self._record_undo(undo_node)

    def _delete_edge(
        self, edge_id: str, deleted_edges: set[str], stats: Stats
    ) -> None:
        if edge_id in deleted_edges:
            return
        try:
            old_e = self.storage.get_edge(edge_id)
        except NotFoundError:
            deleted_edges.add(edge_id)  # cascaded away by an earlier node delete
            return
        self.storage.delete_edge(edge_id)
        deleted_edges.add(edge_id)
        stats.relationships_deleted += 1
        self._record_undo(lambda o=old_e: self.storage.create_edge(o))

    # -- WITH / RETURN projection ---------------------------------------------------
    def _with(
        self, clause: ast.WithClause, rows: list[dict], params: dict, stats: Stats
    ) -> list[dict]:
        ret = ast.ReturnClause(
            clause.items, clause.distinct, clause.order_by, clause.skip,
            clause.limit, clause.star,
        )
        columns, data = self._project(ret, rows, params, stats, star_keep=clause.star,
                                      original_rows=rows)
        out = [dict(zip(columns, r)) for r in data]
        if clause.where is not None:
            out = [
                r for r in out
                if evaluate(clause.where, EvalContext(r, params, self)) is True
            ]
        return out

    def _project(
        self,
        clause: ast.ReturnClause,
        rows: list[dict],
        params: dict,
        stats: Stats,
        star_keep: bool = False,
        original_rows: Optional[list[dict]] = None,
    ) -> tuple[list[str], list[list[Any]]]:
        _t_proj = time.perf_counter()
        with _tracer.span("cypher.project"):
            out = self._project_inner(clause, rows, params, stats,
                                      star_keep, original_rows)
        _STAGE_PROJECT.observe(time.perf_counter() - _t_proj)
        return out

    def _project_inner(
        self,
        clause: ast.ReturnClause,
        rows: list[dict],
        params: dict,
        stats: Stats,
        star_keep: bool = False,
        original_rows: Optional[list[dict]] = None,
    ) -> tuple[list[str], list[list[Any]]]:
        items = list(clause.items)
        star = getattr(clause, "star", False)
        # RETURN * / WITH * expands to all bound variables
        if star:
            star_cols = sorted({k for r in rows for k in r.keys()})
            star_items = [ast.ReturnItem(ast.Variable(c), c) for c in star_cols]
            items = star_items + items
        columns = [it.key for it in items]
        has_agg = any(_contains_aggregate(it.expr) for it in items)
        if has_agg:
            data = self._aggregate_project(items, rows, params)
            source_rows: list[dict] = [{} for _ in data]
        else:
            data = []
            source_rows = []
            for row in rows:
                ctx = EvalContext(row, params, self)
                data.append([evaluate(it.expr, ctx) for it in items])
                source_rows.append(row)
        if clause.distinct:
            seen = set()
            unique, unique_src = [], []
            for r, src in zip(data, source_rows):
                key = _hashable(r)
                if key not in seen:
                    seen.add(key)
                    unique.append(r)
                    unique_src.append(src)
            data, source_rows = unique, unique_src
        if clause.order_by:
            data = self._order_by(
                clause.order_by, columns, data, source_rows, params
            )
        if clause.skip is not None:
            n = evaluate(clause.skip, EvalContext({}, params, self))
            data = data[int(n):]
        if clause.limit is not None:
            n = evaluate(clause.limit, EvalContext({}, params, self))
            data = data[: int(n)]
        return columns, data

    def _order_by(self, order_items, columns, data, source_rows, params):
        # ORDER BY may reference output columns OR pre-projection variables.
        # Keys are evaluated ONCE per row, then sorted with one stable pass
        # per key (last key first — stability composes them). A pass whose
        # values are all-numeric or all-string sorts on the native value;
        # only mixed-type/entity passes pay for the _SortKey comparison
        # wrapper (profiled: wrapper comparisons dominated traversal+sort
        # query time before this).
        keyed = []
        for row_vals, src in zip(data, source_rows):
            binding = dict(src)
            binding.update(dict(zip(columns, row_vals)))
            keys = []
            for oi in order_items:
                if isinstance(oi.expr, ast.Variable) and oi.expr.name in binding:
                    v = binding[oi.expr.name]
                else:
                    v = evaluate(oi.expr, EvalContext(binding, params, self))
                keys.append(v)
            keyed.append((keys, row_vals))

        return _multisort(keyed, [oi.descending for oi in order_items])

    def _aggregate_project(self, items, rows, params) -> list[list[Any]]:
        group_idx = [i for i, it in enumerate(items) if not _contains_aggregate(it.expr)]
        agg_idx = [i for i, it in enumerate(items) if _contains_aggregate(it.expr)]
        groups: dict[Any, dict] = {}
        order: list[Any] = []
        for row in rows:
            ctx = EvalContext(row, params, self)
            gkey_vals = [evaluate(items[i].expr, ctx) for i in group_idx]
            gkey = _hashable(gkey_vals)
            if gkey not in groups:
                groups[gkey] = {"key_vals": gkey_vals, "rows": []}
                order.append(gkey)
            groups[gkey]["rows"].append(row)
        if not rows and not group_idx:
            groups[()] = {"key_vals": [], "rows": []}
            order.append(())
        out = []
        for gkey in order:
            g = groups[gkey]
            vals: list[Any] = [None] * len(items)
            for pos, i in enumerate(group_idx):
                vals[i] = g["key_vals"][pos]
            for i in agg_idx:
                vals[i] = self._eval_aggregate(items[i].expr, g["rows"], params)
            out.append(vals)
        return out

    def _eval_aggregate(self, expr: ast.Expr, rows: list[dict], params: dict) -> Any:
        if isinstance(expr, ast.FunctionCall) and is_aggregate(expr.name):
            name = expr.name
            if name == "count" and expr.args and isinstance(expr.args[0], ast.Literal) \
                    and expr.args[0].value == "*":
                return len(rows)
            values = []
            for row in rows:
                ctx = EvalContext(row, params, self)
                v = evaluate(expr.args[0], ctx) if expr.args else None
                if v is not None:
                    values.append(v)
            if expr.distinct:
                seen = set()
                uniq = []
                for v in values:
                    k = _hashable([v])
                    if k not in seen:
                        seen.add(k)
                        uniq.append(v)
                values = uniq
            if name == "count":
                return len(values)
            if name == "collect":
                return values
            if name == "sum":
                return sum(values) if values else 0
            if name == "avg":
                return sum(values) / len(values) if values else None
            if name == "min":
                return min(values) if values else None
            if name == "max":
                return max(values) if values else None
            if name in ("stdev", "stdevp"):
                if len(values) < 2:
                    return 0.0
                arr = np.asarray(values, np.float64)
                return float(arr.std(ddof=1 if name == "stdev" else 0))
            if name in ("percentilecont", "percentiledisc"):
                if len(expr.args) != 2:
                    raise CypherSyntaxError(f"{name} expects (value, percentile)")
                p = evaluate(
                    expr.args[1],
                    EvalContext(rows[0] if rows else {}, params, self),
                )
                if not values:
                    return None
                arr = np.sort(np.asarray(values, np.float64))
                if name == "percentilecont":
                    return float(np.quantile(arr, float(p)))
                # nearest-rank (discrete)
                idx = max(int(np.ceil(float(p) * len(arr))) - 1, 0)
                v = arr[min(idx, len(arr) - 1)]
                return int(v) if float(v).is_integer() and all(
                    isinstance(x, int) for x in values
                ) else float(v)
        # expression containing aggregates, e.g. count(x) + 1
        if isinstance(expr, ast.BinaryOp):
            left = (
                self._eval_aggregate(expr.left, rows, params)
                if _contains_aggregate(expr.left)
                else evaluate(expr.left, EvalContext(rows[0] if rows else {}, params, self))
            )
            right = (
                self._eval_aggregate(expr.right, rows, params)
                if _contains_aggregate(expr.right)
                else evaluate(expr.right, EvalContext(rows[0] if rows else {}, params, self))
            )
            return _binary_value(expr.op, left, right)
        if isinstance(expr, ast.FunctionCall):
            # scalar fn over aggregate args, e.g. round(avg(x))
            args = [
                self._eval_aggregate(a, rows, params)
                if _contains_aggregate(a)
                else evaluate(a, EvalContext(rows[0] if rows else {}, params, self))
                for a in expr.args
            ]
            fn = FUNCTIONS.get(expr.name) or self.lookup_function(expr.name)
            if fn is None:
                raise CypherSyntaxError(f"unknown function {expr.name}()")
            return fn(*args)
        raise CypherSyntaxError("invalid aggregate expression")

    # -- UNWIND / CALL / FOREACH / LOAD CSV -----------------------------------------
    def _unwind(self, clause: ast.UnwindClause, rows, params) -> list[dict]:
        out = []
        for row in rows:
            v = evaluate(clause.expr, EvalContext(row, params, self))
            if v is None:
                continue
            items = v if isinstance(v, list) else [v]
            for item in items:
                nr = dict(row)
                nr[clause.variable] = item
                if clause.where is not None and evaluate(
                    clause.where, EvalContext(nr, params, self)
                ) is not True:
                    # UNWIND ... WHERE row filter (reference dialect)
                    continue
                out.append(nr)
        return out

    def _call(self, clause: ast.CallClause, rows, params, stats) -> list[dict]:
        fn = PROCEDURES.get(clause.procedure)
        if fn is None:
            raise CypherSyntaxError(f"unknown procedure {clause.procedure}")
        self._last_call_columns: list[str] = []
        out = []
        for row in rows:
            args = [
                evaluate(a, EvalContext(row, params, self)) for a in clause.args
            ]
            cols, data = fn(self, args, row)
            self._last_call_columns = list(cols)
            if not clause.yield_items and not clause.yield_star:
                # no YIELD: procedure acts as a side effect / passthrough
                if not data:
                    out.append(row)
                for r in data:
                    nr = dict(row)
                    nr.update(dict(zip(cols, r)))
                    out.append(nr)
                continue
            names = (
                [(c, None) for c in cols] if clause.yield_star else clause.yield_items
            )
            for r in data:
                rec = dict(zip(cols, r))
                nr = dict(row)
                for name, alias in names:
                    if name not in rec:
                        raise CypherSyntaxError(
                            f"procedure {clause.procedure} does not yield `{name}`"
                        )
                    nr[alias or name] = rec[name]
                if clause.where is not None and evaluate(
                    clause.where, EvalContext(nr, params, self)
                ) is not True:
                    continue
                out.append(nr)
        return self._apply_order_skip_limit(
            out, clause.order_by, clause.skip, clause.limit, params
        )

    def _apply_order_skip_limit(self, rows, order_by, skip, limit, params):
        """Shared ORDER BY/SKIP/LIMIT tail for the RETURN-less CALL forms
        (standalone CALL ... YIELD and CALL { ... } subqueries)."""
        if order_by:
            def sort_keys(r):
                return [
                    _SortKey(
                        evaluate(oi.expr, EvalContext(r, params, self)),
                        oi.descending,
                    )
                    for oi in order_by
                ]

            rows.sort(key=sort_keys)
        if skip is not None:
            rows = rows[int(evaluate(skip, EvalContext({}, params, self))):]
        if limit is not None:
            rows = rows[: int(evaluate(limit, EvalContext({}, params, self)))]
        return rows

    def eval_collect_subquery(self, e, ctx: EvalContext) -> list:
        """COLLECT { ... RETURN expr } — correlated single-column subquery
        per row; returns the column values as a list (Neo4j 5)."""
        res = self._run_query(
            e.query, ctx.params, start_rows=[dict(ctx.bindings)],
            stats=Stats(),
        )
        if len(res.columns) != 1:
            raise CypherSyntaxError(
                "COLLECT subquery must return exactly one column"
            )
        return [row[0] for row in res.rows]

    def _call_subquery(self, clause: ast.CallSubquery, rows, params, stats) -> list[dict]:
        if clause.in_transactions:
            return self._call_in_transactions(clause, rows, params, stats)
        out = []
        returns = any(
            isinstance(c, ast.ReturnClause) for c in clause.query.clauses
        )
        for row in rows:
            # full query semantics per input row — including UNION branches;
            # writes inside the subquery accumulate into the outer stats
            res = self._run_query(clause.query, params, start_rows=[row], stats=stats)
            if not returns:
                out.append(row)
                continue
            for r in res.rows:
                nr = dict(row)
                nr.update(dict(zip(res.columns, r)))
                out.append(nr)
        # reference-dialect tail: CALL { ... } ORDER BY/SKIP/LIMIT
        return self._apply_order_skip_limit(
            out, clause.order_by, clause.skip, clause.limit, params
        )

    def _call_in_transactions(
        self, clause: ast.CallSubquery, rows, params, stats
    ) -> list[dict]:
        """CALL { ... } IN TRANSACTIONS OF n ROWS — input rows run through
        the subquery in committed batches (Neo4j ON ERROR FAIL semantics:
        earlier batches stay committed, the failing batch aborts the query).
        WAL transaction markers bracket each batch when the storage chain
        supports them."""
        out = []
        returns = any(
            isinstance(c, ast.ReturnClause) for c in clause.query.clauses
        )
        batch = max(clause.batch_rows, 1)
        tx_begin = getattr(self.storage, "tx_begin", None)
        tx_commit = getattr(self.storage, "tx_commit", None)
        for start in range(0, len(rows), batch):
            chunk = rows[start : start + batch]
            txid = str(uuid.uuid4())
            if callable(tx_begin):
                tx_begin(txid)
            # checkpoint the implicit statement frame: once this batch
            # commits, its mutations are durable and must NOT be undone by a
            # later batch's failure (ON ERROR FAIL: earlier batches stay)
            mark = (len(self._tx_undo)
                    if self._tx_implicit and self._tx_undo is not None
                    else None)
            try:
                for row in chunk:
                    res = self._run_query(
                        clause.query, params, start_rows=[row], stats=stats
                    )
                    if returns:
                        for r in res.rows:
                            nr = dict(row)
                            nr.update(dict(zip(res.columns, r)))
                            out.append(nr)
                    else:
                        out.append(row)
            except Exception:
                if callable(getattr(self.storage, "tx_rollback", None)):
                    self.storage.tx_rollback(txid)
                raise
            if callable(tx_commit):
                tx_commit(txid)
            if mark is not None:
                del self._tx_undo[mark:]
        return out

    def _foreach(self, clause: ast.ForeachClause, rows, params, stats) -> list[dict]:
        for row in rows:
            v = evaluate(clause.expr, EvalContext(row, params, self))
            if v is None:
                continue
            if not isinstance(v, list):
                raise CypherTypeError("FOREACH expects a list")
            for item in v:
                inner = dict(row)
                inner[clause.variable] = item
                inner_rows = [inner]
                for c in clause.updates:
                    inner_rows = self._apply_clause(c, inner_rows, params, stats)
        return rows

    def _load_csv(self, clause: ast.LoadCsvClause, rows, params) -> list[dict]:
        # The reference refuses LOAD CSV in embedded mode outright
        # (clauses.go:1800 "not supported"); here it exists as an opt-in
        # superset gated exactly like apoc.load.* — never a default
        # capability, confinable to an import directory.
        from nornicdb_tpu.config import resolve_import_url

        out = []
        for row in rows:
            url = evaluate(clause.url, EvalContext(row, params, self))
            try:
                path = resolve_import_url(str(url))
            except PermissionError as e:
                raise CypherTypeError(str(e)) from None
            with open(path, newline="") as f:
                reader = csv_mod.reader(f, delimiter=clause.field_terminator)
                data = list(reader)
            if clause.with_headers:
                if not data:
                    continue
                headers = data[0]
                for rec in data[1:]:
                    nr = dict(row)
                    nr[clause.variable] = dict(zip(headers, rec))
                    out.append(nr)
            else:
                for rec in data:
                    nr = dict(row)
                    nr[clause.variable] = list(rec)
                    out.append(nr)
        return out

    # -- pattern expressions (WHERE (a)-[:X]->(b), EXISTS {}, COUNT {}) -----------
    def eval_pattern_expr(self, e, ctx: EvalContext) -> Any:
        if isinstance(e, ast.PatternPredicate):
            it = self.matcher.match_path(e.pattern, ctx.bindings, ctx.params)
            return next(iter(it), None) is not None
        if isinstance(e, (ast.ExistsSubquery, ast.CountSubquery)):
            count = 0
            for r in self.matcher.match_path(e.pattern, ctx.bindings, ctx.params):
                if e.where is None or evaluate(
                    e.where, EvalContext(r, ctx.params, self)
                ) is True:
                    count += 1
                    if isinstance(e, ast.ExistsSubquery):
                        return True
            return count if isinstance(e, ast.CountSubquery) else False
        raise CypherTypeError("unknown pattern expression")

    def eval_pattern_comprehension(self, e, ctx: EvalContext) -> list:
        """[(a)-[:R]->(b) WHERE p | expr] — match from current bindings,
        filter, project."""
        out = []
        for row in self.matcher.match_path(e.pattern, ctx.bindings, ctx.params):
            row_ctx = EvalContext(row, ctx.params, self)
            if e.where is not None and evaluate(e.where, row_ctx) is not True:
                continue
            out.append(evaluate(e.projection, row_ctx))
        return out

    # -- hooks -------------------------------------------------------------------
    def get_node_or_none(self, node_id: str) -> Optional[Node]:
        try:
            return self.storage.get_node(node_id)
        except NotFoundError:
            return None

    def lookup_function(self, name: str) -> Optional[Callable]:
        """Plugin / APOC function lookup (ref: PluginFunctionLookup db.go:933)."""
        fn = self._plugin_functions.get(name)
        if fn is not None:
            return fn
        if name.startswith("apoc."):
            try:
                from nornicdb_tpu.apoc import lookup as apoc_lookup

                return apoc_lookup(name)
            except ImportError:
                return None
        return None

    def register_function(self, name: str, fn: Callable) -> None:
        self._plugin_functions[name.lower()] = fn

    # -- transactions ---------------------------------------------------------------
    def _tx_command(self, stmt: ast.TxCommand) -> Result:
        if stmt.op == "begin":
            if self._tx_undo is not None:
                raise TransactionError("transaction already open")
            self._tx_undo = []
            self._tx_id = str(uuid.uuid4())
            wal = getattr(self.storage, "tx_begin", None)
            if callable(wal):
                wal(self._tx_id)
        elif stmt.op == "commit":
            if self._tx_undo is None:
                raise TransactionError("no open transaction")
            wal = getattr(self.storage, "tx_commit", None)
            if callable(wal):
                wal(self._tx_id)
            self._tx_undo = None
        elif stmt.op == "rollback":
            if self._tx_undo is None:
                raise TransactionError("no open transaction")
            self._apply_undos(self._tx_undo)
            wal = getattr(self.storage, "tx_rollback", None)
            if callable(wal):
                wal(self._tx_id)
            self._tx_undo = None
        return Result([], [])

    # thread-local views over _tx_state (see __init__ for why)
    @property
    def _tx_undo(self) -> Optional[list]:
        return getattr(self._tx_state, "undo", None)

    @_tx_undo.setter
    def _tx_undo(self, v: Optional[list]) -> None:
        self._tx_state.undo = v

    @property
    def _tx_implicit(self) -> bool:
        return getattr(self._tx_state, "implicit", False)

    @_tx_implicit.setter
    def _tx_implicit(self, v: bool) -> None:
        self._tx_state.implicit = v

    @property
    def _tx_id(self) -> Optional[str]:
        return getattr(self._tx_state, "txid", None)

    @_tx_id.setter
    def _tx_id(self, v: Optional[str]) -> None:
        self._tx_state.txid = v

    def _record_undo(self, fn: Callable[[], None]) -> None:
        if self._tx_undo is not None:
            self._tx_undo.append(fn)

    def _run_query_atomic(self, stmt: ast.Query, params: dict) -> Result:
        """Statement-level atomicity (ref: chaos_injection_test.go
        TestRollback_* — 'partial writes are rolled back on error,
        preventing data corruption from failed queries').

        Outside an explicit transaction, every statement runs in an
        implicit undo frame: if any clause fails mid-statement (undefined
        function in a later SET, type error after a CREATE...), the
        mutations already applied are undone in reverse order, so a failed
        statement leaves storage exactly as it found it. Inside an explicit
        transaction the open frame already accumulates undos, and
        BEGIN/ROLLBACK owns the decision.

        Memory: the frame holds one undo closure (and, for SET/DELETE, the
        pre-image copy) per mutation until the statement finishes — the
        price of atomicity, same as the reference's rollback tracking. For
        bulk imports, CALL { ... } IN TRANSACTIONS OF n ROWS both bounds
        this (committed batches drop their undos) and matches the tool the
        reference points bulk writers at."""
        if self._tx_undo is not None:
            return self._run_query(stmt, params)
        if not _is_write_query(stmt):
            return self._run_query(stmt, params)
        # single-writer while a frame is live: see _write_stmt_lock. An
        # explicit BEGIN..COMMIT still interleaves with other writers
        # between ITS statements (a session lock held across client round
        # trips would let an abandoned connection wedge every writer) —
        # same read-committed caveat as the reference's executor.
        with self._write_stmt_lock:
            self._tx_undo = []
            self._tx_implicit = True
            try:
                return self._run_query(stmt, params)
            except Exception:
                self._apply_undos(self._tx_undo)
                raise
            finally:
                self._tx_undo = None
                self._tx_implicit = False

    def _invalidate_cache_for_ddl(self) -> None:
        """Index/constraint DDL changes what reads can see (a fulltext CALL
        cached as empty before CREATE INDEX must not survive it), but DDL
        statements bypass the write-classified cache path — clear
        explicitly."""
        if self.cache is not None:
            self.cache.clear()
        # DDL moves planning decisions (index-backed anchors): drop every
        # cached columnar plan (counted as invalidations; the schema
        # generation stamp also catches DDL issued via another executor
        # sharing this SchemaManager)
        self.columnar.cache.clear()

    def _query_limits(self):
        """(limits, query_bucket) for this executor's database. LimitedEngine
        carries both; the default database's executor (main facade chain)
        consults the manager instead."""
        limits = getattr(self.storage, "limits", None)
        if limits is not None:
            return limits, getattr(self.storage, "query_bucket", None)
        db = self.db
        # lazily-created manager: only consult it if DDL ever instantiated
        # it, and only for executors on the default facade chain (per-DB
        # executors carry a LimitedEngine and returned above)
        if db is not None and getattr(db, "_dbmanager", None) is not None \
                and self.storage is getattr(db, "storage", None):
            return db._dbmanager.query_limit_state(db.default_database)
        return None, None

    def _apply_undos(self, undos: list) -> None:
        """Apply undo closures in reverse, with per-database rate limits
        suspended: a rollback must never itself be throttled, or the
        statement would be left half-unwound."""
        import contextlib as _ctx

        exempt = getattr(self.storage, "exempt_writes", None)
        cm = exempt() if callable(exempt) else _ctx.nullcontext()
        with cm:
            for undo in reversed(undos):
                try:
                    undo()
                except Exception:
                    # best effort: keep unwinding, but a failed undo step
                    # means a partially rolled-back tx — operators must
                    # be able to see it
                    _log.warning("tx rollback: undo step failed",
                                 exc_info=True)

    # -- DDL / admin ------------------------------------------------------------------
    def _create_index(self, stmt: ast.CreateIndex) -> Result:
        self.schema.create_index(
            stmt.name, stmt.kind, stmt.label, stmt.properties, stmt.options,
            stmt.if_not_exists,
        )
        if stmt.kind == "vector" and self.db is not None:
            registry = getattr(self.db, "vectorspaces", None)
            if registry is not None:
                from nornicdb_tpu.vectorspace import VectorSpaceKey

                opts = stmt.options.get("indexConfig", stmt.options) or {}
                dims = int(opts.get("vector.dimensions", 0) or 0)
                sim = str(opts.get("vector.similarity_function", "cosine"))
                if dims:
                    registry.register(VectorSpaceKey(stmt.name, dims, sim.lower()))
        r = Result([], [])
        r.stats.indexes_added = 1
        return r

    def _show(self, stmt: ast.ShowCommand) -> Result:
        if stmt.what == "indexes":
            cols = ["name", "type", "labelsOrTypes", "properties", "options"]
            rows = [
                [i.name, i.kind, [i.label], i.properties, i.options]
                for i in self.schema.list_indexes()
            ]
            return Result(cols, rows)
        if stmt.what == "constraints":
            cols = ["name", "type", "labelsOrTypes", "properties"]
            rows = [
                [c.name, c.kind.upper(), [c.label], c.properties]
                for c in self.schema.list_constraints()
            ]
            return Result(cols, rows)
        if stmt.what == "databases":
            mgr = getattr(self.db, "database_manager", None) if self.db else None
            if mgr is not None:
                return Result(
                    ["name", "default"],
                    [[n, n == mgr.default_database] for n in mgr.list_databases()],
                )
            return Result(["name", "default"], [["neo4j", True]])
        if stmt.what == "procedures":
            return Result(["name"], [[p] for p in sorted(PROCEDURES)])
        if stmt.what == "functions":
            names = sorted(set(FUNCTIONS) | set(self._plugin_functions))
            return Result(["name"], [[f] for f in names])
        if stmt.what == "aliases":
            mgr = getattr(self.db, "database_manager", None) if self.db else None
            if mgr is not None:
                pairs = mgr.list_aliases()
                if stmt.target:
                    pairs = [(a, t) for a, t in pairs if t == stmt.target]
                return Result(["name", "database"],
                              [[a, t] for a, t in pairs])
            return Result(["name", "database"], [])
        if stmt.what == "limits":
            # columns per system_commands_test.go:511: a single "unlimited"
            # row when nothing is set, else one row per configured limit
            mgr = getattr(self.db, "database_manager", None) if self.db else None
            if mgr is None:
                raise CypherSyntaxError(
                    "multi-database commands require a DatabaseManager")
            from nornicdb_tpu.multidb.manager import DatabaseLimits

            limits = mgr.get_limits(stmt.target)
            cols = ["database", "limit", "value", "description"]
            rows = [
                [stmt.target, f, getattr(limits, f),
                 f.replace("_", " ")]
                for f in DatabaseLimits.FIELD_NAMES if getattr(limits, f)
            ]
            if not rows:
                rows = [[stmt.target, "unlimited", None,
                         "no limits configured"]]
            return Result(cols, rows)
        raise CypherSyntaxError(f"unsupported SHOW {stmt.what}")

    def _database_command(self, stmt: ast.DatabaseCommand) -> Result:
        mgr = getattr(self.db, "database_manager", None) if self.db else None
        if mgr is None:
            raise CypherSyntaxError("multi-database commands require a DatabaseManager")
        if stmt.op == "create":
            mgr.create_database(stmt.name, if_not_exists=stmt.if_not_exists)
        elif stmt.op == "drop":
            mgr.drop_database(stmt.name, if_exists=stmt.if_exists)
            invalidate = getattr(self.db, "invalidate_database_cache", None)
            if callable(invalidate):
                invalidate(stmt.name)
        elif stmt.op == "create_alias":
            mgr.create_alias(stmt.name, stmt.options["target"])
        elif stmt.op == "drop_alias":
            try:
                mgr.drop_alias(stmt.name)
            except NotFoundError:
                if not stmt.if_exists:
                    raise
        elif stmt.op == "set_limits":
            # ALTER DATABASE name SET LIMIT k = v (ref:
            # system_commands_test.go:423-486): unknown keys must error,
            # existing limit values are preserved unless overridden
            from nornicdb_tpu.multidb.manager import DatabaseLimits

            current = mgr.get_limits(stmt.name)
            updates = stmt.options["limits"]
            for key in updates:
                if key not in DatabaseLimits.FIELD_NAMES:
                    raise CypherSyntaxError(
                        f"unknown limit {key!r} (valid: "
                        f"{', '.join(DatabaseLimits.FIELD_NAMES)})"
                    )
            # the default database is served by the main facade chain, not
            # a LimitedEngine: write-side limits cannot be enforced there —
            # refuse rather than confirm-and-ignore (query-side limits ARE
            # enforced via the manager's query_limit_state)
            if mgr.resolve(stmt.name) == mgr.default_database:
                inert = {"max_nodes", "max_edges",
                         "max_writes_per_second"} & set(updates)
                if inert:
                    raise CypherSyntaxError(
                        f"limits {sorted(inert)} are not enforceable on the "
                        "default database; create a dedicated database for "
                        "write-side quotas"
                    )
            merged = {f: getattr(current, f) for f in DatabaseLimits.FIELD_NAMES}
            merged.update({
                k: (float(v) if k == "max_query_time" else int(v))
                for k, v in updates.items()
            })
            mgr.set_limits(stmt.name, DatabaseLimits(**merged))
        elif stmt.op == "create_composite":
            mgr.create_composite(stmt.name)
        elif stmt.op == "composite_add_alias":
            # ALTER COMPOSITE DATABASE c ADD ALIAS a FOR DATABASE t:
            # the alias becomes a constituent route into the composite
            alias = stmt.options["alias"]
            target = stmt.options["target"]
            if alias != target:
                try:
                    mgr.create_alias(alias, target)
                except AlreadyExistsError:
                    # tolerable only when the existing name already routes
                    # to the same target; a collision with a different
                    # database must surface, not half-apply
                    if mgr.resolve(alias) != target:
                        raise
            mgr.add_constituent(stmt.name, target)
        elif stmt.op == "composite_drop_alias":
            alias = stmt.options["alias"]
            target = mgr.resolve(alias)
            constituents = mgr._composites.get(stmt.name, [])
            # the resolved target must actually be a constituent — otherwise
            # remove_constituent would no-op while drop_alias still deleted
            # the global alias, half-applying the command
            if target not in constituents:
                raise NotFoundError(
                    f"alias {alias} not found in composite {stmt.name}"
                )
            mgr.remove_constituent(stmt.name, target)
            if target != alias:
                mgr.drop_alias(alias)
        else:
            raise CypherSyntaxError(f"unsupported database command {stmt.op}")
        return Result([], [])

    def _use_command(self, stmt: ast.UseCommand, params: dict) -> Result:
        if self.db is None or getattr(self.db, "database_manager", None) is None:
            raise CypherSyntaxError("USE requires a DatabaseManager")
        ex = self.db.executor_for(stmt.database)
        if stmt.query is None:
            return Result([], [])
        return ex.execute_statement(stmt.query, params)

    def _explain(self, q: ast.Query) -> str:
        lines = ["Query plan:"]
        for c in q.clauses:
            lines.append(f"  {type(c).__name__}")
        # per-operator engine report (columnar vs generic) + plan-cache
        # hit/miss for the columnar pipeline (docs/operations.md
        # "Columnar Cypher execution")
        try:
            lines.extend(self.columnar.explain_lines(q))
        except Exception:
            _log.debug("columnar explain failed", exc_info=True)
        return "\n".join(lines)


# ---------------------------------------------------------------- helpers
# single source of truth in ast.py, shared with has_updating_clause so the
# parse-time COLLECT gate and RBAC/cache classification can't diverge
_WRITE_CLAUSES = ast._UPDATING_CLAUSES


# functions whose results must never be served from the query cache
_NONDETERMINISTIC_FNS = {
    "rand", "randomuuid", "timestamp",
    "apoc.create.uuid", "apoc.text.random", "apoc.date.currenttimestamp",
    "apoc.coll.shuffle", "apoc.coll.randomitem",
    "apoc.util.sleep",  # side effect: caching it would skip the delay
}


def classify_query_text(query: str) -> str:
    """Permission class ("read" | "write") of a raw query string.
    Memoized for normal-sized texts: Bolt calls this on EVERY RUN under
    auth and the class of a fixed text never changes — but oversized
    texts bypass the cache, or a client could pin gigabytes of RAM by
    sending thousands of unique multi-megabyte queries as cache keys."""
    try:
        if len(query) > 4096:
            return _classify_query(query)
        return _classify_query_cached(query)
    except RecursionError:
        # pathologically nested expressions blow the AST walk — the
        # conservative class cannot leak privileges, and the executor
        # will reject the query on its own terms
        return "write"


@functools.lru_cache(maxsize=4096)
def _classify_query_cached(query: str) -> str:
    return _classify_query(query)


def _classify_query(query: str) -> str:
    """AST-based, shared by the HTTP tx API and Bolt RBAC gates: any CALL of a
    procedure ast.procedure_is_readonly rejects counts as a write (readonly
    prefixes minus MUTATING_PROCEDURE_EXCEPTIONS like gds.graph.project),
    so mutating procedures (CALL apoc.refactor.*, apoc.trigger.add, ...)
    can't slip past a keyword regex under a viewer token (ref: auth gating of
    /db/{db}/tx/commit, server_middleware.go). Unparseable input classifies
    as write — the executor rejects it anyway, and the conservative class
    cannot leak privileges.
    """
    try:
        stmt = parse(query)
    except Exception:
        # deliberate conservative class: unparseable input is treated as a
        # write (the executor will reject it anyway); log at debug so the
        # classification is traceable without flooding on bad clients
        _log.debug("unparseable query classified as write", exc_info=True)
        return "write"
    if isinstance(stmt, ast.Query):
        return "write" if _is_write_query(stmt) else "read"
    if isinstance(stmt, ast.UseCommand):
        if stmt.query is not None:
            return "write" if _is_write_query(stmt.query) else "read"
        return "read"
    if isinstance(stmt, ast.ShowCommand):
        return "read"
    # TxCommand (BEGIN/COMMIT/ROLLBACK) classifies as write: on the stateless
    # HTTP endpoint a viewer-opened BEGIN would pin the shared executor's tx
    # open forever (deferring WAL compaction unboundedly) and let a later
    # ROLLBACK wipe other users' writes. Bolt exempts tx keywords from this
    # gate (read-only explicit transactions stay allowed there, where the
    # session owns and cleans up its tx).
    return "write"  # TxCommand, index/constraint DDL, database commands


def _is_write_query(q: ast.Query) -> bool:
    for c in q.clauses:
        if isinstance(c, _WRITE_CLAUSES):
            return True
        if isinstance(c, ast.CallClause) and not ast.procedure_is_readonly(
            c.procedure
        ):
            return True  # index DDL procs / apoc.create / unknown may mutate
        if isinstance(c, ast.CallSubquery) and _is_write_query(c.query):
            return True
    # defense-in-depth: query-bearing expressions (COLLECT { }) are rejected
    # at parse time when they contain updating clauses, but classification
    # must not depend on that — an AST built another way still classifies
    # correctly for RBAC and cacheability.
    for node in _walk_exprs(q):
        if isinstance(node, ast.CollectSubquery) and _is_write_query(node.query):
            return True
    return any(_is_write_query(sub) for sub, _ in q.unions)


def _walk_exprs(q: ast.Query):
    """Yield every expression node reachable from the query's clauses."""

    def walk(e):
        if e is None:
            return
        yield e
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, list):
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        yield from walk(x)
            elif isinstance(v, dict):
                for x in v.values():
                    if hasattr(x, "__dataclass_fields__"):
                        yield from walk(x)
            elif hasattr(v, "__dataclass_fields__"):
                yield from walk(v)

    for c in q.clauses:
        yield from walk(c)
    for sub, _ in q.unions:
        yield from _walk_exprs(sub)


def _is_nondeterministic(q: ast.Query) -> bool:
    for node in _walk_exprs(q):
        if isinstance(node, ast.FunctionCall):
            name = node.name
            if name in _NONDETERMINISTIC_FNS or name.startswith("kalman."):
                return True
    return False


def _pattern_labels(p: ast.PatternPath) -> tuple[set[str], bool]:
    """(labels, fully_labeled): fully_labeled=False when any node pattern
    has no label (the read could match anything)."""
    labels: set[str] = set()
    fully = True
    for el in p.elements:
        if isinstance(el, ast.NodePattern):
            if el.labels:
                labels.update(el.labels)
            else:
                fully = False
    return labels, fully


def _read_cache_labels(q: ast.Query) -> set[str]:
    """Labels a cached read depends on. Returns the EMPTY set (= invalidated
    by every write) unless every dependency is label-scoped — pattern
    predicates and EXISTS/COUNT subqueries also force the unscoped bucket."""
    labels: set[str] = set()
    for c in q.clauses:
        pats = list(getattr(c, "patterns", []) or [])
        if isinstance(c, ast.MergeClause):
            pats.append(c.pattern)
        for p in pats:
            got, fully = _pattern_labels(p)
            if not fully:
                return set()
            labels.update(got)
        if isinstance(c, ast.CallClause):
            return set()  # procedure reads scan arbitrary data
        if isinstance(c, ast.CallSubquery):
            inner = _read_cache_labels(c.query)
            if not inner:
                return set()
            labels.update(inner)
    for node in _walk_exprs(q):
        if isinstance(
            node,
            (
                ast.PatternPredicate,
                ast.ExistsSubquery,
                ast.CountSubquery,
                ast.CollectSubquery,
            ),
        ):
            return set()
    for sub, _ in q.unions:
        inner = _read_cache_labels(sub)
        if not inner:
            return set()
        labels.update(inner)
    return labels


def _write_labels(q: ast.Query) -> set[str]:
    """Labels a write may affect — includes labels added/removed via
    SET/REMOVE/MERGE items. Empty set means 'unscoped: clear everything'."""
    labels: set[str] = set()
    unscoped = False
    for c in q.clauses:
        pats = list(getattr(c, "patterns", []) or [])
        if isinstance(c, ast.MergeClause):
            pats.append(c.pattern)
            for item in list(c.on_create) + list(c.on_match):
                labels.update(item.labels)
        for p in pats:
            got, fully = _pattern_labels(p)
            labels.update(got)
            if not fully and isinstance(c, (ast.CreateClause, ast.MergeClause)):
                unscoped = True
        if isinstance(c, (ast.SetClause, ast.RemoveClause)):
            for item in c.items:
                labels.update(item.labels)
        if isinstance(c, ast.ForeachClause):
            unscoped = True  # nested updates: play safe
        if isinstance(c, ast.CallClause) and not ast.procedure_is_readonly(
            c.procedure
        ):
            unscoped = True
        if isinstance(c, ast.CallSubquery):
            inner = _write_labels(c.query)
            if inner:
                labels.update(inner)
            elif _is_write_query(c.query):
                unscoped = True
    for sub, _ in q.unions:
        inner = _write_labels(sub)
        if inner:
            labels.update(inner)
        elif _is_write_query(sub):
            unscoped = True
    return set() if unscoped else labels


class _SortKey:
    """Comparable wrapper: mixed-type tolerant, nulls sort last (asc),
    honours per-key DESC. Used only for mixed-type sort passes — see
    _multisort."""

    __slots__ = ("v", "desc")

    def __init__(self, v, desc: bool):
        self.v = v
        self.desc = desc

    def _cmp(self, other) -> int:
        a, b = self.v, other.v
        if a is None and b is None:
            return 0
        if a is None:
            return 1  # nulls last in ascending
        if b is None:
            return -1
        if isinstance(a, (Node, Edge)):
            a = a.id
        if isinstance(b, (Node, Edge)):
            b = b.id
        try:
            if a == b:
                return 0
            return -1 if a < b else 1
        except TypeError:
            ta, tb = type(a).__name__, type(b).__name__
            if ta != tb:
                return -1 if ta < tb else 1
            sa, sb = str(a), str(b)
            return 0 if sa == sb else (-1 if sa < sb else 1)

    def __lt__(self, other) -> bool:
        c = self._cmp(other)
        return c > 0 if self.desc else c < 0

    def __eq__(self, other) -> bool:
        return self._cmp(other) == 0


_IMMUTABLE_SCALARS = (str, int, float, bool, bytes, type(None))


def _deep_copy_json(v):
    """Recursive copy for query-result value trees. copy.deepcopy's memo
    machinery costs ~27x more per tiny container (measured 4.3us vs 0.16us
    for a 4-element list) and dominated the cached-serve cost (154us of a
    187us cached read). Result values are trees — property data is
    JSON-able and Cypher values nest finitely — so no cycle memo is needed.
    Every mutable type a result can legally carry is handled explicitly
    (ndarray/tuple/set included — aliasing any of them would let a caller
    poison the cache); anything unrecognized falls back to deepcopy rather
    than alias."""
    if isinstance(v, _IMMUTABLE_SCALARS):
        return v
    if isinstance(v, list):
        return [_deep_copy_json(x) for x in v]
    if isinstance(v, dict):
        return {k: _deep_copy_json(x) for k, x in v.items()}
    if isinstance(v, (Node, Edge)):
        return _copy_cached_value(v)
    if isinstance(v, tuple):
        return tuple(_deep_copy_json(x) for x in v)
    if isinstance(v, np.ndarray):
        return v.copy()
    if isinstance(v, set):
        return {_deep_copy_json(x) for x in v}
    if isinstance(v, frozenset):
        return v
    return copy.deepcopy(v)


def _copy_cached_value(v):
    """Deep enough that no mutable state is shared with the cache: entity
    copies get their property VALUES copied too (Node.copy is shallow on
    values), and every other row value routes through the typed tree copy —
    including tuples/ndarrays/sets at the top level."""
    if isinstance(v, (Node, Edge)):
        c = v.copy()
        c.properties = {
            k: (x if isinstance(x, _IMMUTABLE_SCALARS)
                else _deep_copy_json(x))
            for k, x in c.properties.items()
        }
        return c
    return _deep_copy_json(v)


def _copy_result(r: "Result") -> "Result":
    """Structural copy deep enough that mutating the returned rows, a
    returned node/edge's properties, or a collected list cannot reach the
    cached object."""
    return Result(
        list(r.columns),
        [[_copy_cached_value(v) for v in row] for row in r.rows],
        dataclasses.replace(r.stats),  # Stats is mutable too
        r.plan,
    )


def _multisort(keyed: list, descs: list) -> list:
    """Stable multi-key sort of (keys, payload) pairs: one stable pass per
    key, last key first (stability composes them). A pass whose non-null
    values are all-numeric or all-string sorts natively; only
    mixed-type/entity passes pay for the _SortKey comparison wrapper.
    Null is the largest value: last in ASC, first in DESC (Neo4j order)."""
    for ki in range(len(descs) - 1, -1, -1):
        desc = descs[ki]
        nonnull = [t for t in keyed if t[0][ki] is not None]
        vals = [t[0][ki] for t in nonnull]
        if all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for v in vals) or all(isinstance(v, str) for v in vals):
            nulls = [t for t in keyed if t[0][ki] is None]
            nonnull.sort(key=lambda t, ki=ki: t[0][ki], reverse=desc)
            keyed = (nulls + nonnull) if desc else (nonnull + nulls)
        else:
            keyed.sort(key=lambda t, ki=ki, desc=desc: _SortKey(t[0][ki], desc))
    return [payload for _, payload in keyed]


def _pattern_variables(pattern: ast.PatternPath) -> list[str]:
    out = []
    if pattern.name:
        out.append(pattern.name)
    for el in pattern.elements:
        v = getattr(el, "variable", None)
        if v:
            out.append(v)
    return out


def _contains_aggregate(e: ast.Expr) -> bool:
    if isinstance(e, ast.FunctionCall):
        if is_aggregate(e.name):
            return True
        return any(_contains_aggregate(a) for a in e.args)
    if isinstance(e, ast.BinaryOp):
        return _contains_aggregate(e.left) or _contains_aggregate(e.right)
    if isinstance(e, ast.UnaryOp):
        return _contains_aggregate(e.operand)
    if isinstance(e, ast.Property):
        return _contains_aggregate(e.subject)
    return False


def _and_exprs(
    a: Optional[ast.Expr], b: Optional[ast.Expr]
) -> Optional[ast.Expr]:
    if a is None:
        return b
    if b is None:
        return a
    return ast.BinaryOp("AND", a, b)


def _hashable(vals: Iterable[Any]) -> Any:
    out = []
    for v in vals:
        if isinstance(v, (Node, Edge)):
            out.append(("__ent__", v.id))
        elif isinstance(v, list):
            out.append(_hashable(v))
        elif isinstance(v, dict):
            out.append(tuple(sorted((k, _hashable([x])) for k, x in v.items())))
        elif isinstance(v, np.ndarray):
            out.append(v.tobytes())
        else:
            out.append(v)
    return tuple(out)


def _to_storable(v: Any) -> Any:
    if isinstance(v, (Node, Edge)):
        raise CypherTypeError("cannot store an entity as a property")
    return v


def _binary_value(op: str, a: Any, b: Any) -> Any:
    from nornicdb_tpu.cypher.expr import _binary  # reuse via tiny shim

    e = ast.BinaryOp(op, ast.Literal(a), ast.Literal(b))
    return _binary(e, EvalContext({}, {}, None))


# ---------------------------------------------------------------- procedures
@procedure("db.labels")
def proc_labels(ex: CypherExecutor, args, row):
    labels = sorted({l for n in ex.storage.all_nodes() for l in n.labels})
    return ["label"], [[l] for l in labels]


@procedure("db.relationshiptypes")
def proc_rel_types(ex: CypherExecutor, args, row):
    types = sorted({e.type for e in ex.storage.all_edges()})
    return ["relationshipType"], [[t] for t in types]


@procedure("db.propertykeys")
def proc_prop_keys(ex: CypherExecutor, args, row):
    keys: set[str] = set()
    for n in ex.storage.all_nodes():
        keys.update(n.properties.keys())
    for e in ex.storage.all_edges():
        keys.update(e.properties.keys())
    return ["propertyKey"], [[k] for k in sorted(keys)]


@procedure("dbms.components")
def proc_components(ex: CypherExecutor, args, row):
    from nornicdb_tpu import __version__

    return (
        ["name", "versions", "edition"],
        [["NornicDB-TPU", [__version__], "tpu"]],
    )


@procedure("db.index.vector.querynodes")
def proc_vector_query(ex: CypherExecutor, args, row):
    """(ref: call_vector.go:35 — accepts a vector OR a string; strings are
    auto-embedded server-side)."""
    if len(args) < 3:
        raise CypherSyntaxError(
            "db.index.vector.queryNodes(indexName, k, vectorOrText)"
        )
    index_name, k, query = args[0], int(args[1]), args[2]
    if isinstance(query, str):
        embedder = getattr(ex.db, "embedder", None) if ex.db else None
        if embedder is None:
            raise CypherTypeError(
                "string query requires an embedder (SetEmbedder)"
            )
        query = embedder.embed(query)
    vec = np.asarray(query, np.float32)
    svc = ex.db.search if ex.db is not None else None
    if svc is None:
        raise CypherTypeError("vector search requires the DB search service")
    hits = svc.vector_candidates(vec, k=k)
    out = []
    for nid, score in hits:
        node = ex.get_node_or_none(nid)
        if node is not None:
            out.append([node, float(score)])
    return ["node", "score"], out


# built-in fulltext index names that work without explicit creation
# (ref: neo4j_compat_test.go:265 — 'node_search' and 'default' must answer
# on a bare store, Mimir compatibility)
_BUILTIN_FULLTEXT = ("node_search", "default")


@procedure("db.index.fulltext.querynodes")
def proc_fulltext_query(ex: CypherExecutor, args, row):
    """(ref: call_fulltext.go; builtin-index contract
    neo4j_compat_test.go:243 — unknown index errors immediately, built-in
    names answer with BM25 over node text even without the DB facade)."""
    if len(args) < 2:
        raise CypherSyntaxError("db.index.fulltext.queryNodes(indexName, query)")
    index_name = str(args[0])
    query = str(args[1])
    limit = int(args[2]) if len(args) > 2 else 10
    svc = ex.db.search if ex.db is not None else None
    if svc is not None:
        hits = svc._bm25.search(query, limit)
    else:
        known = index_name in _BUILTIN_FULLTEXT or any(
            i.name == index_name and i.kind == "fulltext"
            for i in ex.schema.list_indexes()
        )
        if not known:
            raise CypherTypeError(
                f"there is no such fulltext schema index: {index_name}"
            )
        from nornicdb_tpu.search.bm25 import BM25Index

        idx = BM25Index()
        for n in ex.storage.all_nodes():
            text = " ".join(
                str(v) for v in n.properties.values() if isinstance(v, str)
            )
            if text:
                idx.index(n.id, text)
        hits = idx.search(query, limit)
    out = []
    for nid, score in hits:
        node = ex.get_node_or_none(nid)
        if node is not None:
            out.append([node, float(score)])
    return ["node", "score"], out


@procedure("db.index.vector.createnodeindex")
def proc_vector_create(ex: CypherExecutor, args, row):
    # legacy creation form (name, label, prop, dims, similarity)
    name, label, prop = str(args[0]), str(args[1]), str(args[2])
    dims = int(args[3]) if len(args) > 3 else 0
    sim = str(args[4]) if len(args) > 4 else "cosine"
    ex.schema.create_index(
        name, "vector", label, [prop],
        {"vector.dimensions": dims, "vector.similarity_function": sim},
        if_not_exists=True,
    )
    return [], []


@procedure("db.index.vector.createrelationshipindex")
def proc_vector_create_rel(ex: CypherExecutor, args, row):
    """db.index.vector.createRelationshipIndex(name, relType, prop, dims,
    similarity) — relationship vectors live in an edge property (ref:
    vector_procedures_test.go:719: edges carry {features: [...]})."""
    name, rel_type, prop = str(args[0]), str(args[1]), str(args[2])
    dims = int(args[3]) if len(args) > 3 else 0
    sim = str(args[4]) if len(args) > 4 else "cosine"
    ex.schema.create_index(
        name, "vector-rel", rel_type, [prop],
        {"vector.dimensions": dims, "vector.similarity_function": sim},
        if_not_exists=True,
    )
    return [], []


@procedure("db.index.vector.queryrelationships")
def proc_vector_query_rels(ex: CypherExecutor, args, row):
    """db.index.vector.queryRelationships(indexName, k, vectorOrText)
    YIELD relationship, score. Unknown index -> empty result with the
    right columns (ref: vector_procedures_test.go:782-787)."""
    if len(args) < 3:
        raise CypherSyntaxError(
            "db.index.vector.queryRelationships(indexName, k, vectorOrText)"
        )
    index_name, k, query = str(args[0]), int(args[1]), args[2]
    idx = next(
        (i for i in ex.schema.list_indexes()
         if i.name == index_name and i.kind == "vector-rel"),
        None,
    )
    if idx is None:
        return ["relationship", "score"], []
    if isinstance(query, str):
        embedder = getattr(ex.db, "embedder", None) if ex.db else None
        if embedder is None:
            raise CypherTypeError(
                "string query requires an embedder (SetEmbedder)"
            )
        query = embedder.embed(query)
    q = np.asarray(query, np.float32)
    qn = float(np.linalg.norm(q)) or 1.0
    prop = idx.properties[0]
    sim = str(idx.options.get("vector.similarity_function", "cosine")).lower()
    scored = []
    for e in ex.storage.get_edges_by_type(idx.label):
        vec = e.properties.get(prop)
        if not isinstance(vec, (list, tuple)) or not vec:
            continue
        v = np.asarray(vec, np.float32)
        if v.shape != q.shape:
            continue
        if sim == "euclidean":
            # Neo4j's euclidean score: 1 / (1 + d^2) — higher is closer
            d2 = float(np.sum((q - v) ** 2))
            score = 1.0 / (1.0 + d2)
        else:
            vn = float(np.linalg.norm(v)) or 1.0
            score = float(np.dot(q, v) / (qn * vn))
        scored.append((score, e))
    scored.sort(key=lambda t: -t[0])
    return ["relationship", "score"], [[e, s] for s, e in scored[:k]]


@procedure("db.index.vector.drop")
def proc_vector_drop(ex: CypherExecutor, args, row):
    ex.schema.drop_index(str(args[0]) if args else "", if_exists=True)
    return [], []


@procedure("db.awaitindexes")
def proc_await_indexes(ex: CypherExecutor, args, row):
    return [], []


@procedure("db.indexes")
def proc_db_indexes(ex: CypherExecutor, args, row):
    """Legacy listing (ref: clauses_test.go CALL db.indexes())."""
    return (["name", "type", "labelsOrTypes", "properties"],
            [[i.name, i.kind, [i.label], i.properties]
             for i in ex.schema.list_indexes()])


@procedure("dbms.functions")
def proc_dbms_functions(ex: CypherExecutor, args, row):
    names = sorted(set(FUNCTIONS) | set(ex._plugin_functions))
    return ["name"], [[n] for n in names]


@procedure("nornicdb.decay.info")
def proc_decay_info(ex: CypherExecutor, args, row):
    """(ref: clauses_test.go:427 — one row describing the decay config)"""
    decay = getattr(ex.db, "decay", None) if ex.db else None
    cfg = getattr(decay, "config", None)
    return (["enabled", "halfLifeDays", "floor"],
            [[decay is not None,
              getattr(cfg, "half_life_days", 30.0),
              getattr(cfg, "floor", 0.1)]])


@procedure("db.schema.nodeproperties")
def proc_schema_node_properties(ex: CypherExecutor, args, row):
    """(ref: clauses_test.go:468) nodeLabels + propertyName + types."""
    seen: dict[tuple, set] = {}
    for n in ex.storage.all_nodes():
        for k, v in n.properties.items():
            seen.setdefault((tuple(sorted(n.labels)), k), set()).add(
                type(v).__name__)
    return (["nodeLabels", "propertyName", "propertyTypes"],
            [[list(labels), key, sorted(types)]
             for (labels, key), types in sorted(seen.items())])


@procedure("db.constraints")
def proc_db_constraints(ex: CypherExecutor, args, row):
    """Legacy listing (ref: db_procedures_test.go CALL db.constraints())."""
    return (["name", "type", "labelsOrTypes", "properties"],
            [[c.name, c.kind.upper(), [c.label], c.properties]
             for c in ex.schema.list_constraints()])


@procedure("db.stats.retrieveallanthestats")
def proc_db_stats_retrieve(ex: CypherExecutor, args, row):
    """(sic — the reference registers this exact name,
    db_procedures_test.go: db.stats.retrieveAllAnTheStats)"""
    return (["section", "data"],
            [["GRAPH COUNTS", {
                "nodes": ex.storage.node_count(),
                "relationships": ex.storage.edge_count(),
            }]])


@procedure("gds.version")
def proc_gds_version(ex: CypherExecutor, args, row):
    return ["version"], [["2.5.0-nornicdb-tpu"]]


@procedure("nornicdb.version")
def proc_nornic_version(ex: CypherExecutor, args, row):
    """(ref: apoc_integration_test.go:32)"""
    return ["version", "edition"], [["0.4.0", "tpu"]]


@procedure("nornicdb.stats")
def proc_nornic_stats(ex: CypherExecutor, args, row):
    return (["nodes", "relationships", "labels"],
            [[ex.storage.node_count(), ex.storage.edge_count(),
              sorted({l for n in ex.storage.all_nodes()
                      for l in n.labels})]])


@procedure("db.create.setnodevectorproperty")
def proc_set_node_vector(ex: CypherExecutor, args, row):
    """db.create.setNodeVectorProperty(nodeIdOrNode, prop, vector)
    (ref: vector_procedures_test.go:184)."""
    if len(args) < 3:
        raise CypherSyntaxError(
            "db.create.setNodeVectorProperty(node, key, vector)")
    target, prop, vec = args[0], str(args[1]), args[2]
    node = target if isinstance(target, Node) else ex.storage.get_node(str(target))
    old = node.copy()  # pre-image BEFORE the mutation, like every undo site
    node.properties[prop] = [float(v) for v in (vec or [])]
    ex.storage.update_node(node)
    ex._record_undo(lambda o=old: ex.storage.update_node(o))
    return ["node"], [[node]]


@procedure("db.create.setrelationshipvectorproperty")
def proc_set_rel_vector(ex: CypherExecutor, args, row):
    if len(args) < 3:
        raise CypherSyntaxError(
            "db.create.setRelationshipVectorProperty(rel, key, vector)")
    target, prop, vec = args[0], str(args[1]), args[2]
    edge = target if isinstance(target, Edge) else ex.storage.get_edge(str(target))
    old = edge.copy()
    edge.properties[prop] = [float(v) for v in (vec or [])]
    ex.storage.update_edge(edge)
    ex._record_undo(lambda o=old: ex.storage.update_edge(o))
    return ["relationship"], [[edge]]


@procedure("db.index.fulltext.createrelationshipindex")
def proc_fulltext_create_rel(ex: CypherExecutor, args, row):
    """db.index.fulltext.createRelationshipIndex(name, relType, prop)."""
    name, rel_type = str(args[0]), str(args[1])
    props = [str(p) for p in args[2:]] or ["text"]
    ex.schema.create_index(name, "fulltext-rel", rel_type, props, {},
                           if_not_exists=True)
    return [], []


@procedure("db.index.fulltext.queryrelationships")
def proc_fulltext_query_rels(ex: CypherExecutor, args, row):
    """YIELD relationship, score: BM25-free substring/token scoring over
    the indexed edge properties (parity shape; unknown index -> empty)."""
    if len(args) < 2:
        raise CypherSyntaxError(
            "db.index.fulltext.queryRelationships(indexName, query)")
    index_name, query = str(args[0]), str(args[1]).lower()
    idx = next(
        (i for i in ex.schema.list_indexes()
         if i.name == index_name and i.kind == "fulltext-rel"),
        None,
    )
    if idx is None:
        return ["relationship", "score"], []
    terms = query.split()
    out = []
    for e in ex.storage.get_edges_by_type(idx.label):
        text = " ".join(
            str(e.properties.get(p, "")) for p in idx.properties
        ).lower()
        hits = sum(1 for t in terms if t in text)
        if hits:
            out.append([e, hits / max(len(terms), 1)])
    out.sort(key=lambda r: -r[1])
    return ["relationship", "score"], out


@procedure("db.awaitindex")
def proc_await_index2(ex: CypherExecutor, args, row):
    """db.awaitIndex(name[, timeoutSeconds]) yields status — indexes are
    maintained synchronously, and the reference tolerates unknown names
    (db_procedures_test.go:126 awaits 'my_index' on an empty store)."""
    return ["status"], [["online"]]


@procedure("db.resampleindex")
def proc_resample_index(ex: CypherExecutor, args, row):
    """db.resampleIndex(name) — statistics resampling is a no-op (no
    cost-based planner statistics in this engine)."""
    return [], []


@procedure("db.resampleoutdatedindexes")
def proc_resample_outdated(ex: CypherExecutor, args, row):
    return [], []


@procedure("db.ping")
def proc_ping(ex: CypherExecutor, args, row):
    return ["success"], [[True]]


@procedure("db.info")
def proc_db_info(ex: CypherExecutor, args, row):
    import time as _time

    return (
        ["id", "name", "creationDate", "nodeCount", "edgeCount"],
        [[
            "nornicdb-tpu", "neo4j",
            _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
            ex.storage.node_count(), ex.storage.edge_count(),
        ]],
    )


@procedure("db.clearquerycaches")
def proc_clear_query_caches(ex: CypherExecutor, args, row):
    if ex.cache is not None:
        ex.cache.clear()
    return ["value"], [["Query caches cleared"]]


# db.stats.* query-statistics collection (ref: the reference's db.stats
# surface; stats here are the executor's own counters)
@procedure("db.stats.collect")
def proc_stats_collect(ex: CypherExecutor, args, row):
    ex._stats_collecting = True
    return ["section", "success", "message"], [
        [str(args[0]) if args else "QUERIES", True, "collection started"]
    ]


@procedure("db.stats.stop")
def proc_stats_stop(ex: CypherExecutor, args, row):
    ex._stats_collecting = False
    return ["section", "success", "message"], [
        [str(args[0]) if args else "QUERIES", True, "collection stopped"]
    ]


@procedure("db.stats.status")
def proc_stats_status(ex: CypherExecutor, args, row):
    collecting = bool(getattr(ex, "_stats_collecting", False))
    return ["section", "status"], [
        ["QUERIES", "collecting" if collecting else "idle"]
    ]


@procedure("db.stats.retrieve")
def proc_stats_retrieve(ex: CypherExecutor, args, row):
    section = str(args[0]) if args else "QUERIES"
    return ["section", "data"], [
        [section, {"queryCount": ex.query_count}]
    ]


@procedure("db.stats.clear")
def proc_stats_clear(ex: CypherExecutor, args, row):
    return ["section", "success"], [["QUERIES", True]]


@procedure("dbms.info")
def proc_dbms_info(ex: CypherExecutor, args, row):
    from nornicdb_tpu import __version__

    return (
        ["id", "name", "creationDate"],
        [["nornicdb-tpu", "DBMS", __version__]],
    )


@procedure("dbms.listconfig")
def proc_dbms_list_config(ex: CypherExecutor, args, row):
    cfg = getattr(ex.db, "config", None) if ex.db else None
    rows = []
    if cfg is not None:
        for k, v in sorted(vars(cfg).items()):
            if isinstance(v, (str, int, float, bool)) or v is None:
                rows.append([k, str(v)])
    return ["name", "value"], rows


@procedure("dbms.clientconfig")
def proc_dbms_client_config(ex: CypherExecutor, args, row):
    return ["name", "value"], []


@procedure("dbms.listconnections")
def proc_dbms_list_connections(ex: CypherExecutor, args, row):
    return (
        ["connectionId", "connectTime", "connector", "username"],
        [],
    )


@procedure("dbms.procedures")
def proc_dbms_procedures(ex: CypherExecutor, args, row):
    return (
        ["name", "signature"],
        [[name, f"{name}(...)"] for name in sorted(PROCEDURES)],
    )


@procedure("tx.setmetadata")
def proc_tx_set_metadata(ex: CypherExecutor, args, row):
    """tx.setMetaData(map) — attaches metadata to the current transaction
    (surfaced through dbms.listConnections in the reference; stored on
    the executor here)."""
    ex._tx_metadata = args[0] if args and isinstance(args[0], dict) else {}
    return [], []


@procedure("db.index.fulltext.createnodeindex")
def proc_fulltext_create(ex: CypherExecutor, args, row):
    """db.index.fulltext.createNodeIndex(name, labelsOrLabel, propsOrProp)
    — legacy creation form (ref: call_fulltext.go)."""
    name = str(args[0]) if args else ""
    labels = args[1] if len(args) > 1 else []
    props = args[2] if len(args) > 2 else []
    if isinstance(labels, str):
        labels = [labels]
    if isinstance(props, str):
        props = [props]
    ex.schema.create_index(
        name, "fulltext", str(labels[0]) if labels else "",
        [str(p) for p in props], {}, if_not_exists=True,
    )
    return [], []


@procedure("db.index.fulltext.drop")
def proc_fulltext_drop(ex: CypherExecutor, args, row):
    name = str(args[0]) if args else ""
    ex.schema.drop_index(name, if_exists=True)
    return [], []


@procedure("db.index.fulltext.listavailableanalyzers")
def proc_fulltext_analyzers(ex: CypherExecutor, args, row):
    return (
        ["analyzer", "description"],
        [["standard", "BM25 tokenizer (lowercase, word boundaries)"]],
    )
